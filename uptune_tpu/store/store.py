"""Content-addressed trial results store: the port's answer to the
reference's SQLite results database (`/root/reference/python/uptune/
api.py` SQLAlchemy sync + CSV archives).

An in-memory table (key -> row) fronts an append-only on-disk shard
layout inside one store directory:

* ``seg-<instance>.jsonl`` — per-instance append-only segment.  Each
  process appends ONLY to its own segment (unique token), one complete
  JSON line per row via a single ``O_APPEND`` write, so N concurrent
  instances never interleave bytes and readers never see a torn row in
  the middle of a file — at worst an incomplete tail line, which is
  simply not parsed until its newline arrives.
* ``base.jsonl`` — optional compacted snapshot.  ``compact()`` merges
  everything visible into a new base (atomic tmp+rename) and truncates
  only the caller's OWN segment; other instances' live segments are
  never touched, and duplicate keys across base/segments are harmless
  (first finite row wins on load).

Multi-instance exchange is just this layout plus ``refresh()``: each
instance periodically re-scans the directory, reads the newly appended
complete lines of every other segment from its remembered offset, and
merges the rows — any instance's measured config becomes a cache hit
for all of them.

Rows are scoped by ``keys.scope_id`` (space signature + eval
signature), so one directory safely holds many programs' results;
lookups can only ever hit rows recorded for the same space, the same
program content, and the same stage.  Failure rows (``qor: null``) are
recorded for bookkeeping but never served: a build that failed once may
have failed transiently, and re-measuring a failure is the safe side of
that bet.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from .. import obs
from ..obs import faults
from .keys import eval_signature, scope_id, trial_key


def _finite(q) -> bool:
    return q is not None and q == q and abs(q) != float("inf")


def _resolve_fsync(explicit) -> bool:
    """The store's durability knob (docs/STORE.md "Durability"):
    explicit argument > UT_STORE_FSYNC env > ut.config('store-fsync')
    > off.  The O_APPEND protocol already survives process SIGKILL
    through the page cache; fsync additionally survives power loss at
    the cost of one disk barrier per recorded build."""
    if explicit is not None:
        return bool(explicit)
    env = os.environ.get("UT_STORE_FSYNC", "").strip().lower()
    if env:
        return env in ("1", "true", "yes", "on")
    from ..api.session import settings
    return bool(settings.get("store-fsync"))


class ResultStore:
    """One instance's handle on a shared store directory.

    Parameters
    ----------
    root : str
        Store directory (created if missing); shareable between
        concurrent processes.
    space_sig : sequence of str
        Structural space signature (``Tuner._space_sig()`` form).
    command : str | list
        The evaluation command (content-addressed via keys.py).
    stage : int
        Pipeline stage index the results belong to.
    extra_files : optional paths whose CONTENT shapes the measurement
        (template sources); hashed into the eval signature.
    refresh_interval : float
        Minimum seconds between directory re-scans in
        ``maybe_refresh()``.
    """

    def __init__(self, root: str, space_sig: Sequence[str], command,
                 *, stage: int = 0,
                 extra_files: Optional[Sequence[str]] = None,
                 env: Optional[Dict[str, str]] = None,
                 refresh_interval: float = 2.0,
                 fsync: Optional[bool] = None):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.fsync = _resolve_fsync(fsync)
        # the session server shares ONE store handle between its
        # per-connection threads (the cross-tenant memo), so the
        # table/offset/segment mutations take a reentrant lock; the
        # single-threaded driver path pays one uncontended acquire per
        # lookup/record.  CROSS-PROCESS safety was never the lock's
        # job — that is the O_APPEND segment protocol.  Disk appends
        # take _io_lock INSTEAD so a lookup (held under a tenant
        # group's lock in the serving plane) never waits on another
        # tenant's os.write; acquire order is _lock -> _io_lock,
        # never the reverse
        self._lock = threading.RLock()
        self._io_lock = threading.Lock()
        self._closed = False
        self.eval_sig = eval_signature(command, stage,
                                       extra_files=extra_files, env=env)
        self.scope = scope_id(list(space_sig), self.eval_sig)
        self.refresh_interval = float(refresh_interval)
        # unique per-instance segment token: pid + entropy (two stores
        # opened by one process must not share a segment either)
        self.instance = f"{os.getpid():d}-{os.urandom(4).hex()}"
        self._seg_path = os.path.join(self.root,
                                      f"seg-{self.instance}.jsonl")
        self._seg_fd: Optional[int] = None
        self._rows: Dict[str, Dict[str, Any]] = {}
        # path -> (inode, byte offset past the last complete line)
        self._offsets: Dict[str, tuple] = {}
        self._last_refresh = 0.0
        self.hits = 0
        self.misses = 0
        self.recorded = 0
        self.foreign_rows = 0   # rows merged from other instances
        # keys merged from siblings AFTER the initial open: the
        # exchange plane acts on these deltas only (rows already
        # present at open are a previous run's results — cross-RUN
        # propagation is warm start's job, not exchange's)
        self._fresh_foreign: set = set()
        self._loading = True
        self._load_all()
        self._loading = False

    # -- loading -------------------------------------------------------
    def _shard_files(self) -> List[str]:
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            return []
        out = []
        for n in names:
            if n == "base.jsonl" or (n.startswith("seg-")
                                     and n.endswith(".jsonl")):
                out.append(os.path.join(self.root, n))
        return out

    def _merge(self, row: Dict[str, Any], foreign: bool) -> None:
        k = row.get("k")
        if not isinstance(k, str):
            return
        cur = self._rows.get(k)
        # first finite measurement wins; a finite row may replace a
        # recorded failure (another instance's retry succeeded)
        if cur is None or (not _finite(cur.get("qor"))
                           and _finite(row.get("qor"))):
            self._rows[k] = row
            if foreign:
                self.foreign_rows += 1
                if not self._loading:
                    self._fresh_foreign.add(k)

    def _read_new_lines(self, path: str) -> int:
        """Parse newly appended COMPLETE lines of one shard file from
        the remembered offset; a torn tail (no newline yet) stays
        unconsumed until a later pass.  Offsets are bound to the file's
        IDENTITY (inode): a sibling's compact() replaces base.jsonl by
        rename and may recreate its own segment from empty — a stale
        byte offset into the new file would silently skip rows, so an
        inode change or a shrink resets the offset to 0 (re-reads merge
        away as duplicates)."""
        ino, off = self._offsets.get(path, (None, 0))
        try:
            with open(path, "rb") as f:
                st = os.fstat(f.fileno())
                if st.st_ino != ino or st.st_size < off:
                    off = 0   # replaced or truncated: start over
                ino = st.st_ino
                f.seek(off)
                buf = f.read()
        except OSError:
            return 0
        if not buf:
            self._offsets[path] = (ino, off)
            return 0
        end = buf.rfind(b"\n")
        if end < 0:
            self._offsets[path] = (ino, off)
            return 0
        self._offsets[path] = (ino, off + end + 1)
        n = 0
        for line in buf[: end + 1].splitlines():
            if not line.strip():
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue   # defensive: one bad row never poisons a shard
            self._merge(row, foreign=path != self._seg_path)
            n += 1
        return n

    def _load_all(self) -> int:
        n = 0
        for path in self._shard_files():
            if path == self._seg_path:
                continue   # own appends are already in memory
            n += self._read_new_lines(path)
        return n

    def refresh(self) -> int:
        """Re-scan the directory for other instances' appends; returns
        the number of FOREIGN rows read (this instance's own segment is
        never re-read — its rows entered memory at record() time), so a
        truthy refresh really means siblings produced something."""
        with self._lock:
            self._last_refresh = time.monotonic()
            with obs.span("store.refresh") as sp:
                n = self._load_all()
                sp.set(rows=n)
            return n

    def maybe_refresh(self) -> int:
        """Time-gated refresh() for call sites inside hot loops."""
        if time.monotonic() - self._last_refresh < self.refresh_interval:
            return 0
        return self.refresh()

    # -- queries -------------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)

    def __bool__(self) -> bool:
        # An open-but-empty store must stay truthy: ``if store:`` call
        # sites would otherwise never record the first row.
        return True

    def lookup(self, cfg: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """The recorded row for this config under THIS scope, or None.
        Only successful (finite-QoR) rows are served; failure rows are
        re-measured (see module docstring)."""
        with self._lock:
            row = self._rows.get(trial_key(self.scope, cfg))
            if row is not None and _finite(row.get("qor")):
                self.hits += 1
                obs.count("store.hits")
                return row
            self.misses += 1
            obs.count("store.misses")
            return None

    def scope_rows(self) -> List[Dict[str, Any]]:
        """All finite rows recorded for this (space, eval) scope — the
        warm-start training/replay set."""
        with self._lock:
            return [r for r in self._rows.values()
                    if r.get("scope") == self.scope
                    and _finite(r.get("qor"))]

    def best_row(self, sense: str = "min") -> Optional[Dict[str, Any]]:
        rows = self.scope_rows()
        if not rows:
            return None
        pick = min if sense == "min" else max
        return pick(rows, key=lambda r: float(r["qor"]))

    def pop_fresh_rows(self) -> List[Dict[str, Any]]:
        """Finite in-scope rows merged from SIBLING instances since the
        last call (rows present at open never appear): the exchange
        plane's delta feed.  Consuming clears the set."""
        with self._lock:
            if not self._fresh_foreign:
                return []
            keys, self._fresh_foreign = self._fresh_foreign, set()
            out = []
            for k in keys:
                r = self._rows.get(k)
                if r is not None and r.get("scope") == self.scope \
                        and _finite(r.get("qor")):
                    out.append(r)
            return out

    # -- writes --------------------------------------------------------
    def _append(self, row: Dict[str, Any]) -> Optional[int]:
        """Write one row to the segment (caller holds ``_io_lock``).
        With fsync on, returns a dup'd fd for the caller to flush
        OUTSIDE the lock — fsync is inode-wide, so the dup covers this
        append even if the original fd is closed meanwhile; holding
        ``_io_lock`` across the barrier would queue every concurrent
        append behind one disk flush (R102).  Returns None otherwise."""
        if self._closed:
            # a record() racing close() (server stop vs an in-flight
            # tell) must not resurrect the segment: reopening here
            # would leak the fd and leave a stray seg file behind
            return None
        if self._seg_fd is None:
            self._seg_fd = os.open(
                self._seg_path,
                os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        data = (json.dumps(row, separators=(",", ":"),
                           allow_nan=False) + "\n").encode()
        os.write(self._seg_fd, data)   # one write = one atomic line
        if self.fsync:
            # UT_STORE_FSYNC / ut.config('store-fsync'): recorded
            # builds survive power loss, one barrier per append
            return os.dup(self._seg_fd)
        return None

    def record(self, cfg: Dict[str, Any], qor: Optional[float],
               dur: float = 0.0, *, u: Optional[Sequence[float]] = None,
               perms: Optional[Sequence[Sequence[int]]] = None,
               source: str = "") -> Optional[Dict[str, Any]]:
        """Record one measured trial (USER-oriented QoR; None = build
        failure).  Returns the stored row, or None when an equal-or-
        better row for the key already exists (idempotent re-records,
        e.g. archive ingestion over a live store, append nothing)."""
        faults.fire("store.record")
        with self._lock:
            k = trial_key(self.scope, cfg)
            cur = self._rows.get(k)
            if cur is not None and (_finite(cur.get("qor"))
                                    or not _finite(qor)):
                return None
            row: Dict[str, Any] = {
                "k": k, "scope": self.scope, "cfg": cfg,
                "qor": (float(qor) if _finite(qor) else None),
                "dur": round(float(dur), 6), "t": round(time.time(), 3),
                "src": source or self.instance,
            }
            if u is not None:
                row["u"] = [float(x) for x in u]
            if perms is not None:
                row["perms"] = [[int(i) for i in p] for p in perms]
            self._rows[k] = row
            self.recorded += 1
            obs.count("store.recorded")
        # the disk append runs outside _lock (lookups on the serving
        # path must not queue behind it); _io_lock serializes fd use.
        # Same-key dedup already resolved above, and segment line
        # ORDER across threads is irrelevant — rows are keyed and
        # duplicate keys merge away on load
        with self._io_lock:
            fd = self._append(row)
        if fd is not None:
            # the durability barrier runs outside BOTH locks on the
            # dup'd fd; the row is on disk when record() returns, the
            # memo-before-reply contract, without serializing other
            # threads' appends behind the flush
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        return row

    def ingest_archive(self, path: str) -> int:
        """Replay a driver jsonl trial archive into the store (exact
        unit vectors preserved), so resume and pre-store runs share the
        cache path.  Rows already present are skipped."""
        n = 0
        try:
            with open(path, "rb") as f:
                for line in f:
                    if not line.endswith(b"\n"):
                        break   # torn tail
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        break
                    if "cfg" not in rec:
                        continue   # space_sig header row
                    if self.record(rec["cfg"], rec.get("qor"),
                                   rec.get("time", 0.0),
                                   u=rec.get("u"), perms=rec.get("perms"),
                                   source="archive") is not None:
                        n += 1
        except OSError:
            return n
        return n

    # -- maintenance ---------------------------------------------------
    def compact(self) -> int:
        """Merge every visible row into a fresh ``base.jsonl`` (atomic
        rename) and retire this instance's own segment.  Other
        instances' segments are left alone — their rows are now ALSO in
        the base, and duplicate keys merge away on load.

        The whole-store write + fsync runs OUTSIDE the locks (a
        shared-handle tenant's lookup/record must not queue behind a
        full disk flush — R102); correctness comes from rotating the
        segment first: under ``_lock``+``_io_lock`` the own segment is
        closed and renamed to a ``seg-*-old.jsonl`` name that still
        matches the sibling scan pattern, so (a) any record() landing
        mid-compact reopens a FRESH segment and its row survives the
        retirement, and (b) a crash before the base rename loses
        nothing — the rotated segment is still scanned on next load."""
        with self._lock:
            self.refresh()
            old: Optional[str] = os.path.join(
                self.root, f"seg-{self.instance}-old.jsonl")
            with self._io_lock:
                if self._seg_fd is not None:
                    os.close(self._seg_fd)
                    self._seg_fd = None
                try:
                    os.rename(self._seg_path, old)
                except OSError:
                    old = None          # no segment yet
            self._offsets.pop(self._seg_path, None)
            snapshot = list(self._rows.values())
            # per-instance tmp name: two siblings compacting
            # concurrently must not truncate each other's in-flight
            # snapshot (each publishes a FULL merged view, so
            # last-rename-wins is safe)
            tmp = os.path.join(self.root,
                               f"base.jsonl.{self.instance}.tmp")
        with open(tmp, "w") as f:
            for row in snapshot:
                f.write(json.dumps(row, separators=(",", ":")) + "\n")
            f.flush()
            os.fsync(f.fileno())
        with self._lock:
            base = os.path.join(self.root, "base.jsonl")
            os.replace(tmp, base)
            # base content changed identity: re-read from 0 next
            # refresh
            self._offsets.pop(base, None)
            self._read_new_lines(base)
            if old is not None:
                # every rotated row is now in the base (the snapshot
                # was taken after the rotation): safe to drop
                try:
                    os.unlink(old)
                except OSError:
                    pass
                self._offsets.pop(old, None)
            return len(self._rows)

    def close(self) -> None:
        # the serving plane shares one handle across tenant threads,
        # so a close must not race a record()'s in-flight os.write —
        # _io_lock is the fd-lifecycle lock
        with self._io_lock:
            self._closed = True
            if self._seg_fd is not None:
                os.close(self._seg_fd)
                self._seg_fd = None

    def __enter__(self) -> "ResultStore":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def stats(self) -> Dict[str, Any]:
        return {"rows": len(self._rows), "hits": self.hits,
                "misses": self.misses, "recorded": self.recorded,
                "foreign_rows": self.foreign_rows,
                "scope": self.scope}
