"""ML plugins (the reference's L0 layer, SURVEY §1): NOTEARS causal
discovery as JAX kernels; the surrogate-model plugins live in
`uptune_tpu.surrogate`, the QuickEst estimator in `uptune_tpu.quickest`."""
from .notears import covariate_graph, h_func, notears, simulate_dag

__all__ = ["notears", "h_func", "covariate_graph", "simulate_dag"]
