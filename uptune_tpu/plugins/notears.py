"""NOTEARS causal structure discovery as JAX kernels.

The reference ships two versions: a 50-line scipy one without L1
(`/root/reference/python/uptune/plugins/causaldiscovery.py:14-67`) and a
full L1-regularized one whose inner solver lives in a C++ extension that
is absent from the repo (`plugins/notears.py:19,44-46` calls
`cppext.minimize_subproblem` / `cppext.h_func`).  SURVEY §2.3 marks that
extension as the one numeric native kernel to rebuild — here it is
TPU-native instead: the whole augmented-Lagrangian subproblem is one
jitted `lax.scan` of projected-Adam steps on the (w+, w-) split, and the
acyclicity function h(W) = tr(e^{W∘W}) - d is a single `expm` per step
(MXU matmuls via Padé squaring).

Intended use (the reference's commented-out hook, api.py:728-732):
learn a DAG over the archive's covariate columns (`ut.feature` values)
plus the QoR, and surface which covariates causally drive the
objective.
"""
from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def h_func(w: jax.Array) -> jax.Array:
    """Acyclicity measure: tr(e^{W∘W}) - d; zero iff W is a DAG
    (identical math to causaldiscovery.py:31-33)."""
    d = w.shape[0]
    return jnp.trace(jax.scipy.linalg.expm(w * w)) - d


def _smooth_obj(w, x, rho, alpha):
    """Least-squares loss + augmented-Lagrangian acyclicity terms (the
    smooth part of the subproblem; L1 is handled by the split)."""
    n = x.shape[0]
    r = x - x @ w
    loss = 0.5 / n * jnp.sum(r * r)
    h = h_func(w)
    return loss + 0.5 * rho * h * h + alpha * h


class _AdamCarry(NamedTuple):
    wp: jax.Array
    wm: jax.Array
    m: jax.Array
    v: jax.Array


def _minimize_subproblem(w0: jax.Array, x: jax.Array, rho: jax.Array,
                         alpha: jax.Array, lambda1: float,
                         free: jax.Array, steps: int,
                         lr: float) -> jax.Array:
    """min_W smooth(W) + lambda1*||W||_1 via the standard (w+, w-) >= 0
    split (as NOTEARS does under L-BFGS-B bounds): the objective becomes
    smooth + linear, solved with projected Adam; `free` masks entries
    pinned to zero (diagonal, user-forbidden edges)."""
    d = w0.shape[0]

    def obj(wp, wm):
        w = (wp - wm) * free
        return _smooth_obj(w, x, rho, alpha) + lambda1 * jnp.sum(wp + wm)

    grad = jax.grad(lambda ws: obj(ws[0], ws[1]))

    def body(c: _AdamCarry, i):
        g = grad(jnp.stack([c.wp, c.wm]))
        m = 0.9 * c.m + 0.1 * g
        v = 0.999 * c.v + 0.001 * g * g
        t = i + 1.0
        mh = m / (1.0 - 0.9 ** t)
        vh = v / (1.0 - 0.999 ** t)
        ws = jnp.stack([c.wp, c.wm]) - lr * mh / (jnp.sqrt(vh) + 1e-8)
        ws = jnp.maximum(ws, 0.0) * free[None]   # project to the feasible set
        return _AdamCarry(ws[0], ws[1], m, v), None

    wp0 = jnp.maximum(w0, 0.0)
    wm0 = jnp.maximum(-w0, 0.0)
    z = jnp.zeros((2, d, d))
    carry, _ = jax.lax.scan(body, _AdamCarry(wp0, wm0, z, z),
                            jnp.arange(float(steps)))
    return (carry.wp - carry.wm) * free


def _ols_refit(x: np.ndarray, support: np.ndarray) -> np.ndarray:
    """Exact least-squares weights on a fixed DAG support: each column
    regressed on its support parents.  Undoes the L1 + penalty shrinkage
    of the augmented-Lagrangian iterate (whose job was structure, not
    magnitude)."""
    d = x.shape[1]
    w = np.zeros((d, d), np.float32)
    for j in range(d):
        parents = np.nonzero(support[:, j])[0]
        if len(parents) == 0:
            continue
        coef, *_ = np.linalg.lstsq(x[:, parents], x[:, j], rcond=None)
        w[parents, j] = coef
    return w


def _break_cycles(w: np.ndarray) -> np.ndarray:
    """Drop the smallest-|w| edge ON A CYCLE until the support is acyclic
    (the near-DAG iterate can carry tiny cycle-closing entries).
    Edges between topologically-sortable nodes are never touched — only
    the subgraph Kahn's algorithm cannot sort is cyclic."""
    w = w.copy()
    d = w.shape[0]
    while True:
        # Kahn's algorithm on the support; unsorted nodes form the
        # cycle-involved subgraph
        adj = w != 0
        indeg = adj.sum(0).copy()
        sorted_mask = np.zeros(d, bool)
        queue = [j for j in range(d) if indeg[j] == 0]
        while queue:
            u = queue.pop()
            sorted_mask[u] = True
            for v in np.nonzero(adj[u])[0]:
                indeg[v] -= 1
                if indeg[v] == 0:
                    queue.append(int(v))
        if sorted_mask.all():
            return w
        cyc = ~sorted_mask
        in_cycle_sub = adj & cyc[:, None] & cyc[None, :]
        nz = np.abs(np.where(in_cycle_sub, w, np.inf))
        i, j = np.unravel_index(np.argmin(nz), w.shape)
        w[i, j] = 0.0


def notears(x: np.ndarray, lambda1: float = 0.1, max_iter: int = 100,
            h_tol: float = 1e-5, w_threshold: float = 0.3,
            inner_steps: int = 400, lr: float = 2e-2,
            support_threshold: float = 0.1, rho_max: float = 1e8,
            forbidden: Optional[np.ndarray] = None) -> np.ndarray:
    """Learn a weighted DAG adjacency matrix from samples.

    Mirrors the reference driver loop (plugins/notears.py:39-55): dual
    ascent on alpha with rho escalation while h fails to decrease 4x,
    stop at h <= h_tol, threshold small weights.

    Parameters
    ----------
    x : [n, d] sample matrix (columns = variables).
    lambda1 : L1 edge sparsity weight.
    forbidden : optional [d, d] bool mask of edges forced to zero (the
        simple reference version hardcodes such a mask for covariate
        columns, causaldiscovery.py:50-51); the diagonal is always
        forced.
    """
    x = np.asarray(x, np.float32)
    n, d = x.shape
    x = x - x.mean(0)                       # NOTEARS assumes centered data
    # scale by ONE global scalar so the fixed-step-size inner solver sees
    # O(1) magnitudes.  W is invariant to global scaling; per-column
    # standardization would instead destroy the relative-variance signal
    # NOTEARS needs to identify edge DIRECTIONS (observed: it reverses
    # edges on standardized data)
    x = x / max(float(x.std()), 1e-8)
    free = 1.0 - np.eye(d, dtype=np.float32)
    if forbidden is not None:
        free = free * (1.0 - np.asarray(forbidden, np.float32))
    free_j = jnp.asarray(free)
    xj = jnp.asarray(x)

    solve = jax.jit(lambda w, rho, alpha: _minimize_subproblem(
        w, xj, rho, alpha, lambda1, free_j, inner_steps, lr))
    hj = jax.jit(h_func)

    # Dual ascent with a rho CAP, unlike the reference's 1e20 runaway:
    # past ~1e8 the penalty term dwarfs the data term and the iterate
    # collapses toward W=0 (observed empirically: the support is found
    # by h ~ 1e-5, then destroyed).  Magnitude precision comes from the
    # OLS refit below, so h_tol only needs to certify the structure.
    w_est = jnp.zeros((d, d))
    rho, alpha, h = 1.0, 0.0, np.inf
    for _ in range(max_iter):
        if rho >= rho_max:
            break   # penalty saturated; accept the current iterate
        while rho < rho_max:
            w_new = solve(w_est, jnp.float32(rho), jnp.float32(alpha))
            h_new = float(hj(w_new))
            if h_new > 0.25 * h:
                rho *= 10
            else:
                break
        w_est, h = w_new, h_new
        alpha += rho * h
        if h <= h_tol:
            break
    # the augmented-Lagrangian iterate carries L1/penalty shrinkage (the
    # Adam inner solver tolerates less rho escalation than L-BFGS-B), so
    # use it for STRUCTURE only: support at a loose threshold, break any
    # residual near-DAG cycles, refit exact magnitudes by OLS on the
    # support, then apply the reference's final w_threshold.
    w_sup = np.array(w_est)
    w_sup[np.abs(w_sup) < support_threshold] = 0.0
    w_sup = _break_cycles(w_sup)
    w = _ols_refit(x, w_sup != 0)   # W is global-scale invariant
    w[np.abs(w) < w_threshold] = 0.0
    return w


# ----------------------------------------------------------------------
# integration with the tuning archive (the api.py:728-732 hook, live)
def covariate_graph(covars: Sequence[dict], qor: Sequence[float],
                    lambda1: float = 0.1,
                    w_threshold: float = 0.3) -> dict:
    """Learn a DAG over per-trial covariates (`ut.feature` records) plus
    the QoR column; returns {'names': [...], 'w': [d, d] list,
    'drivers': [names with a direct edge into qor]}."""
    names = sorted({k for c in covars for k in c})
    rows = []
    for c, q in zip(covars, qor):
        if not all(k in c for k in names):
            continue
        if not np.isfinite(q):
            continue
        rows.append([float(c[k]) for k in names] + [float(q)])
    if len(rows) < 10:
        raise ValueError(
            f"need >= 10 complete covariate rows, have {len(rows)}")
    x = np.asarray(rows, np.float32)
    # standardize so lambda1 is scale-free across mixed covariate units.
    # That sacrifices variance-based direction identification, so encode
    # the domain fact instead: the QoR is a SINK (nothing is caused by
    # the objective value) — forbid its outgoing edges.
    x = (x - x.mean(0)) / np.maximum(x.std(0), 1e-8)
    qcol = len(names)
    forbid = np.zeros((qcol + 1, qcol + 1), bool)
    forbid[qcol, :] = True
    w = notears(x, lambda1=lambda1, w_threshold=w_threshold,
                forbidden=forbid)
    drivers = [names[i] for i in range(len(names)) if w[i, qcol] != 0.0]
    return {"names": names + ["qor"], "w": w.tolist(),
            "drivers": drivers}


def simulate_dag(key, d: int, n_edges: int, n_samples: int,
                 w_range=(0.5, 2.0), noise: float = 1.0):
    """Random linear-Gaussian SEM for tests (the reference generates the
    same via networkx + utils.simulate_sem, causaldiscovery.py:71-88):
    lower-triangular W guarantees acyclicity; X solves x = W^T x + z."""
    kw, ks, kz = jax.random.split(key, 3)
    d_pairs = [(i, j) for j in range(d) for i in range(j)]
    idx = jax.random.choice(kw, len(d_pairs), (min(n_edges, len(d_pairs)),),
                            replace=False)
    w = np.zeros((d, d), np.float32)
    mag = np.asarray(jax.random.uniform(
        ks, (len(d_pairs),), minval=w_range[0], maxval=w_range[1]))
    sign = np.where(np.asarray(
        jax.random.bernoulli(kz, 0.5, (len(d_pairs),))), 1.0, -1.0)
    for k in np.asarray(idx):
        i, j = d_pairs[int(k)]
        w[i, j] = mag[int(k)] * sign[int(k)]
    z = np.asarray(jax.random.normal(
        jax.random.fold_in(kz, 1), (n_samples, d))) * noise
    # x (I - W) = z  =>  x = z (I - W)^{-1}
    x = z @ np.linalg.inv(np.eye(d, dtype=np.float32) - w)
    return w, x.astype(np.float32)
