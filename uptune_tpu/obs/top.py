"""`ut top` — live terminal dashboard for a running tuning process.

Two data sources, one view:

* ``ut top --addr host:port`` polls a running `ut serve` process's
  ``{"op": "metrics"}`` scrape over the wire (rates are computed from
  counter deltas between successive polls);
* ``ut top --metrics out.json.metrics.jsonl`` tails a flight-recorder
  timeline on disk (any traced run: `ut serve`, `ut prog.py --trace`,
  bench.py) — rows already carry per-window deltas, so rates read
  straight off the newest row.  Works on a LIVE file and post-mortem
  on a crashed run's tail alike.  ``--metrics`` repeats and accepts
  globs (``'out.json.metrics.jsonl*'`` picks up ``.hN`` replica
  files): several files render as ONE fleet-rolled frame
  (obs.hub.fleet_rollup — counters summed, gauges last-write,
  labeled-approximate percentiles) with each row labeled per source.

Since ISSUE 14 ``--addr`` may also point at a fleet-telemetry hub
(`ut hub`): its metrics op serves the fleet rollup in the same scrape
shape, so the frame just works; ``--fleet`` adds a per-source panel
(one line per shipping process: age, rates, drops, alerts) fed by the
hub's ``sources`` op — or derived per file in multi ``--metrics``
mode.  The tail reader follows the flight recorder's rotation chain
(``<file>.N`` … ``<file>.1``), so a freshly rotated timeline still
yields a full frame.

The frame shows the serving plane's vitals: active sessions, epoch
batch fill, ask/tell rates and latency percentiles, worker-pool
utilization, store hit rate, and surrogate refit lag — the numbers an
operator needs before pod-scale work lands (ROADMAP items 1 and 3).
Every field is pulled defensively: a metrics stream missing a family
(a driver run has no `serve.*`) renders "—", never a crash.

``--once`` prints a single frame and exits (scripts, tests); with
``--json`` that frame is one JSON object (counters/gauges/hists/rates)
so CI asserts on fields instead of scraping text.  The refresh loop
redraws with ANSI cursor-home + clear and exits cleanly on ^C / a
vanished server.
"""
from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["Sample", "rates", "render", "fleet_lines", "main"]

CLEAR = "\x1b[H\x1b[2J"


class Sample:
    """One metrics observation: absolute counters/gauges/hists at time
    `t`, plus (for flight-recorder rows) the row's own window deltas."""

    def __init__(self, t: float, counters: Dict[str, float],
                 gauges: Dict[str, float], hists: Dict[str, Any],
                 deltas: Optional[Dict[str, float]] = None,
                 dt: Optional[float] = None,
                 meta: Optional[Dict[str, Any]] = None):
        self.t = t
        self.counters = counters
        self.gauges = gauges
        self.hists = hists
        self.deltas = deltas
        self.dt = dt
        self.meta = dict(meta or {})


def sample_from_scrape(resp: Dict[str, Any]) -> Sample:
    """A serve (or hub — same shape, plus fleet window deltas and a
    source count) `{"op": "metrics"}` response -> Sample."""
    m = resp.get("metrics", {}) or {}
    return Sample(time.time(), m.get("counters", {}) or {},
                  m.get("gauges", {}) or {}, m.get("hists", {}) or {},
                  deltas=m.get("deltas"), dt=m.get("dt") or None,
                  meta={"sessions": resp.get("sessions"),
                        "uptime_s": resp.get("uptime_s"),
                        "sources": resp.get("sources")})


def sample_from_row(row: Dict[str, Any]) -> Sample:
    """A flight-recorder JSONL row -> Sample."""
    return Sample(float(row.get("t", 0.0)),
                  row.get("counters", {}) or {},
                  row.get("gauges", {}) or {},
                  row.get("hists", {}) or {},
                  deltas=row.get("deltas"), dt=row.get("dt"),
                  meta={"final": row.get("final", False),
                        "trace": row.get("trace")})


TAIL_BYTES = 256 * 1024


def _tail_rows(path: str, n: int) -> List[Dict[str, Any]]:
    """Newest-first parseable rows from ONE file's tail."""
    try:
        with open(path, "rb") as f:
            f.seek(0, 2)
            size = f.tell()
            f.seek(max(0, size - TAIL_BYTES))
            lines = f.read().decode("utf-8", "replace").splitlines()
    except OSError:
        return []
    out: List[Dict[str, Any]] = []
    for line in reversed(lines):
        if len(out) >= n:
            break
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(row, dict) and "counters" in row:
            out.append(row)
    return out


def last_rows(path: str, n: int = 2) -> List[Dict[str, Any]]:
    """The last `n` parseable rows of a metrics JSONL (tail-tolerant:
    a row being appended right now is skipped).  Reads only the final
    `TAIL_BYTES` of each file — a rotation-capped timeline near 20k
    rows is megabytes, and the refresh loop calls this every couple
    of seconds; the first (possibly truncated) line of a mid-file
    seek fails to parse and is skipped like any torn row.  When the
    live file holds fewer than `n` rows (it just rotated), older
    rotation generations (``<path>.1`` … ``<path>.N``) fill in, so a
    freshly capped timeline still renders a full frame."""
    out = _tail_rows(path, n)
    gen = 1
    while len(out) < n:
        older = _tail_rows(f"{path}.{gen}", n - len(out))
        if not older:
            break
        out.extend(older)
        gen += 1
    return list(reversed(out))


def rates(prev: Optional[Sample], cur: Sample) -> Dict[str, float]:
    """Per-second counter rates for the displayed window.  Prefers the
    row's own deltas (flight-recorder source, exact window); falls
    back to diffing successive polls (scrape source)."""
    if cur.deltas is not None and cur.dt:
        return {k: v / cur.dt for k, v in cur.deltas.items()}
    if prev is None or cur.t <= prev.t:
        return {}
    dt = cur.t - prev.t
    return {k: (v - prev.counters.get(k, 0)) / dt
            for k, v in cur.counters.items()}


def _fmt(v: Any, unit: str = "", nd: int = 1) -> str:
    if v is None:
        return "—"
    if isinstance(v, float):
        return f"{v:,.{nd}f}{unit}"
    return f"{v}{unit}"


def _hist_p(hists: Dict[str, Any], name: str, p: str) -> Optional[float]:
    h = hists.get(name)
    return h.get(p) if isinstance(h, dict) else None


def _source_row(label: str, row: Dict[str, Any]) -> Dict[str, Any]:
    """Normalize one flight-recorder/window row into the per-source
    panel shape (the hub's `sources` op emits the same keys, through
    the same shared rate helper)."""
    from .hub import window_rates
    rates_ = window_rates(row)
    t = float(row.get("t") or 0.0)
    return {"source": label,
            "age_s": round(max(0.0, time.time() - t), 1) if t else None,
            "rates": rates_, "final": bool(row.get("final")),
            "stale": False, "dropped": None, "alerts": None,
            "journal_rows": None}


def fleet_lines(sources: List[Dict[str, Any]],
                width: int = 78) -> List[str]:
    """The per-source panel (`--fleet`): one labeled line per shipping
    process / metrics file, worst (stale) first."""
    out = [f"sources   ({len(sources)})"]
    rows = sorted(sources, key=lambda r: (not r.get("stale"),
                                          str(r.get("source"))))
    for r in rows:
        rate = r.get("rates") or {}
        main_rate = (rate.get("serve.asks") or rate.get("driver.asks")
                     or rate.get("serve.tells")
                     or rate.get("store.recorded"))
        flags = []
        if r.get("stale"):
            flags.append("STALE")
        if r.get("final"):
            flags.append("final")
        out.append(
            ("  {:<30}{:>7}s {:>9}/s  drop{:>4}  alrt{:>3} {}")
            .format(
                str(r.get("source"))[:30],
                _fmt(r.get("age_s")),
                _fmt(main_rate),
                _fmt(r.get("dropped"), nd=0),
                _fmt(r.get("alerts"), nd=0),
                " ".join(flags))[:width].rstrip())
    return out


def render(prev: Optional[Sample], cur: Sample, source: str,
           width: int = 78,
           sources: Optional[List[Dict[str, Any]]] = None) -> str:
    """One dashboard frame as text (pure: testable without a tty)."""
    r = rates(prev, cur)
    c, g, h = cur.counters, cur.gauges, cur.hists
    hits = c.get("store.hits", 0)
    misses = c.get("store.misses", 0)
    hit_rate = (hits / (hits + misses) if hits + misses else None)
    up = cur.meta.get("uptime_s")
    lines = [
        f"ut top — {source}"[:width],
        time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(cur.t))
        + (f"   up {up:,.0f}s" if up is not None else "")
        + (f"   window {cur.dt:.2f}s" if cur.dt else "")
        + (f"   sources {cur.meta['sources']}"
           if cur.meta.get("sources") is not None else "")
        + ("   [FINAL]" if cur.meta.get("final") else ""),
        "-" * min(width, 60),
        "serve     sessions {}   batch fill {}   groups+ {}".format(
            _fmt(g.get("serve.sessions.active"), nd=0),
            _fmt(g.get("serve.batch_fill"), nd=2),
            _fmt(c.get("serve.groups_created"), nd=0)),
        "rates     asks/s {}   tells/s {}   proposes/s {}   "
        "store-served/s {}".format(
            _fmt(r.get("serve.asks", r.get("driver.asks"))),
            _fmt(r.get("serve.tells", r.get("driver.told"))),
            _fmt(r.get("serve.proposes")),
            _fmt(r.get("serve.store_served"))),
        "latency   ask p50/p95 {}/{} ms   tell p50/p95 {}/{} ms".format(
            _fmt(_hist_p(h, "serve.ask_ms", "p50"), nd=2),
            _fmt(_hist_p(h, "serve.ask_ms", "p95"), nd=2),
            _fmt(_hist_p(h, "serve.tell_ms", "p50"), nd=2),
            _fmt(_hist_p(h, "serve.tell_ms", "p95"), nd=2)),
        "workers   busy {}   utilization {}   builds/s {}   "
        "build p95 {} s".format(
            _fmt(g.get("pool.busy"), nd=0),
            _fmt(g.get("pool.utilization"), nd=2),
            _fmt(r.get("pool.launched")),
            _fmt(_hist_p(h, "pool.build_s", "p95"), nd=2)),
        # recorded/acked-appends light up against a store-server
        # scrape (`ut top --addr` on a `ut store` process, ISSUE 18)
        "store     hits {}   misses {}   hit-rate {}   recorded {}   "
        "acked-appends {}   serve p95 {} ms".format(
            _fmt(hits, nd=0), _fmt(misses, nd=0),
            _fmt(None if hit_rate is None else 100 * hit_rate, "%"),
            _fmt(c.get("store.recorded"), nd=0),
            _fmt(c.get("rstore.appends"), nd=0),
            _fmt(_hist_p(h, "store.serve_ms", "p95"), nd=2)),
        "learn     snapshot v{}   refit lag {} rows   "
        "new bests {}".format(
            _fmt(g.get("surrogate.snapshot_version"), nd=0),
            _fmt(g.get("surrogate.refit_lag_rows"), nd=0),
            _fmt(c.get("serve.new_bests", c.get("driver.new_bests")),
                 nd=0)),
        # device panel (ISSUE 13): compile count/time + persistent-
        # cache outcome from the obs.device counters, achieved rates
        # and util fractions from the last measured window's aggregate
        # gauges — all "—" for an untraced / pre-ISSUE-13 stream
        "device    programs {}   compiles {} ({} ms)   "
        "cache hit/miss {}/{}   dispatches/s {}".format(
            _fmt(g.get("device.programs"), nd=0),
            _fmt(c.get("device.compiles"), nd=0),
            _fmt(_hist_p(h, "device.compile_ms", "sum"), nd=0),
            _fmt(c.get("device.compile_cache_hits"), nd=0),
            _fmt(c.get("device.compile_cache_misses"), nd=0),
            _fmt(r.get("device.dispatches"))),
        "roofline  flops/s {}   HBM B/s {}   MXU {}   HBM {}   "
        "AI {}".format(
            _fmt(g.get("device.achieved_flops_per_s"), nd=0),
            _fmt(g.get("device.achieved_hbm_bytes_per_s"), nd=0),
            _fmt(g.get("device.mxu_util"), nd=6),
            _fmt(g.get("device.hbm_util"), nd=4),
            _fmt(g.get("device.arith_intensity"), nd=3)),
    ]
    # search-quality panel (ISSUE 12): the journal-derived gauges a
    # QualityMonitor publishes; a run without a journal renders "—"
    if any(k.startswith("search.") for k in g):
        lines += [
            "search    best {}   tells {}   since-best {}   "
            "regret {}".format(
                _fmt(g.get("search.best_qor"), nd=4),
                _fmt(g.get("search.tells"), nd=0),
                _fmt(g.get("search.tells_since_best"), nd=0),
                _fmt(g.get("search.regret_proxy"), nd=4)),
            "quality   cal MAE {}   rank-corr {}   cover95 {}   "
            "dup {}   alerts {}".format(
                _fmt(g.get("search.cal_mae"), nd=4),
                _fmt(g.get("search.cal_rank_corr"), nd=2),
                _fmt(g.get("search.cal_cover95"), nd=2),
                _fmt(g.get("search.dup_rate"), nd=2),
                _fmt(c.get("search.alerts", 0), nd=0)),
        ]
    # anything moving that the fixed panel doesn't show (top deltas)
    shown = {"serve.asks", "serve.tells", "serve.proposes",
             "serve.store_served", "driver.asks", "driver.told",
             "pool.launched"}
    extras = sorted(((v, k) for k, v in r.items()
                     if v > 0 and k not in shown), reverse=True)[:4]
    if extras:
        lines.append("also      " + "   ".join(
            f"{k} {_fmt(v)}/s" for v, k in extras)[:width - 10])
    if sources is not None:
        lines += fleet_lines(sources, width)
    return "\n".join(lines)


# ------------------------------------------------------------------ CLI
def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="ut top",
        description="live dashboard over a running uptune-tpu server "
                    "or a flight-recorder metrics timeline "
                    "(docs/OBSERVABILITY.md)")
    src = p.add_mutually_exclusive_group()
    src.add_argument("--addr", default=None, metavar="HOST:PORT",
                     help="poll a running `ut serve` process's "
                          "metrics op — or a fleet-telemetry hub "
                          "(`ut hub`), whose scrape is the live "
                          "fleet rollup (default: the configured "
                          "serve-host:serve-port)")
    src.add_argument("--metrics", default=None, metavar="JSONL",
                     action="append",
                     help="tail flight-recorder metrics timeline(s) "
                          "instead of polling a server.  Repeatable "
                          "and glob-expanded ('out.json.metrics"
                          ".jsonl*' includes .hN replica files); "
                          "several files render one fleet-rolled "
                          "frame with per-source labels")
    p.add_argument("--fleet", action="store_true",
                   help="add the per-source panel: one labeled line "
                        "per shipping process (hub `sources` op) or "
                        "per metrics file")
    p.add_argument("--interval", type=float, default=2.0,
                   help="refresh cadence in seconds (default 2)")
    p.add_argument("--once", action="store_true",
                   help="print one frame and exit (scripts/tests)")
    p.add_argument("--json", action="store_true",
                   help="with --once: print the frame as one JSON "
                        "object (counters, gauges, hists, computed "
                        "rates, meta) instead of the rendered text, "
                        "so scripts/CI assert on fields rather than "
                        "scraping the dashboard")
    args = p.parse_args(argv)
    if args.json and not args.once:
        p.error("--json requires --once (one machine-readable frame)")

    client = None
    prev: Optional[Sample] = None
    # glob-expanded, order-stable, deduped metrics path set (an
    # unmatched pattern stays literal: the file may appear later)
    mpaths: List[str] = []
    for pat in (args.metrics or []):
        hits = sorted(_glob.glob(pat)) or [pat]
        for h in hits:
            if h not in mpaths:
                mpaths.append(h)

    def poll() -> Tuple[Optional[Sample], str,
                        Optional[List[Dict[str, Any]]]]:
        nonlocal client
        if mpaths:
            if len(mpaths) == 1:
                # single file: the historical exact-window frame (one
                # tail read per tick)
                rows = last_rows(mpaths[0], 2)
                if not rows:
                    return None, mpaths[0], None
                srcs = ([_source_row(os.path.basename(mpaths[0]),
                                     rows[-1])]
                        if args.fleet else None)
                return sample_from_row(rows[-1]), mpaths[0], srcs
            per: List[Tuple[str, Dict[str, Any]]] = []
            for path in mpaths:
                rows = last_rows(path, 1)
                if rows:
                    per.append((os.path.basename(path), rows[-1]))
            label = f"{len(mpaths)} metrics files"
            if not per:
                return None, label, None
            from .hub import fleet_rollup
            roll = fleet_rollup(per)
            cur = Sample(
                max(float(r.get("t") or 0.0) for _, r in per),
                roll["counters"], roll["gauges"], roll["hists"],
                deltas=roll["deltas"], dt=roll["dt"] or None,
                meta={"sources": len(per)})
            srcs = ([_source_row(lbl, row) for lbl, row in per]
                    if args.fleet else None)
            return cur, label, srcs
        from ..serve.client import ServeError, connect
        if client is None:
            client = connect(args.addr)
        resp = client.metrics()
        srcs = None
        if args.fleet:
            try:
                srcs = client.request("sources").get("rows")
            except ServeError:
                srcs = None     # a session server: no sources op
        return (sample_from_scrape(resp),
                f"{client.host}:{client.port}", srcs)

    try:
        while True:
            try:
                cur, source, srcs = poll()
            except (OSError, ValueError, RuntimeError) as e:
                print(f"ut top: {e}", file=sys.stderr)
                return 1
            if cur is None:
                print(f"ut top: no metrics rows yet in {source}",
                      file=sys.stderr)
                if args.once:
                    return 1
            else:
                if args.once and args.json:
                    frame_obj = {"t": cur.t, "source": source,
                                 "counters": cur.counters,
                                 "gauges": cur.gauges,
                                 "hists": cur.hists,
                                 "rates": rates(prev, cur),
                                 "window_s": cur.dt, "meta": cur.meta}
                    if srcs is not None:
                        frame_obj["sources"] = srcs
                    print(json.dumps(frame_obj, sort_keys=True))
                    return 0
                frame = render(prev, cur, source, sources=srcs)
                if args.once:
                    print(frame)
                    return 0
                sys.stdout.write(CLEAR + frame + "\n")
                sys.stdout.flush()
                prev = cur
            time.sleep(max(0.1, args.interval))
    except KeyboardInterrupt:
        return 0
    finally:
        if client is not None:
            client.close()


if __name__ == "__main__":
    sys.exit(main())
