"""Fleet-telemetry hub: one collector every process reports into
(`ut hub`, ISSUE 14).

The reference ran distributed tuning as result transport over ZMQ/S3
plus one global database every search instance wrote to (PAPER.md
L1/L4); this is the TPU-native serving-plane equivalent — a
`WireServer` (serve/wire.py) whose clients are `TelemetryShipper`s
(obs/ship.py): `ut` driver replicas, `ut serve` processes, and bench
clients push window snapshots, journal rows, alerts, and health
rollups; operators and a future sharded front tier (ROADMAP item 1)
pull the fleet view back out over the very same wire:

* ``{"op": "metrics"}`` — the FLEET rollup in the session server's
  scrape shape, so ``ut top --addr <hub>`` works unchanged: counters
  are exact sums of each live source's latest absolute counters,
  gauges are last-write-wins across sources, histogram windows sum
  their exact counts/sums with count-weighted (approximate, and so
  labeled) fleet percentiles.
* ``{"op": "sources"}`` — one row per (host, pid, role): liveness,
  window/journal/alert/drop accounting, per-source headline rates.
* ``{"op": "health", "limit": N}`` — worst-first health across
  sources (stale sources float to the top with status ``stale``),
  the placement/eviction feed for a front tier.
* ``{"op": "ship"}`` / ``{"op": "hello"}`` — the shipper's push ops.

Durability: every acked row is appended (and flushed) to the fleet
timeline JSONL BEFORE the ok reply — a SIGKILLed source loses at
most its one un-acked in-flight batch (BENCH_FLEET's kill test).
The timeline is torn-tail tolerant and rotation-capped exactly like
the flight recorder (`flight.rotate_files`, ``--timeline-rotate``
generations), and a restarting hub REPLAYS the surviving chain so
the fleet view picks up where the dead hub left off.
"""
from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ..serve.wire import RequestError, WireServer
from . import flight

log = logging.getLogger("uptune_tpu")

__all__ = ["TelemetryHub", "fleet_rollup", "main",
           "DEFAULT_TIMELINE", "DEFAULT_TIMELINE_ROWS"]

DEFAULT_TIMELINE = "ut.fleet.jsonl"
DEFAULT_TIMELINE_ROWS = 50000
DEFAULT_WINDOW_RING = 64
DEFAULT_STALE_S = 15.0
HEALTH_MAX_SOURCES = 64         # default health-op payload bound
HEALTH_LIMIT_CAP = 1024         # request `limit` ceiling (serve rule)

_STATUS_RANK = {"failing": 0, "stale": 1, "stalled": 2, "cold": 3,
                "ok": 4}

# the per-source panel's headline counters, shared by the hub's
# `sources` op and `ut top`'s file-mode panel so the two views can
# never drift on what a source's "rate" means
HEADLINE_RATE_KEYS = ("driver.asks", "serve.asks", "serve.tells",
                      "store.recorded")   # the ut-store role's rate


def window_rates(row: Dict[str, Any]) -> Dict[str, float]:
    """Headline per-second rates off one window row's own deltas."""
    dt = float(row.get("dt") or 0.0)
    d = row.get("deltas") or {}
    out: Dict[str, float] = {}
    if dt > 0:
        for k in HEADLINE_RATE_KEYS:
            if d.get(k):
                out[k] = round(d[k] / dt, 1)
    return out


def fleet_rollup(rows: List[Tuple[str, Dict[str, Any]]]
                 ) -> Dict[str, Any]:
    """Aggregate one window row per source into the fleet view.

    `rows` is ``[(source_label, window_row), ...]`` where each row is
    a flight-recorder/shipper window snapshot (absolute ``counters``,
    per-window ``deltas``, ``gauges``, windowed ``hists``, sender
    ``t``/``dt``).  Semantics (docs/OBSERVABILITY.md "Fleet
    telemetry"):

    * **counters** — exact sums of per-source absolutes (the
      exactness contract BENCH_FLEET asserts against the sum of the
      sources' own final flight-recorder rows);
    * **deltas** — sums of the rows' own window deltas (each window
      is exact per source; the fleet window is their union);
    * **gauges** — last-write-wins by sender timestamp (same rule as
      the registry itself, across processes);
    * **hists** — ``count``/``sum``/``window_count``/``window_sum``
      are exact sums; ``p50``/``p95`` are count-WEIGHTED averages of
      the per-source window percentiles — approximate by nature (the
      raw samples never leave their process) and labeled
      ``"approx": true`` so no reader mistakes them for a true fleet
      distribution.

    Also returns ``dt`` (the widest source window, for display
    rates) and ``per_source`` label list.
    """
    counters: Dict[str, float] = {}
    deltas: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    gauge_t: Dict[str, float] = {}
    hists: Dict[str, Dict[str, Any]] = {}
    hist_w: Dict[str, List[Tuple[float, Optional[float],
                                 Optional[float]]]] = {}
    dt = 0.0
    for label, row in rows:
        if not isinstance(row, dict):
            continue
        t = float(row.get("t") or 0.0)
        dt = max(dt, float(row.get("dt") or 0.0))
        for k, v in (row.get("counters") or {}).items():
            counters[k] = counters.get(k, 0) + v
        for k, v in (row.get("deltas") or {}).items():
            deltas[k] = deltas.get(k, 0) + v
        for k, v in (row.get("gauges") or {}).items():
            if t >= gauge_t.get(k, -1.0):
                gauges[k] = v
                gauge_t[k] = t
        for k, h in (row.get("hists") or {}).items():
            if not isinstance(h, dict):
                continue
            agg = hists.setdefault(
                k, {"count": 0, "sum": 0.0, "window_count": 0,
                    "window_sum": 0.0})
            agg["count"] += h.get("count", 0) or 0
            agg["sum"] += h.get("sum", 0.0) or 0.0
            agg["window_count"] += h.get("window_count", 0) or 0
            agg["window_sum"] += h.get("window_sum", 0.0) or 0.0
            wc = h.get("window_count", 0) or 0
            if wc:
                hist_w.setdefault(k, []).append(
                    (wc, h.get("p50"), h.get("p95")))
    for k, parts in hist_w.items():
        # count-weighted average of per-source window percentiles
        for idx, p in ((1, "p50"), (2, "p95")):
            num = den = 0.0
            for part in parts:
                v = part[idx]
                if v is not None:
                    num += part[0] * v
                    den += part[0]
            if den:
                hists[k][p] = round(num / den, 6)
                hists[k]["approx"] = True
    return {"counters": counters, "deltas": deltas, "gauges": gauges,
            "hists": hists, "dt": round(dt, 3),
            "per_source": [label for label, _ in rows]}


class _Source:
    """Per-(host, pid, role) state: the window ring + accounting."""

    __slots__ = ("key", "label", "meta", "first_unix", "last_unix",
                 "windows", "last_window", "journal_rows", "alerts",
                 "health", "health_unix", "dropped", "acked",
                 "final_seen")

    def __init__(self, key: Tuple[str, str, str], meta: Dict[str, Any],
                 ring: int):
        self.key = key
        self.label = f"{key[0]}:{key[1]}:{key[2]}"
        self.meta = dict(meta)
        self.first_unix = time.time()
        self.last_unix = self.first_unix
        self.windows: deque = deque(maxlen=ring)
        self.last_window: Optional[Dict[str, Any]] = None
        self.journal_rows = 0
        self.alerts: deque = deque(maxlen=32)
        self.health: Optional[Dict[str, Any]] = None
        self.health_unix = 0.0
        self.dropped = 0
        self.acked = 0
        self.final_seen = False


class TelemetryHub(WireServer):
    """The fleet collector.  Construct, ``start()``, point shippers
    and ``ut top --addr`` at ``.port``, ``stop()``."""

    WIRE_NAME = "ut-hub"

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 timeline: Optional[str] = DEFAULT_TIMELINE,
                 timeline_rows: int = DEFAULT_TIMELINE_ROWS,
                 timeline_rotate: int = flight.DEFAULT_ROTATE,
                 window_ring: int = DEFAULT_WINDOW_RING,
                 stale_s: float = DEFAULT_STALE_S):
        super().__init__(host, port)
        self.timeline_path = (None if timeline in (None, "", "off")
                              else str(timeline))
        self.timeline_rows = int(timeline_rows)
        self.timeline_rotate = max(1, int(timeline_rotate))
        self.window_ring = int(window_ring)
        self.stale_s = float(stale_s)
        self._sources: Dict[Tuple[str, str, str], _Source] = {}
        self._tl_f = None
        self._tl_rows = 0
        self.timeline_rotations = 0
        self.rows_received = 0
        if self.timeline_path:
            self._replay_timeline()
            self._tl_f = self._open_timeline()

    def _open_timeline(self):
        """Append-open a timeline generation; a FRESH file gets the
        self-describing header line (`ut report` keys fleet-timeline
        detection on it; replay skips it as a non-source line).  An
        EXISTING file (hub restart) resumes its row count, so the
        rotation cap bounds the generation on disk — not merely this
        process's appends."""
        f = open(self.timeline_path, "a")
        if f.tell() == 0:
            f.write(json.dumps({"fleet": 1,
                                "origin_unix": round(time.time(), 3),
                                "pid": os.getpid()}) + "\n")
            f.flush()
            self._tl_rows = 0
        else:
            try:
                with open(self.timeline_path) as rf:
                    self._tl_rows = sum(1 for line in rf
                                        if '"src"' in line)
            except OSError:
                self._tl_rows = 0
        return f

    # -- timeline ------------------------------------------------------
    def _replay_timeline(self) -> None:
        """Restore per-source state from a previous hub's surviving
        rotation chain (oldest generation first), so a restarted hub
        serves the fleet view it had before dying.  Sources restored
        this way show their recorded last-seen age — they go `stale`
        naturally unless their shipper reconnects and resumes."""
        n = 0
        for row in flight.read_chain(self.timeline_path):
            src = row.get("src")
            kind = row.get("kind")
            if not (isinstance(src, str) and isinstance(kind, str)):
                continue    # header / foreign line
            parts = src.split(":")
            if len(parts) != 3:
                continue
            key = (parts[0], parts[1], parts[2])
            s = self._sources.get(key)
            if s is None:
                s = self._sources[key] = _Source(
                    key, {"replayed": True}, self.window_ring)
                s.first_unix = float(row.get("u") or s.first_unix)
            self._fold(s, kind, row.get("row"),
                       at=float(row.get("u") or 0.0) or None)
            n += 1
        if n:
            log.info("[ut-hub] replayed %d timeline rows -> %d "
                     "sources", n, len(self._sources))

    def _append_timeline(self, lines: List[str]) -> None:
        """Durable half of the ack: rows hit the timeline (flushed)
        before the shipper hears ok.  Caller holds `_lock`."""
        if self._tl_f is None or not lines:
            return
        self._tl_f.write("".join(lines))
        self._tl_f.flush()
        self._tl_rows += len(lines)
        if self._tl_rows >= self.timeline_rows:
            self._tl_f.close()
            flight.rotate_files(self.timeline_path,
                                self.timeline_rotate)
            self._tl_f = self._open_timeline()
            self._tl_rows = 0
            self.timeline_rotations += 1

    # -- source folding ------------------------------------------------
    def _fold(self, s: _Source, kind: str, row: Any,
              at: Optional[float] = None) -> None:
        s.last_unix = at if at is not None else time.time()
        if not isinstance(row, dict):
            return
        if kind == "window":
            s.windows.append(row)
            s.last_window = row
            if row.get("final"):
                s.final_seen = True
        elif kind == "journal":
            s.journal_rows += 1
        elif kind == "alert":
            s.alerts.append(row)
        elif kind == "health":
            s.health = row
            s.health_unix = s.last_unix

    def _source_for(self, req: dict) -> _Source:
        meta = req.get("source")
        if not isinstance(meta, dict):
            raise RequestError("missing 'source' object "
                               "({host, pid, role})")
        key = (str(meta.get("host")), str(meta.get("pid")),
               str(meta.get("role")))
        s = self._sources.get(key)
        if s is None:
            s = self._sources[key] = _Source(key, meta,
                                             self.window_ring)
            log.info("[ut-hub] new source %s", s.label)
        return s

    # -- ops -----------------------------------------------------------
    def _op_ping(self, req: dict) -> dict:
        with self._lock:
            return {"t": time.time(), "sources": len(self._sources)}

    def _op_hello(self, req: dict) -> dict:
        with self._lock:
            s = self._source_for(req)
            s.last_unix = time.time()
            return {"source": s.label}

    def _op_ship(self, req: dict) -> dict:
        rows = req.get("rows")
        if not isinstance(rows, list):
            raise RequestError("ship needs 'rows': a list")
        now = time.time()
        with self._lock:
            s = self._source_for(req)
            try:
                s.dropped = int(req.get("dropped", s.dropped))
            except (TypeError, ValueError):
                pass
            lines = []
            for item in rows:
                if not isinstance(item, dict):
                    continue
                kind = str(item.get("kind", "?"))
                row = item.get("row")
                self._fold(s, kind, row, at=now)
                lines.append(json.dumps(
                    {"u": round(now, 3), "src": s.label, "kind": kind,
                     "row": row}, separators=(",", ":")) + "\n")
            # durability before the ack: everything the shipper will
            # consider delivered is already flushed to the timeline
            self._append_timeline(lines)
            s.acked += len(lines)
            self.rows_received += len(lines)
        return {"acked": len(lines)}

    def _op_metrics(self, req: dict) -> dict:
        """The fleet rollup in the session server's scrape shape
        (``ut top --addr <hub>`` renders it unchanged)."""
        with self._lock:
            rows = [(s.label, s.last_window)
                    for s in self._sources.values()
                    if s.last_window is not None]
            n = len(self._sources)
        roll = fleet_rollup(rows)
        return {"sources": n,
                "uptime_s": round(time.time() - self.started_unix, 3),
                "metrics": {"counters": roll["counters"],
                            "gauges": roll["gauges"],
                            "hists": roll["hists"],
                            "deltas": roll["deltas"],
                            "dt": roll["dt"]}}

    def _source_row(self, s: _Source, now: float) -> Dict[str, Any]:
        age = now - s.last_unix
        rates = window_rates(s.last_window or {})
        return {"host": s.key[0], "pid": s.key[1], "role": s.key[2],
                "source": s.label, "age_s": round(age, 3),
                "stale": age > self.stale_s and not s.final_seen,
                "final": s.final_seen,
                "windows": len(s.windows), "journal_rows": s.journal_rows,
                "alerts": len(s.alerts), "dropped": s.dropped,
                "acked": s.acked, "rates": rates}

    def _op_sources(self, req: dict) -> dict:
        now = time.time()
        with self._lock:
            rows = [self._source_row(s, now)
                    for s in self._sources.values()]
        rows.sort(key=lambda r: r["source"])
        return {"sources": len(rows), "rows": rows}

    def _op_health(self, req: dict) -> dict:
        """Worst-first health across sources.  A source that shipped
        a serve-health rollup contributes its own worst verdict; a
        source past the staleness bar reports ``stale``; everything
        else is ``ok``.  `limit` bounds the payload (the serve health
        op's rule, docs/SERVING.md)."""
        try:
            limit = int(req.get("limit", HEALTH_MAX_SOURCES))
        except (TypeError, ValueError) as e:
            raise RequestError(f"limit must be an integer: {e}")
        if not 1 <= limit <= HEALTH_LIMIT_CAP:
            raise RequestError(
                f"limit must be in [1, {HEALTH_LIMIT_CAP}]: {limit}")
        now = time.time()
        rows = []
        by_status: Dict[str, int] = {}
        # rows are built entirely under the lock (the _op_sources
        # rule): s.alerts is a deque a concurrent ship batch appends
        # to — iterating it unlocked raises "deque mutated during
        # iteration" under a health poll racing active shippers
        with self._lock:
            for s in self._sources.values():
                row = self._source_row(s, now)
                status = "ok"
                if row["stale"]:
                    status = "stale"
                h = s.health
                if isinstance(h, dict):
                    # a shipped serve rollup: adopt its worst verdict
                    bys = h.get("by_status")
                    if isinstance(bys, dict) and bys:
                        worst = min(bys, key=lambda k:
                                    _STATUS_RANK.get(k, 9))
                        if _STATUS_RANK.get(worst, 9) < \
                                _STATUS_RANK.get(status, 9):
                            status = worst
                        row["sessions_by_status"] = bys
                if s.alerts and status == "ok":
                    status = "stalled" if any(
                        a.get("kind") == "stall" for a in s.alerts) \
                        else status
                row["status"] = status
                by_status[status] = by_status.get(status, 0) + 1
                rows.append(row)
        rows.sort(key=lambda r: (_STATUS_RANK.get(r["status"], 9),
                                 r["source"]))
        return {"sources": len(rows), "by_status": by_status,
                "truncated": len(rows) > limit,
                "health": rows[:limit]}

    def gauge_values(self, key: str) -> List[float]:
        """One value per LIVE (non-final) source's latest window for
        gauge `key` — the additive-rollup seam: fleet_rollup's gauges
        are last-write-wins (correct for a fleet-wide setting like a
        snapshot version), but a per-process population gauge like
        ``serve.sessions.active`` only means something fleet-wide as a
        SUM, so the front-tier router re-aggregates those few keys
        from the per-source values (serve/router.py metrics op)."""
        out: List[float] = []
        with self._lock:
            for s in self._sources.values():
                if s.final_seen or not isinstance(s.last_window, dict):
                    continue
                v = (s.last_window.get("gauges") or {}).get(key)
                if isinstance(v, (int, float)):
                    out.append(float(v))
        return out

    def _op_stats(self, req: dict) -> dict:
        with self._lock:
            return {"sources": len(self._sources),
                    "rows_received": self.rows_received,
                    "timeline": self.timeline_path,
                    "timeline_rows": self._tl_rows,
                    "timeline_rotations": self.timeline_rotations}

    _OPS = {"ping": _op_ping, "hello": _op_hello, "ship": _op_ship,
            "metrics": _op_metrics, "sources": _op_sources,
            "health": _op_health, "stats": _op_stats}

    def _listen_banner(self) -> str:
        return (f" (timeline={self.timeline_path or 'off'}, "
                f"rotate={self.timeline_rotate})")

    def stop(self) -> None:
        super().stop()
        with self._lock:
            if self._tl_f is not None:
                try:
                    self._tl_f.close()
                except OSError:
                    pass
                self._tl_f = None


# ------------------------------------------------------------------ CLI
def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="ut hub",
        description="fleet-telemetry hub: aggregate every process's "
                    "metrics/journal/health streams live "
                    "(docs/OBSERVABILITY.md 'Fleet telemetry')")
    p.add_argument("--host", default="127.0.0.1",
                   help="bind address (default 127.0.0.1)")
    p.add_argument("--port", type=int, default=8790,
                   help="TCP port; 0 picks an ephemeral port "
                        "(default 8790)")
    p.add_argument("--timeline", default=DEFAULT_TIMELINE,
                   metavar="JSONL",
                   help="durable fleet timeline (every acked row; "
                        "'off' disables; default ut.fleet.jsonl).  An "
                        "existing chain is REPLAYED at startup")
    p.add_argument("--timeline-rows", type=int,
                   default=DEFAULT_TIMELINE_ROWS,
                   help="rows per timeline generation before rotation "
                        f"(default {DEFAULT_TIMELINE_ROWS})")
    p.add_argument("--timeline-rotate", type=int,
                   default=flight.DEFAULT_ROTATE, metavar="N",
                   help="rotation generations kept (.1 … .N; "
                        "default 1)")
    p.add_argument("--stale", type=float, default=DEFAULT_STALE_S,
                   help="seconds of silence before a source reports "
                        f"stale (default {DEFAULT_STALE_S})")
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="[%(relativeCreated)7.0fms] %(levelname)s %(message)s")
    hub = TelemetryHub(host=args.host, port=args.port,
                       timeline=args.timeline,
                       timeline_rows=args.timeline_rows,
                       timeline_rotate=args.timeline_rotate,
                       stale_s=args.stale)
    try:
        hub.serve_forever()
    finally:
        log.info("[ut-hub] %d rows from %d sources%s",
                 hub.rows_received, len(hub._sources),
                 f"; timeline at {hub.timeline_path}"
                 if hub.timeline_path else "")
    return 0


if __name__ == "__main__":
    sys.exit(main())
