"""Span/event recording core: per-thread lock-free ring buffers.

The unified observability plane's hot half.  Every instrumented plane
(driver ticket lifecycle, WorkerPool slots, the background refit
thread, the store, the engine step loop) calls the tiny module-level
API here; `uptune_tpu.obs.export` turns the recorded rings into a
Perfetto-viewable Chrome trace, a metrics JSONL, and a text summary.

Design constraints (ISSUE 7):

* **Disabled is free.**  `_ENABLED` is a module-level bool checked
  FIRST in every entry point; the disabled path allocates nothing —
  `span()` returns one shared no-op singleton, `event()`/`count()`
  return immediately.  The driver plane sustains ~4.6k asks/s
  (BENCH_DRIVER.json) and instrumentation that is off must not tax it.
* **Enabled is lock-free on the record path.**  Each thread owns its
  own `_Ring` (created once under `_REG_LOCK`, then written without
  any lock): one writer per buffer by construction, so concurrent
  driver + refit-thread + pool bookkeeping never contend or interleave.
  Readers (the exporter) snapshot `buf[:]` + `idx` — under the GIL the
  slot write at `buf[i % cap]` happens-before the `idx` bump, so a
  snapshot never observes a torn record, at worst it misses the very
  newest one.
* **Bounded.**  Rings are fixed-capacity (default 2^16 records); past
  capacity the oldest records are overwritten and `dropped` counts
  them, so a week-long serve process can leave tracing on without
  growing without bound.

Records are plain tuples ``(name, ts, dur, track, attrs)``:

* ``ts``     — seconds since `enable()` (perf_counter timebase);
* ``dur``    — span length in seconds, or None for an instant event;
* ``track``  — explicit lane name (worker slots, synthetic lanes), or
  None for "the thread that recorded it";
* ``attrs``  — small JSON-safe dict or None.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = [
    "enabled", "enable", "disable", "reset", "span", "device_span",
    "event", "complete_span", "emit_at", "new_span_id", "snapshot",
    "trace_origin_unix", "DEFAULT_CAPACITY",
]

DEFAULT_CAPACITY = 1 << 16

_ENABLED = False
_T0 = 0.0            # perf_counter at enable(): the trace origin
_T0_UNIX = 0.0       # wall-clock at enable() (for artifact metadata)
_CAPACITY = DEFAULT_CAPACITY

_REG_LOCK = threading.Lock()
_RINGS: List["_Ring"] = []
_TLS = threading.local()
# bumped on every enable()/reset(): a thread whose cached ring carries
# an older epoch re-registers on its next record, so threads that
# outlive an enable cycle (the refit worker) can't write into a ring
# the exporter no longer sees
_EPOCH = 0


class _Ring:
    """One thread's record buffer.  Single writer (the owning thread);
    `snapshot()` may run from any thread."""

    __slots__ = ("buf", "idx", "cap", "track", "epoch")

    def __init__(self, cap: int, track: str, epoch: int):
        self.buf: List[Optional[tuple]] = [None] * cap
        self.idx = 0
        self.cap = cap
        self.track = track
        self.epoch = epoch

    def append(self, rec: tuple) -> None:
        i = self.idx
        self.buf[i % self.cap] = rec
        self.idx = i + 1   # publish AFTER the slot write (GIL ordering)

    @property
    def dropped(self) -> int:
        return max(0, self.idx - self.cap)

    def snapshot(self) -> List[tuple]:
        """Recorded tuples, oldest first (complete records only)."""
        i, cap = self.idx, self.cap
        buf = self.buf[:]
        if i <= cap:
            return [r for r in buf[:i] if r is not None]
        head = i % cap
        out = buf[head:] + buf[:head]
        return [r for r in out if r is not None]


def _ring() -> _Ring:
    r = getattr(_TLS, "ring", None)
    if r is None or r.epoch != _EPOCH:
        t = threading.current_thread()
        r = _Ring(_CAPACITY, t.name, _EPOCH)
        _TLS.ring = r
        with _REG_LOCK:
            _RINGS.append(r)
    return r


# ---------------------------------------------------------------- flag
def enabled() -> bool:
    return _ENABLED


def enable(capacity: int = DEFAULT_CAPACITY) -> None:
    """Start recording.  Existing rings are cleared so a fresh enable
    always exports one coherent run."""
    global _ENABLED, _T0, _T0_UNIX, _CAPACITY, _EPOCH
    with _REG_LOCK:
        _RINGS.clear()
        _EPOCH += 1
    # other threads' cached rings cannot be cleared from here; the
    # epoch bump makes them re-register on their next record instead
    _CAPACITY = int(capacity)
    _T0 = time.perf_counter()
    _T0_UNIX = time.time()
    _ENABLED = True
    from . import device as _d
    from . import metrics as _m
    _m.reset()
    _d.reset_registry()


def disable() -> None:
    global _ENABLED
    _ENABLED = False


def reset() -> None:
    """Disable AND drop every recorded ring/metric (test isolation)."""
    global _ENABLED, _EPOCH
    _ENABLED = False
    with _REG_LOCK:
        _RINGS.clear()
        _EPOCH += 1
    from . import device as _d
    from . import metrics as _m
    _m.reset()
    _d.reset_registry()


def now() -> float:
    """Seconds since the trace origin (0.0 when disabled)."""
    return time.perf_counter() - _T0 if _ENABLED else 0.0


def trace_origin_unix() -> float:
    return _T0_UNIX


def _record(rec: tuple) -> None:
    _ring().append(rec)


# ---------------------------------------------------------------- spans
class _Noop:
    """Shared do-nothing span: the disabled fast path allocates
    nothing — every disabled `span()` call returns this singleton."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


NOOP = _Noop()


class _Span:
    __slots__ = ("name", "t0", "attrs", "_annot")

    def __init__(self, name: str, attrs: Optional[Dict[str, Any]],
                 annot=None):
        self.name = name
        self.attrs = attrs
        self._annot = annot
        self.t0 = time.perf_counter()

    def set(self, **attrs) -> "_Span":
        """Attach/overwrite attributes after entry (e.g. a row count
        known only at exit)."""
        if self.attrs is None:
            self.attrs = attrs
        else:
            self.attrs.update(attrs)
        return self

    def __enter__(self) -> "_Span":
        if self._annot is not None:
            self._annot.__enter__()
        return self

    def __exit__(self, *exc) -> bool:
        t1 = time.perf_counter()
        if self._annot is not None:
            self._annot.__exit__(*exc)
        _record((self.name, self.t0 - _T0, t1 - self.t0, None,
                 self.attrs))
        return False


def span(name: str, **attrs):
    """``with obs.span("propose", arm=name):`` — one timed span on the
    calling thread's lane.  Returns the shared no-op when disabled."""
    if not _ENABLED:
        return NOOP
    return _Span(name, attrs or None)


def device_span(name: str, **attrs):
    """A span that ALSO opens a `jax.profiler.TraceAnnotation`, so when
    a JAX profile is captured alongside, host spans line up with the
    XLA kernels they dispatched.  No-op when disabled; degrades to a
    plain span if jax (or its profiler) is unavailable."""
    if not _ENABLED:
        return NOOP
    annot = None
    try:
        from jax.profiler import TraceAnnotation
        annot = TraceAnnotation(name)
    except Exception:
        pass
    return _Span(name, attrs or None, annot)


def event(name: str, **attrs) -> None:
    """Instant event on the calling thread's lane."""
    if not _ENABLED:
        return
    _record((name, time.perf_counter() - _T0, None, None, attrs or None))


def complete_span(name: str, t0: float, dur: float,
                  track: Optional[str] = None, **attrs) -> None:
    """Record an already-measured span, optionally on an explicit lane
    (`track`) — how WorkerPool build windows land on per-slot lanes:
    the driver thread emits them at reap time with the slot's own
    launch timestamp.  `t0` is a raw perf_counter() value."""
    if not _ENABLED:
        return
    _record((name, t0 - _T0, max(0.0, dur), track, attrs or None))


def emit_at(name: str, ts: float, dur: Optional[float] = None,
            track: Optional[str] = None,
            attrs: Optional[Dict[str, Any]] = None) -> None:
    """Record an event/span at an EXPLICIT trace-relative timestamp
    (seconds since this process's trace origin) — the foreign-clock
    entry point: a subprocess sidecar's events are re-emitted here
    after their unix-clock offset against our origin is applied
    (`obs.sidecar.merge_into`).  `dur=None` records an instant."""
    if not _ENABLED:
        return
    _record((name, float(ts),
             None if dur is None else max(0.0, float(dur)),
             track, attrs or None))


# span/trace ids for cross-process context propagation: unique within
# a process by the counter, across processes by the pid prefix (good
# enough to join one client's request span to one server handler span
# in a merged trace — not a cryptographic trace id)
_SPAN_SEQ = itertools.count(1)


def new_span_id() -> str:
    return f"{os.getpid():x}-{next(_SPAN_SEQ):x}"


# ------------------------------------------------------------- reading
def snapshot() -> Dict[str, Any]:
    """All recorded events plus ring bookkeeping.

    Returns ``{"events": [...], "dropped": {track: n}, "origin_unix"}``
    where each event is
    ``{"name", "ts", "dur"|None, "track", "attrs"|None}`` and ``ts`` /
    ``dur`` are seconds since the trace origin.  Events are sorted by
    timestamp across tracks."""
    with _REG_LOCK:
        rings = list(_RINGS)
    events = []
    dropped: Dict[str, int] = {}
    for r in rings:
        if r.dropped:
            dropped[r.track] = dropped.get(r.track, 0) + r.dropped
        for name, ts, dur, track, attrs in r.snapshot():
            events.append({"name": name, "ts": ts, "dur": dur,
                           "track": track or r.track, "attrs": attrs})
    events.sort(key=lambda e: e["ts"])
    return {"events": events, "dropped": dropped,
            "origin_unix": _T0_UNIX}
