"""`ut report` — render a tuning journal into a post-run quality
report.

The TPU-native successor of the reference framework's CSV-archive +
`report.py` surface: where `ut-trace` shows *where the time went*,
this shows *whether the search was any good* — convergence curve,
per-arm attribution, surrogate-calibration reliability, store
efficacy, and the alerts the online detector would have raised — all
recomputed EXACTLY from the journal through `obs.quality.replay`
(the same code path the live gauges run), so the report can never
disagree with what `ut top` showed during the run.

    ut report out.journal.jsonl                    # -> .report.html
    ut report out.journal.jsonl --format md -o -   # markdown to stdout
    ut report j.jsonl --metrics trace.json.metrics.jsonl
    ut report 'out.journal.h*.jsonl'               # multi-replica
    ut report ut.fleet.jsonl                       # hub fleet timeline

Multi-source journals (ISSUE 14): several journal files (repeatable
positionals, glob-expanded — e.g. the ``.hN`` files every
``--num-hosts`` replica writes) or ONE hub fleet timeline
(``ut hub --timeline``, detected by its header; each source's shipped
journal rows are split back out) render a single document with a
fleet summary table and per-source attribution sections, each
replayed through the same exact `quality.replay` path.

The HTML is fully self-contained (inline SVG + CSS, no scripts, no
network), so it can be committed next to a bench artifact or attached
to a ticket; the markdown form carries the same numbers for terminals
and code review.  Charts use the repo's validated default palette
(light + dark via prefers-color-scheme); every chart is paired with
the table carrying the same data.
"""
from __future__ import annotations

import argparse
import glob as _glob
import html as _html
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

from . import journal as journal_mod
from . import quality as quality_mod

__all__ = ["analyze", "render", "render_html", "render_markdown",
           "render_multi", "read_sources", "summarize_metrics",
           "device_summary", "main"]

# nominal two-sided central-interval levels for the reliability table
# (z quantiles of the standard normal)
RELIABILITY_LEVELS = ((50, 0.6745), (80, 1.2816), (90, 1.6449),
                      (95, 1.9600), (99, 2.5758))

# categorical slots (validated default palette, references order —
# fixed assignment by first appearance, never cycled; arms past the
# 8th fold to the neutral "other" ink)
_SERIES_LIGHT = ("#2a78d6", "#eb6834", "#1baf7a", "#eda100",
                 "#e87ba4", "#008300", "#4a3aa7", "#e34948")
_SERIES_DARK = ("#3987e5", "#d95926", "#199e70", "#c98500",
                "#d55181", "#008300", "#9085e9", "#e66767")
_OTHER = "#8a8985"


def analyze(header: Dict[str, Any], rows: List[Dict[str, Any]],
            config: Optional[quality_mod.QualityConfig] = None
            ) -> Dict[str, Any]:
    """Everything the renderers need, computed once: the exact quality
    replay plus the row-level sequences the charts draw."""
    mon = quality_mod.replay(rows, config)
    tells: List[Dict[str, Any]] = []
    cal: List[Tuple[float, float, float]] = []   # (mu, sigma, qor)
    store_hits = 0
    store_saved_s = 0.0
    exchanges = 0
    snapshots = 0
    features = 0
    interms = 0
    sessions: Dict[str, Dict[str, Any]] = {}
    sense = "min"
    best: Optional[float] = None
    for row in rows:
        ev = row.get("ev")
        if ev == "step":
            # flatten the per-trial outcome arrays into tell records
            # via the reference compact-encoding decoder (the journal
            # packs one row per ticket — obs/journal.py EVENT_KINDS)
            if row.get("sense") == "max":
                sense = "max"
            for gid, ok, qor, nb, dur, mu, sigma in \
                    journal_mod.step_tells(row):
                if nb and qor is not None:
                    best = float(qor)
                tell = {"t": row.get("t"), "gid": gid,
                        "arm": row.get("arm"), "ok": ok, "qor": qor,
                        "new_best": nb, "best": best, "dur": dur}
                if mu is not None:
                    tell["mu"], tell["sigma"] = mu, sigma
                    if ok and qor is not None:
                        cal.append((float(mu), float(sigma),
                                    float(qor)))
                tells.append(tell)
            if row.get("best") is not None:
                best = float(row["best"])   # authoritative incumbent
        elif ev == "store_hit":
            store_hits += 1
            store_saved_s += float(row.get("dur") or 0.0)
        elif ev == "exchange":
            exchanges += 1
        elif ev == "snapshot":
            snapshots += 1
        elif ev == "feature":
            features += 1
        elif ev == "interm":
            interms += 1
        elif ev == "serve_tell":
            s = sessions.setdefault(str(row.get("session")),
                                    {"tells": 0, "new_bests": 0,
                                     "fails": 0})
            s["tells"] += 1
            s["new_bests"] += int(bool(row.get("new_best")))
            s["fails"] += int(not row.get("ok"))
    reliability = []
    if cal:
        zs = [(q - m) / max(s, 1e-12) for m, s, q in cal]
        for level, zq in RELIABILITY_LEVELS:
            emp = sum(1 for z in zs if abs(z) <= zq) / len(zs)
            reliability.append({"nominal": level,
                                "empirical": round(emp, 4)})
    return {"header": header, "mon": mon, "tells": tells,
            "sense": sense, "cal_rows": len(cal),
            "reliability": reliability, "store_hits": store_hits,
            "store_saved_s": round(store_saved_s, 3),
            "exchanges": exchanges, "snapshots": snapshots,
            "features": features, "interms": interms,
            "sessions": sessions}


def summarize_metrics(metrics_path: str) -> Optional[Dict[str, Any]]:
    """Optional flight-recorder sidecar summary: wall span, row count,
    and the peak per-window rate of the headline counters — the system
    plane's one-paragraph contribution to a search-quality report."""
    rows = []
    try:
        with open(metrics_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue        # torn tail
                if isinstance(row, dict) and "counters" in row:
                    rows.append(row)
    except OSError:
        return None
    if not rows:
        return None
    peaks: Dict[str, float] = {}
    for row in rows:
        dt = row.get("dt") or 0
        if not dt:
            continue
        for k, v in (row.get("deltas") or {}).items():
            rate = v / dt
            if rate > peaks.get(k, 0.0):
                peaks[k] = rate
    top = sorted(peaks.items(), key=lambda kv: -kv[1])[:6]
    return {"rows": len(rows),
            "span_s": round(rows[-1].get("t", 0) - rows[0].get("t", 0),
                            3),
            "final_counters": rows[-1].get("counters", {}),
            "final_gauges": rows[-1].get("gauges", {}),
            "final_hists": rows[-1].get("hists", {}),
            "peak_rates": {k: round(v, 2) for k, v in top}}


# device-telemetry extraction (ISSUE 13): the same replay path as the
# rest of the report — the flight recorder's FINAL row carries the
# run's terminal device.* gauges/counters, exactly what `ut top`
# showed live, so the report can never disagree with the dashboard
_DEV_PROGRAM_FAMILIES = ("flops", "bytes", "compile_ms",
                         "arith_intensity")
_DEV_ROOFLINE_KEYS = ("achieved_flops_per_s",
                      "achieved_hbm_bytes_per_s", "peak_flops_per_s",
                      "peak_hbm_bytes_per_s", "mxu_util", "hbm_util",
                      "arith_intensity")


def device_summary(met: Optional[Dict[str, Any]]
                   ) -> Optional[Dict[str, Any]]:
    """Per-program flops/bytes, the compile breakdown and the roofline
    aggregates from a metrics timeline's final row; None when the run
    carried no device telemetry (the section is simply absent)."""
    if not met:
        return None
    g = met.get("final_gauges") or {}
    c = met.get("final_counters") or {}
    if not any(k.startswith("device.") for k in list(g) + list(c)):
        return None
    progs: Dict[str, Dict[str, Any]] = {}
    for fam in _DEV_PROGRAM_FAMILIES:
        prefix = f"device.{fam}."
        for k, v in g.items():
            if k.startswith(prefix):
                progs.setdefault(k[len(prefix):], {})[fam] = v
    h = (met.get("final_hists") or {}).get("device.compile_ms") or {}
    return {
        "programs": progs,
        "compile": {
            "compiles": c.get("device.compiles"),
            "compile_ms_total": h.get("sum"),
            "cache_hits": c.get("device.compile_cache_hits"),
            "cache_misses": c.get("device.compile_cache_misses"),
            "dispatches": c.get("device.dispatches"),
        },
        "roofline": {k: g.get(f"device.{k}")
                     for k in _DEV_ROOFLINE_KEYS
                     if g.get(f"device.{k}") is not None},
    }


# --------------------------------------------------------------- SVG
def _fmt(v: Any, nd: int = 4) -> str:
    if v is None:
        return "—"
    if isinstance(v, float):
        return f"{v:.{nd}g}"
    return str(v)


def _scale(lo: float, hi: float, a: float, b: float):
    span = (hi - lo) or 1.0
    return lambda v: a + (v - lo) / span * (b - a)


def _ticks(lo: float, hi: float, n: int = 4) -> List[float]:
    span = (hi - lo) or 1.0
    return [lo + span * i / n for i in range(n + 1)]


def _svg_convergence(an: Dict[str, Any], width: int = 640,
                     height: int = 240) -> str:
    """Best-so-far step line over per-tell QoR dots (one series + its
    context marks; y = user-oriented QoR, x = tell index)."""
    tells = [r for r in an["tells"] if r.get("ok")
             and r.get("qor") is not None]
    if len(tells) < 2:
        return ""
    qs = [float(r["qor"]) for r in tells]
    bests = [float(r["best"]) for r in tells if r.get("best") is not None]
    lo = min(qs + bests)
    hi = max(qs + bests)
    ml, mr, mt, mb = 58, 14, 10, 26
    sx = _scale(0, len(tells) - 1, ml, width - mr)
    sy = _scale(lo, hi, height - mb, mt)
    grid, labels = [], []
    for tv in _ticks(lo, hi):
        y = sy(tv)
        grid.append(f'<line x1="{ml}" y1="{y:.1f}" x2="{width - mr}" '
                    f'y2="{y:.1f}" class="grid"/>')
        labels.append(f'<text x="{ml - 6}" y="{y + 3.5:.1f}" '
                      f'class="tick" text-anchor="end">'
                      f'{_fmt(tv, 3)}</text>')
    for tv in _ticks(0, len(tells) - 1):
        x = sx(tv)
        labels.append(f'<text x="{x:.1f}" y="{height - mb + 16}" '
                      f'class="tick" text-anchor="middle">'
                      f'{int(tv)}</text>')
    dots = []
    for i, r in enumerate(tells):
        dots.append(
            f'<circle cx="{sx(i):.1f}" cy="{sy(float(r["qor"])):.1f}" '
            f'r="2" class="dot"><title>tell {i} gid={r.get("gid")} '
            f'arm={_html.escape(str(r.get("arm")))} '
            f'qor={_fmt(float(r["qor"]))}</title></circle>')
    pts, prev_best = [], None
    for i, r in enumerate(tells):
        b = r.get("best")
        if b is None:
            continue
        b = float(b)
        if prev_best is not None:
            pts.append(f"{sx(i):.1f},{sy(prev_best):.1f}")  # step
        pts.append(f"{sx(i):.1f},{sy(b):.1f}")
        prev_best = b
    line = (f'<polyline points="{" ".join(pts)}" class="best"/>'
            if pts else "")
    return (
        f'<svg viewBox="0 0 {width} {height}" role="img" '
        f'aria-label="convergence curve">'
        f'{"".join(grid)}'
        f'<line x1="{ml}" y1="{height - mb}" x2="{width - mr}" '
        f'y2="{height - mb}" class="axis"/>'
        f'{"".join(dots)}{line}{"".join(labels)}'
        f'<text x="{ml}" y="{height - 4}" class="tick">tell index'
        f'</text></svg>'
        f'<div class="legend"><span><i class="sw best-sw"></i>'
        f'best so far</span><span><i class="sw dot-sw"></i>'
        f'per-tell QoR</span></div>')


def _arm_slots(an: Dict[str, Any]) -> Dict[str, int]:
    """Fixed categorical slot per arm, by first appearance in the tell
    stream (never re-assigned, never cycled); -1 = folded to Other."""
    slots: Dict[str, int] = {}
    for r in an["tells"]:
        arm = str(r.get("arm"))
        if arm not in slots:
            slots[arm] = len(slots) if len(slots) < 8 else -1
    return slots


def _svg_arm_timeline(an: Dict[str, Any], width: int = 640,
                      height: int = 64) -> str:
    """Attribution strip: one thin mark per tell, colored by arm;
    new-best tells get a full-height mark."""
    tells = an["tells"]
    if not tells:
        return ""
    slots = _arm_slots(an)
    ml, mr = 58, 14
    sx = _scale(0, max(1, len(tells) - 1), ml, width - mr)
    marks = []
    for i, r in enumerate(tells):
        arm = str(r.get("arm"))
        cls = f"s{slots[arm]}" if slots[arm] >= 0 else "sx"
        h = height - 24 if r.get("new_best") else (height - 24) // 2
        y = height - 18 - h
        marks.append(
            f'<rect x="{sx(i) - 1:.1f}" y="{y}" width="2" '
            f'height="{h}" class="{cls}"><title>tell {i} '
            f'arm={_html.escape(arm)}'
            f'{" NEW BEST" if r.get("new_best") else ""}</title>'
            f'</rect>')
    legend = "".join(
        f'<span><i class="sw {"s%d" % s if s >= 0 else "sx"}-sw"></i>'
        f'{_html.escape(a)}</span>'
        for a, s in slots.items())
    return (
        f'<svg viewBox="0 0 {width} {height}" role="img" '
        f'aria-label="arm attribution timeline">'
        f'<line x1="{ml}" y1="{height - 18}" x2="{width - mr}" '
        f'y2="{height - 18}" class="axis"/>{"".join(marks)}'
        f'<text x="{ml}" y="{height - 4}" class="tick">tell index '
        f'(tall = new best)</text></svg>'
        f'<div class="legend">{legend}</div>')


# ----------------------------------------------------------- renders
def _arm_table(an: Dict[str, Any]) -> List[List[Any]]:
    mon = an["mon"]
    out = []
    for arm, (pulls, evals, bests) in sorted(mon.arm_stats.items()):
        out.append([arm, pulls, evals, bests,
                    _fmt(mon.gauges.get(f"search.arm_evals_share.{arm}"),
                         3),
                    _fmt(mon.gauges.get(f"search.arm_best_share.{arm}"),
                         3)])
    return out


def _summary_pairs(an: Dict[str, Any],
                   met: Optional[Dict[str, Any]]) -> List[Tuple[str, Any]]:
    mon = an["mon"]
    g = mon.gauges
    pairs = [
        ("best QoR", _fmt(mon.best, 6)),
        ("sense", an["sense"]),
        ("tells", mon.tells),
        ("new bests", mon.new_bests),
        ("tells since best", mon.tells_since_best),
        ("regret proxy", _fmt(g.get("search.regret_proxy"))),
        ("pulls", mon.pulls),
        ("dup rate", _fmt(g.get("search.dup_rate"), 3)),
        ("prune rate", _fmt(g.get("search.prune_rate"), 3)),
        ("fail rate", _fmt(g.get("search.fail_rate"), 3)),
        ("store hits", an["store_hits"]),
        ("build time served from store",
         f"{an['store_saved_s']:.1f} s"),
        ("exchange injections", an["exchanges"]),
        ("surrogate snapshots", an["snapshots"]),
        ("calibration rows", an["cal_rows"]),
        ("calibration MAE (window)",
         _fmt(g.get("search.cal_mae"))),
        ("rank corr (window)", _fmt(g.get("search.cal_rank_corr"), 3)),
        ("covariate rows", an["features"]),
        ("interm rows", an["interms"]),
        ("alerts", len(mon.alerts)),
    ]
    if an["sessions"]:
        pairs.append(("serve sessions", len(an["sessions"])))
    if met:
        pairs.append(("flight-recorder rows",
                      f"{met['rows']} over {met['span_s']} s"))
    return pairs


def render_markdown(an: Dict[str, Any],
                    met: Optional[Dict[str, Any]] = None) -> str:
    mon = an["mon"]
    meta = an["header"].get("meta") or {}
    lines = ["# ut report", ""]
    if meta:
        lines += ["run: `" + json.dumps(meta, sort_keys=True) + "`", ""]
    lines += ["## Summary", "", "| metric | value |", "|---|---|"]
    lines += [f"| {k} | {v} |" for k, v in _summary_pairs(an, met)]
    lines += ["", "## Arm attribution", "",
              "| arm | pulls | evals | new bests | evals share | "
              "best share |", "|---|---|---|---|---|---|"]
    for row in _arm_table(an):
        lines.append("| " + " | ".join(str(c) for c in row) + " |")
    if an["reliability"]:
        lines += ["", "## Calibration reliability "
                      f"({an['cal_rows']} joined rows)", "",
                  "| nominal interval | empirical coverage |",
                  "|---|---|"]
        for r in an["reliability"]:
            lines.append(f"| {r['nominal']}% | "
                         f"{100 * r['empirical']:.1f}% |")
    if mon.alerts:
        lines += ["", "## Alerts", "", "| t (s) | kind | detail |",
                  "|---|---|---|"]
        for a in mon.alerts:
            detail = {k: v for k, v in a.items()
                      if k not in ("kind", "t")}
            lines.append(f"| {a['t']:.1f} | {a['kind']} | "
                         f"`{json.dumps(detail, sort_keys=True)}` |")
    else:
        lines += ["", "No alerts fired."]
    if an["sessions"]:
        lines += ["", "## Serve sessions", "",
                  "| session | tells | new bests | fails |",
                  "|---|---|---|---|"]
        for sid in sorted(an["sessions"]):
            s = an["sessions"][sid]
            lines.append(f"| {sid} | {s['tells']} | {s['new_bests']} "
                         f"| {s['fails']} |")
    if met:
        lines += ["", "## System timeline (flight recorder)", "",
                  "| counter | peak rate /s |", "|---|---|"]
        for k, v in met["peak_rates"].items():
            lines.append(f"| {k} | {v} |")
    dev = device_summary(met)
    if dev:
        lines += ["", "## Device & compile", ""]
        comp = dev["compile"]
        lines += ["| metric | value |", "|---|---|"]
        for label, key in (("compiles", "compiles"),
                           ("compile time (ms)", "compile_ms_total"),
                           ("compile-cache hits", "cache_hits"),
                           ("compile-cache misses", "cache_misses"),
                           ("device dispatches", "dispatches")):
            lines.append(f"| {label} | {_fmt(comp.get(key))} |")
        if dev["programs"]:
            lines += ["", "| program | flops | bytes | AI | "
                          "compile ms |", "|---|---|---|---|---|"]
            for name in sorted(dev["programs"]):
                p = dev["programs"][name]
                lines.append(
                    f"| {name} | {_fmt(p.get('flops'))} | "
                    f"{_fmt(p.get('bytes'))} | "
                    f"{_fmt(p.get('arith_intensity'), 3)} | "
                    f"{_fmt(p.get('compile_ms'), 3)} |")
        if dev["roofline"]:
            lines += ["", "| roofline (last measured window) | value |",
                      "|---|---|"]
            for k in sorted(dev["roofline"]):
                lines.append(f"| {k} | {_fmt(dev['roofline'][k])} |")
    return "\n".join(lines) + "\n"


_CSS = """
.viz-root {{
  color-scheme: light;
  --surface-1: #fcfcfb; --text-primary: #0b0b0b;
  --text-secondary: #52514e; --grid: #e7e6e2; --axis: #b5b4af;
  {light}
  --other: #8a8985;
  font: 14px/1.5 system-ui, sans-serif;
  color: var(--text-primary); background: var(--surface-1);
  max-width: 720px; margin: 0 auto; padding: 24px;
}}
@media (prefers-color-scheme: dark) {{
  :root:where(:not([data-theme="light"])) .viz-root {{
    color-scheme: dark;
    --surface-1: #1a1a19; --text-primary: #ffffff;
    --text-secondary: #c3c2b7; --grid: #31302e; --axis: #55544f;
    {dark}
  }}
}}
.viz-root h1 {{ font-size: 20px; }}
.viz-root h2 {{ font-size: 16px; margin-top: 28px; }}
.viz-root table {{ border-collapse: collapse; margin: 8px 0; }}
.viz-root td, .viz-root th {{
  padding: 3px 10px; border-bottom: 1px solid var(--grid);
  text-align: left; font-variant-numeric: tabular-nums; }}
.viz-root th {{ color: var(--text-secondary); font-weight: 600; }}
.viz-root .meta {{ color: var(--text-secondary); }}
.viz-root svg {{ width: 100%; height: auto; display: block; }}
.viz-root .grid {{ stroke: var(--grid); stroke-width: 1; }}
.viz-root .axis {{ stroke: var(--axis); stroke-width: 1; }}
.viz-root .tick {{ fill: var(--text-secondary); font-size: 10px; }}
.viz-root .best {{ fill: none; stroke: var(--s0); stroke-width: 2;
  stroke-linejoin: round; }}
.viz-root .dot {{ fill: var(--axis); }}
.viz-root .legend {{ color: var(--text-secondary); font-size: 12px;
  display: flex; gap: 16px; margin: 4px 0 0 58px; }}
.viz-root .legend .sw {{ display: inline-block; width: 10px;
  height: 10px; border-radius: 2px; margin-right: 5px; }}
.viz-root .best-sw {{ background: var(--s0); }}
.viz-root .dot-sw {{ background: var(--axis); border-radius: 50%; }}
.viz-root .sx {{ fill: var(--other); }}
.viz-root .sx-sw {{ background: var(--other); }}
{series_css}
.viz-root .alert td:nth-child(2) {{ font-weight: 600; }}
"""


def _report_css() -> str:
    """The one CSS block both HTML renderers (single-source and
    fleet) embed — styling fixes land once."""
    series_css = "\n".join(
        f".viz-root .s{i} {{ fill: var(--s{i}); }}\n"
        f".viz-root .s{i}-sw {{ background: var(--s{i}); }}"
        for i in range(8))
    return _CSS.format(
        light="\n  ".join(f"--s{i}: {c};"
                          for i, c in enumerate(_SERIES_LIGHT)),
        dark="\n    ".join(f"--s{i}: {c};"
                           for i, c in enumerate(_SERIES_DARK)),
        series_css=series_css)


def _table_html(headers, rows_) -> str:
    """Escaped HTML table — the shared cell-escaping path of both
    renderers."""
    h = "".join(f"<th>{_html.escape(str(c))}</th>" for c in headers)
    b = "".join(
        "<tr>" + "".join(f"<td>{_html.escape(str(c))}</td>"
                         for c in row) + "</tr>"
        for row in rows_)
    return f"<table><tr>{h}</tr>{b}</table>"


def render_html(an: Dict[str, Any],
                met: Optional[Dict[str, Any]] = None) -> str:
    import time as _time
    meta = an["header"].get("meta") or {}
    origin = an["header"].get("origin_unix")
    when = (_time.strftime("%Y-%m-%d %H:%M:%S",
                           _time.gmtime(origin)) + " UTC"
            if origin else "—")
    css = _report_css()
    table = _table_html

    parts = [
        "<!doctype html><html><head><meta charset='utf-8'>",
        "<title>ut report</title>",
        f"<style>{css}</style></head><body class='viz-root'>",
        "<h1>ut report — search quality</h1>",
        f"<p class='meta'>journal recorded {when}"
        + (f" · {_html.escape(json.dumps(meta, sort_keys=True))}"
           if meta else "") + "</p>",
        "<h2>Summary</h2>",
        table(("metric", "value"), _summary_pairs(an, met)),
    ]
    conv = _svg_convergence(an)
    if conv:
        parts += ["<h2>Convergence</h2>", conv]
    strip = _svg_arm_timeline(an)
    if strip:
        parts += ["<h2>Arm attribution</h2>", strip]
    parts.append(table(("arm", "pulls", "evals", "new bests",
                        "evals share", "best share"), _arm_table(an)))
    if an["reliability"]:
        parts += [f"<h2>Calibration reliability "
                  f"({an['cal_rows']} joined rows)</h2>",
                  table(("nominal interval", "empirical coverage"),
                        [(f"{r['nominal']}%",
                          f"{100 * r['empirical']:.1f}%")
                         for r in an["reliability"]])]
    mon = an["mon"]
    parts.append("<h2>Alerts</h2>")
    if mon.alerts:
        parts.append(table(
            ("t (s)", "kind", "detail"),
            [(f"{a['t']:.1f}", a["kind"],
              json.dumps({k: v for k, v in a.items()
                          if k not in ("kind", "t")}, sort_keys=True))
             for a in mon.alerts]))
    else:
        parts.append("<p class='meta'>No alerts fired.</p>")
    if an["sessions"]:
        parts += ["<h2>Serve sessions</h2>",
                  table(("session", "tells", "new bests", "fails"),
                        [(sid, s["tells"], s["new_bests"], s["fails"])
                         for sid, s in sorted(an["sessions"].items())])]
    if met:
        parts += ["<h2>System timeline (flight recorder)</h2>",
                  table(("counter", "peak rate /s"),
                        sorted(met["peak_rates"].items())),
                  f"<p class='meta'>{met['rows']} rows over "
                  f"{met['span_s']} s</p>"]
    dev = device_summary(met)
    if dev:
        comp = dev["compile"]
        parts += ["<h2>Device &amp; compile</h2>",
                  table(("metric", "value"),
                        [("compiles", _fmt(comp.get("compiles"))),
                         ("compile time (ms)",
                          _fmt(comp.get("compile_ms_total"))),
                         ("compile-cache hits",
                          _fmt(comp.get("cache_hits"))),
                         ("compile-cache misses",
                          _fmt(comp.get("cache_misses"))),
                         ("device dispatches",
                          _fmt(comp.get("dispatches")))])]
        if dev["programs"]:
            parts.append(table(
                ("program", "flops", "bytes", "AI", "compile ms"),
                [(name, _fmt(p.get("flops")), _fmt(p.get("bytes")),
                  _fmt(p.get("arith_intensity"), 3),
                  _fmt(p.get("compile_ms"), 3))
                 for name, p in sorted(dev["programs"].items())]))
        if dev["roofline"]:
            parts.append(table(
                ("roofline (last measured window)", "value"),
                [(k, _fmt(dev["roofline"][k]))
                 for k in sorted(dev["roofline"])]))
    parts.append("</body></html>")
    return "".join(parts)


def render(journal_path: str, metrics_path: Optional[str] = None,
           fmt: str = "html",
           config: Optional[quality_mod.QualityConfig] = None) -> str:
    header, rows = journal_mod.read(journal_path)
    an = analyze(header, rows, config)
    met = summarize_metrics(metrics_path) if metrics_path else None
    if fmt == "md":
        return render_markdown(an, met)
    return render_html(an, met)


# ------------------------------------------------- multi-source (ISSUE 14)
def _is_fleet_timeline(path: str) -> bool:
    """A hub fleet timeline announces itself with a ``{"fleet": 1}``
    header line (obs/hub.py); a plain journal starts with
    ``{"journal": 1}``."""
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    return False
                return isinstance(rec, dict) and "fleet" in rec
    except OSError:
        pass
    return False


def read_fleet(path: str) -> List[Tuple[str, Dict[str, Any],
                                        List[Dict[str, Any]]]]:
    """Split a hub fleet timeline (including its rotation chain) back
    into per-source journal streams: ``[(source_label, header, rows),
    ...]``.  Only ``kind == "journal"`` rows participate — window
    snapshots and health rollups are system-plane telemetry the
    quality replay has no use for."""
    from . import flight
    origin = None
    per: Dict[str, List[Dict[str, Any]]] = {}
    for rec in flight.read_chain(path):
        if "fleet" in rec:
            origin = origin or rec.get("origin_unix")
            continue
        if rec.get("kind") != "journal":
            continue
        row = rec.get("row")
        if isinstance(row, dict) and "ev" in row:
            per.setdefault(str(rec.get("src")), []).append(row)
    return [(src,
             {"journal": journal_mod.SCHEMA_VERSION,
              "origin_unix": origin,
              "meta": {"source": src,
                       "fleet": os.path.basename(path)}},
             rows)
            for src, rows in sorted(per.items())]


def read_sources(paths: List[str]
                 ) -> List[Tuple[str, Dict[str, Any],
                                 List[Dict[str, Any]]]]:
    """Normalize the CLI's positional(s) into per-source journal
    streams.  One fleet timeline expands into its shipped sources;
    journal files contribute one source each, labeled by basename."""
    if len(paths) == 1 and _is_fleet_timeline(paths[0]):
        return read_fleet(paths[0])
    out = []
    for p in paths:
        header, rows = journal_mod.read(p)
        out.append((os.path.basename(p), header, rows))
    return out


def _source_summary_row(label: str, an: Dict[str, Any]) -> List[Any]:
    mon = an["mon"]
    tells = [r for r in an["tells"] if r.get("ok")]
    best = next((r["best"] for r in reversed(an["tells"])
                 if r.get("best") is not None), None)
    return [label, len(tells),
            _fmt(best) if best is not None else "—",
            sum(1 for r in an["tells"] if r.get("new_best")),
            len(mon.alerts), an["store_hits"]]


_FLEET_HEADERS = ("source", "tells", "best", "new bests", "alerts",
                  "store hits")


def render_multi(sources: List[Tuple[str, Dict[str, Any],
                                     List[Dict[str, Any]]]],
                 fmt: str = "html",
                 config: Optional[quality_mod.QualityConfig] = None
                 ) -> str:
    """One document over several sources: a fleet summary table, then
    per-source attribution (summary, arm table, convergence chart in
    HTML, alerts) — every source replayed through the same
    `quality.replay` path as the single-source report."""
    ans = [(label, analyze(header, rows, config))
           for label, header, rows in sources]
    if fmt == "md":
        lines = ["# ut report — fleet", "",
                 f"{len(ans)} sources", "", "## Sources", "",
                 "| " + " | ".join(_FLEET_HEADERS) + " |",
                 "|" + "---|" * len(_FLEET_HEADERS)]
        for label, an in ans:
            lines.append("| " + " | ".join(
                str(c) for c in _source_summary_row(label, an)) + " |")
        for label, an in ans:
            lines += ["", f"## Source: {label}", "",
                      "| metric | value |", "|---|---|"]
            lines += [f"| {k} | {v} |"
                      for k, v in _summary_pairs(an, None)]
            lines += ["", "| arm | pulls | evals | new bests | "
                          "evals share | best share |",
                      "|---|---|---|---|---|---|"]
            for row in _arm_table(an):
                lines.append("| " + " | ".join(str(c) for c in row)
                             + " |")
            mon = an["mon"]
            if mon.alerts:
                lines += ["", "| t (s) | kind |", "|---|---|"]
                lines += [f"| {a['t']:.1f} | {a['kind']} |"
                          for a in mon.alerts]
        return "\n".join(lines) + "\n"

    # html: the single-source document's shared CSS + table helpers
    css = _report_css()
    table = _table_html
    parts = [
        "<!doctype html><html><head><meta charset='utf-8'>",
        "<title>ut report — fleet</title>",
        f"<style>{css}</style></head><body class='viz-root'>",
        "<h1>ut report — fleet</h1>",
        f"<p class='meta'>{len(ans)} sources</p>",
        "<h2>Sources</h2>",
        table(_FLEET_HEADERS,
              [_source_summary_row(label, an) for label, an in ans]),
    ]
    for label, an in ans:
        parts += [f"<h2>Source: {_html.escape(label)}</h2>",
                  table(("metric", "value"), _summary_pairs(an, None))]
        conv = _svg_convergence(an)
        if conv:
            parts.append(conv)
        parts.append(table(("arm", "pulls", "evals", "new bests",
                            "evals share", "best share"),
                           _arm_table(an)))
        mon = an["mon"]
        if mon.alerts:
            parts.append(table(
                ("t (s)", "kind", "detail"),
                [(f"{a['t']:.1f}", a["kind"],
                  json.dumps({k: v for k, v in a.items()
                              if k not in ("kind", "t")},
                             sort_keys=True))
                 for a in mon.alerts]))
    parts.append("</body></html>")
    return "".join(parts)


# ------------------------------------------------------------------ CLI
def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="ut report",
        description="render a tuning journal into a self-contained "
                    "search-quality report (docs/OBSERVABILITY.md "
                    "'Search-quality telemetry')")
    p.add_argument("journal", nargs="+",
                   help="tuning journal JSONL(s) (ut --journal / "
                        "ut serve --journal; repeatable and "
                        "glob-expanded, e.g. 'out.jsonl.h*') — or ONE "
                        "hub fleet timeline (ut hub --timeline), "
                        "split back into its shipped per-source "
                        "journal streams")
    p.add_argument("--metrics", default=None, metavar="JSONL",
                   help="optional flight-recorder metrics timeline to "
                        "fold in (system-plane peak rates; "
                        "single-source reports only)")
    p.add_argument("--format", choices=("html", "md"), default="html")
    p.add_argument("-o", "--out", default=None,
                   help="output path ('-' = stdout; default "
                        "<journal>.report.<fmt>)")
    args = p.parse_args(argv)
    paths: List[str] = []
    for pat in args.journal:
        hits = sorted(_glob.glob(pat)) or [pat]
        for h in hits:
            if h not in paths:
                paths.append(h)
    try:
        if len(paths) == 1 and not _is_fleet_timeline(paths[0]):
            text = render(paths[0], args.metrics, args.format)
        else:
            sources = read_sources(paths)
            if not sources:
                print(f"ut report: no journal rows in {paths}",
                      file=sys.stderr)
                return 1
            text = render_multi(sources, args.format)
    except (OSError, ValueError) as e:
        print(f"ut report: {e}", file=sys.stderr)
        return 1
    out = args.out or f"{paths[0]}.report.{args.format}"
    if out == "-":
        sys.stdout.write(text)
    else:
        with open(out, "w") as f:
            f.write(text)
        print(f"ut report: wrote {out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
