"""Metrics registry: counters, gauges, histograms.

The scrape-able half of the observability plane: where spans answer
"what happened when", metrics answer "how much, in aggregate" — store
hit rates, worker utilization, prefetch queue depth, snapshot
version/refit lag, dedup collisions, engine acquisition rates.  The
session server (uptune_tpu/serve, ROADMAP item 1) serves `snapshot()`
as its ``{"op": "metrics"}`` scrape payload — the seam this module
was written for; `uptune_tpu.obs.export` also writes it as one JSONL
line per run and folds it into the text summary.

Same contract as the span core: every update checks the core's
module-level enabled flag first and returns immediately when tracing
is off, so instrumented hot paths cost one predicate when disabled.
Updates take one small module lock — metric updates are per-ticket /
per-build frequency (hundreds/s), not per-candidate, so contention is
irrelevant next to correctness, and a lock keeps read-modify-write
counters exact under the driver + refit + pool threads.

Histograms keep exact count/sum/min/max forever and the FIRST
`_HIST_CAP` raw samples for percentile estimation; a summary never
lies about totals, only its percentiles degrade to "of the first N"
on very long runs.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from . import core

__all__ = ["count", "gauge", "observe", "snapshot", "window_snapshot",
           "reset", "counter_value"]

_LOCK = threading.Lock()
_COUNTERS: Dict[str, float] = {}
_GAUGES: Dict[str, float] = {}
_HISTS: Dict[str, "_Hist"] = {}
_HIST_CAP = 8192


class _Hist:
    __slots__ = ("n", "total", "vmin", "vmax", "samples")

    def __init__(self):
        self.n = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")
        self.samples: List[float] = []

    def add(self, v: float) -> None:
        self.n += 1
        self.total += v
        if v < self.vmin:
            self.vmin = v
        if v > self.vmax:
            self.vmax = v
        if len(self.samples) < _HIST_CAP:
            self.samples.append(v)

    def summary(self) -> Dict[str, Any]:
        out = {"count": self.n, "sum": round(self.total, 6),
               "min": round(self.vmin, 6), "max": round(self.vmax, 6),
               "mean": round(self.total / self.n, 6) if self.n else None}
        if self.samples:
            s = sorted(self.samples)
            for p in (50, 95, 99):
                out[f"p{p}"] = round(s[min(len(s) - 1,
                                           (len(s) * p) // 100)], 6)
            if self.n > len(self.samples):
                out["sampled"] = len(self.samples)
        return out


def count(name: str, n: float = 1) -> None:
    """Increment a monotonic counter."""
    if not core._ENABLED:
        return
    with _LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + n


def gauge(name: str, value: float) -> None:
    """Set a last-value-wins gauge."""
    if not core._ENABLED:
        return
    with _LOCK:
        _GAUGES[name] = value


def observe(name: str, value: float) -> None:
    """Add one observation to a histogram."""
    if not core._ENABLED:
        return
    with _LOCK:
        h = _HISTS.get(name)
        if h is None:
            h = _HISTS[name] = _Hist()
        h.add(value)


def counter_value(name: str) -> float:
    with _LOCK:
        return _COUNTERS.get(name, 0)


def snapshot() -> Dict[str, Any]:
    """One self-contained metrics snapshot (the JSONL row / scrape
    payload): ``{"counters": {...}, "gauges": {...},
    "hists": {name: summary}}``."""
    with _LOCK:
        return {
            "counters": dict(_COUNTERS),
            "gauges": dict(_GAUGES),
            "hists": {k: h.summary() for k, h in _HISTS.items()},
        }


def window_snapshot(cursor: Optional[Dict[str, Any]] = None):
    """One flight-recorder row: the absolute scrape PLUS what changed
    since `cursor` (the previous call's second return value).

    Returns ``(row, new_cursor)`` where ``row`` is
    ``{"counters": abs, "deltas": {name: since-cursor}, "gauges": abs,
    "hists": {name: window summary}}``.  A histogram's window summary
    reports ``count``/``sum`` for the whole run and
    ``window_count``/``window_sum``/``p50``/``p95`` over ONLY the
    samples recorded since the cursor — so a long-lived server's
    timeline shows each interval's latency distribution, not an
    ever-flattening lifetime percentile.  (Past `_HIST_CAP` retained
    samples the window percentiles go None while the window counts
    stay exact — same honesty rule as `_Hist.summary`.)

    Everything is read under the one metrics lock, so a row is a
    consistent cut: the writer thread and a concurrent scrape can
    never disagree about which update landed in which window."""
    prev_c = (cursor or {}).get("counters", {})
    prev_h = (cursor or {}).get("hists", {})
    with _LOCK:
        counters = dict(_COUNTERS)
        gauges = dict(_GAUGES)
        hists: Dict[str, Any] = {}
        hcur: Dict[str, Any] = {}
        for k, h in _HISTS.items():
            pn, psum, plen = prev_h.get(k, (0, 0.0, 0))
            win = h.samples[plen:]
            summ: Dict[str, Any] = {
                "count": h.n, "sum": round(h.total, 6),
                "window_count": h.n - pn,
                "window_sum": round(h.total - psum, 6),
            }
            if win:
                s = sorted(win)
                for p in (50, 95):
                    summ[f"p{p}"] = round(
                        s[min(len(s) - 1, (len(s) * p) // 100)], 6)
            hists[k] = summ
            hcur[k] = (h.n, h.total, len(h.samples))
    deltas = {k: round(v - prev_c.get(k, 0), 6)
              for k, v in counters.items()}
    row = {"counters": counters, "deltas": deltas, "gauges": gauges,
           "hists": hists}
    return row, {"counters": counters, "hists": hcur}


def reset() -> None:
    with _LOCK:
        _COUNTERS.clear()
        _GAUGES.clear()
        _HISTS.clear()
