"""Tuning journal: an append-only JSONL stream of search *decisions*.

Where the span rings (`obs.core`) answer "what ran when" and the
metrics registry answers "how much, in aggregate", the journal answers
the search-quality questions neither can: which arm proposed each
config, what the surrogate *believed* about it at propose time, what
the build actually measured, which rows the dedup/prune/screen layers
dropped, when the store served a build for free — the reference
framework's CSV archive + SQLite result sync, re-shaped as one typed
event stream (ISSUE 12).

Same contract as the rest of the obs plane:

* **Disabled is free.**  `_ENABLED` is a module-level bool checked
  FIRST in every `emit`; the disabled path allocates nothing.  The
  instrumented call sites (driver ticket lifecycle, store serve path,
  serve-session commits, surrogate publishes) stay in the hot paths
  permanently; BENCH_OBS.json prices the enabled path (>= 0.95x of
  disabled driver throughput, journal active).
* **Off the device hot path.**  `emit` serializes one small dict to a
  string and appends it to an in-memory buffer under a short lock; the
  file write happens every `_FLUSH_EVERY` rows (and at `stop()`), in
  whichever *host* thread crossed the threshold — never inside a
  device dispatch.  A journal row is ~hundreds of bytes at per-ticket
  / per-tell frequency (hundreds/s), not per-candidate.
* **Torn-tail tolerant.**  `read()` skips unparseable trailing lines,
  so a journal from a crashed run replays up to its last complete row
  (the same rule as the trial archive and the flight recorder).

File format: one header line
``{"journal": 1, "origin_unix": ..., "pid": ..., "meta": {...}}``
then one JSON object per event: ``{"ev": <kind>, "t": <seconds since
start>, ...}``.  The event taxonomy lives in docs/OBSERVABILITY.md
("Search-quality telemetry").

Sinks: `add_sink(fn)` registers a callable receiving every emitted row
dict *before* serialization — how `obs.quality.QualityMonitor` derives
live convergence/calibration gauges from the same rows the file gets,
which is what makes its online values exactly reproducible offline
(`quality.replay` feeds the file's rows through the same code).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = ["enabled", "start", "stop", "emit", "emit_row", "flush",
           "path", "add_sink", "remove_sink", "read", "step_tells",
           "disabled_token", "SCHEMA_VERSION", "EVENT_KINDS",
           "DISABLED_TOKENS"]

SCHEMA_VERSION = 1

# the ONE disable vocabulary for journal paths — shared by the `ut`
# and `ut serve` --journal flags, UT_JOURNAL, and ut.config('journal')
# so the surfaces can never diverge on what "off" spells
DISABLED_TOKENS = ("0", "off", "false", "none")


def disabled_token(val) -> bool:
    """True when `val` spells "no journal" (None counts)."""
    return val is None or str(val).strip().lower() in DISABLED_TOKENS

# the closed event vocabulary; `read(strict=True)` (and the committed
# example's tier-1 test) reject rows outside it so the offline tools
# and the online monitor can never silently disagree about the stream.
# Per-TRIAL outcomes ride the `step` row as parallel arrays (qors,
# plus mus / sigmas when the surrogate was fitted at propose time):
# one JSON row per *ticket* keeps emission ~2 us/trial on the driver
# hot path where one row per trial measured ~15 us — the difference
# between holding and losing the >= 0.95x BENCH_OBS bar on a 1-core
# box.  Arrays at their default are OMITTED (compact encoding):
# absent `ok` = all true, absent `nb` = all false, absent `durs` =
# all zero, and contiguous gids collapse to `gid0` (else `gids`);
# `qors` is always present and defines the trial count
EVENT_KINDS = (
    "step",         # one ticket finalized: the arm pull's dedup /
                    # prune / filter verdicts (src, batch, trials,
                    # dup, filtered — captured at propose time),
                    # per-trial outcome arrays, credit, incumbent
    "store_hit",    # a build served from the result store
    "exchange",     # a sibling instance's best injected
    "federate",     # sibling (config, qor) rows fed to the surrogate
    "snapshot",     # surrogate snapshot published
    "feature",      # ut.feature covariates observed by a trial
    "interm",       # ut.interm intermediate feature vector
    "serve_tell",   # one serve-session tell (per-tenant stream)
)

_FLUSH_EVERY = 128

# one reusable encoder: ~25% cheaper per row than json.dumps (which
# re-resolves options per call) on the per-ticket emit path
_ENC = json.JSONEncoder(separators=(",", ":"),
                        check_circular=False).encode

_ENABLED = False
_T0 = 0.0
_PATH: Optional[str] = None
_F = None
_BUF: List[str] = []
_LOCK = threading.Lock()
_SINKS: List[Callable[[Dict[str, Any]], None]] = []


def enabled() -> bool:
    return _ENABLED


def path() -> Optional[str]:
    return _PATH


def start(out_path: str,
          meta: Optional[Dict[str, Any]] = None) -> str:
    """Open the journal at `out_path` (truncating — one file is one
    run) and write the header line.  Idempotent per path: starting the
    already-active path returns it unchanged; starting a different
    path stops the previous journal first."""
    global _ENABLED, _T0, _PATH, _F
    with _LOCK:
        if _ENABLED and _PATH == out_path:
            return out_path
    if _ENABLED:
        stop()
    f = open(out_path, "w")
    hdr = {"journal": SCHEMA_VERSION, "origin_unix": time.time(),
           "pid": os.getpid(), "meta": dict(meta or {})}
    f.write(json.dumps(hdr) + "\n")
    f.flush()
    with _LOCK:
        _F = f
        _PATH = out_path
        _BUF.clear()
        _T0 = time.perf_counter()
        _ENABLED = True
    return out_path


def stop() -> Optional[str]:
    """Flush and close; returns the path that was active.  Sinks stay
    registered — they belong to the caller, not the file."""
    global _ENABLED, _PATH, _F
    with _LOCK:
        _ENABLED = False
        f, p = _F, _PATH
        buf = _BUF[:]
        _BUF.clear()
        _F = None
        _PATH = None
        if f is not None:
            try:
                if buf:
                    f.write("".join(buf))
                f.close()
            except OSError:
                pass    # disk gone: journaling is best-effort
    return p


def add_sink(fn: Callable[[Dict[str, Any]], None]) -> None:
    with _LOCK:
        if fn not in _SINKS:
            _SINKS.append(fn)


def remove_sink(fn: Callable[[Dict[str, Any]], None]) -> None:
    with _LOCK:
        try:
            _SINKS.remove(fn)
        except ValueError:
            pass


def emit(ev: str, **fields: Any) -> None:
    """Record one event.  Every value must already be JSON-safe (the
    instrumented call sites cast device/numpy scalars to python floats
    and ints — the journal never touches a device buffer)."""
    if not _ENABLED:
        return
    # the kwargs dict IS the row (emit owns it): no second dict merge
    # on the hot path
    fields["ev"] = ev
    emit_row(fields)


def emit_row(row: Dict[str, Any]) -> None:
    """`emit` for callers that already hold the row dict (must carry
    "ev"; ownership transfers to the journal).  The driver's step
    emission uses this: re-packing ~20 fields through kwargs was
    measurable against the BENCH_OBS hot-path budget."""
    if not _ENABLED:
        return
    row["t"] = round(time.perf_counter() - _T0, 6)
    line = _ENC(row) + "\n"
    with _LOCK:
        if not _ENABLED:        # stop() raced us: drop, don't crash
            return
        _BUF.append(line)
        # sinks run UNDER the lock, so the online monitor folds rows
        # in exactly the order the file records them — concurrent
        # emitters (serve tenant threads, the async refit worker's
        # snapshot rows vs driver steps) must not be able to reorder
        # the monitor against the file, or the bit-exact
        # online == replay contract (obs/quality.py) breaks.  The
        # driver emit path is single-threaded, so this serializes
        # nothing there; no sink acquires this lock re-entrantly
        # (metrics/ring locks are leaf locks).
        for fn in _SINKS:
            fn(row)
        if len(_BUF) >= _FLUSH_EVERY:
            _write_locked()


def flush() -> None:
    with _LOCK:
        _write_locked()


def _write_locked() -> None:
    """Drain the buffer to disk.  Caller holds _LOCK — one lock keeps
    the buffer, the file handle, and stop() coherent (the registry-
    lock pattern of obs.metrics); the write itself is one buffered
    "".join at per-128-rows frequency, microseconds next to the
    per-ticket cadence feeding it."""
    if _F is None or not _BUF:
        return
    try:
        _F.write("".join(_BUF))
        _F.flush()
    except OSError:
        pass            # disk gone: best-effort
    _BUF.clear()


def step_tells(row: Dict[str, Any]):
    """Decode one step row's compact per-trial arrays into
    ``(gid, ok, qor, nb, dur, mu, sigma)`` tuples — THE reference
    decoder for the compact encoding documented on EVENT_KINDS
    (absent ``ok`` = all true, ``nb`` = all false, ``durs`` = all
    zero, contiguous ids as ``gid0``).  Offline consumers
    (`obs.report`) route through here; `QualityMonitor._on_step`
    keeps a fused inline copy of the SAME semantics for the hot path
    — an encoding change must update both or the report's tell table
    silently disagrees with the replayed gauges beside it."""
    qors = row.get("qors") or ()
    gids = row.get("gids")
    gid0 = row.get("gid0", 0)
    oks = row.get("ok")
    nbs = row.get("nb")
    durs = row.get("durs")
    mus = row.get("mus")
    sigmas = row.get("sigmas")
    for i in range(len(qors)):
        yield (gids[i] if gids is not None else gid0 + i,
               True if oks is None else oks[i],
               qors[i],
               False if nbs is None else nbs[i],
               0.0 if durs is None else durs[i],
               None if mus is None else mus[i],
               None if sigmas is None else sigmas[i])


def read(journal_path: str, strict: bool = False
         ) -> Tuple[Dict[str, Any], List[Dict[str, Any]]]:
    """(header, rows) from a journal file.  Unparseable trailing lines
    (a torn tail from a crashed writer) are dropped; `strict=True`
    raises ValueError on a bad header, an unknown event kind, or a
    torn row that is NOT the final line — the schema validation the
    committed example artifact is held to."""
    header: Dict[str, Any] = {}
    rows: List[Dict[str, Any]] = []
    bad_at: Optional[int] = None
    with open(journal_path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                bad_at = i
                continue
            if bad_at is not None and strict:
                raise ValueError(
                    f"{journal_path}:{bad_at + 1}: torn row in the "
                    f"middle of the stream")
            if i == 0 and "journal" in rec:
                header = rec
                continue
            if not isinstance(rec, dict) or "ev" not in rec:
                if strict:
                    raise ValueError(
                        f"{journal_path}:{i + 1}: not an event row: "
                        f"{line[:80]}")
                continue
            if strict and rec["ev"] not in EVENT_KINDS:
                raise ValueError(
                    f"{journal_path}:{i + 1}: unknown event kind "
                    f"{rec['ev']!r}; known: {EVENT_KINDS}")
            rows.append(rec)
    if strict:
        if header.get("journal") != SCHEMA_VERSION:
            raise ValueError(
                f"{journal_path}: missing/unsupported journal header "
                f"(want version {SCHEMA_VERSION}, got "
                f"{header.get('journal')!r})")
    return header, rows
