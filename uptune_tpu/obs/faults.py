"""Deterministic fault injection for the crash-safety planes (ISSUE 15).

The failover story — durable session checkpoints, lossless restart,
client auto-resume — is only as trustworthy as the crashes it was
tested against, and wall-clock SIGKILLs land wherever the scheduler
happens to be.  This registry gives tests and ``bench.py --failover``
*schedule-driven* faults instead: a named point in the code calls
``faults.fire("ckpt.append")`` and an armed schedule decides, purely
by hit count, whether that exact call crashes the process, sleeps, or
raises — the same run replays the same fault on every box.

Contract (the obs one-flag-check no-op pattern, same as `obs.span` /
`journal.emit`): ``fire`` checks one module-level bool FIRST and
returns immediately when nothing is armed — the call sites live in
the wire loops, the checkpoint appender, the store recorder and the
pool reaper permanently, at the cost of one flag check.  Arming is
test/bench-only, never a production mode.

Points (the seams future shard-failover work reuses):

* ``wire.accept``  — a connection was accepted (serve/wire.py)
* ``wire.read``    — a request line was read, before dispatch
* ``wire.reply``   — a response is about to be written
* ``ckpt.append``  — a session checkpoint record is about to be
  appended (serve/durable.py) — crashing HERE is the
  commit-vs-checkpoint window the bounded-loss contract is about
* ``store.record`` — a trial row is about to be recorded
* ``rstore.append`` — the networked store server (store/server.py)
  is about to durably append an accepted row — crashing HERE is the
  ack-after-durable window the zero-acked-loss contract is about
  (``bench.py --store-remote``'s deterministic kill)
* ``pool.reap``    — a worker-pool build is about to be reaped
* ``route.spawn``  — the front-tier router is about to spawn (or
  respawn) a shard process (serve/router.py)
* ``route.kill``   — fired once per router supervisor tick; arming it
  with ``error`` makes the supervisor SIGKILL its lowest-index live
  shard on that exact tick — the deterministic shard-death injection
  ``bench.py --serve-sharded`` replays on every box

Actions: ``crash`` (``os._exit`` — no atexit, no flush: the closest
in-process stand-in for SIGKILL), ``delay`` (sleep `param` seconds),
``error`` (raise ``FaultInjected``, an OSError the defensive walls
treat like any I/O failure).  A rule fires on exact hit number
(``at=N``, 1-based) or every N-th hit (``every=N``).

Env seam: ``UT_FAULTS="ckpt.append=crash@12,wire.read=delay@3:0.05"``
arms a child process at import-arming call sites (`ut serve` reads it
at startup) — how ``bench.py --failover`` crashes a real serving
process at a deterministic checkpoint append.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

__all__ = ["FaultInjected", "POINTS", "ACTIONS", "armed", "arm",
           "disarm", "fire", "hits", "schedules", "parse_spec",
           "maybe_arm_from_env", "ENV_VAR"]

ENV_VAR = "UT_FAULTS"

POINTS = ("wire.accept", "wire.read", "wire.reply", "ckpt.append",
          "store.record", "rstore.append", "pool.reap", "route.spawn",
          "route.kill")

ACTIONS = ("crash", "delay", "error")

CRASH_EXIT_CODE = 137           # what a SIGKILLed child's 128+9 reads as


class FaultInjected(OSError):
    """An armed `error` schedule fired at a fault point."""


class _Rule:
    """One armed schedule entry: action + when it fires."""

    __slots__ = ("action", "at", "every", "param", "fired")

    def __init__(self, action: str, at: Optional[int],
                 every: Optional[int], param: Optional[float]):
        if action not in ACTIONS:
            raise ValueError(
                f"unknown fault action {action!r}; valid: {ACTIONS}")
        if (at is None) == (every is None):
            raise ValueError("exactly one of at=/every= must be given")
        if at is not None and at < 1:
            raise ValueError(f"at= is a 1-based hit number: {at}")
        if every is not None and every < 1:
            raise ValueError(f"every= must be >= 1: {every}")
        self.action = action
        self.at = at
        self.every = every
        self.param = param
        self.fired = 0

    def matches(self, n: int) -> bool:
        if self.at is not None:
            return n == self.at
        return n % self.every == 0

    def describe(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"action": self.action,
                               "fired": self.fired}
        if self.at is not None:
            out["at"] = self.at
        if self.every is not None:
            out["every"] = self.every
        if self.param is not None:
            out["param"] = self.param
        return out


_ARMED = False                  # the ONE flag fire() checks first
_LOCK = threading.Lock()
_RULES: Dict[str, List[_Rule]] = {}
_HITS: Dict[str, int] = {}


def armed() -> bool:
    return _ARMED


def hits(point: Optional[str] = None):
    """Hit counters (all points, or one) — counted only while armed."""
    with _LOCK:
        if point is not None:
            return _HITS.get(point, 0)
        return dict(_HITS)


def schedules() -> Dict[str, List[Dict[str, Any]]]:
    with _LOCK:
        return {p: [r.describe() for r in rs]
                for p, rs in _RULES.items()}


def arm(point: str, action: str, *, at: Optional[int] = None,
        every: Optional[int] = None,
        param: Optional[float] = None) -> None:
    """Arm one schedule rule at a named point.  Unknown points are
    rejected eagerly — a typo must fail the test arming it, not
    silently never fire."""
    global _ARMED
    if point not in POINTS:
        raise ValueError(
            f"unknown fault point {point!r}; valid: {POINTS}")
    rule = _Rule(action, at, every, param)
    with _LOCK:
        _RULES.setdefault(point, []).append(rule)
        _ARMED = True


def disarm(point: Optional[str] = None) -> None:
    """Drop one point's schedules (or everything), resetting hit
    counters; the flag drops with the last schedule so disarmed cost
    returns to one flag check."""
    global _ARMED
    with _LOCK:
        if point is None:
            _RULES.clear()
            _HITS.clear()
        else:
            _RULES.pop(point, None)
            _HITS.pop(point, None)
        _ARMED = bool(_RULES)


def fire(point: str) -> None:
    """The call-site seam.  Disarmed: one module-flag check, nothing
    allocated, nothing locked (no **kwargs either — an empty kwargs
    dict per call would tax the disarmed wire/store hot paths).
    Armed: count the hit and apply any matching rule — crash exits
    the process immediately (no atexit, no buffered flush: the
    SIGKILL stand-in), delay sleeps, error raises FaultInjected for
    the caller's normal error walls."""
    if not _ARMED:
        return
    _fire(point)


def _fire(point: str) -> None:
    with _LOCK:
        n = _HITS.get(point, 0) + 1
        _HITS[point] = n
        todo = [r for r in _RULES.get(point, ()) if r.matches(n)]
        for r in todo:
            r.fired += 1
    for r in todo:
        if r.action == "crash":
            # os._exit, not sys.exit: no exception unwind, no atexit,
            # no flush — committed state must already be durable
            os._exit(int(r.param) if r.param is not None
                     else CRASH_EXIT_CODE)
        elif r.action == "delay":
            time.sleep(float(r.param) if r.param is not None else 0.05)
        else:
            raise FaultInjected(
                f"injected fault at {point} (hit {n})")


def parse_spec(spec: str) -> Iterator[Tuple[str, str, int, int,
                                            Optional[float]]]:
    """Parse the UT_FAULTS grammar into arm() argument tuples:
    ``point=action@N[:param]`` (exact hit) or
    ``point=action%N[:param]`` (every N-th), comma-separated.
    Yields (point, action, at, every, param) with exactly one of
    at/every non-zero."""
    for entry in str(spec).split(","):
        entry = entry.strip()
        if not entry:
            continue
        point, sep, rest = entry.partition("=")
        if not sep:
            raise ValueError(f"bad fault spec {entry!r}: no '='")
        param: Optional[float] = None
        if ":" in rest:
            rest, _, ptxt = rest.partition(":")
            param = float(ptxt)
        at = every = 0
        if "@" in rest:
            action, _, ntxt = rest.partition("@")
            at = int(ntxt)
        elif "%" in rest:
            action, _, ntxt = rest.partition("%")
            every = int(ntxt)
        else:
            action, at = rest, 1
        yield point.strip(), action.strip(), at, every, param


def maybe_arm_from_env(env: Optional[dict] = None) -> int:
    """``UT_FAULTS=<spec>`` arms this process's fault schedules (the
    seam bench.py --failover uses to crash a child `ut serve` at a
    deterministic fault-point hit).  Returns the number of rules
    armed; unset/empty arms nothing."""
    e = os.environ if env is None else env
    spec = e.get(ENV_VAR, "").strip()
    if not spec:
        return 0
    n = 0
    for point, action, at, every, param in parse_spec(spec):
        arm(point, action, at=at or None, every=every or None,
            param=param)
        n += 1
    return n
