"""Unified observability plane: cross-plane span tracing + metrics.

Every asynchronous plane this repo grew (PR 2 prefetch, PR 4 store,
PR 5 background refit, PR 6 batched engine) is instrumented through
this package's tiny module-level API:

    from uptune_tpu import obs

    with obs.span("ticket.propose", arm=name):   # timed span
        ...
    obs.event("ticket.open", gid=gid)            # instant event
    obs.count("store.hit")                       # counter
    obs.gauge("prefetch.depth", len(queue))      # gauge
    obs.observe("store.serve_ms", dt * 1e3)      # histogram

Everything is a no-op until `obs.enable()` (or a `--trace` / `UT_TRACE`
run): the disabled path is one module-flag check and allocates nothing,
so instrumentation stays in the hot paths permanently (BENCH_OBS.json
holds the measured cost of both paths).  When enabled, each thread
records into its own lock-free ring buffer; exporters turn the rings
into a Perfetto-viewable Chrome trace (one lane per thread / worker
slot), a metrics JSONL, and a text summary.  See docs/OBSERVABILITY.md
for the span taxonomy and metric names.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

from . import device, faults, flight, journal, quality, ship
from .core import (DEFAULT_CAPACITY, complete_span, device_span,
                   disable, emit_at, enable, enabled, event,
                   new_span_id, now, reset, snapshot, span,
                   trace_origin_unix)
from .export import (chrome_trace, prometheus_text, text_summary,
                     validate_trace, write_metrics_jsonl, write_trace)
from .metrics import count, counter_value, gauge, observe
from .metrics import snapshot as metrics_snapshot
from .metrics import window_snapshot

__all__ = [
    "enabled", "enable", "disable", "reset", "span", "device_span",
    "event", "complete_span", "emit_at", "new_span_id", "count",
    "gauge", "observe", "snapshot", "metrics_snapshot",
    "window_snapshot", "chrome_trace", "write_trace",
    "write_metrics_jsonl", "prometheus_text", "text_summary",
    "validate_trace", "now", "trace_origin_unix",
    "maybe_enable_from_env", "finish", "start_flight_recorder",
    "install_exit_flush", "instrument_device_fn", "DEFAULT_CAPACITY",
    "journal", "quality", "start_journal", "stop_journal",
    "maybe_journal_from_env", "device", "faults", "flight", "ship",
]


def start_journal(path: str, meta: Optional[Dict[str, Any]] = None,
                  monitor: bool = True):
    """Start the tuning journal (obs.journal) and, by default, attach
    a publishing `QualityMonitor` so convergence/calibration gauges
    ride the metrics registry, the flight recorder, and `ut top`'s
    search panel for free (docs/OBSERVABILITY.md "Search-quality
    telemetry").  Returns the monitor (or None)."""
    journal.start(path, meta=meta)
    return quality.attach() if monitor else None


def stop_journal(mon=None) -> Optional[str]:
    """Flush + close the journal; detaches `mon` when given."""
    if mon is not None:
        quality.detach(mon)
    return journal.stop()


def maybe_journal_from_env(env: Optional[dict] = None):
    """`UT_JOURNAL=<path>` starts the tuning journal for this process
    (the CLI's `--journal` flag layers above it).  Returns the
    attached QualityMonitor, or None when unset/disabled."""
    e = os.environ if env is None else env
    val = e.get("UT_JOURNAL", "").strip()
    if not val or journal.disabled_token(val):
        return None
    return start_journal(val)


def instrument_device_fn(fn, name: str, **attrs):
    """Wrap a jitted callable so every invocation records a
    `device_span` (host span + jax.profiler.TraceAnnotation) — the
    engine plane's seam: the whole fused/batched step loop is ONE
    compiled program, so its observability unit is the dispatch call.
    Since ISSUE 13 the wrapper is also the device-telemetry harvest
    point (`obs.device`): a program first dispatched while tracing is
    on compiles under an `engine.compile` span (persistent-cache
    hit/miss attributed) and publishes its XLA cost/memory analysis
    as `device.*` gauges.  The `.lower` attribute is forwarded for
    AOT compile / cost-analysis paths (bench.py); when tracing is
    disabled the wrapper costs one flag check."""
    return device.instrument(fn, name, **attrs)


def maybe_enable_from_env(env: Optional[dict] = None) -> Optional[str]:
    """`UT_TRACE=<path>` turns tracing on for this process (bench.py /
    `ut` CLI hook; the CLI's `--trace` flag and `ut.config('trace')`
    layer above it).  Returns the trace output path when enabled,
    None otherwise.  `UT_TRACE=1` enables recording without a
    default output path (callers export explicitly)."""
    e = os.environ if env is None else env
    val = e.get("UT_TRACE", "").strip()
    if not val or val.lower() in ("0", "off", "false", "none"):
        return None
    enable()
    return None if val.lower() in ("1", "true", "yes", "on") else val


def finish(path: Optional[str],
           extra: Optional[Dict[str, Any]] = None,
           metrics_path: Optional[str] = None) -> Optional[dict]:
    """End-of-run export: write the Chrome trace to `path` and settle
    the metrics sidecar next to it (`<path>.metrics.jsonl` unless
    `metrics_path` overrides) — when a flight recorder is running on
    that sidecar it is stopped (writing its final timeline row);
    otherwise one legacy metrics-snapshot line is appended.  Returns
    the trace document.  A None path skips the files (summary-only
    callers).  Recording stays enabled — callers own
    disable()/reset()."""
    if not enabled():
        return None
    doc = None
    if path:
        doc = write_trace(path, extra=extra)
        mpath = metrics_path or path + ".metrics.jsonl"
        rec = flight.active_for(mpath)
        if rec is not None:
            rec.stop()
        elif not flight.had_recorder(mpath):
            write_metrics_jsonl(mpath,
                                extra={"trace": os.path.basename(path)})
        # this path is settled: the exit-flush hook must not overwrite
        # the document (it would drop caller extras like the
        # trace-guard report written on the clean path)
        _FLUSH_REGISTRY.pop(path, None)
    return doc


def start_flight_recorder(trace_path: str,
                          interval: float = flight.DEFAULT_INTERVAL,
                          metrics_path: Optional[str] = None,
                          max_rows: int = flight.DEFAULT_MAX_ROWS,
                          rotate: int = flight.DEFAULT_ROTATE
                          ) -> "flight.FlightRecorder":
    """Start the periodic metrics timeline for a traced run, on the
    same `<trace>.metrics.jsonl` sidecar `finish()` settles (so the
    one-shot scrape becomes a timeline, not a second file).  `ut top
    --metrics <sidecar>` tails it live; `interval <= 0` is rejected by
    the caller layer ('off').  `rotate` is the generation-chain depth
    kept past the row cap (`--metrics-rotate`; default 1, the
    historical single-`.1` behavior)."""
    return flight.start(metrics_path or trace_path + ".metrics.jsonl",
                        interval=interval, max_rows=max_rows,
                        extra={"trace": os.path.basename(trace_path)},
                        rotate=rotate)


# ------------------------------------------------------- exit flushing
# a run interrupted by ^C (or a supervisor's SIGTERM) must still leave
# a valid — merely truncated — trace and a metrics tail on disk.  The
# registry maps trace path -> extra dict; one set of hooks flushes all.
_FLUSH_REGISTRY: Dict[str, Dict[str, Any]] = {}
_FLUSH_STATE: Dict[str, Any] = {"hooked": False, "flushing": False,
                                "reason": None}


def _flush_all(reason: str) -> None:
    if _FLUSH_STATE["flushing"]:
        return              # re-entrant call during a flush
    _FLUSH_STATE["flushing"] = True
    try:
        for path, extra in list(_FLUSH_REGISTRY.items()):
            try:
                finish(path, extra={**extra, "flushed_on": reason})
            except OSError:
                pass        # output dir vanished: nothing to save to
        # the tuning journal's buffered tail rides the same graceful
        # flush: an interrupted run keeps its search telemetry too
        journal.flush()
        # and the fleet shipper's final window: a SIGTERM'd process
        # ships its terminal counters before the interpreter dies, so
        # the hub's exactness contract (fleet counters == the sum of
        # per-source finals) holds through graceful shutdowns
        ship.stop()
        # an active jax.profiler capture must also settle, or the
        # XPlane dump is lost on exactly the failed/^C runs one most
        # wants to profile (stop_trace is idempotent-safe when no
        # capture is active)
        try:
            device.stop_trace()
        except Exception:
            pass
    finally:
        _FLUSH_STATE["flushing"] = False


def _flush_atexit() -> None:
    _flush_all(_FLUSH_STATE["reason"] or "atexit")


def install_exit_flush(path: Optional[str],
                       extra: Optional[Dict[str, Any]] = None) -> None:
    """Register `path` for graceful telemetry flushing: the trace (and
    the flight recorder's final row) is written at interpreter exit,
    not only on the clean end-of-run `finish()` path — including exits
    forced by SIGINT/SIGTERM.  `path=None` installs the hooks without
    registering a trace — the journal-without-trace shape: a SIGTERM'd
    `ut serve --journal` must still flush its buffered journal tail
    (and unwind through the server's own finally), even though there
    is no trace document to write.  The signal handlers themselves do NO
    I/O and take NO locks: a Python signal handler runs on the main
    thread between bytecodes, possibly inside a frame that already
    holds the (non-reentrant) metrics/ring locks the flush needs, so
    flushing inline could deadlock the very ^C it serves.  Instead the
    handler records the reason and unwinds (KeyboardInterrupt /
    SystemExit), and the atexit hook — running after the stack, and
    therefore every lock, is released — performs the actual flush,
    tagged with the recorded signal.  Handlers chain to whatever was
    installed before (default SIGINT behavior is preserved);
    installation is skipped silently off the main thread, where Python
    forbids signal handlers.  Idempotent per path."""
    import atexit
    import signal
    import sys

    if path is not None:
        _FLUSH_REGISTRY[path] = dict(extra or {})
    if _FLUSH_STATE["hooked"]:
        return
    _FLUSH_STATE["hooked"] = True
    atexit.register(_flush_atexit)

    def _chain(sig, prev):
        def handler(signum, frame):
            _FLUSH_STATE["reason"] = f"signal:{signum}"
            if callable(prev):
                prev(signum, frame)
            elif signum == signal.SIGINT:
                raise KeyboardInterrupt
            else:
                sys.exit(128 + signum)
        return handler

    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            signal.signal(sig, _chain(sig, signal.getsignal(sig)))
        except (ValueError, OSError):
            pass            # non-main thread / unsupported platform
