"""Unified observability plane: cross-plane span tracing + metrics.

Every asynchronous plane this repo grew (PR 2 prefetch, PR 4 store,
PR 5 background refit, PR 6 batched engine) is instrumented through
this package's tiny module-level API:

    from uptune_tpu import obs

    with obs.span("ticket.propose", arm=name):   # timed span
        ...
    obs.event("ticket.open", gid=gid)            # instant event
    obs.count("store.hit")                       # counter
    obs.gauge("prefetch.depth", len(queue))      # gauge
    obs.observe("store.serve_ms", dt * 1e3)      # histogram

Everything is a no-op until `obs.enable()` (or a `--trace` / `UT_TRACE`
run): the disabled path is one module-flag check and allocates nothing,
so instrumentation stays in the hot paths permanently (BENCH_OBS.json
holds the measured cost of both paths).  When enabled, each thread
records into its own lock-free ring buffer; exporters turn the rings
into a Perfetto-viewable Chrome trace (one lane per thread / worker
slot), a metrics JSONL, and a text summary.  See docs/OBSERVABILITY.md
for the span taxonomy and metric names.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

from .core import (DEFAULT_CAPACITY, complete_span, device_span,
                   disable, enable, enabled, event, now, reset,
                   snapshot, span, trace_origin_unix)
from .export import (chrome_trace, text_summary, validate_trace,
                     write_metrics_jsonl, write_trace)
from .metrics import count, counter_value, gauge, observe
from .metrics import snapshot as metrics_snapshot

__all__ = [
    "enabled", "enable", "disable", "reset", "span", "device_span",
    "event", "complete_span", "count", "gauge", "observe", "snapshot",
    "metrics_snapshot", "chrome_trace", "write_trace",
    "write_metrics_jsonl", "text_summary", "validate_trace", "now",
    "trace_origin_unix", "maybe_enable_from_env", "finish",
    "instrument_device_fn", "DEFAULT_CAPACITY",
]


def instrument_device_fn(fn, name: str, **attrs):
    """Wrap a jitted callable so every invocation records a
    `device_span` (host span + jax.profiler.TraceAnnotation) — the
    engine plane's seam: the whole fused/batched step loop is ONE
    compiled program, so its observability unit is the dispatch call.
    The `.lower` attribute is forwarded for AOT compile / cost-analysis
    paths (bench.py); when tracing is disabled the wrapper costs one
    flag check."""
    import functools

    @functools.wraps(fn)
    def wrapper(*a, **kw):
        if not enabled():
            return fn(*a, **kw)
        with device_span(name, **attrs):
            return fn(*a, **kw)

    if hasattr(fn, "lower"):
        wrapper.lower = fn.lower
    return wrapper


def maybe_enable_from_env(env: Optional[dict] = None) -> Optional[str]:
    """`UT_TRACE=<path>` turns tracing on for this process (bench.py /
    `ut` CLI hook; the CLI's `--trace` flag and `ut.config('trace')`
    layer above it).  Returns the trace output path when enabled,
    None otherwise.  `UT_TRACE=1` enables recording without a
    default output path (callers export explicitly)."""
    e = os.environ if env is None else env
    val = e.get("UT_TRACE", "").strip()
    if not val or val.lower() in ("0", "off", "false", "none"):
        return None
    enable()
    return None if val.lower() in ("1", "true", "yes", "on") else val


def finish(path: Optional[str],
           extra: Optional[Dict[str, Any]] = None,
           metrics_path: Optional[str] = None) -> Optional[dict]:
    """End-of-run export: write the Chrome trace to `path`, append one
    metrics-snapshot line next to it (`<path>.metrics.jsonl` unless
    `metrics_path` overrides), and return the trace document.  A None
    path skips the files (summary-only callers).  Recording stays
    enabled — callers own disable()/reset()."""
    if not enabled():
        return None
    doc = None
    if path:
        doc = write_trace(path, extra=extra)
        write_metrics_jsonl(metrics_path or path + ".metrics.jsonl",
                            extra={"trace": os.path.basename(path)})
    return doc
