"""Exporters: Chrome trace-event JSON (Perfetto), metrics JSONL, text
summary.

The trace JSON follows the Chrome trace-event format's flavor that
Perfetto ingests directly (https://ui.perfetto.dev -> open file):

* one ``pid`` (the tuning process), one ``tid`` per LANE — a lane is
  either a real thread (driver MainThread, the ``ut-surrogate-refit``
  worker) or a synthetic track (``worker-N`` build slots, emitted by
  the driver thread at reap time with the slot's own timestamps) — so
  the background refit and every WorkerPool slot render as horizontal
  lanes against the driver's ticket spans;
* complete spans are ``"ph": "X"`` events with microsecond ``ts`` /
  ``dur``; instants are ``"ph": "i"`` scope-thread events; lane names
  arrive as ``"ph": "M"`` thread_name metadata records.

`validate_trace` is the schema contract: the round-trip test and the
committed example artifact are both held to it.
"""
from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

from . import core, metrics

__all__ = ["chrome_trace", "write_trace", "write_metrics_jsonl",
           "text_summary", "validate_trace", "prometheus_text"]

PID = 1


def _lane_order(track: str) -> tuple:
    """Sort key: driver thread first, worker slots next (numeric), then
    auxiliary threads (refit worker, ...)."""
    if track == "MainThread":
        return (0, 0, track)
    if track.startswith("worker-"):
        try:
            return (1, int(track.split("-", 1)[1]), track)
        except ValueError:
            return (1, 1 << 30, track)
    return (2, 0, track)


def chrome_trace(snap: Optional[Dict[str, Any]] = None,
                 extra: Optional[Dict[str, Any]] = None
                 ) -> Dict[str, Any]:
    """Build the Chrome trace-event document from a core snapshot
    (default: the live rings) plus the metrics snapshot."""
    if snap is None:
        snap = core.snapshot()
    tracks: List[str] = []
    for e in snap["events"]:
        if e["track"] not in tracks:
            tracks.append(e["track"])
    tracks.sort(key=_lane_order)
    tid_of = {t: i + 1 for i, t in enumerate(tracks)}
    events: List[Dict[str, Any]] = []
    for t, tid in tid_of.items():
        events.append({"ph": "M", "pid": PID, "tid": tid,
                       "name": "thread_name", "args": {"name": t}})
        events.append({"ph": "M", "pid": PID, "tid": tid,
                       "name": "thread_sort_index",
                       "args": {"sort_index": _lane_order(t)[0] * 1000
                                + _lane_order(t)[1]}})
    for e in snap["events"]:
        rec: Dict[str, Any] = {
            "name": e["name"],
            "cat": e["name"].split(".", 1)[0],
            "pid": PID,
            "tid": tid_of[e["track"]],
            "ts": round(e["ts"] * 1e6, 3),
        }
        if e["dur"] is None:
            rec["ph"] = "i"
            rec["s"] = "t"
        else:
            rec["ph"] = "X"
            rec["dur"] = round(e["dur"] * 1e6, 3)
        if e["attrs"]:
            rec["args"] = e["attrs"]
        events.append(rec)
    other: Dict[str, Any] = {
        "origin_unix": snap.get("origin_unix", 0.0),
        "dropped": snap.get("dropped", {}),
        "metrics": metrics.snapshot(),
    }
    # combined-profile reference (ISSUE 13): when a programmatic
    # jax.profiler capture ran (`ut --device-trace` / UT_DEVICE_TRACE),
    # point at its XPlane dump dir so the host trace and the XLA
    # kernel profile open side by side (docs/OBSERVABILITY.md)
    from . import device as _device
    if _device.trace_dir():
        other["device_trace"] = _device.trace_dir()
    if extra:
        other.update(extra)
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": other}


def write_trace(path: str, extra: Optional[Dict[str, Any]] = None
                ) -> Dict[str, Any]:
    """Write the Perfetto-viewable trace JSON; returns the document."""
    doc = chrome_trace(extra=extra)
    with open(path, "w") as f:
        json.dump(doc, f)
    return doc


def write_metrics_jsonl(path: str,
                        extra: Optional[Dict[str, Any]] = None) -> None:
    """Append ONE metrics-snapshot line (a scrape row): counters,
    gauges, histogram summaries, wall-clock timestamp."""
    row = {"t": round(time.time(), 3), **metrics.snapshot()}
    if extra:
        row.update(extra)
    with open(path, "a") as f:
        f.write(json.dumps(row) + "\n")


def validate_trace(doc: Any) -> None:
    """Schema contract for the exported trace (raises ValueError):
    every event has ph/pid/tid/name; X events carry numeric ts and
    dur >= 0; instants carry ts; every (pid, tid) used by a timed
    event has a thread_name metadata record.  Lanes are keyed by the
    (pid, tid) PAIR — tids are per-process in the Chrome format, so a
    merged multi-process document (`ut-trace merge`) legitimately
    reuses tid 1 under every pid."""
    def fail(msg):
        raise ValueError(f"trace schema: {msg}")

    if not isinstance(doc, dict) or "traceEvents" not in doc:
        fail("document must be a dict with a 'traceEvents' list")
    evs = doc["traceEvents"]
    if not isinstance(evs, list):
        fail("'traceEvents' must be a list")
    named_lanes = set()
    used_lanes = set()
    for i, e in enumerate(evs):
        if not isinstance(e, dict):
            fail(f"event {i} is not an object")
        for k in ("ph", "pid", "tid", "name"):
            if k not in e:
                fail(f"event {i} missing {k!r}")
        if e["ph"] == "M":
            if e["name"] == "thread_name":
                if not e.get("args", {}).get("name"):
                    fail(f"event {i}: thread_name without args.name")
                named_lanes.add((e["pid"], e["tid"]))
            continue
        if e["ph"] not in ("X", "i", "C"):
            fail(f"event {i}: unknown phase {e['ph']!r}")
        if not isinstance(e.get("ts"), (int, float)):
            fail(f"event {i}: non-numeric ts")
        used_lanes.add((e["pid"], e["tid"]))
        if e["ph"] == "X":
            d = e.get("dur")
            if not isinstance(d, (int, float)) or d < 0:
                fail(f"event {i}: X event needs dur >= 0")
        if "args" in e:
            try:
                json.dumps(e["args"])
            except (TypeError, ValueError):
                fail(f"event {i}: args not JSON-serializable")
    missing = used_lanes - named_lanes
    if missing:
        fail(f"lanes {sorted(missing)} have events but no thread_name "
             f"metadata (they would be anonymous in Perfetto)")


def _prom_name(name: str) -> str:
    """Metric-registry name -> Prometheus metric name: dots and every
    other illegal character become underscores, one `ut_` namespace
    prefix."""
    out = []
    for ch in name:
        out.append(ch if (ch.isascii() and (ch.isalnum() or ch == "_"))
                   else "_")
    s = "".join(out)
    if s and s[0].isdigit():
        s = "_" + s
    return "ut_" + s


def prometheus_text(snap: Optional[Dict[str, Any]] = None) -> str:
    """Prometheus text exposition (version 0.0.4) of a metrics
    snapshot: counters as `counter`, gauges as `gauge`, histogram
    summaries as `summary` (quantile series + `_sum`/`_count`).  The
    serve `{"op": "metrics", "format": "prometheus"}` scrape returns
    this string so a textfile-collector / sidecar exporter can relay
    the registry without learning the JSON schema."""
    if snap is None:
        snap = metrics.snapshot()
    lines: List[str] = []
    for k in sorted(snap.get("counters", {})):
        n = _prom_name(k)
        lines.append(f"# TYPE {n} counter")
        lines.append(f"{n} {snap['counters'][k]:g}")
    for k in sorted(snap.get("gauges", {})):
        n = _prom_name(k)
        lines.append(f"# TYPE {n} gauge")
        lines.append(f"{n} {snap['gauges'][k]:g}")
    for k in sorted(snap.get("hists", {})):
        h = snap["hists"][k]
        n = _prom_name(k)
        lines.append(f"# TYPE {n} summary")
        for q, key in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
            if h.get(key) is not None:
                lines.append(f'{n}{{quantile="{q}"}} {h[key]:g}')
        lines.append(f"{n}_sum {h.get('sum', 0):g}")
        lines.append(f"{n}_count {h.get('count', 0):g}")
    return "\n".join(lines) + ("\n" if lines else "")


def text_summary(snap: Optional[Dict[str, Any]] = None) -> str:
    """End-of-run human summary: per-span-name count/total/mean, the
    counters and gauges, histogram percentiles, and drop warnings."""
    if snap is None:
        snap = core.snapshot()
    per: Dict[str, List[float]] = {}
    insts: Dict[str, int] = {}
    for e in snap["events"]:
        if e["dur"] is None:
            insts[e["name"]] = insts.get(e["name"], 0) + 1
        else:
            per.setdefault(e["name"], []).append(e["dur"])
    lines = ["== obs summary =="]
    if per:
        lines.append("spans (count / total s / mean ms):")
        for name in sorted(per):
            ds = per[name]
            lines.append(f"  {name:<28} {len(ds):>6}  "
                         f"{sum(ds):>9.3f}  "
                         f"{1e3 * sum(ds) / len(ds):>9.3f}")
    if insts:
        lines.append("events:")
        for name in sorted(insts):
            lines.append(f"  {name:<28} {insts[name]:>6}")
    m = metrics.snapshot()
    if m["counters"]:
        lines.append("counters:")
        for k in sorted(m["counters"]):
            lines.append(f"  {k:<28} {m['counters'][k]:>10g}")
    if m["gauges"]:
        lines.append("gauges:")
        for k in sorted(m["gauges"]):
            lines.append(f"  {k:<28} {m['gauges'][k]:>10g}")
    if m["hists"]:
        lines.append("histograms:")
        for k in sorted(m["hists"]):
            h = m["hists"][k]
            lines.append(
                f"  {k:<28} n={h['count']} mean={h['mean']} "
                f"p50={h.get('p50')} p95={h.get('p95')} "
                f"max={h['max']}")
    if snap.get("dropped"):
        lines.append(f"DROPPED events (ring capacity exceeded): "
                     f"{snap['dropped']}")
    return "\n".join(lines)
