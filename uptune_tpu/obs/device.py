"""Device-plane telemetry: compile/cost/roofline observability for
every engine program (ISSUE 13).

The obs plane watches the host system (spans, metrics, distributed
traces) and the search itself (journal, quality gauges); this module
watches the DEVICE — what each compiled engine program costs in
FLOPs/bytes/memory, how long its compiles took (and whether the
persistent XLA compile cache served them), and how the achieved
rates over measured step windows sit against the chip's published
roofline peaks.  Three layers:

* **Harvest** — `harvest(compiled)` reads XLA's own
  ``cost_analysis()`` + ``memory_analysis()`` for a compiled program:
  flops, bytes accessed, transcendentals, and peak temp/argument/
  output/code memory.  `instrument(fn, name)` (the implementation
  behind ``obs.instrument_device_fn``, the seam already wrapping
  FusedEngine/BatchedEngine ``jit_run`` and the driver's per-arm
  programs) harvests automatically at compile time: the first traced
  call lowers + compiles the program under an ``engine.compile`` span
  (with persistent compile-cache hit/miss attribution from
  ``jax.monitoring`` events) and reuses the AOT executable for every
  later dispatch — same single trace, same compile, plus the cost
  model read while the compiler state is in hand.
* **Registry + gauges** — per-program records (`programs()`) publish
  ``device.*`` counters/gauges into ``obs.metrics``, so the flight
  recorder, the Prometheus exposition, the serve metrics scrape,
  ``ut top``'s device panel, and ``ut report``'s "Device & compile"
  section all carry them for free.  `record_window(name, wall_s)`
  turns a MEASURED step window (caller-blocked wall, as bench.py
  records) into achieved flops/s + HBM B/s and MXU/HBM utilization
  against `PEAKS` — the per-platform peak table promoted out of
  bench.py.  Dispatch-window rates are also published per call; they
  are an upper bound for async callers (the dispatch may return
  before the device finishes), so artifact numbers come from
  `record_window` over explicitly blocked reps.
* **Profiler capture** — `start_trace(dir)` / `stop_trace()` wrap
  ``jax.profiler`` so ``ut --device-trace DIR`` / ``UT_DEVICE_TRACE``
  dump an XPlane profile whose directory is referenced from the
  Chrome-trace export (``otherData.device_trace``): host spans and
  XLA kernels land in one combined Perfetto view
  (docs/OBSERVABILITY.md "Device telemetry").

Disabled is free, same contract as the rest of the package: every
entry point checks the core enabled flag first; the disabled
instrument path is one flag check + one dict write and returns the
shared no-op singleton's behavior (no spans, no metrics, no
registry).  jax itself is imported lazily — importing obs must not
initialize a backend.
"""
from __future__ import annotations

import functools
import os
import threading
import time
from typing import Any, Dict, Optional, Tuple

from . import core, metrics

__all__ = [
    "PEAKS", "resolve_peaks", "utilization", "harvest",
    "validate_record", "instrument", "record_window", "programs",
    "compile_totals", "reset_registry", "start_trace", "stop_trace",
    "trace_dir", "maybe_trace_from_env",
]

# Published per-chip peaks for roofline estimates, promoted out of
# bench.py (ISSUE 13): substring of device_kind -> (peak flops/s,
# peak HBM B/s).  Upper bounds from public per-chip specs; the bf16
# MXU peak is quoted even though the engines run f32, so a flops
# utilization read against it is a conservative lower bound on
# achievable MFU.  Unknown devices (CPU, future chips) resolve to
# None and get NO utilization claims — an estimate against a made-up
# peak would be worse than silence.
PEAKS: Dict[str, Tuple[float, float]] = {
    "v6": (918e12, 1640e9),
    "v5p": (459e12, 2765e9),
    "v5e": (197e12, 819e9),
    "v5 lite": (197e12, 819e9),
    "v4": (275e12, 1200e9),
    "v3": (123e12, 900e9),
    "v2": (45e12, 700e9),
}


def resolve_peaks(device_kind: Optional[str]
                  ) -> Optional[Tuple[float, float]]:
    """(peak_flops_per_s, peak_hbm_bytes_per_s) for a device_kind, by
    case-insensitive substring match against `PEAKS`; None when the
    device is unknown (no roofline claims for it)."""
    kind = (device_kind or "").lower()
    for sub, peaks in PEAKS.items():
        if sub in kind:
            return peaks
    return None


def utilization(device_kind: Optional[str],
                flops_per_s: Optional[float] = None,
                bytes_per_s: Optional[float] = None) -> Dict[str, Any]:
    """Roofline utilization vs the published per-chip peaks — the
    shape bench.py's artifacts carry: empty for unknown devices,
    peaks always present for known ones, `mxu_util`/`hbm_util` when
    the achieved rates are given."""
    peaks = resolve_peaks(device_kind)
    if peaks is None:
        return {}
    pf, pb = peaks
    out: Dict[str, Any] = {"peak_flops_per_s": pf,
                           "peak_hbm_bytes_per_s": pb}
    if flops_per_s:
        out["mxu_util"] = round(flops_per_s / pf, 6)
    if bytes_per_s:
        out["hbm_util"] = round(bytes_per_s / pb, 4)
    return out


# ------------------------------------------------------------ harvest
def harvest(compiled) -> Dict[str, Any]:
    """XLA's cost + memory analysis for one compiled program.

    Always returns the full schema (`validate_record`); fields the
    backend doesn't expose are None.  ``flops`` / ``bytes_accessed``
    come from the compiler's cost model over the whole program;
    ``peak_memory`` is the executable's own allocation plan
    (temp/argument/output/generated-code bytes)."""
    rec: Dict[str, Any] = {"flops": None, "bytes_accessed": None,
                           "transcendentals": None, "peak_memory": None}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):   # one entry per computation
            ca = ca[0] if ca else {}
        for field, key in (("flops", "flops"),
                           ("bytes_accessed", "bytes accessed"),
                           ("transcendentals", "transcendentals")):
            v = ca.get(key)
            if v:
                rec[field] = float(v)
    except Exception:       # backend-dependent: absent, not an error
        pass
    try:
        ma = compiled.memory_analysis()
        rec["peak_memory"] = {
            "temp_bytes": int(ma.temp_size_in_bytes),
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "alias_bytes": int(ma.alias_size_in_bytes),
            "generated_code_bytes": int(ma.generated_code_size_in_bytes),
        }
    except Exception:
        pass
    if rec["flops"] and rec["bytes_accessed"]:
        rec["arith_intensity"] = round(
            rec["flops"] / rec["bytes_accessed"], 6)
    else:
        rec["arith_intensity"] = None
    return rec


_MEM_KEYS = ("temp_bytes", "argument_bytes", "output_bytes",
             "alias_bytes", "generated_code_bytes")


def validate_record(rec: Any) -> None:
    """Schema contract for a harvested cost record (raises ValueError)
    — what tests and artifact consumers hold `harvest` output to."""
    def fail(msg):
        raise ValueError(f"device record schema: {msg}")

    if not isinstance(rec, dict):
        fail("record must be a dict")
    for k in ("flops", "bytes_accessed", "transcendentals",
              "arith_intensity"):
        if k not in rec:
            fail(f"missing {k!r}")
        v = rec[k]
        if v is not None and (not isinstance(v, (int, float))
                              or v < 0):
            fail(f"{k!r} must be a non-negative number or None")
    if "peak_memory" not in rec:
        fail("missing 'peak_memory'")
    pm = rec["peak_memory"]
    if pm is not None:
        if not isinstance(pm, dict):
            fail("'peak_memory' must be a dict or None")
        for k in _MEM_KEYS:
            if not isinstance(pm.get(k), int) or pm[k] < 0:
                fail(f"peak_memory.{k} must be a non-negative int")


# ----------------------------------------------------------- registry
_LOCK = threading.Lock()
_PROGRAMS: Dict[str, Dict[str, Any]] = {}
_COMPILES = 0           # process totals (read without the lock: two
_COMPILE_S = 0.0        # GIL-atomic reads for StepStats deltas)
_TLS = threading.local()   # .program: name being compiled right now
_LISTENER = {"installed": False}


def _program(name: str) -> Dict[str, Any]:
    rec = _PROGRAMS.get(name)
    if rec is None:
        rec = _PROGRAMS[name] = {
            "name": name, "cost": None, "compiles": 0,
            "compile_s": 0.0, "cache": None, "cache_hits": 0,
            "cache_misses": 0, "dispatches": 0, "dispatch_s": 0.0,
        }
    return rec


def programs() -> Dict[str, Dict[str, Any]]:
    """Per-program telemetry records (copies): harvested cost/memory,
    compile count/time, cache attribution, dispatch totals."""
    with _LOCK:
        return {k: dict(v) for k, v in _PROGRAMS.items()}


def compile_totals() -> Tuple[int, float]:
    """(compile count, compile seconds) since enable — the cheap
    getter driver StepStats reads deltas of (0 when telemetry never
    ran)."""
    return _COMPILES, _COMPILE_S


def reset_registry() -> None:
    global _COMPILES, _COMPILE_S
    with _LOCK:
        _PROGRAMS.clear()
        _COMPILES = 0
        _COMPILE_S = 0.0


def _on_monitoring_event(event: str, **kw) -> None:
    """jax.monitoring listener: persistent compile-cache hits/misses,
    attributed to the program whose harvest compile is running on this
    thread (or to '(other)' for compiles outside the instrument seam:
    surrogate fits, user programs)."""
    if not core._ENABLED:
        return
    if event.endswith("/cache_hits"):
        kind = "cache_hits"
    elif event.endswith("/cache_misses"):
        kind = "cache_misses"
    else:
        return
    name = getattr(_TLS, "program", None) or "(other)"
    metrics.count(f"device.compile_{kind}")
    with _LOCK:
        _program(name)[kind] += 1


def _install_listener() -> None:
    """Register the cache-event listener ONCE per process (the jax
    monitoring registry has no unregister; the callback is inert while
    tracing is off)."""
    if _LISTENER["installed"]:
        return
    _LISTENER["installed"] = True
    try:
        from jax import monitoring
        monitoring.register_event_listener(_on_monitoring_event)
    except Exception:
        pass        # older jax without monitoring: attribution absent


def _publish_cost(name: str, rec: Dict[str, Any]) -> None:
    cost = rec.get("cost") or {}
    if cost.get("flops"):
        metrics.gauge(f"device.flops.{name}", cost["flops"])
    if cost.get("bytes_accessed"):
        metrics.gauge(f"device.bytes.{name}", cost["bytes_accessed"])
    if cost.get("arith_intensity"):
        metrics.gauge(f"device.arith_intensity.{name}",
                      cost["arith_intensity"])
    pm = cost.get("peak_memory")
    if pm:
        metrics.gauge(f"device.mem_temp_bytes.{name}", pm["temp_bytes"])
        metrics.gauge(f"device.mem_arg_bytes.{name}",
                      pm["argument_bytes"])
        metrics.gauge(f"device.mem_out_bytes.{name}",
                      pm["output_bytes"])
    metrics.gauge(f"device.compile_ms.{name}",
                  round(rec["compile_s"] * 1e3, 3))
    with _LOCK:
        metrics.gauge("device.programs", len(_PROGRAMS))


def _harvest_compiled(name: str, fn, args, kwargs):
    """First traced call of an instrumented program: lower + compile
    it AOT under an `engine.compile` span, harvest the cost model,
    attribute the persistent-cache outcome, publish gauges.  Returns
    the compiled executable (reused for every later dispatch — the
    lowering IS the program's one trace), or None when the program
    can't take the AOT path (no .lower, lowering failed)."""
    global _COMPILES, _COMPILE_S
    _install_listener()
    try:
        lowered = fn.lower(*args, **kwargs)
    except Exception:
        return None
    _TLS.program = name
    h0, m0 = None, None
    with _LOCK:
        rec = _program(name)
        h0, m0 = rec["cache_hits"], rec["cache_misses"]
    t0 = time.perf_counter()
    with core.span("engine.compile", program=name) as sp:
        try:
            compiled = lowered.compile()
        except Exception:
            _TLS.program = None
            return None
        dur = time.perf_counter() - t0
        _TLS.program = None
        cost = harvest(compiled)
        with _LOCK:
            rec = _program(name)
            rec["cost"] = cost
            rec["compiles"] += 1
            rec["compile_s"] += dur
            dh = rec["cache_hits"] - h0
            dm = rec["cache_misses"] - m0
            # one compile usually consults the cache once; a hit that
            # also missed sub-computations still counts as a miss (the
            # big executable was built, not loaded)
            rec["cache"] = ("miss" if dm else
                            "hit" if dh else "off")
            _COMPILES += 1
            _COMPILE_S += dur
        sp.set(ms=round(dur * 1e3, 3), cache=rec["cache"],
               flops=cost.get("flops"),
               bytes=cost.get("bytes_accessed"))
    metrics.count("device.compiles")
    metrics.observe("device.compile_ms", dur * 1e3)
    _publish_cost(name, rec)
    return compiled


def _record_dispatch(name: str, dur: float) -> None:
    metrics.count("device.dispatches")
    metrics.observe("device.dispatch_ms", dur * 1e3)
    with _LOCK:
        rec = _program(name)
        rec["dispatches"] += 1
        rec["dispatch_s"] += dur


def _device_kind() -> str:
    try:
        import jax
        return getattr(jax.devices()[0], "device_kind", "") or ""
    except Exception:
        return ""


def record_window(name: str, wall_s: float,
                  device_kind: Optional[str] = None) -> Dict[str, Any]:
    """Publish achieved-rate + utilization gauges for one MEASURED
    step window of program `name` (caller-blocked wall seconds, the
    honest denominator — bench.py blocks around its reps and calls
    this).  Returns the computed fields; no-op-empty when telemetry
    is off or the program has no harvested cost."""
    if not core._ENABLED or wall_s <= 0:
        return {}
    with _LOCK:
        rec = _PROGRAMS.get(name)
        cost = dict(rec["cost"]) if rec and rec.get("cost") else None
    if not cost:
        return {}
    kind = _device_kind() if device_kind is None else device_kind
    out: Dict[str, Any] = {}
    flops, nbytes = cost.get("flops"), cost.get("bytes_accessed")
    if flops:
        out["achieved_flops_per_s"] = flops / wall_s
    if nbytes:
        out["achieved_hbm_bytes_per_s"] = nbytes / wall_s
    out.update(utilization(kind, out.get("achieved_flops_per_s"),
                           out.get("achieved_hbm_bytes_per_s")))
    if cost.get("arith_intensity"):
        out["arith_intensity"] = cost["arith_intensity"]
    for k, v in out.items():
        metrics.gauge(f"device.{k}.{name}", v)
        metrics.gauge(f"device.{k}", v)     # aggregate: last window
    return out


# --------------------------------------------------------- instrument
def instrument(fn, name: str, **attrs):
    """Wrap a jitted callable for device telemetry — the
    implementation behind ``obs.instrument_device_fn``.

    Disabled path: one flag check (plus remembering the program went
    warm, so a later enable never re-traces it).  Enabled path: the
    program's FIRST call takes the AOT route (`_harvest_compiled`) —
    lower once (the same single trace a direct call would cost),
    compile under an `engine.compile` span with cache attribution,
    harvest the cost model — and every call dispatches under a
    `device_span` with dispatch totals recorded.  A program already
    warmed while telemetry was off is dispatch-tracked only (lowering
    it again would be a second trace — the strict trace-guard
    contract outranks a late harvest).  `.lower` is forwarded from
    the original wrapper for explicit AOT/bench paths."""
    st = {"warm": False, "compiled": None}

    @functools.wraps(fn)
    def wrapper(*a, **kw):
        if not core._ENABLED:
            st["warm"] = True
            return fn(*a, **kw)
        call = st["compiled"]
        if call is None:
            if not st["warm"] and hasattr(fn, "lower"):
                st["compiled"] = call = _harvest_compiled(
                    name, fn, a, kw)
            st["warm"] = True
            if call is None:
                call = fn
        t0 = time.perf_counter()
        with core.device_span(name, **attrs):
            try:
                out = call(*a, **kw)
            except TypeError:
                if call is fn:
                    raise
                # aval drift: the AOT executable was compiled for
                # different input types — fall back to the jit
                # wrapper (which re-specializes) for this and every
                # later call
                st["compiled"] = None
                st["warm"] = True
                out = fn(*a, **kw)
        _record_dispatch(name, time.perf_counter() - t0)
        return out

    if hasattr(fn, "lower"):
        wrapper.lower = fn.lower
    return wrapper


# ------------------------------------------------- profiler capture
_TRACE = {"dir": None, "active": False}


def start_trace(out_dir: str) -> Optional[str]:
    """Programmatic ``jax.profiler`` capture into `out_dir` (the
    ``ut --device-trace DIR`` / ``UT_DEVICE_TRACE`` path).  The
    XPlane dump lands under ``<dir>/plugins/profile/...`` and the
    directory is referenced from the Chrome-trace export
    (``otherData.device_trace``) so the two open side by side in
    Perfetto.  Returns the directory, or None when the profiler is
    unavailable.  Idempotent while a capture is active."""
    if _TRACE["active"]:
        return _TRACE["dir"]
    try:
        import jax
        os.makedirs(out_dir, exist_ok=True)
        jax.profiler.start_trace(out_dir)
    except Exception:
        return None
    _TRACE["dir"] = out_dir
    _TRACE["active"] = True
    return out_dir


def stop_trace() -> Optional[str]:
    """Stop an active profiler capture; returns its directory (kept
    as `trace_dir()` so a later export still references the dump)."""
    if not _TRACE["active"]:
        return None
    _TRACE["active"] = False
    try:
        import jax
        jax.profiler.stop_trace()
    except Exception:
        pass
    return _TRACE["dir"]


def trace_dir() -> Optional[str]:
    """Directory of the active (or last finished) profiler capture in
    this process — what the Chrome-trace export references."""
    return _TRACE["dir"]


def maybe_trace_from_env(env: Optional[dict] = None) -> Optional[str]:
    """``UT_DEVICE_TRACE=<dir>`` starts a profiler capture for this
    process (the CLI's ``--device-trace`` flag layers above it)."""
    e = os.environ if env is None else env
    val = e.get("UT_DEVICE_TRACE", "").strip()
    if not val or val.lower() in ("0", "off", "false", "none"):
        return None
    return start_trace(val)
