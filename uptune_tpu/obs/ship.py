"""Fleet-telemetry shipper: push this process's telemetry streams to
a networked hub (obs/hub.py) over the serve plane's newline-JSON/TCP
wire (ISSUE 14).

The distributed-obs layer (PR 10/12/13) watches one process end to
end but only meets its peers post-mortem — `ut-trace` merges, `.hN`
flight-recorder files, journal files copied by hand.  The reference
shipped the live half as ZMQ/S3 result transport into one global
database every search instance reported into (PAPER.md L1/L4); a
`TelemetryShipper` is the TPU-native equivalent: any process started
with ``--telemetry HOST:PORT`` / ``UT_TELEMETRY`` /
``ut.config({'telemetry': ...})`` pushes, once per interval,

* one **window snapshot** row (`obs.metrics.window_snapshot` — the
  same shape as a flight-recorder row, cut on the shipper's own
  cursor so a local recorder and the hub never fight over windows),
* the **journal rows** emitted since the last window (a
  `journal.add_sink` subscriber),
* every **obs.alert** the quality monitor fired
  (`quality.add_alert_sink`), and
* an optional **health rollup** from a caller-provided callable (the
  serve CLI wires the server's ``{"op": "health"}`` rollup here).

Hot-path contract (the BENCH_OBS / BENCH_FLEET >= 0.95x bar):
``offer()`` is a bounded append under a leaf lock — it NEVER blocks,
never touches a socket, and when the hub is slow or gone the queue
drops its OLDEST rows with explicit accounting (``dropped`` is
carried in every ship request, counted hub-side per source, and
published locally as the ``ship.dropped`` counter).  All socket work
happens on one background daemon thread with
reconnect-plus-exponential-backoff; a dead hub costs the process
nothing but the dropped telemetry.

Durability contract (BENCH_FLEET's kill test): a batch is removed
from the shipper only after the hub ACKS it — and the hub acks only
after appending to its durable fleet timeline — so a SIGKILLed
source loses at most the one in-flight (un-acked) window.
"""
from __future__ import annotations

import json
import os
import random
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..utils.net import reject_self_connect
from . import core, journal, metrics, quality

__all__ = ["TelemetryShipper", "start", "stop", "active",
           "maybe_ship_from_env", "source_label", "backoff_jitter",
           "DEFAULT_INTERVAL", "DEFAULT_QUEUE_MAX",
           "DEFAULT_BATCH_MAX"]

DEFAULT_INTERVAL = 1.0
DEFAULT_QUEUE_MAX = 4096        # queued rows (each ~hundreds of bytes)
DEFAULT_BATCH_MAX = 512         # rows per ship request (ack unit)
BACKOFF_BASE = 0.25
BACKOFF_MAX = 5.0

# reconnect jitter (ISSUE 15 satellite): after a hub restart a whole
# fleet used to reconnect in LOCKSTEP on the same 0.25s..5s schedule —
# a thundering herd on the hub accept loop every backoff tick.  Each
# process waits a uniformly drawn fraction [1/2, 1] of its current
# backoff instead; the exponential GROWTH stays deterministic, only
# the wait is spread.  Per-process RNG: the herd decorrelates even
# when every process starts from the same fork image
_JITTER_RNG = random.Random(os.urandom(8))


def backoff_jitter(backoff: float) -> float:
    """The jittered wait for one reconnect backoff step."""
    return float(backoff) * (0.5 + 0.5 * _JITTER_RNG.random())


def source_label(src: Dict[str, Any]) -> str:
    """The hub's source key, rendered: ``host:pid:role``."""
    return f"{src.get('host')}:{src.get('pid')}:{src.get('role')}"


class TelemetryShipper:
    """One process's telemetry push loop.  Construct + ``start()``,
    or use the module-level ``start(addr, role=...)`` registry."""

    def __init__(self, addr: str, role: str = "ut",
                 interval: float = DEFAULT_INTERVAL,
                 queue_max: int = DEFAULT_QUEUE_MAX,
                 batch_max: int = DEFAULT_BATCH_MAX,
                 backoff_base: float = BACKOFF_BASE,
                 backoff_max: float = BACKOFF_MAX,
                 health_provider: Optional[Callable[[], dict]] = None,
                 connect_timeout: float = 5.0):
        host, _, port = str(addr).rpartition(":")
        if not host:
            raise ValueError(
                f"telemetry address must be 'host:port', got {addr!r}")
        self.addr = (host, int(port))
        self.source = {"host": socket.gethostname(),
                       "pid": os.getpid(), "role": str(role)}
        self.interval = max(0.02, float(interval))
        self.queue_max = int(queue_max)
        self.batch_max = max(1, int(batch_max))
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self.health_provider = health_provider
        self.connect_timeout = float(connect_timeout)
        # accounting (read by stats()/tests/bench; ints are GIL-atomic
        # enough for telemetry, exact counts are updated under _qlock)
        self.dropped = 0        # rows shed by the bounded queue
        self.acked = 0          # rows the hub confirmed durable
        self.shipped_batches = 0
        self.connects = 0       # successful connections
        self.failures = 0       # connect/send failures
        self.windows = 0
        self._q: List[Dict[str, Any]] = []
        self._qlock = threading.Lock()      # leaf lock: offer() only
        self._pending: Optional[List[Dict[str, Any]]] = None
        self._cursor: Optional[Dict[str, Any]] = None
        self._last_window_t = time.time()
        self._sock: Optional[socket.socket] = None
        self._file = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- hot path ------------------------------------------------------
    def offer(self, kind: str, row: Dict[str, Any]) -> bool:
        """Queue one telemetry row; never blocks, never raises.  At
        capacity the OLDEST queued row is shed (live telemetry favors
        recency) and counted.  Refused after stop() — except from the
        shipper's own final-window cut, which rides `_offer`."""
        if self._stop.is_set():
            return False
        self._offer(kind, row)
        return True

    def _offer(self, kind: str, row: Dict[str, Any]) -> None:
        item = {"kind": kind, "row": row}
        with self._qlock:
            if len(self._q) >= self.queue_max:
                self._q.pop(0)
                self.dropped += 1
                metrics.count("ship.dropped")
            self._q.append(item)

    # journal rows arrive under journal._LOCK — offer's leaf lock keeps
    # the sink O(append); the row is shallow-copied because the shipper
    # serializes it later, on its own thread
    def _journal_sink(self, row: Dict[str, Any]) -> None:
        self.offer("journal", dict(row))

    def _alert_sink(self, rec: Dict[str, Any]) -> None:
        self.offer("alert", dict(rec))

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "TelemetryShipper":
        # shipping implies a live metrics registry (same rule as the
        # serving process: obs stays enabled so windows have content)
        if not core.enabled():
            core.enable()
        journal.add_sink(self._journal_sink)
        quality.add_alert_sink(self._alert_sink)
        self._thread = threading.Thread(
            target=self._loop, name="ut-telemetry-shipper", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        """Final window + best-effort drain, then close.  Idempotent."""
        if self._stop.is_set():
            return
        journal.remove_sink(self._journal_sink)
        quality.remove_alert_sink(self._alert_sink)
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
        self._close()

    def stats(self) -> Dict[str, Any]:
        with self._qlock:
            queued = len(self._q)
        return {"source": dict(self.source), "queued": queued,
                "dropped": self.dropped, "acked": self.acked,
                "batches": self.shipped_batches,
                "connects": self.connects, "failures": self.failures,
                "windows": self.windows}

    # -- shipping loop -------------------------------------------------
    def _loop(self) -> None:
        backoff = self.backoff_base
        while True:
            stopping = self._stop.wait(self.interval)
            if not stopping:
                self._cut_window()
            try:
                self._flush()
                backoff = self.backoff_base     # a full flush resets it
            except (OSError, ValueError):
                self.failures += 1
                self._close()
                if not stopping:
                    # reconnect-with-backoff: sleep here (not the hub's
                    # problem), capped, reset on the next success —
                    # jittered so a restarted hub's whole fleet does
                    # not reconnect in lockstep (backoff_jitter)
                    if self._stop.wait(backoff_jitter(backoff)):
                        stopping = True
                    backoff = min(self.backoff_max, backoff * 2)
            if stopping:
                # the terminal cut happens HERE — strictly after
                # stop() is observed, including when it landed during
                # the backoff wait above — so the last window always
                # carries final=true and the terminal counters (the
                # exactness contract's clean-shutdown half)
                self._cut_window(final=True)
                try:
                    self._flush()
                except (OSError, ValueError):
                    self.failures += 1
                self._close()
                return

    def _cut_window(self, final: bool = False) -> None:
        now = time.time()
        row, self._cursor = metrics.window_snapshot(self._cursor)
        row = {"t": round(now, 3),
               "dt": round(now - self._last_window_t, 3), **row}
        self._last_window_t = now
        if final:
            row["final"] = True
        self.windows += 1
        self._offer("window", row)
        if self.health_provider is not None:
            try:
                h = self.health_provider()
            except Exception:   # health is best-effort telemetry
                h = None
            if h:
                self._offer("health", {"t": round(now, 3), **h})

    def _flush(self) -> None:
        """Ship everything queued, one acked batch at a time.  The
        in-flight batch (`_pending`) survives a failed send and is
        retried before new rows — acked-exactly-once from the queue's
        point of view (the hub may see a batch twice only when the ACK
        itself was lost; rows are telemetry windows, so a re-append is
        visible in the timeline, never double-counted in the rollup
        which keys on absolute counters)."""
        while True:
            if self._pending is None:
                with self._qlock:
                    if not self._q:
                        return
                    batch = self._q[:self.batch_max]
                    del self._q[:self.batch_max]
                # assigned OUTSIDE _qlock: _pending is single-owner
                # (only this shipper thread ever touches it), and
                # never writing it under the lock keeps that ownership
                # checkable (R103) instead of looking shared
                self._pending = batch
            self._send_batch(self._pending)
            self.acked += len(self._pending)
            self.shipped_batches += 1
            self._pending = None

    def _send_batch(self, rows: List[Dict[str, Any]]) -> None:
        f = self._ensure_conn()
        with self._qlock:
            # producer threads bump `dropped` under _qlock in _offer;
            # an unlocked read here could tear against that increment
            dropped = self.dropped
        req = {"op": "ship", "source": self.source, "rows": rows,
               "dropped": dropped}
        f.write(json.dumps(req, separators=(",", ":")).encode() + b"\n")
        f.flush()
        line = f.readline()
        if not line:
            raise OSError("hub closed the connection")
        resp = json.loads(line)
        if not resp.get("ok"):
            raise ValueError(
                f"hub rejected batch: {resp.get('error')}")

    def _ensure_conn(self):
        if self._file is not None:
            return self._file
        s = socket.create_connection(self.addr,
                                     timeout=self.connect_timeout)
        reject_self_connect(s, f"{self.addr[0]}:{self.addr[1]}")
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        f = s.makefile("rwb")
        # hello announces the source (and survives hub restarts: every
        # ship request re-carries the source, hello is a courtesy that
        # registers idle processes in `sources` before data flows)
        hello = {"op": "hello", "source": self.source,
                 "start_unix": round(time.time(), 3)}
        f.write(json.dumps(hello, separators=(",", ":")).encode()
                + b"\n")
        f.flush()
        line = f.readline()
        if not line or not json.loads(line).get("ok"):
            try:
                f.close()
                s.close()
            except OSError:
                pass
            raise OSError("hub refused hello")
        self._sock, self._file = s, f
        self.connects += 1
        return f

    def _close(self) -> None:
        if self._file is not None:
            try:
                self._file.close()
                self._sock.close()
            except OSError:
                pass
        self._file = None
        self._sock = None


# -- module registry (the CLI / env seam) ------------------------------
_ACTIVE: Optional[TelemetryShipper] = None
_REG_LOCK = threading.Lock()

DISABLED_TOKENS = ("0", "off", "false", "none")


def disabled_token(val) -> bool:
    return val is None or str(val).strip().lower() in DISABLED_TOKENS


def start(addr: str, role: str = "ut",
          **kw: Any) -> TelemetryShipper:
    """Start (or return the already-running) shipper for this
    process.  A second start with a different address replaces the
    first (stopping it cleanly)."""
    global _ACTIVE
    with _REG_LOCK:
        cur = _ACTIVE
    if cur is not None and not cur._stop.is_set():
        if f"{cur.addr[0]}:{cur.addr[1]}" == str(addr) \
                and cur.source["role"] == str(role):
            return cur
        cur.stop()
    shipper = TelemetryShipper(addr, role=role, **kw)
    with _REG_LOCK:
        _ACTIVE = shipper
    shipper.start()
    return shipper


def active() -> Optional[TelemetryShipper]:
    with _REG_LOCK:
        return _ACTIVE


def stop() -> None:
    with _REG_LOCK:
        shipper = _ACTIVE
    if shipper is not None:
        shipper.stop()


def maybe_ship_from_env(role: str = "ut",
                        env: Optional[dict] = None
                        ) -> Optional[TelemetryShipper]:
    """``UT_TELEMETRY=host:port`` starts the shipper for this process
    (the CLIs' ``--telemetry`` flag and ``ut.config('telemetry')``
    layer above it, same precedence as trace/journal).  ``--num-hosts``
    replicas inherit the env, so every replica ships automatically
    with its UT_PROCESS_ID folded into the role."""
    e = os.environ if env is None else env
    val = e.get("UT_TELEMETRY", "").strip()
    if not val or disabled_token(val):
        return None
    pid_env = e.get("UT_PROCESS_ID")
    if pid_env and pid_env != "0":
        role = f"{role}.h{pid_env}"
    return start(val, role=role)
