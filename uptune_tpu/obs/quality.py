"""Online search-quality analytics derived from the tuning journal.

`QualityMonitor` consumes journal rows (as a `journal.add_sink`
subscriber) and maintains the live quality signals the system plane
cannot see: the convergence state of the incumbent, a simple-regret
proxy, rolling surrogate calibration (MAE, rank correlation, z-score
interval coverage), per-arm credit shares, dedup/prune/store-hit
rates, and a stall / miscalibration / failure-rate detector that
raises `obs` alert events.

Two properties are load-bearing (ISSUE 12 acceptance):

* **Exact offline reproducibility.**  The monitor's only input is the
  journal row stream, its state is plain python floats/deques, and it
  never reads a clock — so `replay(rows)` over a journal FILE produces
  bit-identical gauges to the live run that wrote it (JSON round-trips
  python floats exactly).  The unit tests hold the online
  `obs.metrics` gauges to equality with a replay of the same journal.
* **Free distribution.**  With `publish=True` every gauge update also
  lands in the `obs.metrics` registry, so the signals ride the flight
  recorder timeline, the Prometheus exposition, the serve metrics op
  and the `ut top` "search" panel with zero extra wiring.

`SessionQuality` is the per-tenant sibling: a tiny always-on
accumulator each serve session updates at tell time, surfaced through
the server's ``{"op": "health"}`` op (docs/SERVING.md) so tenants and
a sharded front tier (ROADMAP item 1) can poll session health without
scraping the whole registry.
"""
from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, NamedTuple, Optional

from . import core, journal, metrics

__all__ = ["QualityConfig", "QualityMonitor", "SessionQuality",
           "attach", "detach", "replay", "add_alert_sink",
           "remove_alert_sink", "Z50", "Z95"]

# alert fan-out beyond the local obs plane: each sink receives every
# PUBLISHED alert record (live monitors only — offline `replay` keeps
# its alerts in `.alerts` and never calls sinks, preserving the
# exactness contract's purity).  The fleet-telemetry shipper
# (obs/ship.py, ISSUE 14) registers here so `obs.alert` events reach
# the hub the moment they fire, not a window later.
_ALERT_SINKS: List[Any] = []


def add_alert_sink(fn) -> None:
    if fn not in _ALERT_SINKS:
        _ALERT_SINKS.append(fn)


def remove_alert_sink(fn) -> None:
    try:
        _ALERT_SINKS.remove(fn)
    except ValueError:
        pass

# two-sided standard-normal quantiles for the nominal 50% / 95%
# predictive intervals the coverage gauges score
Z50 = 0.6745
Z95 = 1.96


class QualityConfig(NamedTuple):
    """Detector thresholds + rolling-window sizes.  Defaults are
    documented in docs/OBSERVABILITY.md; serve exposes its own
    (smaller) stall default through the health op."""
    cal_window: int = 128      # joined (mu, sigma, qor) rows kept
    qor_window: int = 64       # recent finite QoRs (regret proxy)
    rate_window: int = 64      # recent pulls (dedup/prune rates)
    fail_window: int = 32      # recent tells (failure rate)
    stall_tells: int = 200     # alert: no new best in N tells
    min_cal_rows: int = 40     # calibration alerts need >= this many
    cover95_lo: float = 0.5    # overconfident below this 95% coverage
    # intervals HUNDREDS of times wider than the typical error carry
    # no ranking information: median |z| under this fires the
    # detector (0.674 is the 50%-interval quantile; 1e-3 means the
    # claimed uncertainty is ~670x the actual error — a units bug or
    # a miswired sigma, not a conservative model).  High COVERAGE
    # alone is never a defect, and a cautious GP near convergence
    # legitimately sits at med |z| ~ 0.03 (the committed example)
    wide_z_lo: float = 1e-3
    fail_rate_hi: float = 0.5  # failing above this windowed rate
    # gauge-publication cadence in journal ROWS: detectors run on
    # every row (cheap running counters), but the derived gauges
    # (regret sort, calibration scan, rates, arm shares) recompute
    # every Nth row + at `finalize()` — the exactness contract holds
    # because replay applies the same cadence and both sides finalize
    publish_every: int = 8


def _rankcorr(xs: List[float], ys: List[float]) -> Optional[float]:
    """Spearman rank correlation via ordinal ranks (stable sort, so
    ties break deterministically — replay-exact by construction)."""
    n = len(xs)
    if n < 3:
        return None

    def ranks(v: List[float]) -> List[int]:
        order = sorted(range(n), key=lambda i: (v[i], i))
        r = [0] * n
        for rank, i in enumerate(order):
            r[i] = rank
        return r

    ra, rb = ranks(xs), ranks(ys)
    mean = (n - 1) / 2.0
    num = sum((a - mean) * (b - mean) for a, b in zip(ra, rb))
    den = sum((a - mean) ** 2 for a in ra)
    if den == 0:
        return None
    return num / den


class QualityMonitor:
    """Fold journal rows into live quality gauges + alerts.

    `publish=True` mirrors every gauge into `obs.metrics` (prefix
    ``search.``) and raises alerts as ``obs.alert`` events plus
    ``search.alerts.<kind>`` counters; `publish=False` (the offline
    replay mode) keeps everything in `.gauges` / `.alerts` only."""

    def __init__(self, config: Optional[QualityConfig] = None,
                 publish: bool = False):
        self.cfg = config or QualityConfig()
        self.publish = publish
        self.gauges: Dict[str, float] = {}
        self.alerts: List[Dict[str, Any]] = []
        # counts
        self.tells = 0
        self.new_bests = 0
        self.tells_since_best = 0
        self.store_hits = 0
        self.pulls = 0
        self.best: Optional[float] = None
        # rolling windows.  _ok and _pull_rows keep RUNNING aggregates
        # (count of failures / columnwise sums) updated on append and
        # evict: re-summing a 64-wide window on every step row was one
        # of the measurable costs inside the BENCH_OBS >= 0.95x budget
        cfg = self.cfg
        self._cal: deque = deque(maxlen=cfg.cal_window)   # (mu, sd, q)
        self._qors: deque = deque(maxlen=cfg.qor_window)
        self._ok: deque = deque()                         # bool
        self._ok_fails = 0
        self._pull_rows: deque = deque()
        self._pull_sums = [0, 0, 0, 0, 0]  # batch/trials/pruned/filt/dup
        # per-arm attribution (from step rows): [pulls, evals, bests]
        self.arm_stats: Dict[str, List[int]] = {}
        # detector re-arm state: one alert per episode
        self._armed = {"stall": True, "miscalibration": True,
                       "failures": True}
        self._t = 0.0              # last row's journal-relative time
        self._sense_max = False    # set by rows carrying sense="max"
        self._rows = 0             # tell-carrying rows (cadence clock)

    # -- plumbing ------------------------------------------------------
    def _set(self, name: str, value: Optional[float]) -> None:
        if value is None:
            self.gauges.pop(name, None)
            return
        value = float(value)
        # unchanged-value early exit: most per-row publications repeat
        # the previous value (stable arm shares, a flat incumbent) and
        # the metrics-lock round trip is the cost that matters on the
        # driver hot path (BENCH_OBS budget)
        if self.gauges.get(name) == value:
            return
        self.gauges[name] = value
        if self.publish:
            metrics.gauge(name, value)

    def _alert(self, kind: str, row_t: float, **info: Any) -> None:
        if not self._armed[kind]:
            return
        self._armed[kind] = False
        rec = {"kind": kind, "t": round(float(row_t), 6), **info}
        self.alerts.append(rec)
        self._set(f"search.alerts.{kind}",
                  self.gauges.get(f"search.alerts.{kind}", 0) + 1)
        if self.publish:
            core.event("obs.alert", **rec)
            metrics.count("search.alerts")
            for fn in list(_ALERT_SINKS):
                try:
                    fn(rec)
                except Exception:   # a sink must never fail the search
                    pass

    # -- row dispatch --------------------------------------------------
    def on_row(self, row: Dict[str, Any]) -> None:
        self._t = float(row.get("t", 0.0))
        ev = row.get("ev")
        if ev == "step":
            self._on_step(row)
        elif ev == "serve_tell":
            self._on_serve_tell(row)
        elif ev == "store_hit":
            self.store_hits += 1
            self._set("search.store_hit_rate",
                      self.store_hits / max(1, self.tells))
        elif ev == "snapshot":
            self._set("search.snapshot_version", row.get("version"))

    def _push_ok(self, ok: bool) -> None:
        ring = self._ok
        ring.append(ok)
        if not ok:
            self._ok_fails += 1
        if len(ring) > self.cfg.fail_window:
            if not ring.popleft():
                self._ok_fails -= 1

    # -- steps: per-trial outcome arrays + credit ----------------------
    def _on_step(self, row: Dict[str, Any]) -> None:
        if row.get("sense") == "max":
            self._sense_max = True
        arm = str(row.get("arm", "?"))
        st = self.arm_stats.setdefault(arm, [0, 0, 0])
        st[0] += 1
        st[1] += int(row.get("evaluated", 0))
        st[2] += int(bool(row.get("new_best")))
        qors = row.get("qors") or ()
        # fused inline copy of the compact-encoding semantics whose
        # reference decoder is journal.step_tells (absent `ok` = all
        # true, absent `nb` = all false) — change BOTH or neither.
        # The dominant row shape (every trial fine, no new best)
        # takes the BULK path — C-level deque.extend instead of a
        # per-trial python loop; this is the one per-TRIAL code path
        # in the monitor and it is measured against the BENCH_OBS
        # budget
        n = len(qors)
        oks = row.get("ok")
        nbs = row.get("nb")
        mus = row.get("mus")
        sigmas = row.get("sigmas")
        qor_ring, cal, ok_ring = self._qors, self._cal, self._ok
        if oks is None and nbs is None:
            self.tells += n
            self.tells_since_best += n
            ok_ring.extend([True] * n)
            over = len(ok_ring) - self.cfg.fail_window
            for _ in range(over if over > 0 else 0):
                if not ok_ring.popleft():
                    self._ok_fails -= 1
            qor_ring.extend(qors)
            if mus is not None:
                cal.extend(zip(mus, sigmas, qors))
        else:
            push_ok = self._push_ok
            since = self.tells_since_best
            for i in range(n):
                q = qors[i]
                ok = True if oks is None else bool(oks[i])
                self.tells += 1
                push_ok(ok)
                if nbs is not None and nbs[i]:
                    self.new_bests += 1
                    since = 0
                    self._armed["stall"] = True
                    if q is not None:
                        self.best = float(q)
                else:
                    since += 1
                if ok and q is not None:
                    qor_ring.append(float(q))
                    if mus is not None:
                        cal.append((float(mus[i]), float(sigmas[i]),
                                    float(q)))
            self.tells_since_best = since
        best = row.get("best")
        if best is not None:
            self.best = float(best)     # authoritative (incl. preload)
        batch = row.get("batch")
        if batch:
            # the pull verdicts ride the step row (captured at ticket
            # open): dedup / prune / filter rates over a rolling pull
            # window, via running columnwise sums
            self.pulls += 1
            rec = (int(batch), int(row.get("trials", 0)),
                   int(row.get("pruned", 0)),
                   int(row.get("filtered", 0)),
                   int(row.get("dup", 0)))
            sums = self._pull_sums
            ring = self._pull_rows
            ring.append(rec)
            for j in range(5):
                sums[j] += rec[j]
            if len(ring) > self.cfg.rate_window:
                old = ring.popleft()
                for j in range(5):
                    sums[j] -= old[j]
        self._after_tells()

    def _on_serve_tell(self, row: Dict[str, Any]) -> None:
        """Serve-session rows: the global stream mixes tenants whose
        QoR scales are incomparable, so ONLY tenant-agnostic signals
        update here — tell count and the failure window.  One
        tenant's new best must not reset the (cross-tenant
        meaningless) stall counter or overwrite `search.best_qor`;
        per-session convergence verdicts live in SessionQuality and
        the health op."""
        self.tells += 1
        self._push_ok(bool(row.get("ok")))
        self._after_tells()

    def _after_tells(self) -> None:
        """Per-row detectors (cheap running counters), plus the full
        gauge publication at the `publish_every` row cadence — the
        heavy recomputation (regret sort, calibration scan, rates,
        arm shares) off the every-row path is what keeps the journal
        inside the BENCH_OBS >= 0.95x budget.  `finalize()` publishes
        the terminal state, so end-of-run reads are cadence-exact."""
        cfg = self.cfg
        self._rows += 1
        # detectors run on EVERY row: an alert must not wait out the
        # publication cadence
        if self.tells_since_best >= cfg.stall_tells:
            self._alert("stall", self._t,
                        tells_since_best=self.tells_since_best,
                        best=self.best)
        n_ok = len(self._ok)
        fr = self._ok_fails / n_ok if n_ok else None
        if fr is not None and n_ok >= cfg.fail_window \
                and fr > cfg.fail_rate_hi:
            self._alert("failures", self._t, fail_rate=round(fr, 6))
        elif fr is not None and fr <= cfg.fail_rate_hi:
            self._armed["failures"] = True
        if self._rows % max(1, cfg.publish_every) == 0:
            self._publish()

    def finalize(self) -> None:
        """Publish the terminal gauge state.  Called by `detach` /
        `obs.stop_journal` on the live side and by `replay` on the
        offline side — BOTH finalize, which is what keeps the
        cadence-batched gauges exactly equal across them."""
        self._publish()

    def _publish(self) -> None:
        self._set("search.tells", self.tells)
        self._set("search.new_bests", self.new_bests)
        self._set("search.tells_since_best", self.tells_since_best)
        self._set("search.best_qor", self.best)
        # simple-regret proxy: how far the *typical* recent sample sits
        # above the incumbent (sense-normalized: rows carry
        # user-oriented values, and rows spell out sense="max") — high
        # means still exploring, -> 0 as the search concentrates on the
        # optimum region.  A proxy, not regret: the true optimum is
        # unknown mid-run.
        if self._qors and self.best is not None:
            qs = sorted(self._qors)
            med = qs[len(qs) // 2]
            self._set("search.regret_proxy",
                      self.best - med if self._sense_max
                      else med - self.best)
        if self._ok:
            self._set("search.fail_rate",
                      self._ok_fails / len(self._ok))
        tot = self._pull_sums[0]
        if tot:
            self._set("search.pulls", self.pulls)
            self._set("search.dup_rate", self._pull_sums[4] / tot)
            self._set("search.prune_rate", self._pull_sums[2] / tot)
            self._set("search.novel_rate", self._pull_sums[1] / tot)
        evals = sum(s[1] for s in self.arm_stats.values())
        bests = sum(s[2] for s in self.arm_stats.values())
        for name, s in self.arm_stats.items():
            if evals:
                self._set(f"search.arm_evals_share.{name}",
                          s[1] / evals)
            if bests:
                self._set(f"search.arm_best_share.{name}",
                          s[2] / bests)
        self._recalibrate()

    def _recalibrate(self) -> None:
        cfg = self.cfg
        n = len(self._cal)
        if not n:
            return
        mus = [m for m, _, _ in self._cal]
        qs = [q for _, _, q in self._cal]
        abs_err = [abs(q - m) for m, _, q in self._cal]
        azs = sorted(abs(q - m) / max(s, 1e-12)
                     for m, s, q in self._cal)
        cover50 = sum(1 for z in azs if z <= Z50) / n
        cover95 = sum(1 for z in azs if z <= Z95) / n
        med_z = azs[n // 2]
        self._set("search.cal_rows", n)
        self._set("search.cal_mae", sum(abs_err) / n)
        self._set("search.cal_rank_corr", _rankcorr(mus, qs))
        self._set("search.cal_cover50", cover50)
        self._set("search.cal_cover95", cover95)
        self._set("search.cal_med_abs_z", med_z)
        if n >= cfg.min_cal_rows:
            bad = (cover95 < cfg.cover95_lo or med_z < cfg.wide_z_lo)
            if bad:
                self._alert("miscalibration", self._t,
                            cover50=round(cover50, 6),
                            cover95=round(cover95, 6),
                            med_abs_z=round(med_z, 6))
            else:
                self._armed["miscalibration"] = True

    # journal sink protocol: the monitor IS its row callback
    def __call__(self, row: Dict[str, Any]) -> None:
        self.on_row(row)


def attach(config: Optional[QualityConfig] = None) -> QualityMonitor:
    """Create a publishing monitor and subscribe it to the journal
    stream; the caller owns `detach`."""
    mon = QualityMonitor(config, publish=True)
    journal.add_sink(mon)
    return mon


def detach(mon: QualityMonitor) -> None:
    journal.remove_sink(mon)
    mon.finalize()


def replay(rows, config: Optional[QualityConfig] = None
           ) -> QualityMonitor:
    """Offline recomputation: feed journal rows (as `journal.read`
    returns them) through a fresh non-publishing monitor.  On the rows
    a live run journaled, the result's `.gauges`/`.alerts` equal the
    live monitor's exactly — the property `ut report` and the
    online-vs-offline unit tests rest on."""
    mon = QualityMonitor(config, publish=False)
    for row in rows:
        mon(row)
    mon.finalize()
    return mon


class SessionQuality:
    """Per-serve-session health accumulator: a few integers and one
    bounded ring, updated under the session's group lock at tell time
    (always on — cheap enough that the health op needs no flag)."""

    __slots__ = ("tells", "new_bests", "tells_since_best", "_ok")

    FAIL_WINDOW = 32

    def __init__(self):
        self.tells = 0
        self.new_bests = 0
        self.tells_since_best = 0
        self._ok: deque = deque(maxlen=self.FAIL_WINDOW)

    def on_tell(self, ok: bool, new_best: bool) -> None:
        self.tells += 1
        self._ok.append(bool(ok))
        if new_best:
            self.new_bests += 1
            self.tells_since_best = 0
        else:
            self.tells_since_best += 1

    def fail_rate(self) -> Optional[float]:
        if not self._ok:
            return None
        return round((len(self._ok) - sum(self._ok)) / len(self._ok), 6)

    def health(self, *, stall_tells: int = 64,
               fail_rate_hi: float = 0.5) -> Dict[str, Any]:
        """One status verdict + the numbers behind it.  `cold` = no
        tells yet; `failing` wins over `stalled` (a session whose
        builds all fail is stalled *because* it is failing)."""
        fr = self.fail_rate()
        if self.tells == 0:
            status = "cold"
        elif fr is not None and len(self._ok) >= self._ok.maxlen \
                and fr > fail_rate_hi:
            status = "failing"
        elif self.tells_since_best >= stall_tells:
            status = "stalled"
        else:
            status = "ok"
        return {"status": status, "tells": self.tells,
                "new_bests": self.new_bests,
                "tells_since_best": self.tells_since_best,
                "fail_rate": fr}

    def state(self) -> list:
        """JSON-clean snapshot for the serve checkpoint plane
        (ISSUE 15): counters + the failure ring as 0/1 bits, so a
        restored session's health verdict replays exactly."""
        return [self.tells, self.new_bests, self.tells_since_best,
                [1 if b else 0 for b in self._ok]]

    def restore(self, state) -> None:
        try:
            tells, new_bests, since, ring = state
            self.tells = int(tells)
            self.new_bests = int(new_bests)
            self.tells_since_best = int(since)
            self._ok = deque((bool(b) for b in ring),
                             maxlen=self.FAIL_WINDOW)
        except (TypeError, ValueError):
            pass        # a malformed record degrades health, not restore
