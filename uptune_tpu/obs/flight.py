"""Metrics flight recorder: the one-shot scrape, turned into a
timeline.

PR 7's metrics registry was read exactly once — at `obs.finish()` — so
a crashed run, an interrupted run, or a week-long `ut serve` process
left no usable metrics history at all.  A ``FlightRecorder`` is a
background daemon thread that appends one `metrics.window_snapshot`
row to a JSONL file every `interval` seconds: absolute counters PLUS
per-window counter deltas and histogram-window percentiles, so rates
("asks/s over the last second") read straight off consecutive rows
without diffing absolute scrapes.  `ut top --metrics <file>` tails
exactly this stream.

Bounded by construction: at `max_rows` the file rotates — the current
generation moves to ``<path>.1`` (older generations shift to ``.2`` …
``.N`` up to the configured `rotate` depth; default 1, the historical
behavior) — so leaving the recorder on forever costs a fixed disk
budget.  `chain(path)` lists the surviving generations oldest-first
and `read_chain(path)` replays their rows in write order: `ut top`'s
tail and the fleet hub's timeline replay both read through rotation
boundaries instead of forgetting everything at each cap.  `stop()`
writes one final row (marked ``"final": true``) and is idempotent —
it is called from the normal `obs.finish()` path, the SIGINT/atexit
flush (`obs.install_exit_flush`), or both.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from . import metrics

__all__ = ["FlightRecorder", "start", "stop", "active_for",
           "rotate_files", "chain", "read_chain",
           "DEFAULT_INTERVAL", "DEFAULT_MAX_ROWS", "DEFAULT_ROTATE"]

DEFAULT_INTERVAL = 1.0
DEFAULT_MAX_ROWS = 20000
DEFAULT_ROTATE = 1


def rotate_files(path: str, depth: int) -> None:
    """Shift the rotation chain one generation: ``.N-1`` -> ``.N`` …
    ``<path>`` -> ``.1`` (the oldest generation past `depth` is
    dropped).  Best-effort per link — a vanished generation never
    breaks the shift.  Shared by the flight recorder and the fleet
    hub's timeline (obs/hub.py), so every rotation-capped JSONL in
    the obs plane ages the same way."""
    depth = max(1, int(depth))
    for i in range(depth, 1, -1):
        try:
            os.replace(f"{path}.{i - 1}", f"{path}.{i}")
        except OSError:
            pass
    try:
        os.replace(path, path + ".1")
    except OSError:
        pass


def chain(path: str) -> List[str]:
    """Existing generations of a rotation-capped JSONL, OLDEST first
    (``.N`` … ``.1``, then the live file)."""
    out: List[str] = []
    n = 1
    while os.path.exists(f"{path}.{n}"):
        n += 1
    for i in range(n - 1, 0, -1):
        out.append(f"{path}.{i}")
    if os.path.exists(path):
        out.append(path)
    return out


def read_chain(path: str) -> List[Dict[str, Any]]:
    """Every parseable JSON row across the rotation chain, in write
    order (torn lines skipped — same tolerance as every obs JSONL)."""
    rows: List[Dict[str, Any]] = []
    for p in chain(path):
        try:
            with open(p) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        row = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if isinstance(row, dict):
                        rows.append(row)
        except OSError:
            continue
    return rows

# path -> running recorder; obs.finish() consults this so a run with a
# recorder gets its final row + close instead of a second (schema-
# mismatched) one-shot append
_ACTIVE: Dict[str, "FlightRecorder"] = {}
# every path that EVER had a recorder this process: a later finish()
# (e.g. the clean exit after a signal flush already stopped it) must
# not append a schema-mismatched legacy one-shot row after "final"
_EVER: set = set()
_REG_LOCK = threading.Lock()


class FlightRecorder:
    """One background metrics-snapshot writer.  Construct + `start()`,
    or use the module-level `start(path, ...)` registry helpers."""

    def __init__(self, path: str, interval: float = DEFAULT_INTERVAL,
                 max_rows: int = DEFAULT_MAX_ROWS,
                 extra: Optional[Dict[str, Any]] = None,
                 rotate: int = DEFAULT_ROTATE):
        self.path = path
        self.interval = max(0.01, float(interval))
        self.max_rows = int(max_rows)
        self.rotate = max(1, int(rotate))
        self.extra = dict(extra or {})
        self.rows_written = 0
        self.rotations = 0
        self._cursor: Optional[Dict[str, Any]] = None
        self._last_t = time.time()
        self._f = None
        self._stop = threading.Event()
        self._wlock = threading.Lock()   # row writes: thread vs stop()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "FlightRecorder":
        self._f = open(self.path, "a")
        self._last_t = time.time()
        self._thread = threading.Thread(
            target=self._loop, name="ut-flight-recorder", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self._write_row()
            except OSError:
                return      # disk gone: recording is best-effort

    def stop(self, timeout: float = 5.0) -> None:
        """Final row + close.  Idempotent and safe from signal
        handlers (the writer thread is joined with a bound)."""
        if self._stop.is_set():
            return
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
        try:
            self._write_row(final=True)
        except OSError:
            pass
        with self._wlock:
            if self._f is not None:
                self._f.close()
                self._f = None
        with _REG_LOCK:
            if _ACTIVE.get(self.path) is self:
                del _ACTIVE[self.path]

    # -- rows ----------------------------------------------------------
    def _write_row(self, final: bool = False) -> None:
        with self._wlock:
            if self._f is None:
                return
            now = time.time()
            row, self._cursor = metrics.window_snapshot(self._cursor)
            row = {"t": round(now, 3),
                   "dt": round(now - self._last_t, 3),
                   "pid": os.getpid(), **row}
            self._last_t = now
            if final:
                row["final"] = True
            if self.extra:
                row.update(self.extra)
            self._f.write(json.dumps(row) + "\n")
            self._f.flush()
            self.rows_written += 1
            if self.rows_written % max(1, self.max_rows) == 0 \
                    and not final:
                self._rotate()

    def _rotate(self) -> None:
        """Cap the file: the generation chain shifts one step (the
        oldest past `rotate` is dropped), appends continue fresh."""
        self._f.close()
        rotate_files(self.path, self.rotate)
        self._f = open(self.path, "a")
        self.rotations += 1


# -- module registry (the obs.finish / exit-flush seam) ----------------
def start(path: str, interval: float = DEFAULT_INTERVAL,
          max_rows: int = DEFAULT_MAX_ROWS,
          extra: Optional[Dict[str, Any]] = None,
          rotate: int = DEFAULT_ROTATE) -> FlightRecorder:
    """Start (or return the already-running) recorder for `path`."""
    with _REG_LOCK:
        rec = _ACTIVE.get(path)
        if rec is not None:
            return rec
        rec = FlightRecorder(path, interval=interval, max_rows=max_rows,
                             extra=extra, rotate=rotate)
        _ACTIVE[path] = rec
        _EVER.add(path)
    rec.start()
    return rec


def active_for(path: str) -> Optional[FlightRecorder]:
    with _REG_LOCK:
        return _ACTIVE.get(path)


def had_recorder(path: str) -> bool:
    with _REG_LOCK:
        return path in _EVER


def stop(path: Optional[str] = None) -> None:
    """Stop the recorder for `path` (or every active one)."""
    with _REG_LOCK:
        recs = ([_ACTIVE[path]] if path is not None and path in _ACTIVE
                else list(_ACTIVE.values()) if path is None else [])
    for rec in recs:
        rec.stop()
