"""`ut-trace`: join multi-process trace shards into one document.

The distributed runs this repo now produces leave their telemetry in
per-process shards — a driver's ``--trace`` export, each ``--num-hosts``
replica's ``.hN`` file, a `ut serve` server's shutdown export, a traced
client's own trace, and WorkerPool sandbox sidecar JSONL from children
no reap collected.  Perfetto can open only one file;
``ut-trace merge`` aligns the shards' clocks and emits one
`validate_trace`-clean Chrome document:

* each shard becomes its own **pid** with a ``process_name`` metadata
  record (its declared role — ``otherData.process`` / sidecar header
  ``process`` — or the file's basename), keeping every shard's lanes
  intact under it;
* timestamps are shifted by each shard's unix-clock offset against the
  earliest shard's origin (``otherData.origin_unix``).  On one machine
  that is one clock and the alignment is exact; across hosts it is as
  good as NTP — expect ~ms skew, not ordering guarantees for sub-ms
  spans (docs/OBSERVABILITY.md caveats);
* client/server span JOINS are annotated: a ``client.request`` span
  whose ``ctx`` id matches a ``serve.handle`` span's ``parent`` gains
  ``server_ms`` and ``wire_ms`` args — client-observed latency,
  decomposed into server time and everything else (wire + queueing).

CLI::

    ut-trace merge -o merged.json driver.json serve.json client.json \
        ut.temp/temp.0/ut.trace.jsonl
    ut-trace validate merged.json

(also ``python -m uptune_tpu.obs.merge``).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

from . import sidecar
from .export import validate_trace

__all__ = ["load_shard", "merge_shards", "merge_files", "main"]


class ShardError(ValueError):
    """A file that is neither a Chrome-trace document nor a sidecar."""


def _norm_chrome(doc: Dict[str, Any], path: str) -> Dict[str, Any]:
    """Chrome-trace document -> normalized shard: events in SECONDS
    relative to the shard's own origin, lanes resolved to names."""
    evs = doc.get("traceEvents")
    if not isinstance(evs, list):
        raise ShardError(f"{path}: no traceEvents list")
    other = doc.get("otherData", {}) or {}
    lane_of: Dict[Any, str] = {}
    for e in evs:
        if isinstance(e, dict) and e.get("ph") == "M" \
                and e.get("name") == "thread_name":
            lane_of[e.get("tid")] = e.get("args", {}).get(
                "name", f"tid-{e.get('tid')}")
    events = []
    for e in evs:
        if not isinstance(e, dict) or e.get("ph") not in ("X", "i", "C"):
            continue
        events.append({
            "name": e.get("name", "?"),
            "ts": float(e.get("ts", 0.0)) / 1e6,
            "dur": (float(e["dur"]) / 1e6
                    if isinstance(e.get("dur"), (int, float)) else None),
            "track": lane_of.get(e.get("tid"), f"tid-{e.get('tid')}"),
            "attrs": e.get("args"),
            "ph": e["ph"],
        })
    return {
        "path": path,
        "process": other.get("process") or os.path.basename(path),
        "origin_unix": float(other.get("origin_unix", 0.0) or 0.0),
        "events": events,
        "other": other,
    }


def _norm_sidecar(header: Dict[str, Any], events: List[Dict[str, Any]],
                  path: str) -> Dict[str, Any]:
    out = []
    for e in events:
        out.append({"name": e.get("name", "?"),
                    "ts": float(e.get("ts", 0.0)),
                    "dur": e.get("dur"),
                    "track": e.get("track") or "child",
                    "attrs": e.get("attrs"),
                    "ph": "i" if e.get("dur") is None else "X"})
    proc = header.get("process") or "worker-child"
    if header.get("gid") is not None:
        proc = f"{proc} gid={header['gid']}"
    return {"path": path, "process": proc,
            "origin_unix": float(header.get("origin_unix", 0.0) or 0.0),
            "events": out, "other": dict(header)}


def load_shard(path: str) -> Dict[str, Any]:
    """Load one shard file: a Chrome trace-event JSON document (the
    ``--trace`` exports) or a sandbox sidecar JSONL."""
    parsed = sidecar.read(path)
    if parsed is not None:
        return _norm_sidecar(parsed[0], parsed[1], path)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise ShardError(f"{path}: unreadable ({e})")
    if not isinstance(doc, dict):
        raise ShardError(f"{path}: not a trace document")
    return _norm_chrome(doc, path)


def _annotate_joins(shards: List[Dict[str, Any]]) -> int:
    """Cross-shard client/server span join: `client.request` spans
    (args.ctx) matched to `serve.handle` spans (args.parent) gain
    server_ms + wire_ms.  Works within one shard too (an in-process
    client).  Returns the number of joins made."""
    handlers: Dict[str, Dict[str, Any]] = {}
    for sh in shards:
        for e in sh["events"]:
            if e["name"] == "serve.handle" and e["dur"] is not None:
                parent = (e.get("attrs") or {}).get("parent")
                if parent:
                    handlers[str(parent)] = e
    joins = 0
    for sh in shards:
        for e in sh["events"]:
            if e["name"] != "client.request" or e["dur"] is None:
                continue
            ctx = (e.get("attrs") or {}).get("ctx")
            h = handlers.get(str(ctx)) if ctx else None
            if h is None:
                continue
            server_ms = h["dur"] * 1e3
            attrs = dict(e.get("attrs") or {})
            attrs["server_ms"] = round(server_ms, 3)
            attrs["wire_ms"] = round(
                max(0.0, e["dur"] * 1e3 - server_ms), 3)
            e["attrs"] = attrs
            joins += 1
    return joins


def merge_shards(shards: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Normalized shards -> one Chrome document: pid per shard,
    process/thread metadata, clock-offset-aligned timestamps."""
    if not shards:
        raise ShardError("nothing to merge")
    joins = _annotate_joins(shards)
    origins = [s["origin_unix"] for s in shards if s["origin_unix"] > 0]
    base = min(origins) if origins else 0.0
    events: List[Dict[str, Any]] = []
    manifest = []
    for pid0, sh in enumerate(shards):
        pid = pid0 + 1
        offset = (sh["origin_unix"] - base
                  if sh["origin_unix"] > 0 else 0.0)
        events.append({"ph": "M", "pid": pid, "tid": 0,
                       "name": "process_name",
                       "args": {"name": sh["process"]}})
        events.append({"ph": "M", "pid": pid, "tid": 0,
                       "name": "process_sort_index",
                       "args": {"sort_index": pid}})
        tracks: List[str] = []
        for e in sh["events"]:
            if e["track"] not in tracks:
                tracks.append(e["track"])
        tid_of = {t: i + 1 for i, t in enumerate(tracks)}
        for t, tid in tid_of.items():
            events.append({"ph": "M", "pid": pid, "tid": tid,
                           "name": "thread_name", "args": {"name": t}})
        for e in sh["events"]:
            rec: Dict[str, Any] = {
                "name": e["name"],
                "cat": e["name"].split(".", 1)[0],
                "pid": pid,
                "tid": tid_of[e["track"]],
                "ts": round((e["ts"] + offset) * 1e6, 3),
            }
            if e["dur"] is None:
                rec["ph"] = "i"
                rec["s"] = "t"
            else:
                rec["ph"] = "X"
                rec["dur"] = round(max(0.0, e["dur"]) * 1e6, 3)
            if e["attrs"]:
                rec["args"] = e["attrs"]
            events.append(rec)
        manifest.append({"path": sh["path"], "pid": pid,
                         "process": sh["process"],
                         "origin_unix": sh["origin_unix"],
                         "offset_s": round(offset, 6),
                         "events": len(sh["events"])})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"origin_unix": base, "merged": manifest,
                          "joins": joins,
                          "merged_by": "ut-trace merge"}}


def merge_files(paths: List[str],
                out: Optional[str] = None) -> Dict[str, Any]:
    """Load + merge + (optionally) write; always validates."""
    doc = merge_shards([load_shard(p) for p in paths])
    validate_trace(doc)
    if out:
        with open(out, "w") as f:
            json.dump(doc, f)
    return doc


# ------------------------------------------------------------------ CLI
def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="ut-trace",
        description="merge / validate uptune-tpu observability traces "
                    "(docs/OBSERVABILITY.md)")
    sub = p.add_subparsers(dest="cmd", required=True)
    pm = sub.add_parser(
        "merge", help="join trace shards (Chrome-trace JSON exports "
                      "and/or sandbox sidecar JSONL) into one "
                      "Perfetto-viewable document")
    pm.add_argument("shards", nargs="+", metavar="SHARD")
    pm.add_argument("-o", "--out", required=True, metavar="OUT.json")
    pv = sub.add_parser("validate",
                        help="check a trace document against the "
                             "schema contract")
    pv.add_argument("doc", metavar="TRACE.json")
    args = p.parse_args(argv)

    if args.cmd == "merge":
        try:
            doc = merge_files(args.shards, out=args.out)
        except (ShardError, ValueError, OSError) as e:
            print(f"ut-trace: {e}", file=sys.stderr)
            return 1
        m = doc["otherData"]["merged"]
        print(f"ut-trace: merged {len(m)} shard(s), "
              f"{sum(s['events'] for s in m)} event(s), "
              f"{doc['otherData']['joins']} client/server join(s) "
              f"-> {args.out}")
        return 0
    try:
        with open(args.doc) as f:
            validate_trace(json.load(f))
    except (OSError, ValueError) as e:
        print(f"ut-trace: INVALID: {e}", file=sys.stderr)
        return 1
    print(f"ut-trace: {args.doc} is schema-clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
