"""Subprocess trace sidecars: child-side span recording for WorkerPool
evaluations, merged into the driver's timeline at reap.

A sandboxed trial subprocess cannot write into the driver's rings, so
PR 7 rendered each build as ONE opaque ``pool.build`` span.  This
module decomposes it: when the driver traces, ``WorkerPool.submit``
exports ``UT_TRACE_SIDECAR=<sandbox>/ut.trace.jsonl`` into the trial's
environment; the child (the user program importing ``uptune_tpu``)
sees the variable during protocol-state init, turns its own obs plane
on, and at interpreter exit dumps everything it recorded to the
sidecar file — one JSON header line (clock origin, pid, gid) plus one
line per event.  At reap the driver reads the file back, aligns the
child's clock against its own trace origin (both sides record their
``time.time()`` origin; on one machine that is one clock, across hosts
it is NTP-accurate — docs/OBSERVABILITY.md caveats), and re-emits the
events under the slot's ``worker-N`` lane, where they nest inside the
``pool.build`` window.

The same file format doubles as a merge shard: ``ut-trace merge``
accepts sidecar JSONL next to full Chrome-trace documents, giving a
still-running (or crashed) child's partial telemetry a seat in the
merged document even when no reap ever collected it.
"""
from __future__ import annotations

import atexit
import json
import os
from typing import Any, Dict, List, Optional, Tuple

from . import core

__all__ = ["SIDECAR_ENV", "SIDECAR_FILE", "maybe_init_child", "dump",
           "read", "merge_into"]

SIDECAR_ENV = "UT_TRACE_SIDECAR"
SIDECAR_FILE = "ut.trace.jsonl"

# the path this process registered an atexit dump for (guards against
# double registration when protocol state is re-initialized in-process)
_REGISTERED: Optional[str] = None


def maybe_init_child(env: Optional[dict] = None) -> Optional[str]:
    """Child-side hook: when ``UT_TRACE_SIDECAR`` names a path, enable
    recording in THIS process and register an atexit dump to it.
    Returns the path when armed, None otherwise.  Idempotent — the
    protocol state may be re-initialized without stacking dumps."""
    global _REGISTERED
    path = (os.environ if env is None else env).get(SIDECAR_ENV,
                                                    "").strip()
    if not path or path.lower() in ("0", "off", "false", "none"):
        return None
    if _REGISTERED == path:
        return path
    if not core.enabled():
        core.enable()
    if _REGISTERED is None:
        atexit.register(_dump_registered)
    _REGISTERED = path
    return path


def _dump_registered() -> None:
    if _REGISTERED is not None:
        try:
            dump(_REGISTERED)
        except OSError:
            pass    # sandbox deleted under us (timeout kill): nothing
            # to report to — the driver already reaped the slot


def dump(path: str, process: str = "worker-child") -> None:
    """Write everything recorded so far to the sidecar file (atomic
    tmp+rename: the driver may poll mid-write).  Also stamps a
    ``child.run`` span covering the whole recorded window, so the
    worker lane shows the subprocess's full extent even when the user
    program recorded nothing else."""
    core.emit_at("child.run", 0.0, core.now(),
                 attrs={"pid": os.getpid()})
    snap = core.snapshot()
    header = {
        "sidecar": 1,
        "origin_unix": snap.get("origin_unix", 0.0),
        "pid": os.getpid(),
        "process": process,
        "gid": os.environ.get("UT_GLOBAL_ID"),
        "slot": os.environ.get("UT_CURR_INDEX"),
        "stage": os.environ.get("UT_CURR_STAGE"),
    }
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(json.dumps(header) + "\n")
        for e in snap["events"]:
            f.write(json.dumps({"name": e["name"], "ts": e["ts"],
                                "dur": e["dur"], "track": e["track"],
                                "attrs": e["attrs"]}) + "\n")
    os.replace(tmp, path)


def read(path: str) -> Optional[Tuple[Dict[str, Any],
                                      List[Dict[str, Any]]]]:
    """Parse a sidecar file -> (header, events), or None when the file
    is missing, empty, or not a sidecar (torn tails are tolerated the
    same way the store tolerates them: complete leading lines win)."""
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError:
        return None
    if not lines:
        return None
    try:
        header = json.loads(lines[0])
    except json.JSONDecodeError:
        return None
    if not isinstance(header, dict) or "sidecar" not in header:
        return None
    events = []
    for line in lines[1:]:
        try:
            e = json.loads(line)
        except json.JSONDecodeError:
            break           # torn tail: keep what is complete
        if isinstance(e, dict) and "name" in e and "ts" in e:
            events.append(e)
    return header, events


def merge_into(path: str, track: str) -> int:
    """Driver-side reap hook: align a child sidecar's clock against
    this process's trace origin and re-emit its events onto `track`
    (the slot's worker lane).  Returns the number of events merged;
    0 when tracing is off or the sidecar is absent/unreadable.  The
    consumed file is removed so a slot reused without a fresh sidecar
    can never replay a previous trial's events."""
    if not core.enabled():
        return 0
    parsed = read(path)
    if parsed is None:
        return 0
    header, events = parsed
    offset = (float(header.get("origin_unix", 0.0) or 0.0)
              - core.trace_origin_unix())
    gid = header.get("gid")
    try:
        gid = int(gid)      # env-protocol strings -> the driver's ints
    except (TypeError, ValueError):
        pass
    n = 0
    for e in events:
        attrs = dict(e.get("attrs") or {})
        attrs.setdefault("child_pid", header.get("pid"))
        if gid is not None:
            attrs.setdefault("gid", gid)
        core.emit_at(e["name"], float(e["ts"]) + offset, e.get("dur"),
                     track, attrs)
        n += 1
    try:
        os.unlink(path)
    except OSError:
        pass
    return n
