"""QoR reporting + metadata API: `ut.target`, `ut.interm`, `ut.feature`,
`ut.save`, `ut.get_global_id`, `ut.get_local_id`, `ut.get_meta_data`.

Behavioral spec from the reference (`/root/reference/python/uptune/
report.py:45-201`), re-built on the explicit per-process protocol state in
`uptune_tpu.api.state` instead of class-attribute globals:

* ``target(val, 'min'|'max')`` —
  ANALYSIS: flush the recorded search space to ``ut.params.json``, record
  the default QoR, and advance the stage counter (each `target` call marks
  a stage boundary, so multi-stage spaces are discovered in one profiling
  run).
  TUNE, single-stage: append ``[index, val, trend]`` to
  ``ut.qor_stage0.json`` and keep running.
  TUNE, multi-stage: acts as a breakpoint (report.py:69-79) — when the
  program reaches the stage being tuned (``UT_CURR_STAGE``) it writes the
  stage QoR and exits 0; earlier breakpoints just advance the stage
  counter (resetting the positional counter for the next stage's
  ``ut.tune`` calls).

* ``interm(features)`` — intermediate feature vector for the multi-stage
  surrogate filter; under ``UT_MULTI_STAGE_SAMPLE`` the call is the 'pre'
  phase breakpoint (report.py:85-103).

* ``feature(val, name)`` — covariate registration (report.py:187-201),
  persisted to ``covars.json`` in the work dir.

* ``save(objective)`` — decorator reporting a function's return value as
  the target QoR (report.py:35-43).
"""
from __future__ import annotations

import functools
import json
import os
import sys
from typing import Any, Callable, Optional, Sequence

from .state import ANALYSIS, BEST, STATE, TUNE

INTERIM_FILE = "ut.interim_features.json"
FEATURES_FILE = "ut.features.json"
COVARS_FILE = "covars.json"


def _check_qor(val: Any, objective: str) -> float:
    if isinstance(val, bool) or not isinstance(val, (int, float)):
        raise TypeError(f"QoR must be a real number, got {val!r}")
    if objective not in ("min", "max"):
        raise ValueError(f"objective must be 'min' or 'max', "
                         f"got {objective!r}")
    return float(val)


def target(val: Any, objective: str = "min") -> Any:
    """Register the target QoR of this run; returns `val` unchanged."""
    qor = _check_qor(val, objective)
    mode = STATE.mode
    if mode == ANALYSIS:
        # each target() call closes one stage of the space discovery
        STATE.flush_params()
        STATE.write_default_qor(qor, objective)
        STATE.cur_stage += 1
        STATE.count = 0
    elif mode == TUNE:
        # lands in the trial's trace sidecar (when the driver traces):
        # the moment the user program produced its QoR, visible inside
        # the slot's build window after the reap-time merge
        from .. import obs
        obs.event("child.target", qor=qor, stage=STATE.cur_stage)
        n_stages = (len(STATE.params_meta) if STATE.params_meta
                    else max(1, len(STATE.recorded)))
        if n_stages <= 1:
            STATE.write_qor_row(STATE.index, qor, objective)
        else:
            # multi-stage breakpoint semantics
            if STATE.cur_stage == STATE.stage:
                STATE.write_qor_row(STATE.index, qor, objective)
                sys.exit(0)
            if STATE.cur_stage > STATE.stage:
                raise RuntimeError(
                    f"breakpoint past the tuned stage: at stage "
                    f"{STATE.cur_stage}, tuning stage {STATE.stage}")
            STATE.cur_stage += 1
            STATE.count = 0
    elif mode == BEST:
        # no QoR write, but stage/counter bookkeeping must still advance
        # so unnamed params in stages >= 1 bind positionally
        STATE.cur_stage += 1
        STATE.count = 0
    return val


def save(objective: str = "min") -> Callable:
    """Decorator: report the wrapped function's return value via target."""
    def decorator(fn: Callable) -> Callable:
        @functools.wraps(fn)
        def run(*args, **kwargs):
            return target(fn(*args, **kwargs), objective)
        return run
    return decorator


def interm(features: Sequence[Any], shape: Optional[int] = None):
    """Report an intermediate feature vector (multi-stage 'pre' phase)."""
    feats = list(features)
    if shape is not None and len(feats) != shape:
        raise ValueError(f"feature shape mismatch: {len(feats)} != {shape}")
    mode = STATE.mode
    path = os.path.join(STATE.work_dir, FEATURES_FILE)
    if mode == ANALYSIS:
        # marker file whose presence selects multi-stage mode
        # (async_task_scheduler.py:465-474)
        with open(os.path.join(STATE.work_dir, INTERIM_FILE), "w") as f:
            json.dump({"shape": len(feats)}, f)
        with open(path, "w") as f:
            json.dump([[-1, feats]], f)
    elif mode == TUNE:
        with open(path, "w") as f:
            json.dump([[STATE.index, feats]], f)
        # rides the trial's trace sidecar when the driver traces (like
        # child.target); the persisted file above is what the reap path
        # reads into the tuning journal (exec/pool.py, ISSUE 12)
        from .. import obs
        obs.event("child.interm", n=len(feats), stage=STATE.cur_stage)
        if os.environ.get("UT_MULTI_STAGE_SAMPLE"):
            sys.exit(0)  # 'pre'-phase breakpoint
    return features


def feature(val: Any, name: str) -> Any:
    """Register a named covariate observed by this run."""
    from . import constraint as _c
    path = os.path.join(STATE.work_dir, COVARS_FILE)
    data = {}
    if os.path.exists(path):
        try:
            with open(path) as f:
                data = json.load(f)
        except json.JSONDecodeError:
            data = {}
    # register in every mode: ut.vars.<name> bounds must resolve during
    # TUNE/BEST trials too, not only in the analysis run
    _c.register(name, val)
    data[name] = val
    with open(path, "w") as f:
        json.dump(data, f)
    if STATE.mode == TUNE:
        # sidecar visibility for traced runs; the journal row itself is
        # emitted by the driver at reap from the file just written
        from .. import obs
        obs.event("child.feature", covar=str(name))
    return val


def get_global_id():
    """Global trial id under tuning; 'base' outside a tuning run."""
    if os.environ.get("UT_TUNE_START"):
        return STATE.global_id
    return "base"


def get_local_id() -> Optional[int]:
    """Worker-slot index under tuning; None outside a tuning run."""
    if os.environ.get("UT_TUNE_START"):
        return STATE.index
    return None


def get_meta_data(key: str) -> Optional[str]:
    """Read a protocol env var; UT_WORK_DIR falls back to cwd."""
    val = os.environ.get(key)
    if val is not None:
        return val
    if key == "UT_WORK_DIR":
        return os.getcwd()
    raise RuntimeError(f"no metadata {key!r}: program not under tuning")
