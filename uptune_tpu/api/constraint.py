"""Constraint / covariate registry: `ut.register`, `ut.rule`,
`ut.constraint`, `ut.vars`.

The reference's version (`/root/reference/python/uptune/add/
constraint.py:11-60`) records sympy-symbol VarNodes and decorator lists
but never enforces anything (the wrappers even reference an undefined
`func`).  Here the registry is functional: rules are config predicates the
controller applies before publishing a proposal (invalid configs are
resampled/rejected), and constraints are QoR predicates applied when a
result arrives (violating results are treated as failures).

    ut.register("v1", 8)                 # covariate / symbolic var
    @ut.rule()
    def no_both(cfg):                    # search-space restriction
        return not (cfg["a"] and cfg["b"])
    @ut.constraint()
    def qor_sane(qor, cfg):              # QoR-condition
        return qor < 1e6
    ut.tune(5, (2, ut.vars.v1))          # inter-parameter bound
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional


class VarNode:
    """A named symbolic value usable as a tune() bound.

    Resolves to its current value via int()/float(), so
    ``ut.tune(5, (2, ut.vars.v1))`` works anywhere a number does.
    """

    def __init__(self, name: str, value: Any = None):
        self.name = name
        self.value = value

    def _resolve(self) -> Any:
        if self.value is None:
            raise ValueError(f"VarNode {self.name!r} has no value yet")
        return self.value

    def __int__(self) -> int:
        return int(self._resolve())

    def __float__(self) -> float:
        return float(self._resolve())

    def __index__(self) -> int:
        return int(self._resolve())

    def __le__(self, other):
        return self._resolve() <= other

    def __ge__(self, other):
        return self._resolve() >= other

    def __lt__(self, other):
        return self._resolve() < other

    def __gt__(self, other):
        return self._resolve() > other

    def __eq__(self, other):
        if isinstance(other, VarNode):
            return self.name == other.name
        return self._resolve() == other

    def __hash__(self):
        return hash(self.name)

    def __repr__(self):
        return f"VarNode(name={self.name!r}, value={self.value!r})"


class Registry:
    """Process-wide store of vars, rules and QoR constraints."""

    def __init__(self):
        self.nodes: Dict[str, VarNode] = {}
        self.rules: List[Callable[[Dict[str, Any]], bool]] = []
        self.constraints: List[Callable[..., bool]] = []
        self.custom_models: List[Any] = []

    def clear(self) -> None:
        self.nodes.clear()
        self.rules.clear()
        self.constraints.clear()
        self.custom_models.clear()

    # ------------------------------------------------------------------
    def check_config(self, cfg: Dict[str, Any]) -> bool:
        """True iff every registered rule accepts the config."""
        return all(bool(r(cfg)) for r in self.rules)

    def check_qor(self, qor: Any, cfg: Dict[str, Any]) -> bool:
        """True iff every registered QoR constraint accepts the result."""
        for c in self.constraints:
            try:
                ok = c(qor, cfg)
            except TypeError:
                ok = c(qor)  # single-argument constraint
            if not ok:
                return False
        return True


REGISTRY = Registry()


def register(name_or_var: Any, value: Any = None,
             name: Optional[str] = None) -> VarNode:
    """Register a named variable/covariate; returns its VarNode."""
    if isinstance(name_or_var, VarNode):
        node = name_or_var
        node.name = name or node.name
    else:
        node = VarNode(name or str(name_or_var), value)
    REGISTRY.nodes[node.name] = node
    return node


def rule(name: Optional[str] = None) -> Callable:
    """Decorator registering a search-space restriction cfg -> bool."""
    def decorator(fn: Callable[[Dict[str, Any]], bool]) -> Callable:
        fn._ut_rule_name = name or fn.__name__
        REGISTRY.rules.append(fn)
        return fn
    return decorator


def constraint(name: Optional[str] = None) -> Callable:
    """Decorator registering a QoR condition (qor[, cfg]) -> bool."""
    def decorator(fn: Callable) -> Callable:
        fn._ut_constraint_name = name or fn.__name__
        REGISTRY.constraints.append(fn)
        return fn
    return decorator


class _Vars:
    """`ut.vars.<name>` accessor over the registry."""

    def __getattr__(self, name: str) -> VarNode:
        try:
            return REGISTRY.nodes[name]
        except KeyError:
            raise AttributeError(f"no registered variable {name!r}")

    def __dir__(self):
        return sorted(REGISTRY.nodes)


vars = _Vars()
