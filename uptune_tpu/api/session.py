"""Session settings + best-config persistence: `ut.config`, `ut.init`,
`ut.get_best`.

Mirrors the reference's validated settings dict
(`/root/reference/python/uptune/__init__.py:45-55,79-83`) and best-config
round trip (`api.py:52-65,146-149`): the controller writes ``best.json``
on every improvement; ``get_best()`` reads it back; ``init(apply_best=
True)`` switches the process into BEST mode so subsequent ``ut.tune()``
calls serve the best config.

Precedence contract (tests/python/test_async_execute.py:5-14 in the
reference): CLI flags > ``ut.config(...)`` > these defaults.  The CLI
layer (`uptune_tpu.cli`) reads this dict for any flag the user did not
pass explicitly.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

from .state import BEST_FILE, STATE

DEFAULTS: Dict[str, Any] = {
    "test-limit": 10,
    "runtime-limit": 7200,
    "timeout": 72000,
    "parallel-factor": 2,
    "async-interval": 0.05,
    "gpu-num": 0,
    "cpu-num": 1,
    "learning-model": [],
    "training-data": None,
    "online-training": False,
    "technique": None,
    "seed": 0,
    # async ticket prefetch depth for the program-mode controller
    # (None = one pool width of lookahead; 0 = lockstep propose-on-free)
    "prefetch-depth": None,
    # persistent XLA compilation cache base dir for driver programs
    # (None = default resolution: UT_COMPILE_CACHE_DIR, else .xla_cache
    # at the repo root / ~/.cache/uptune_tpu/xla; 'off' disables).  The
    # controller appends a per-space-signature subdir, so repeated tunes
    # of the same program skip first-step compiles
    "compile-cache-dir": None,
    # content-addressed trial results store (docs/STORE.md): directory
    # of append-only result shards consulted before every build — a hit
    # serves the recorded QoR without launching the program, and N
    # concurrent instances sharing one directory exchange results.
    # None = <work_dir>/ut.temp/store; the literal 'off' disables
    "store-dir": None,
    # fsync every store segment append (docs/STORE.md "Durability"):
    # the O_APPEND protocol already survives process SIGKILL via the
    # page cache; this knob additionally survives power loss / kernel
    # panic at the cost of one fsync per recorded build.  Layered
    # under the UT_STORE_FSYNC env var; off by default — a recorded
    # build is re-measurable, so most deployments prefer the append
    # to stay off the critical path
    "store-fsync": False,
    # warm-start a fresh tune from the store's recorded rows for the
    # same (space, program): preload best-so-far + dedup history +
    # surrogate training set before the first acquisition
    "warm-start": False,
    # cooperative search (ISSUE 18, docs/STORE.md "Remote store"):
    # when the store brings in sibling rows at exchange time, also
    # feed the non-elite (config, qor) rows into the local surrogate's
    # training set — K cooperating instances train on one pooled
    # evidence set.  Off disables the federated feed (elite migration
    # alone still runs)
    "federate": True,
    # migration cadence in seconds: minimum interval between store
    # refreshes (directory re-scan or remote delta pull), which gates
    # both elite migration and the federated feed
    "exchange-interval": 2.0,
    # observability plane (docs/OBSERVABILITY.md): a path turns on
    # cross-plane span tracing for the run and writes a
    # Perfetto-viewable Chrome trace there (+ a metrics-snapshot JSONL
    # next to it); None/'off' leaves tracing disabled (the
    # instrumented hot paths cost one flag check).  Layered under the
    # `ut --trace` flag and the UT_TRACE env var
    "trace": None,
    # tuning journal (docs/OBSERVABILITY.md "Search-quality
    # telemetry"): a path streams structured search events (arm pulls,
    # dedup/prune verdicts, tells joined with the surrogate's
    # propose-time mu/sigma, store hits) to an append-only JSONL and
    # derives live convergence/calibration gauges + stall alerts from
    # them; render post-hoc with `ut report`.  Layered under the
    # `ut --journal` flag and the UT_JOURNAL env var; None/'off'
    # leaves it disabled (one flag check per call site)
    "journal": None,
    # fleet telemetry (docs/OBSERVABILITY.md "Fleet telemetry"):
    # 'host:port' of a running `ut hub` collector — the process ships
    # metrics window snapshots, journal rows, alerts and health
    # rollups there over a bounded never-blocking queue.  Layered
    # under the `--telemetry` flags and the UT_TELEMETRY env var
    # (which --num-hosts replicas inherit); None/'off' disables
    "telemetry": None,
    # async surrogate plane (docs/PERF.md): 'on' (None = default) moves
    # the O(N^3) GP refit + fit_auto hyperparameter sweep onto a
    # background worker publishing versioned snapshots, so the driver
    # tell path never blocks on learning; 'off' runs the full refit
    # synchronously inline again (note: O(N^2) incremental extension
    # between refits stays on in both modes — disable it via
    # surrogate_opts={'incremental': False})
    "surrogate-async": None,
    # Pallas kernel routing (ops/routing.py): 'auto' (None = default)
    # routes each kernel site by backend + shape qualification — the
    # compiled TPU kernel, the interpret-mode kernel on CPU where the
    # site opts in, the XLA fallback otherwise; 'interpret' forces the
    # kernel route in interpret mode wherever shapes are supported
    # (debugging/CI: kernel math everywhere, any host); 'off' forces
    # the XLA fallback everywhere (bisection).  Layered UNDER the
    # UT_PALLAS env var (env wins — the knob must be forceable on a
    # subprocess without touching its code)
    "pallas": None,
    # tuning-as-a-service session server (`ut serve`, docs/SERVING.md).
    # Same precedence contract as every other key: CLI flags >
    # ut.config(...) > these defaults.
    # bind address / TCP port (0 = pick an ephemeral port and print it)
    "serve-host": "127.0.0.1",
    "serve-port": 8765,
    # instance-slot capacity of each engine group: sessions sharing one
    # space signature are packed onto one BatchedEngine instance axis
    # (proposals batch ACROSS tenants); when a group fills, another
    # group of the same signature is allocated
    "serve-slots": 64,
    # admission limit across all groups ('server full' above it)
    "serve-max-sessions": 4096,
    # shared cross-tenant results memo: one content-addressed store
    # directory mounted under every session's scope — a config one
    # tenant measured is served to any other tenant's ask without a
    # build.  None = ut.serve/store under the server's cwd; 'off'
    # disables the memo
    "serve-store-dir": None,
    # crash-safe serving (docs/SERVING.md "Durability & failover"):
    # a directory (or 'on' for <store-dir>/checkpoints) turns on the
    # write-ahead session checkpoint plane — every committed session
    # transition is journaled before its reply, `ut serve --durable`
    # recovers all live sessions on restart, and resuming clients
    # re-attach losslessly.  None/'off' disables
    "serve-durable": None,
    # fsync each checkpoint append (power-loss durability; SIGKILL
    # durability needs no fsync — same tradeoff as store-fsync)
    "serve-durable-fsync": False,
}

settings: Dict[str, Any] = dict(DEFAULTS)


def config(user: Dict[str, Any]) -> Dict[str, Any]:
    """Override session settings; unknown keys are rejected."""
    if not isinstance(user, dict):
        raise TypeError(f"config expects a dict, got {type(user).__name__}")
    unknown = sorted(set(user) - set(DEFAULTS))
    if unknown:
        raise KeyError(
            f"unknown setting(s) {unknown}; valid: {sorted(DEFAULTS)}")
    settings.update(user)
    return settings


def reset_settings() -> None:
    """Restore defaults (used by tests and between CLI runs)."""
    settings.clear()
    settings.update(DEFAULTS)


def init(apply_best: bool = False) -> None:
    """Mark the process as running under uptune; optionally apply the
    best known config to subsequent ut.tune() calls."""
    if os.environ.get("EZTUNING"):
        return
    os.environ["UPTUNE"] = "True"
    if apply_best:
        os.environ["BEST"] = "True"
        STATE.reset()


def best_path(work_dir: Optional[str] = None) -> str:
    return os.path.join(work_dir or STATE.work_dir, BEST_FILE)


def get_best(work_dir: Optional[str] = None) -> Tuple[Dict[str, Any], Any]:
    """-> (best config dict, its QoR)."""
    path = best_path(work_dir)
    if not os.path.isfile(path):
        raise FileNotFoundError(
            f"no best config at {path}: run a tuning session first")
    with open(path) as f:
        best = json.load(f)
    if isinstance(best, dict) and "config" in best:
        return best["config"], best.get("qor")
    if isinstance(best, (list, tuple)) and len(best) == 2:
        return dict(best[0]), best[1]
    raise ValueError(f"unrecognized best.json payload at {path}")


def write_best(cfg: Dict[str, Any], qor: Any,
               work_dir: Optional[str] = None,
               filename: Optional[str] = None) -> None:
    """Controller-side write of best.json (api.py:146-149).  `filename`
    overrides BEST_FILE (multi-host replicas write best.h{N}.json so N
    processes never race on one file)."""
    path = (os.path.join(work_dir or STATE.work_dir, filename)
            if filename else best_path(work_dir))
    with open(path, "w") as f:
        json.dump({"config": cfg, "qor": qor}, f, indent=1)
