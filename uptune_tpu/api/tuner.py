"""Custom-tuner decorator: `@ut.model(name, weight)`.

The reference declares this hook as a stub (`/root/reference/python/
uptune/tuners/tuner.py:7-14`) — the decorated function was stored and
never called.  Here a registered model is a real proposal source: the
controller asks it for configs at startup
(`uptune_tpu.exec.controller.ProgramTuner._host_proposals`) and injects
them as attributed trials via `Tuner.inject` — evaluated ahead of any
technique batch, archived under the model's name, but outside the AUC
bandit's credit loop (injected tickets never touch technique state).

A model is a callable ``(history, space) -> config_dict`` where history
is a list of ``(config_dict, qor)`` pairs seen so far.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

from .constraint import REGISTRY


def model(name: Optional[str] = None, weight: float = 1.0) -> Callable:
    """Decorator registering a user-defined proposal model."""
    def decorator(fn: Callable) -> Callable:
        fn._ut_model_name = name or fn.__name__
        fn._ut_model_weight = float(weight)
        REGISTRY.custom_models.append(fn)
        return fn
    return decorator


def registered_models() -> List[Callable]:
    return list(REGISTRY.custom_models)
