"""Process-side protocol state for user programs under tuning.

A user program importing `uptune_tpu as ut` runs in one of four modes,
selected by environment variables — the same env protocol as the reference
(`/root/reference/python/uptune/template/types.py:57-138`, `api.py:861-868`,
`src/uptune.h:21-26`):

==================  =======================================================
(none)              DEFAULT: `ut.tune()` returns its default value
UT_BEFORE_RUN_PROFILE  ANALYSIS: record the search space; `ut.target()`
                    flushes it to ut.params.json + ut.default_qor.json
UT_TUNE_START       TUNE: `ut.tune()` serves values from the proposal JSON
                    published by the controller for (stage, index)
BEST                BEST: serve values from best.json (apply_best)
==================  =======================================================

Proposal lookup is by the reference's order-dependent positional counter
(`types.py:132-134`): the k-th `ut.tune()` call binds to the k-th recorded
parameter.  The controller additionally publishes a name-keyed map, and we
look up by *name first*, falling back to position — robust when names are
given, compatible when not.

Deliberate divergences from the reference protocol (the controller in
`uptune_tpu.exec` is written against THIS contract):
  * work dir env var is ``UT_WORK_DIR`` (reference: ``UT_TEMP_DIR``,
    api.py:94) — one variable for both roles.
  * proposal files are ``configs/ut.dr_stage{S}_index{I}.json``
    (reference: ``configs/{stage}-{index}.json``) — self-describing names.
"""
from __future__ import annotations

import json
import os
import sys
from typing import Any, Dict, List, Optional

DEFAULT, ANALYSIS, TUNE, BEST = "default", "analysis", "tune", "best"

PARAMS_FILE = "ut.params.json"
DEFAULT_QOR_FILE = "ut.default_qor.json"
BEST_FILE = "best.json"


def _truthy(v: Optional[str]) -> bool:
    return bool(v) and v.lower() not in ("0", "false", "off", "")


class _ProtocolState:
    """Singleton holding the per-process run state."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.mode = self._detect_mode()
        if self.mode == TUNE:
            # trace-context propagation (docs/OBSERVABILITY.md): a
            # traced driver exports UT_TRACE_SIDECAR into the sandbox
            # env; this child then records its own spans and dumps
            # them at exit for the reap-time merge.  Inert (one env
            # check) for untraced runs.
            from ..obs import sidecar
            sidecar.maybe_init_child()
        self.work_dir = os.environ.get("UT_WORK_DIR", os.getcwd())
        self.index = int(os.environ.get("UT_CURR_INDEX", "0"))
        self.stage = int(os.environ.get("UT_CURR_STAGE", "0"))
        self.global_id = int(os.environ.get("UT_GLOBAL_ID", "0"))
        # ANALYSIS: recorded per-stage param specs
        self.recorded: List[List[Dict[str, Any]]] = [[]]
        # TUNE/BEST: per-stage counters + loaded proposal
        self.count = 0
        self.cur_stage = 0          # which ut.target breakpoint we're in
        self.proposal: Optional[Dict[str, Any]] = None
        self.params_meta: Optional[List[List[Dict[str, Any]]]] = None
        self.qor_records: List[Any] = []
        self.features: List[Any] = []
        self.interm_feats: List[Any] = []

    @staticmethod
    def _detect_mode() -> str:
        env = os.environ
        if _truthy(env.get("UT_BEFORE_RUN_PROFILE")):
            return ANALYSIS
        if _truthy(env.get("UT_TUNE_START")):
            return TUNE
        if _truthy(env.get("BEST")):
            return BEST
        return DEFAULT

    # ------------------------------------------------------------------
    # ANALYSIS side
    def record_param(self, rec: Dict[str, Any]) -> None:
        while len(self.recorded) <= self.cur_stage:
            self.recorded.append([])
        stage = self.recorded[self.cur_stage]
        rec = dict(rec)
        if not rec.get("name"):
            rec["name"] = f"v{self.cur_stage}_{len(stage)}"
        names = {r["name"] for st in self.recorded for r in st}
        if rec["name"] in names:
            raise ValueError(
                f"duplicate tunable parameter name {rec['name']!r}")
        stage.append(rec)

    def flush_params(self) -> None:
        # atomic (tmp + rename): multi-host replicas may run their
        # analysis passes concurrently in one work_dir; a torn
        # ut.params.json read by the sibling would crash its space build
        path = os.path.join(self.work_dir, PARAMS_FILE)
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.recorded, f, indent=1)
        os.replace(tmp, path)

    # ------------------------------------------------------------------
    # TUNE side
    def _load_params_meta(self) -> None:
        """Load ut.params.json (if present) for positional binding."""
        ppath = os.path.join(self.work_dir, PARAMS_FILE)
        if os.path.exists(ppath):
            with open(ppath) as f:
                self.params_meta = json.load(f)

    def _load_proposal(self) -> None:
        from .. import obs
        cfg_dir = os.path.join(self.work_dir, "configs")
        path = os.path.join(
            cfg_dir, f"ut.dr_stage{self.stage}_index{self.index}.json")
        with obs.span("child.load_proposal", stage=self.stage):
            with open(path) as f:
                self.proposal = json.load(f)
            self._load_params_meta()
        # merge best configs of earlier stages (template/access.py:19-25,
        # types.py:124-129): stage s trials replay stages < s from their
        # published best
        for s in range(self.stage):
            bpath = os.path.join(cfg_dir, f"{s}-best.json")
            if os.path.exists(bpath):
                with open(bpath) as f:
                    prev = json.load(f)
                for k, v in prev.items():
                    self.proposal.setdefault(k, v)

    def _load_best(self) -> None:
        path = os.path.join(self.work_dir, BEST_FILE)
        with open(path) as f:
            best = json.load(f)
        # controller writes {"config": {...}, "qor": q}; also accept a
        # bare config dict or the reference's [config, qor] list shape
        if isinstance(best, dict):
            self.proposal = best.get("config", best)
        elif (isinstance(best, list) and len(best) == 2
              and isinstance(best[0], dict)):
            self.proposal = best[0]
        else:
            raise ValueError(f"unrecognized best.json payload: {best!r}")
        # params metadata enables the positional-counter fallback for
        # unnamed ut.tune() calls (the reference's common style,
        # types.py:132-134) in BEST mode too
        self._load_params_meta()

    def next_value(self, name: Optional[str], default: Any) -> Any:
        """Serve the value for the next ut.tune() call."""
        if self.proposal is None:
            try:
                (self._load_best if self.mode == BEST
                 else self._load_proposal)()
            except (OSError, json.JSONDecodeError, ValueError):
                return default  # no/bad published config: run as default
        key = None
        if name and name in self.proposal:
            key = name
        elif self.params_meta is not None:
            # positional counter within the current stage (types.py:132-134)
            stage_params = (self.params_meta[self.cur_stage]
                            if self.cur_stage < len(self.params_meta) else [])
            if self.count < len(stage_params):
                key = stage_params[self.count]["name"]
        self.count += 1
        if key is None or key not in self.proposal:
            return default
        return self.proposal[key]

    # ------------------------------------------------------------------
    # QoR side
    def write_qor_row(self, index: int, value: Any, trend: str) -> None:
        """Append an [index, val, trend] row to the current stage's QoR
        file (the reference's row shape, report.py:62-79); multi-stage
        breakpoint control flow lives in report.target."""
        path = os.path.join(self.work_dir,
                            f"ut.qor_stage{self.cur_stage}.json")
        rows = []
        if os.path.exists(path):
            try:
                with open(path) as f:
                    rows = json.load(f)
            except json.JSONDecodeError:
                rows = []
        rows.append([index, value, trend])
        with open(path, "w") as f:
            json.dump(rows, f)

    def write_default_qor(self, value: Any, trend: str) -> None:
        path = os.path.join(self.work_dir, DEFAULT_QOR_FILE)
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"qor": value, "trend": trend,
                       "stage": self.cur_stage}, f)
        os.replace(tmp, path)


STATE = _ProtocolState()
