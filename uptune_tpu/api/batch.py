"""Library surface for the batched multi-instance engine:
`uptune_tpu.tune_batch(...)` — N on-device tunes of one space as one
compiled program (engine/batched.py), returning per-instance results.

The reference's analogue is launching N OpenTuner processes and
joining their CSV archives; here the whole portfolio is a single
donate-in-place jitted run.
"""
from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional, Sequence

import numpy as np


class BatchTuneResult(NamedTuple):
    """Per-instance outcomes of one batched run (USER orientation)."""
    best_config: Dict[str, Any]     # globally best instance's config
    best_qor: float                 # its QoR
    best_configs: List[Dict[str, Any]]  # per-instance incumbents
    best_qors: np.ndarray           # [n_instances]
    evals: np.ndarray               # [n_instances] novel evaluations
    acqs: np.ndarray                # [n_instances] candidates processed
    state: Any                      # final stacked EngineState
    engine: Any                     # the BatchedEngine (for resuming)


def tune_batch(space, objective, n_instances: int, steps: int,
               seed: int = 0, arms: Optional[Sequence] = None,
               sense: str = "min", exchange_every: int = 0,
               history_capacity: int = 1 << 13,
               eval_fn=None, mesh=None,
               state=None, engine=None) -> BatchTuneResult:
    """Run `n_instances` independent on-device tunes of `space` (same
    space signature => ONE compiled vmapped program) for `steps` fused
    steps each.

    `objective(vals [B, D], perms) -> [B]` is a pure-JAX device
    objective over the FLATTENED candidate batch (all instances score
    in one dispatch); `eval_fn(cands) -> [B]` overrides it with a
    CandBatch-level evaluator (e.g. engine.surrogate_eval_fn's fused
    GP scoring).  `exchange_every=k` exchanges the global best across
    the instance axis every k steps (portfolio-of-portfolios);
    `mesh` (engine.make_instance_mesh) shards the instance axis over
    devices.  Pass `state=prev.state, engine=prev.engine` to continue
    a previous batched run: the engine reuse keeps the already-
    compiled program (a fresh call would retrace — compiles dominate
    small runs), and a caller-supplied state is NOT donated
    (prev.state stays readable); only internally-created states
    update in place."""
    import jax

    from ..engine import BatchedEngine, FusedEngine

    be = engine
    if be is None:
        eng = FusedEngine(space, objective, arms=arms,
                          history_capacity=history_capacity, sense=sense)
        be = BatchedEngine(eng, n_instances,
                           exchange_every=exchange_every, mesh=mesh)
    elif be.n_instances != n_instances:
        raise ValueError(
            f"engine has {be.n_instances} instances, got "
            f"n_instances={n_instances}")
    donate = state is None
    if state is None:
        state = be.init(jax.random.PRNGKey(seed))
    state = be.jit_run(steps, eval_fn, donate=donate)(state)
    cfg, qor = be.best(state)
    return BatchTuneResult(
        cfg, qor, be.best_configs(state), be.best_qors(state),
        np.asarray(state.evals), np.asarray(state.acqs), state, be)
