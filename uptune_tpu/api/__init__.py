"""User-facing API layer: the intrusive tune/target protocol
(`tuneapi`, `report`, `state`), session settings (`session`), and the
constraint/covariate registry (`constraint`)."""
