"""`ut.tune()` — the intrusive tuning API.

Type-dispatch and call semantics follow the reference
(`/root/reference/python/uptune/template/tuneapi.py:35-93` and the typed
Tune* value-interception classes `template/types.py:57-235`), without the
instance-registry metaclass: the per-process protocol state lives in
`uptune_tpu.api.state.STATE`.

    x = ut.tune(3, (1, 9))                # IntParam
    r = ut.tune(0.5, (0.0, 2.0))          # FloatParam
    f = ut.tune(True)                     # BoolParam
    o = ut.tune('-O2', ['-O1','-O2'])     # EnumParam
    p = ut.tune([0,1,2], [0,1,2])         # PermutationParam

In DEFAULT mode the call returns its default; in ANALYSIS mode it records
the parameter and returns the default; in TUNE/BEST mode it returns the
proposal value for this call site.
"""
from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

from .state import ANALYSIS, BEST, STATE, TUNE


def _space_record(name: Optional[str], default: Any,
                  space: Any) -> dict:
    """Classify (default, space) exactly like the reference's tune()
    dispatch (tuneapi.py:35-93) into a serializable param record."""
    if isinstance(default, bool):
        return {"name": name, "type": "bool", "default": default}
    if isinstance(default, list):
        if not isinstance(space, (list, tuple)) or set(space) != set(default):
            raise TypeError(
                f"permutation default must be an ordering of its space: "
                f"{default!r} vs {space!r}")
        return {"name": name, "type": "perm", "default": list(default),
                "items": list(space)}
    if isinstance(space, (list,)):
        if default not in space:
            raise ValueError(f"default {default!r} not in options {space!r}")
        return {"name": name, "type": "enum", "default": default,
                "options": list(space)}
    if isinstance(space, tuple) and len(space) == 2:
        lo, hi = space
        if not (lo <= default <= hi):
            raise ValueError(f"default {default!r} outside ({lo!r}, {hi!r})")
        if isinstance(default, int) and isinstance(lo, int) \
                and isinstance(hi, int):
            return {"name": name, "type": "int", "default": default,
                    "lo": lo, "hi": hi}
        return {"name": name, "type": "float", "default": float(default),
                "lo": float(lo), "hi": float(hi)}
    if space is None and isinstance(default, bool):
        return {"name": name, "type": "bool", "default": default}
    raise TypeError(
        f"cannot classify tunable: default={default!r} space={space!r}")


def tune(default: Any, space: Any = None,
         name: Optional[str] = None) -> Any:
    """Declare a tunable value; returns the served value for this run."""
    if space is None and not isinstance(default, bool):
        raise TypeError("tune() needs a space unless default is a bool")
    mode = STATE.mode
    if mode == ANALYSIS:
        STATE.record_param(_space_record(name, default, space))
        return default
    if mode in (TUNE, BEST):
        val = STATE.next_value(name, default)
        return _coerce(val, default, space)
    return default


def _coerce(val: Any, default: Any, space: Any) -> Any:
    """JSON round-trips lose tuple/int-ness; restore the default's type."""
    if isinstance(default, bool):
        return bool(val)
    if isinstance(default, int) and not isinstance(val, list):
        return int(round(float(val)))
    if isinstance(default, float):
        return float(val)
    return val


# typed aliases mirroring template/types.py:153-235 (usable directly and
# from template-mode annotations)
def TuneInt(default: int, space: Tuple[int, int],
            name: Optional[str] = None) -> int:
    return tune(int(default), (int(space[0]), int(space[1])), name)


def TuneFloat(default: float, space: Tuple[float, float],
              name: Optional[str] = None) -> float:
    return tune(float(default), (float(space[0]), float(space[1])), name)


def TuneEnum(default: Any, options: Sequence[Any],
             name: Optional[str] = None) -> Any:
    return tune(default, list(options), name)


def TuneBool(default: bool, name: Optional[str] = None) -> bool:
    return tune(bool(default), None, name)


def TunePermutation(default: Sequence[Any],
                    name: Optional[str] = None) -> list:
    return tune(list(default), list(default), name)
