"""EDA report feature extractors: `ut.vhls` and `ut.quartus`.

Re-implements the reference's report scrapers —
`/root/reference/python/uptune/report.py:122-174` (Vivado HLS XML via
xmltodict, Quartus via add/features.py) and
`/root/reference/python/uptune/add/features.py:4-110` (STA summary,
synthesis report, fitter utilization line parsers) — with stdlib-only
parsing (xml.etree, no xmltodict/tabulate) and numeric feature dicts
instead of printed tables, so the extracted values feed directly into
`ut.feature` covariates, the surrogate, and QuickEst.
"""
from __future__ import annotations

import os
import re
import xml.etree.ElementTree as ET
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

from .report import feature as register_feature


def _num(text: str) -> Any:
    """'1,234' -> 1234; '3.52' -> 3.52; otherwise the stripped string."""
    t = str(text).strip().replace(",", "")
    try:
        return int(t)
    except ValueError:
        pass
    try:
        return float(t)
    except ValueError:
        return t


# ---------------------------------------------------------------- vhls
def vhls(path: str, target: Optional[str] = None,
         register: bool = False) -> Any:
    """Parse a Vivado HLS csynth XML report (report.py:122-161).

    Returns a flat dict: version/family/part/top plus numeric
    target_cp, estimated_cp, latency_min/max, interval_min/max, and
    per-resource {name}_used / {name}_avail / {name}_util_pct.
    `target` returns that single entry; `register=True` additionally
    registers every numeric entry as a `ut.feature` covariate."""
    if not os.path.isfile(path):
        raise RuntimeError(f"Cannot find {path}, run csyn first")
    root = ET.parse(path).getroot()      # <profile>

    def text(xpath: str, default: str = "") -> str:
        el = root.find(xpath)
        return el.text if el is not None and el.text is not None \
            else default

    res: Dict[str, Any] = {
        "hls_version": "Vivado HLS " + text("ReportVersion/Version"),
        "product_family": text("UserAssignments/ProductFamily"),
        "part": text("UserAssignments/Part"),
        "top": text("UserAssignments/TopModelName"),
        "clock_unit": text("UserAssignments/unit", "ns"),
        "target_cp": _num(text("UserAssignments/TargetClockPeriod", "0")),
        "estimated_cp": _num(text(
            "PerformanceEstimates/SummaryOfTimingAnalysis/"
            "EstimatedClockPeriod", "0")),
        "latency_min": _num(text(
            "PerformanceEstimates/SummaryOfOverallLatency/"
            "Best-caseLatency", "0")),
        "latency_max": _num(text(
            "PerformanceEstimates/SummaryOfOverallLatency/"
            "Worst-caseLatency", "0")),
        "interval_min": _num(text(
            "PerformanceEstimates/SummaryOfOverallLatency/"
            "Interval-min", "0")),
        "interval_max": _num(text(
            "PerformanceEstimates/SummaryOfOverallLatency/"
            "Interval-max", "0")),
    }
    est = root.find("AreaEstimates/Resources")
    avail = root.find("AreaEstimates/AvailableResources")
    for name in ("BRAM_18K", "DSP48E", "FF", "LUT"):
        used = _num(est.findtext(name, "0")) if est is not None else 0
        total = _num(avail.findtext(name, "0")) if avail is not None else 0
        key = name.lower()
        res[f"{key}_used"] = used
        res[f"{key}_avail"] = total
        res[f"{key}_util_pct"] = (
            round(100.0 * used / total, 2) if total else 0.0)
    if register:
        for k, v in res.items():
            if isinstance(v, (int, float)):
                register_feature(v, f"vhls_{k}")
    if target is not None:
        return res[target]
    return res


# ------------------------------------------------------------- quartus
def get_timing(design: str, workdir: str,
               stage: str) -> Tuple[Any, Any]:
    """(slack, tns) from {design}.sta.{stage}.summary
    (add/features.py:4-17); 'None' entries become 0."""
    def numeric(text: str) -> Any:
        v = _num(text)
        return 0 if isinstance(v, str) else v   # 'None' etc. -> 0

    slack: Any = 0
    tns: Any = 0
    path = os.path.join(workdir, f"{design}.sta.{stage}.summary")
    with open(path) as f:
        for line in f:
            if "Slack" in line:
                slack = numeric(line.split(":")[-1])
            elif "TNS" in line:
                tns = numeric(line.split(":")[-1])
                break
    return slack, tns


_SYN_KEYS = ("boundary_port", "fourteennm_ff", "fourteennm_lcell_comb",
             "fourteennm_mac", "Max LUT depth", "Average LUT depth")


def get_syn_features(design: str, workdir: str) -> "OrderedDict[str, Any]":
    """Synthesis-report resource rows (add/features.py:38-57): cells are
    the third ';'-separated column of the matching table line."""
    out: "OrderedDict[str, Any]" = OrderedDict(
        (k, 0) for k in _SYN_KEYS)
    path = os.path.join(workdir, f"{design}.syn.rpt")
    with open(path) as f:
        for line in f:
            for key in _SYN_KEYS:
                if key in line and out[key] == 0:
                    parts = line.split(";")
                    if len(parts) > 2:
                        out[key] = _num(parts[2])
                    break
    return out


_FIT_KEYS = ("Logic utilization (in ALMs)",
             "Total dedicated logic registers", "Total pins",
             "Total block memory bits", "Total RAM Blocks",
             "Total DSP Blocks")


def get_utilization(design: str, workdir: str,
                    stage: str) -> "OrderedDict[str, Any]":
    """Fitter summary utilization (add/features.py:60-80): 'key : a / b'
    lines keep the numerator."""
    out: "OrderedDict[str, Any]" = OrderedDict(
        (k, 0) for k in _FIT_KEYS)
    path = os.path.join(workdir, f"{design}.fit.{stage}.summary")
    with open(path) as f:
        for line in f:
            for key in _FIT_KEYS:
                if key in line and out[key] == 0:
                    val = line.split(":", 1)[1]
                    if "/" in val:
                        val = val.split("/")[0]
                    out[key] = _num(val)
                    break
    return out


def quartus(design: str, path: str, target: Optional[str] = None,
            stage: str = "syn", register: bool = True) -> Any:
    """Aggregate Quartus features for a design work dir and register
    them as covariates (report.py:163-174 getQuartus semantics).
    Missing report files contribute nothing rather than raising — the
    flow may not have reached every stage yet."""
    vec: Dict[str, Any] = {}
    try:
        slack, tns = get_timing(design, path, stage)
        vec["slack"], vec["tns"] = slack, tns
    except OSError:
        pass
    try:
        vec.update(get_syn_features(design, path))
    except OSError:
        pass
    try:
        vec.update(get_utilization(design, path, stage))
    except OSError:
        pass
    clean: Dict[str, Any] = {}
    for k, v in vec.items():
        if v == "None" or v is None:
            v = 0
        if not isinstance(v, (int, float)):
            try:
                v = float(v)
            except (TypeError, ValueError):
                continue
        clean[k] = v
        if register:
            register_feature(v, k)
    if target is not None:
        if target not in clean:
            raise KeyError(
                f"quartus feature {target!r} unavailable — its report "
                f"file under {path!r} is missing or the value was "
                f"non-numeric; extracted: {sorted(clean)}")
        return clean[target]
    return clean
