"""Template (non-intrusive) tuning mode: comment-directive extraction +
per-trial rendering.

The reference scans the user file for `{% x = TuneInt(2, (1, 8)) %}`
comment annotations, rewrites them into a Jinja2 `.tpl` with `${{ }}`
variable delimiters and renders one source file per trial
(`/root/reference/python/uptune/src/codegen.py:153-196`,
`src/template.py:13-46`).  Differences here, both deliberate:

* unnamed annotations get the *annotated variable's own name* instead of
  a random 8-char string (codegen.py:58-67) — deterministic across runs,
  so archives resume without the reference's name-reload dance
  (codegen.py:42-52);
* values are rendered with Python repr semantics via a `py` filter, so
  enum strings/bools arrive as valid source without the reference's
  bool `patch` filter hack (template.py:40-46).

Supported annotation calls: TuneInt, TuneFloat, TuneEnum, TuneBool,
TuneLog (log-scale int), TunePow2, TunePermutation.
"""
from __future__ import annotations

import json
import re
from typing import Any, Dict, List, Optional, Tuple

ANNOT_RE = re.compile(
    r"\{%\s*([A-Za-z_]\w*)\s*=\s*(Tune\w+)\s*\((.*?)\)\s*%\}")

VAR_OPEN, VAR_CLOSE = "${{", "}}"


def _rec(name, type_, default, **kw):
    rec = {"name": name, "type": type_, "default": default}
    rec.update(kw)
    return rec


def _builders(var: str):
    """Annotation-call namespace; `var` is the annotated variable name,
    used when no explicit name is given."""
    def TuneInt(default, scope, name=None):
        return _rec(name or var, "int", int(default),
                    lo=int(scope[0]), hi=int(scope[1]))

    def TuneFloat(default, scope, name=None):
        return _rec(name or var, "float", float(default),
                    lo=float(scope[0]), hi=float(scope[1]))

    def TuneEnum(default, options, name=None):
        return _rec(name or var, "enum", default, options=list(options))

    def TuneBool(default, name=None):
        return _rec(name or var, "bool", bool(default))

    def TuneLog(default, scope, name=None):
        return _rec(name or var, "log_int", int(default),
                    lo=int(scope[0]), hi=int(scope[1]))

    def TunePow2(default, scope, name=None):
        return _rec(name or var, "pow2", int(default),
                    lo=int(scope[0]), hi=int(scope[1]))

    def TunePermutation(default, name=None):
        return _rec(name or var, "perm", list(default),
                    items=list(default))

    return {k: v for k, v in locals().items() if k.startswith("Tune")}


class TemplateProgram:
    """An annotated source file compiled to (param records, Jinja tpl)."""

    def __init__(self, path: str):
        self.path = path
        with open(path) as f:
            src = f.read()
        self.records: List[Dict[str, Any]] = []
        lines = []
        seen = set()
        for lineno, line in enumerate(src.splitlines(keepends=True), 1):
            m = ANNOT_RE.search(line)
            if not m:
                lines.append(line)
                continue
            var, call, args = m.groups()
            try:
                rec = eval(f"{call}({args})", {"__builtins__": {}},
                           _builders(var))
            except Exception as e:
                raise ValueError(
                    f"{path}:{lineno}: bad annotation "
                    f"{{% {var} = {call}({args}) %}}: {e}") from e
            if rec["name"] in seen:
                raise ValueError(
                    f"{path}:{lineno}: duplicate tunable name "
                    f"{rec['name']!r}")
            seen.add(rec["name"])
            self.records.append(rec)
            # rewrite `var = <anything>  # {% ... %}` into a render slot
            assign = re.match(rf"(\s*){re.escape(var)}\s*=", line)
            if assign is None:
                raise ValueError(
                    f"{path}:{lineno}: annotation variable {var!r} does "
                    f"not match the line's assignment target")
            indent = assign.group(1)
            lines.append(
                f"{indent}{var} = {VAR_OPEN} cfg[{rec['name']!r}] | py "
                f"{VAR_CLOSE}\n")
        self.tpl = "".join(lines)

    @property
    def is_template(self) -> bool:
        return bool(self.records)

    # ------------------------------------------------------------------
    def render(self, cfg: Optional[Dict[str, Any]] = None) -> str:
        """Render source with `cfg` (defaults when None)."""
        import jinja2
        env = jinja2.Environment(
            block_start_string="{#", block_end_string="#}",
            variable_start_string=VAR_OPEN, variable_end_string=VAR_CLOSE,
            keep_trailing_newline=True)
        env.filters["py"] = repr
        full = dict(self.defaults())
        full.update(cfg or {})
        return env.from_string(self.tpl).render(cfg=full)

    def render_to(self, path: str, cfg: Optional[Dict[str, Any]] = None
                  ) -> None:
        import os
        if os.path.islink(path):
            os.unlink(path)   # replace the sandbox symlink, not its target
        with open(path, "w") as f:
            f.write(self.render(cfg))

    def defaults(self) -> Dict[str, Any]:
        return {r["name"]: r["default"] for r in self.records}

    def write_params(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump([self.records], f, indent=1)


def detect_template(path: str) -> Optional[TemplateProgram]:
    """Return a TemplateProgram if the file carries annotations."""
    tp = TemplateProgram(path)
    return tp if tp.is_template else None
