"""The black-box evaluation plane: subprocess measurement, sandboxed
worker pools, and the program-tuning controller that drives the on-device
Tuner through its ask/tell surface.

Replaces the reference's Ray-actor execution layer
(`/root/reference/python/uptune/api.py:813-910` RunProgram,
`api.py:399-594` async_execute, `src/single_stage.py:13-82`) with a
dependency-free subprocess pool: the search side runs as batched XLA
programs on the TPU, so the host side only needs cheap process
supervision, not a distributed object store.
"""
from .measure import call_program
from .pool import WorkerPool
from .controller import ProgramTuner
from .space_io import space_from_params, stage_spaces, default_config

__all__ = ["call_program", "WorkerPool", "ProgramTuner",
           "space_from_params", "stage_spaces", "default_config"]
