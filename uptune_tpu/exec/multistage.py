"""Multi-stage tuning modes.

Two distinct modes, auto-selected like the reference
(`/root/reference/python/uptune/src/async_task_scheduler.py:465-474`):

* **DecoupledTuner** — the program declares >1 `ut.target` breakpoint
  (>1 stage in ut.params.json).  Each pipeline stage gets its own Tuner +
  WorkerPool and all stages tune concurrently; a stage-s trial replays
  stages < s from their current best configs (the best-config stack,
  async_task_scheduler.py:106-145 + 117-126), published as
  `configs/{s}-best.json`.

* **MultiStageTuner** — the program declares an `ut.interm(features)`
  checkpoint (marker file ut.interim_features.json).  Tuning runs in
  surrogate-filtered epochs (src/multi_stage.py:50-165): a candidate pool
  of cand_factor x parallel proposals runs the cheap 'pre' phase to the
  interm breakpoint, a feature-space surrogate scores the emitted
  vectors, only `parallel` survivors run the full 'post' phase, and the
  surrogate retrains online on (features, QoR) pairs.
"""
from __future__ import annotations

import json
import logging
import os
import shutil
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..api.session import write_best
from ..driver.driver import TuneResult, Tuner
from .controller import ProgramTuner
from .pool import WorkerPool
from .space_io import default_config, space_from_params

log = logging.getLogger("uptune_tpu")

INTERIM_FILE = "ut.interim_features.json"
FEATURES_FILE = "ut.features.json"


def select_mode(pt: ProgramTuner) -> str:
    """'decouple' | 'multistage' | 'single' (a_t_s.py:465-474)."""
    if pt.params is not None and len(pt.params) > 1:
        return "decouple"
    if os.path.isfile(os.path.join(pt.work_dir, INTERIM_FILE)):
        return "multistage"
    return "single"


def run_auto(pt: ProgramTuner) -> TuneResult:
    """Analyze (if needed) and dispatch to the right mode."""
    if pt.params is None:
        pt.analyze()
    mode = select_mode(pt)
    if mode == "decouple":
        return DecoupledTuner(pt).run()
    if mode == "multistage":
        return MultiStageTuner(pt).run()
    return pt.run()


# ---------------------------------------------------------------------
class _Stage:
    def __init__(self, index: int, records, tuner: Tuner,
                 pool: WorkerPool):
        self.index = index
        self.records = records
        self.tuner = tuner
        self.pool = pool
        self.queue: List = []
        self.dry_asks = 0
        self.best_published: Optional[float] = None


class DecoupledTuner:
    """Stage-parallel pipeline tuning over one ProgramTuner's program."""

    def __init__(self, pt: ProgramTuner):
        if pt.params is None:
            pt.analyze()
        if len(pt.params) < 2:
            raise ValueError("decouple mode needs >= 2 stages")
        self.pt = pt
        self.work_dir = pt.work_dir
        os.makedirs(os.path.join(self.work_dir, "configs"), exist_ok=True)

    def _publish_stage_best(self, stage: _Stage) -> None:
        """Push a stage's best config onto the best-config stack
        (a_t_s.py:117-126) for downstream stages to replay."""
        res = stage.tuner.result()
        if not res.best_config:
            return
        if stage.best_published is not None and \
                res.best_qor >= stage.best_published:
            return
        stage.best_published = res.best_qor
        path = os.path.join(self.work_dir, "configs",
                            f"{stage.index}-best.json")
        with open(path, "w") as f:
            json.dump(res.best_config, f)

    def _pre_launch(self, stage_idx: int):
        """Sandboxes need the upstream best-config stack + any template
        render."""
        tpl = self.pt.template
        tpl_name = (os.path.basename(tpl.path) if tpl else None)

        def hook(sb, index, trial):
            for t in range(stage_idx):
                src = os.path.join(self.work_dir, "configs",
                                   f"{t}-best.json")
                if os.path.isfile(src):
                    shutil.copy(src, os.path.join(sb, "configs",
                                                  f"{t}-best.json"))
            if tpl is not None:
                tpl.render_to(os.path.join(sb, tpl_name), trial.config)
        return hook

    def run(self, test_limit: Optional[int] = None,
            time_limit: Optional[float] = None) -> TuneResult:
        pt = self.pt
        limit = int(test_limit if test_limit is not None
                    else pt.test_limit)
        wall = time_limit if time_limit is not None else pt.timeout
        stages: List[_Stage] = []
        try:
            for s, records in enumerate(pt.params):
                tuner = Tuner(
                    space_from_params(records), None,
                    technique=pt.technique, seed=pt.seed + s,
                    sense=pt.sense,
                    archive=os.path.join(self.work_dir,
                                         f"ut.archive_stage{s}.jsonl"),
                    resume=pt.resume, hooks=pt.hooks,
                    label=f"stage{s}")
                pool = WorkerPool(
                    pt.command, self.work_dir, pt.parallel,
                    runtime_limit=pt.runtime_limit, env=pt.env_extra,
                    sandbox=pt.use_sandbox, slot_prefix=f"s{s}.",
                    pre_launch=self._pre_launch(s)).start()
                st = _Stage(s, records, tuner, pool)
                st.queue.extend(tuner.inject([default_config(records)],
                                             "seed"))
                stages.append(st)

            t0 = time.time()
            while True:
                progress = False
                for st in stages:
                    tuner, pool = st.tuner, st.pool
                    if (tuner.told + pool.busy_count + len(st.queue)
                            < limit and
                            len(st.queue) < len(pool.free_slots())
                            and st.dry_asks < 8):
                        asked = tuner.ask(
                            min_trials=len(pool.free_slots()))
                        st.queue.extend(asked)
                        st.dry_asks = 0 if asked else st.dry_asks + 1
                    while st.queue and pool.free_slots() and \
                            tuner.told + pool.busy_count < limit:
                        pool.submit(st.queue.pop(0), stage=st.index)
                        progress = True
                    for trial, qor, dur, info in pool.poll(pt.interval):
                        stats = tuner.tell(trial, qor, dur)
                        progress = True
                        if stats is not None and stats.was_new_best:
                            self._publish_stage_best(st)
                done = all(
                    st.tuner.told >= limit or (
                        st.pool.busy_count == 0 and not st.queue
                        and st.dry_asks >= 8)
                    for st in stages) and all(
                    st.pool.busy_count == 0 for st in stages)
                if done or (wall and time.time() - t0 > wall):
                    break
                if not progress:
                    time.sleep(pt.interval)
            for st in stages:
                for trial, qor, dur, info in st.pool.drain(
                        timeout=pt.runtime_limit):
                    st.tuner.tell(trial, qor, dur)
                while st.queue:
                    st.tuner.cancel(st.queue.pop(0))
        finally:
            for st in stages:
                st.pool.shutdown()
                st.tuner.close()

        # merged result: every stage's best params; QoR = final stage's
        merged: Dict[str, Any] = {}
        for st in stages:
            merged.update(st.tuner.result().best_config)
        last = stages[-1].tuner.result()
        res = TuneResult(merged, last.best_qor,
                         sum(st.tuner.evals for st in stages),
                         sum(st.tuner.steps for st in stages),
                         last.trace)
        if merged:
            write_best(merged, res.best_qor, work_dir=self.work_dir)
        return res


# ---------------------------------------------------------------------
class _FeatureSurrogate:
    """GP over program-emitted feature vectors (the reference's XGBoost
    ensemble role, src/multi_stage.py:8-22 score + xgbregressor.py)."""

    def __init__(self, seed: int = 0, max_points: int = 1024):
        import jax
        from ..surrogate import gp as gp_mod
        self._gp = gp_mod
        self._fit = jax.jit(gp_mod.fit)
        self._predict = jax.jit(gp_mod.predict)
        self._key = jax.random.PRNGKey(seed)
        self.max_points = max_points
        self._xs: List[np.ndarray] = []
        self._ys: List[float] = []
        self._state = None
        self._mu = self._sd = None   # feature z-score stats

    @property
    def fitted(self) -> bool:
        return self._state is not None

    def observe(self, feats, qor: float) -> None:
        if feats is None or not np.isfinite(qor):
            return
        self._xs.append(np.asarray(feats, np.float32))
        self._ys.append(float(qor))

    def refit(self) -> None:
        import jax
        import jax.numpy as jnp
        if len(self._ys) < 8:
            return
        xs = np.stack(self._xs)
        # program features are raw-scale; z-score them so the GP's unit
        # lengthscale prior is meaningful
        self._mu = xs.mean(axis=0)
        self._sd = xs.std(axis=0) + 1e-8
        x = jnp.asarray((xs - self._mu) / self._sd)
        y = jnp.asarray(np.asarray(self._ys, np.float32))
        self._key, ks = jax.random.split(self._key)
        x, y = self._gp.subsample(ks, x, y, self.max_points)
        self._state = self._fit(x, y)

    def scores(self, feats: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp
        x = (np.asarray(feats, np.float32) - self._mu) / self._sd
        mean, _ = self._predict(self._state, jnp.asarray(x))
        return np.asarray(mean)


class MultiStageTuner:
    """Surrogate-filtered pre/post epoch tuning (multirun)."""

    def __init__(self, pt: ProgramTuner, *, cand_factor: int = 6,
                 keep_split: float = 0.5, retrain_interval: int = 2):
        if pt.params is None:
            pt.analyze()
        self.pt = pt
        self.cand_factor = cand_factor       # pool = factor x parallel
        self.keep_split = keep_split         # sample within best split
        self.retrain_interval = retrain_interval
        self.surrogate = _FeatureSurrogate(seed=pt.seed)
        self._rng = np.random.RandomState(pt.seed)

    @staticmethod
    def _parse_features(sandbox: str, stage: int):
        path = os.path.join(sandbox, FEATURES_FILE)
        try:
            with open(path) as f:
                rows = json.load(f)
            return list(map(float, rows[-1][1]))
        except (OSError, json.JSONDecodeError, IndexError, TypeError,
                ValueError):
            return None

    def _select(self, trials, feats) -> List[int]:
        """Indices of trials promoted to the 'post' phase."""
        k = min(self.pt.parallel, len(trials))
        valid = [i for i, f in enumerate(feats) if f is not None]
        if not valid:
            return []
        if not self.surrogate.fitted:
            return list(self._rng.choice(valid, size=min(k, len(valid)),
                                         replace=False))
        fmat = np.stack([feats[i] for i in valid])
        scores = self.surrogate.scores(fmat)
        order = np.argsort(scores)           # engine orientation: low=good
        split = max(k, int(np.ceil(len(order) * self.keep_split)))
        top = [valid[i] for i in order[:split]]
        picked = self._rng.choice(len(top), size=min(k, len(top)),
                                 replace=False)
        return [top[i] for i in picked]

    def run(self, test_limit: Optional[int] = None,
            time_limit: Optional[float] = None) -> TuneResult:
        pt = self.pt
        limit = int(test_limit if test_limit is not None
                    else pt.test_limit)
        wall = time_limit if time_limit is not None else pt.timeout
        records = pt.params[0]
        space = space_from_params(records)
        tuner = pt._make_tuner(space)
        pt.tuner = tuner

        tpl = pt.template
        tpl_name = os.path.basename(tpl.path) if tpl else None

        def pre_launch(sb, index, trial):
            fpath = os.path.join(sb, FEATURES_FILE)
            if os.path.isfile(fpath):
                os.unlink(fpath)
            if tpl is not None:
                tpl.render_to(os.path.join(sb, tpl_name), trial.config)

        n_pre = pt.parallel * self.cand_factor
        pre_pool = WorkerPool(
            pt.command, pt.work_dir, n_pre,
            runtime_limit=pt.runtime_limit, env=pt.env_extra,
            sandbox=pt.use_sandbox, slot_prefix="pre.",
            pre_launch=pre_launch,
            result_parser=self._parse_features).start()
        post_pool = WorkerPool(
            pt.command, pt.work_dir, pt.parallel,
            runtime_limit=pt.runtime_limit, env=pt.env_extra,
            sandbox=pt.use_sandbox, slot_prefix="post.",
            pre_launch=pre_launch).start()

        # seed: defaults' QoR is known from the profiling run
        seed_trials = tuner.inject([default_config(records)], "seed")
        if seed_trials and pt.default_qor is not None:
            for tr in seed_trials:
                tuner.tell(tr, pt.default_qor)

        t0 = time.time()
        epoch = 0
        feat_of: Dict[int, Any] = {}         # gid -> feature vector
        try:
            while tuner.told < limit:
                epoch += 1
                asked = tuner.ask(min_trials=n_pre)
                # cancel the tail of the last ticket instead of slicing
                # it off: an orphaned (never told/cancelled) trial keeps
                # its whole ticket open forever — evals stalls and its
                # pending hashes are never released
                trials = asked[:n_pre]
                for tr in asked[n_pre:]:
                    tuner.cancel(tr)
                if not trials:
                    break
                # ---- 'pre' phase: run to the interm breakpoint
                for tr in trials:
                    pre_pool.submit(
                        tr, stage=0,
                        extra_env={"UT_MULTI_STAGE_SAMPLE": "1"})
                feats: List[Any] = [None] * len(trials)
                pos = {tr.gid: i for i, tr in enumerate(trials)}
                for trial, fv, dur, info in pre_pool.drain(
                        timeout=pt.runtime_limit):
                    feats[pos[trial.gid]] = fv
                # ---- select survivors, cancel the rest
                chosen = set(self._select(trials, feats))
                post = []
                for i, tr in enumerate(trials):
                    if i in chosen:
                        feat_of[tr.gid] = feats[i]
                        post.append(tr)
                    else:
                        tuner.cancel(tr)
                # ---- 'post' phase: full runs
                for tr in post:
                    post_pool.submit(tr, stage=0)
                for trial, qor, dur, info in post_pool.drain(
                        timeout=pt.runtime_limit):
                    stats = tuner.tell(trial, qor, dur)
                    if qor is not None:
                        self.surrogate.observe(
                            feat_of.pop(trial.gid, None),
                            tuner.sign * qor)
                    pt._maybe_new_best(stats)
                if epoch % self.retrain_interval == 0:
                    self.surrogate.refit()
                if wall and time.time() - t0 > wall:
                    break
        finally:
            pre_pool.shutdown()
            post_pool.shutdown()
            tuner.close()
        res = tuner.result()
        if res.best_config:
            write_best(res.best_config, res.best_qor, work_dir=pt.work_dir)
        return res


ProgramTuner.run_auto = run_auto
