"""Subprocess measurement with timeout kill and resource limits.

The spec is the reference's `call_program`
(`/root/reference/python/uptune/api.py:857-907` and
`opentuner/measurement/interface.py:231-346`): run the user program in
its own process group, enforce a wall-clock limit by SIGTERM-then-SIGKILL
of the whole group, optionally cap address space via setrlimit, and
report (returncode, stdout, stderr, wall time, timed_out).
"""
from __future__ import annotations

import os
import signal
import subprocess
import time
from typing import Any, Dict, Optional


def _preexec(memory_limit: Optional[int]):
    """Child-side setup: own process group + optional memory cap
    (interface.py:309-325 preexec_setpgid_setrlimit)."""
    def setup():
        os.setsid()
        if memory_limit:
            import resource
            resource.setrlimit(resource.RLIMIT_AS,
                               (memory_limit, memory_limit))
    return setup


def kill_process_group(proc: subprocess.Popen,
                       grace_s: float = 2.0) -> None:
    """SIGTERM the child's whole process group, escalate to SIGKILL
    (api.py:893-900, interface.py:335-346 goodkillpg)."""
    try:
        pgid = os.getpgid(proc.pid)
    except ProcessLookupError:
        return
    try:
        os.killpg(pgid, signal.SIGTERM)
    except ProcessLookupError:
        return
    deadline = time.time() + grace_s
    while time.time() < deadline:
        if proc.poll() is not None:
            return
        time.sleep(0.05)
    try:
        os.killpg(pgid, signal.SIGKILL)
    except ProcessLookupError:
        pass
    proc.wait()


def call_program(cmd, *, limit: Optional[float] = None,
                 env: Optional[Dict[str, str]] = None,
                 cwd: Optional[str] = None,
                 memory_limit: Optional[int] = None,
                 capture: bool = True) -> Dict[str, Any]:
    """Run `cmd` (str -> shell, list -> exec) to completion or `limit`
    seconds; returns {'returncode', 'stdout', 'stderr', 'time',
    'timeout'}.  A timed-out run has returncode < 0 and timeout=True."""
    t0 = time.time()
    pipe = subprocess.PIPE if capture else None
    proc = subprocess.Popen(
        cmd, shell=isinstance(cmd, str), cwd=cwd, env=env,
        stdout=pipe, stderr=pipe, text=True,
        preexec_fn=_preexec(memory_limit))
    timed_out = False
    try:
        out, err = proc.communicate(timeout=limit)
    except subprocess.TimeoutExpired:
        timed_out = True
        kill_process_group(proc)
        out, err = (proc.communicate() if capture else ("", ""))
    return {"returncode": proc.returncode, "stdout": out or "",
            "stderr": err or "", "time": time.time() - t0,
            "timeout": timed_out}
