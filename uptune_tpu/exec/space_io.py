"""Bridge between the JSON param records written by the intrusive API
(`ut.params.json`, see uptune_tpu/api/state.py) and the device-side
`Space`.

The reference builds an OpenTuner ConfigurationManipulator from the same
records (`/root/reference/python/uptune/api.py:179-199` create_params);
here each record becomes one typed ParamSpec lane of a flat-encoded
Space.
"""
from __future__ import annotations

from typing import Any, Dict, List, Sequence

from ..space import params as P
from ..space.spec import Space


def _spec_from_record(rec: Dict[str, Any]) -> P.ParamSpec:
    name, kind = rec["name"], rec["type"]
    if kind == "int":
        return P.IntParam(name, int(rec["lo"]), int(rec["hi"]))
    if kind == "float":
        return P.FloatParam(name, float(rec["lo"]), float(rec["hi"]))
    if kind == "bool":
        return P.BoolParam(name)
    if kind == "enum":
        opts = rec["options"]
        # JSON round-trips lists; options must be hashable for codecs
        return P.EnumParam(name, tuple(
            tuple(o) if isinstance(o, list) else o for o in opts))
    if kind == "perm":
        return P.PermParam(name, tuple(
            tuple(o) if isinstance(o, list) else o for o in rec["items"]))
    if kind == "log_int":
        return P.LogIntParam(name, int(rec["lo"]), int(rec["hi"]))
    if kind == "log_float":
        return P.LogFloatParam(name, float(rec["lo"]), float(rec["hi"]))
    if kind == "pow2":
        return P.Pow2Param(name, int(rec["lo"]), int(rec["hi"]))
    if kind == "selector":
        return P.SelectorParam(name, tuple(rec["choices"]),
                               int(rec.get("max_cutoff", 0)))
    if kind == "bool_array":
        return P.BoolArrayParam(name, int(rec["n"]))
    if kind == "int_array":
        return P.IntArrayParam(name, int(rec["n"]), int(rec["lo"]),
                               int(rec["hi"]))
    if kind == "float_array":
        return P.FloatArrayParam(name, int(rec["n"]), float(rec["lo"]),
                                 float(rec["hi"]))
    raise ValueError(f"unknown param record type {kind!r} for {name!r}")


def space_from_params(records: Sequence[Dict[str, Any]]) -> Space:
    """Build a Space from ONE stage's param records."""
    return Space([_spec_from_record(r) for r in records])


def stage_spaces(all_records: Sequence[Sequence[Dict[str, Any]]]
                 ) -> List[Space]:
    """Build one Space per stage from the full ut.params.json payload."""
    return [space_from_params(stage) for stage in all_records]


def records_from_space(space: Space) -> List[Dict[str, Any]]:
    """The inverse bridge: serialize a library `Space` back into the
    JSON param records `space_from_params` consumes.  The session
    client (uptune_tpu/serve) sends these over the wire so a server
    rebuilds an identical Space — identical `Space.signature()`, so two
    tenants opening from the same Space land in the same engine group.
    Only JSON-representable option/item values survive the round trip
    (the wire format is JSON); ScheduleParam dependencies do not cross
    the wire."""
    out: List[Dict[str, Any]] = []
    for s in space.specs:
        if isinstance(s, P.ScheduleParam):
            raise ValueError(
                f"ScheduleParam {s.name!r} is not wire-serializable")
        if isinstance(s, P.PermParam):
            out.append({"name": s.name, "type": "perm",
                        "items": [list(o) if isinstance(o, tuple) else o
                                  for o in s.items]})
        elif isinstance(s, P.SelectorParam):
            out.append({"name": s.name, "type": "selector",
                        "choices": list(s.choices),
                        "max_cutoff": s.max_cutoff})
        elif isinstance(s, P.EnumParam):
            out.append({"name": s.name, "type": "enum",
                        "options": [list(o) if isinstance(o, tuple) else o
                                    for o in s.options]})
        elif isinstance(s, P.BoolArrayParam):
            out.append({"name": s.name, "type": "bool_array", "n": s.n})
        elif isinstance(s, P.IntArrayParam):
            out.append({"name": s.name, "type": "int_array", "n": s.n,
                        "lo": s.lo, "hi": s.hi})
        elif isinstance(s, P.FloatArrayParam):
            out.append({"name": s.name, "type": "float_array", "n": s.n,
                        "lo": s.lo, "hi": s.hi})
        elif isinstance(s, P.BoolParam):
            out.append({"name": s.name, "type": "bool"})
        elif isinstance(s, (P.LogIntParam,)):
            out.append({"name": s.name, "type": "log_int",
                        "lo": s.lo, "hi": s.hi})
        elif isinstance(s, (P.LogFloatParam,)):
            out.append({"name": s.name, "type": "log_float",
                        "lo": s.lo, "hi": s.hi})
        elif isinstance(s, P.Pow2Param):
            out.append({"name": s.name, "type": "pow2",
                        "lo": s.lo, "hi": s.hi})
        elif isinstance(s, P.IntParam):
            out.append({"name": s.name, "type": "int",
                        "lo": s.lo, "hi": s.hi})
        elif isinstance(s, P.FloatParam):
            out.append({"name": s.name, "type": "float",
                        "lo": s.lo, "hi": s.hi})
        else:
            raise ValueError(
                f"no wire form for param {s.name!r} ({type(s).__name__})")
    return out


def default_config(records: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """The program's declared defaults as a config dict (the seed trial —
    the reference captures its QoR in the profiling run)."""
    out = {}
    for r in records:
        v = r.get("default")
        out[r["name"]] = list(v) if r["type"] == "perm" else v
    return out
