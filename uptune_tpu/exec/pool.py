"""Sandboxed subprocess worker pool for black-box program evaluation.

Replaces the reference's Ray actor pool (`/root/reference/python/uptune/
api.py:813-910` RunProgram + the free-list dispatch `api.py:458-554` and
dead-actor replacement `api.py:668-679`) with plain POSIX process
supervision:

* each worker slot owns a sandbox dir (`ut.temp/temp.{i}`) populated
  with symlinks to the work dir's files (api.py:104-125 prepare_workdir),
  so concurrent trials never collide on build artifacts;
* a trial is submitted by publishing its config JSON into the sandbox
  (`configs/ut.dr_stage{S}_index{I}.json`, the publish side of
  async_task_scheduler.py:315-338) and launching the user command with
  the UT_* env protocol;
* poll() sweeps slots: completed runs have their QoR file parsed,
  timed-out runs are process-group-killed and their sandbox rebuilt
  (the dead-worker replacement semantics).
"""
from __future__ import annotations

import json
import os
import shutil
import subprocess
import time
from typing import Any, Dict, List, Optional, Tuple

from .. import obs
from ..obs import faults, sidecar
from .measure import _preexec, kill_process_group

PROTOCOL_FILES = ("ut.params.json",)   # copied (not symlinked) per sandbox


class _Slot:
    __slots__ = ("index", "sandbox", "proc", "trial", "t0", "t0p",
                 "deadline", "stage", "log_f", "err_f")

    def __init__(self, index: int, sandbox: str):
        self.index = index
        self.sandbox = sandbox
        self.proc: Optional[subprocess.Popen] = None
        self.trial = None
        self.t0 = 0.0
        self.t0p = 0.0       # perf_counter at launch (obs build span)
        self.deadline = float("inf")
        self.stage = 0
        self.log_f = None
        self.err_f = None

    @property
    def busy(self) -> bool:
        return self.proc is not None


class WorkerPool:
    """N sandboxed subprocess evaluation slots.

    Parameters
    ----------
    command : str | list
        The user program invocation (run with cwd = the slot sandbox).
    work_dir : str
        Directory holding the user program + protocol files.
    n_workers : int
        Parallel evaluation width (the reference's --parallel-factor).
    runtime_limit : float | None
        Per-trial wall-clock limit in seconds (api.py:25-28 default 7200).
    env : dict | None
        Extra environment for every trial (merged over os.environ).
    memory_limit : int | None
        Per-trial address-space cap in bytes (setrlimit).
    sandbox : bool
        If False, all slots share work_dir directly (only safe for
        parallel=1 or read-only programs).
    """

    def __init__(self, command, work_dir: str, n_workers: int = 2, *,
                 runtime_limit: Optional[float] = 7200.0,
                 env: Optional[Dict[str, str]] = None,
                 memory_limit: Optional[int] = None,
                 sandbox: bool = True,
                 pre_launch=None,
                 result_parser=None,
                 slot_prefix: str = ""):
        # pre_launch(sandbox_dir, slot_index, trial) runs after the config
        # publish and before the subprocess starts — template mode renders
        # the per-trial source file here (src/single_stage.py:26-27)
        self.pre_launch = pre_launch
        # result_parser(sandbox_dir, stage) -> value|None overrides the
        # default QoR-file parse (multi-stage 'pre' phases read feature
        # vectors instead, src/multi_stage.py:88-102)
        self.result_parser = result_parser
        # slot_prefix namespaces sandbox dirs so several pools (one per
        # pipeline stage in decouple mode) share one work dir
        self.slot_prefix = slot_prefix
        self.command = command
        self.work_dir = os.path.abspath(work_dir)
        self.n_workers = int(n_workers)
        self.runtime_limit = runtime_limit
        self.env_extra = dict(env or {})
        self.memory_limit = memory_limit
        self.use_sandbox = sandbox
        self.temp_root = os.path.join(self.work_dir, "ut.temp")
        self.replaced = 0          # dead-worker replacements performed
        self.launched = 0
        self.busy_s = 0.0          # summed per-trial wall time (reaped)
        self._t_started = time.time()
        self._slots: List[_Slot] = []

    # ------------------------------------------------------------------
    def start(self) -> "WorkerPool":
        os.makedirs(self.temp_root, exist_ok=True)
        self._slots = [
            _Slot(i, self._build_sandbox(i)) for i in range(self.n_workers)]
        self._t_started = time.time()
        return self

    def _build_sandbox(self, index: int) -> str:
        if not self.use_sandbox:
            os.makedirs(os.path.join(self.work_dir, "configs"),
                        exist_ok=True)
            return self.work_dir
        path = os.path.join(self.temp_root,
                            f"temp.{self.slot_prefix}{index}")
        if os.path.isdir(path):
            shutil.rmtree(path)
        os.makedirs(os.path.join(path, "configs"))
        for name in os.listdir(self.work_dir):
            # protocol outputs (ut.*) stay per-sandbox; everything else is
            # shared read-only via symlink (api.py:113-123)
            if name.startswith("ut.") or name == "configs":
                continue
            os.symlink(os.path.join(self.work_dir, name),
                       os.path.join(path, name))
        for name in PROTOCOL_FILES:
            src = os.path.join(self.work_dir, name)
            if os.path.isfile(src):
                shutil.copy(src, os.path.join(path, name))
        return path

    def _replace_sandbox(self, slot: _Slot) -> None:
        """Rebuild a slot after a kill — the dead-worker replacement
        (api.py:668-679: delete the actor, create a fresh one)."""
        self.replaced += 1
        slot.sandbox = self._build_sandbox(slot.index)

    # ------------------------------------------------------------------
    def free_slots(self) -> List[int]:
        return [s.index for s in self._slots if not s.busy]

    @property
    def busy_count(self) -> int:
        return sum(1 for s in self._slots if s.busy)

    @property
    def n_free(self) -> int:
        return sum(1 for s in self._slots if not s.busy)

    def stats(self) -> Dict[str, Any]:
        """Pool-side scoreboard (logs + bench artifacts): launches =
        real builds started (the store's cache hits never reach here),
        dead-worker replacements, slot-seconds spent building, and
        utilization."""
        return {"launched": self.launched, "replaced": self.replaced,
                "busy_s": round(self.busy_s, 4),
                "utilization": round(self.utilization(), 4)}

    def utilization(self) -> float:
        """Fraction of slot-seconds spent running trials since start()
        (reaped trials only).  1.0 = every slot always building; the gap
        to 1.0 is dispatch overhead the driver failed to hide — the
        number async prefetch exists to push up."""
        wall = max(time.time() - self._t_started, 1e-9)
        return min(1.0, self.busy_s / (wall * max(1, self.n_workers)))

    def submit(self, trial, stage: int = 0,
               extra_env: Optional[Dict[str, str]] = None) -> int:
        """Publish the trial's config and launch it on a free slot;
        returns the slot index."""
        free = [s for s in self._slots if not s.busy]
        if not free:
            raise RuntimeError("no free worker slot")
        slot = free[0]
        sb = slot.sandbox
        # clear stale protocol outputs (incl. a previous trial's trace
        # sidecar: a reused slot must never replay old child spans)
        for name in os.listdir(sb):
            if name.startswith("ut.qor_stage") or name in (
                    "ut.features.json", sidecar.SIDECAR_FILE):
                os.unlink(os.path.join(sb, name))
        cfg_path = os.path.join(
            sb, "configs", f"ut.dr_stage{stage}_index{slot.index}.json")
        with open(cfg_path, "w") as f:
            json.dump(trial.config, f)

        env = dict(os.environ)
        env.update(self.env_extra)
        env.update(extra_env or {})
        env.update({
            "UT_TUNE_START": "True",
            "UT_CURR_INDEX": str(slot.index),
            "UT_CURR_STAGE": str(stage),
            "UT_GLOBAL_ID": str(trial.gid),
            "UT_WORK_DIR": sb,
        })
        env.pop("UT_BEFORE_RUN_PROFILE", None)
        # trace-context propagation (docs/OBSERVABILITY.md): when the
        # driver traces, the child records its own spans and dumps them
        # to a per-sandbox sidecar merged back at reap.  Pop first so a
        # stale path from an enclosing traced run never leaks into an
        # untraced child (it would dump into a foreign sandbox)
        env.pop(sidecar.SIDECAR_ENV, None)
        if obs.enabled():
            env[sidecar.SIDECAR_ENV] = os.path.join(
                sb, sidecar.SIDECAR_FILE)
        if self.pre_launch is not None:
            self.pre_launch(sb, slot.index, trial)
        slot.log_f = open(os.path.join(sb, "ut.run.log"), "w")
        slot.err_f = open(os.path.join(sb, "ut.run.err"), "w")
        slot.proc = subprocess.Popen(
            self.command, shell=isinstance(self.command, str), cwd=sb,
            env=env, stdout=slot.log_f, stderr=slot.err_f,
            preexec_fn=_preexec(self.memory_limit))
        slot.trial = trial
        slot.t0 = time.time()
        slot.t0p = time.perf_counter()
        slot.deadline = (slot.t0 + self.runtime_limit
                         if self.runtime_limit else float("inf"))
        slot.stage = stage
        self.launched += 1
        obs.count("pool.launched")
        obs.gauge("pool.busy", self.busy_count)
        return slot.index

    # ------------------------------------------------------------------
    def _parse_qor(self, slot: _Slot) -> Optional[float]:
        """Last [index, val, trend] row of the stage QoR file, or None."""
        path = os.path.join(slot.sandbox,
                            f"ut.qor_stage{slot.stage}.json")
        try:
            with open(path) as f:
                rows = json.load(f)
            return float(rows[-1][1])
        except (OSError, json.JSONDecodeError, IndexError, TypeError,
                ValueError):
            return None

    def _reap(self, slot: _Slot, *, killed: bool) -> Tuple[Any, Optional[
            float], float, Dict[str, Any]]:
        faults.fire("pool.reap")
        dur = time.time() - slot.t0
        self.busy_s += dur
        rc = slot.proc.returncode
        for f in (slot.log_f, slot.err_f):
            if f is not None:
                f.close()
        qor = None
        if not killed and rc == 0:
            qor = (self.result_parser(slot.sandbox, slot.stage)
                   if self.result_parser is not None
                   else self._parse_qor(slot))
        info = {"returncode": rc, "timeout": killed, "slot": slot.index,
                "sandbox": slot.sandbox}
        trial = slot.trial
        # the build window on this slot's trace lane (emitted at reap
        # time from the polling thread, with the slot's own launch
        # timestamp): store-hit trials never reach a slot, so their
        # absence from worker lanes is the bypass made visible.  The
        # span stays entirely on the perf_counter timebase (t0p) — the
        # wall-clock `dur` above can go negative across an NTP step
        pdur = time.perf_counter() - slot.t0p
        lane = f"worker-{self.slot_prefix}{slot.index}"
        obs.complete_span(
            "pool.build", t0=slot.t0p, dur=pdur, track=lane,
            gid=getattr(trial, "gid", None), rc=rc, timeout=killed)
        # child-side sidecar spans nest inside the build window on the
        # same lane (clock-offset aligned; killed children usually had
        # no atexit, so an absent file is routine)
        n_child = sidecar.merge_into(
            os.path.join(slot.sandbox, sidecar.SIDECAR_FILE), lane)
        if n_child:
            obs.count("pool.sidecar_events", n_child)
        obs.observe("pool.build_s", pdur)
        obs.gauge("pool.utilization", self.utilization())
        if obs.journal.enabled():
            self._journal_child_rows(slot, trial)
        if killed:
            obs.count("pool.timeouts")
        slot.proc = slot.trial = slot.log_f = slot.err_f = None
        slot.deadline = float("inf")
        if killed:
            self._replace_sandbox(slot)
        return trial, qor, dur, info

    @staticmethod
    def _journal_child_rows(slot: _Slot, trial) -> None:
        """Surface the trial's `ut.feature` covariates and `ut.interm`
        feature vector into the tuning journal (ISSUE 12 satellite):
        the child persisted them to its sandbox (api/report.py), the
        reference fed exactly these rows to its QoR estimator, and the
        journal is where a future transfer prior (ROADMAP item 4b)
        reads them joined to a gid.  `ut.features.json` is cleared at
        submit, so whatever is here came from THIS trial; covars.json
        accumulates per sandbox by design — the current dict is the
        trial's observed state.  Only reached when the journal is on;
        unreadable/absent files are routine (most programs call
        neither API)."""
        from ..api.report import COVARS_FILE, FEATURES_FILE
        gid = getattr(trial, "gid", None)
        try:
            with open(os.path.join(slot.sandbox, COVARS_FILE)) as f:
                covars = json.load(f)
            if isinstance(covars, dict) and covars:
                obs.journal.emit("feature", gid=gid, covars=covars)
        except (OSError, json.JSONDecodeError):
            pass
        try:
            with open(os.path.join(slot.sandbox, FEATURES_FILE)) as f:
                rows = json.load(f)
            # [[index, feats]] (api/report.py interm): journal the
            # vector of the last (only) row
            if rows and isinstance(rows[-1], list) and len(rows[-1]) == 2:
                obs.journal.emit("interm", gid=gid,
                                 feats=list(rows[-1][1]))
        except (OSError, json.JSONDecodeError):
            pass

    def poll(self, timeout: float = 0.05
             ) -> List[Tuple[Any, Optional[float], float, Dict[str, Any]]]:
        """Collect finished/timed-out trials, waiting up to `timeout`
        seconds for at least one if any slot is busy.  Each result is
        (trial, qor | None, wall_time, info)."""
        results = []
        deadline = time.time() + timeout
        while True:
            now = time.time()
            for slot in self._slots:
                if not slot.busy:
                    continue
                if slot.proc.poll() is not None:
                    results.append(self._reap(slot, killed=False))
                elif now > slot.deadline:
                    kill_process_group(slot.proc)
                    results.append(self._reap(slot, killed=True))
            if results or now >= deadline or self.busy_count == 0:
                return results
            time.sleep(min(0.01, max(0.0, deadline - time.time())))

    def drain(self, timeout: Optional[float] = None) -> List[Tuple[
            Any, Optional[float], float, Dict[str, Any]]]:
        """Wait for every busy slot to resolve (bounded by per-trial
        deadlines, plus `timeout` overall if given)."""
        out = []
        t_end = time.time() + timeout if timeout else None
        while self.busy_count:
            out.extend(self.poll(0.1))
            if t_end and time.time() > t_end:
                break
        return out

    def shutdown(self) -> None:
        for slot in self._slots:
            if slot.busy:
                kill_process_group(slot.proc)
                self._reap(slot, killed=True)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.shutdown()
