"""ProgramTuner: end-to-end black-box tuning of an external program.

The TPU-native re-design of the reference's controller stack
(`/root/reference/python/uptune/api.py:399-594` async_execute +
`src/async_task_scheduler.py:20-52` analysis +
`src/single_stage.py:13-82` single-stage run builder):

1. ANALYSIS: run the program once with UT_BEFORE_RUN_PROFILE=On; it
   records its search space (`ut.params.json`) and default QoR.
2. Build the device Space and a Tuner whose proposal side (techniques,
   bandit, dedup, surrogate prune) runs as batched XLA programs.
3. Async evaluation: keep a WorkerPool of subprocess slots busy from the
   Tuner's ask() queue; tell() results back as they arrive (the free-list
   semantics of api.py:458-554) with timeout kill + dead-worker
   replacement; honor @ut.rule config filters, @ut.constraint QoR
   checks, and @ut.model host proposal sources.
4. Persist best.json on every improvement (api.py:146-149) and the jsonl
   trial archive for resume.
5. Content-addressed results store (uptune_tpu/store/, docs/STORE.md):
   every trial is looked up before launch — a hit serves the recorded
   QoR without a build — every measured result is recorded back, and
   concurrent instances sharing one store directory exchange results
   (the reference's SQLite result-database sync, api.py SQLAlchemy).
"""
from __future__ import annotations

import collections
import json
import logging
import os
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import obs
from ..api.constraint import REGISTRY
from ..api.session import settings, write_best
from ..api.state import DEFAULT_QOR_FILE, PARAMS_FILE
from ..api.tuner import registered_models
from ..driver.driver import Trial, Tuner, TuneResult
from .measure import call_program
from .pool import WorkerPool
from .space_io import default_config, space_from_params

log = logging.getLogger("uptune_tpu")


class AnalysisError(RuntimeError):
    pass


class ProgramTuner:
    """Tune an external program invocation over its declared space.

    Parameters mirror the reference's CLI/settings layer (api.py:24-48);
    any left as None falls back to ut.config() session settings.
    """

    def __init__(self, command, work_dir: Optional[str] = None, *,
                 parallel: Optional[int] = None,
                 test_limit: Optional[int] = None,
                 runtime_limit: Optional[float] = None,
                 timeout: Optional[float] = None,
                 technique=None, seed: Optional[int] = None,
                 params_file: Optional[str] = None,
                 archive: Optional[str] = None, resume: bool = False,
                 surrogate=None, surrogate_opts: Optional[dict] = None,
                 surrogate_async: Optional[str] = None,
                 env: Optional[Dict[str, str]] = None,
                 sandbox: bool = True,
                 status_interval: Optional[int] = None,
                 template=None, hooks=None,
                 seed_configs: Optional[List[Dict]] = None,
                 prefetch: Optional[int] = None,
                 compile_cache_dir: Optional[str] = None,
                 store_dir: Optional[str] = None,
                 warm_start: Optional[bool] = None,
                 federate: Optional[bool] = None,
                 exchange_interval: Optional[float] = None):
        # seed_configs: known-good configurations injected as 'seed'
        # trials at startup (the reference's --seed-configuration file
        # loading, opentuner/search/driver.py:37-42) — warm-starts
        # expensive runs from prior bests.  Unlike the declared-defaults
        # seed their QoR is unknown, so they are EVALUATED first.
        # template: a TemplateProgram (non-intrusive mode) — the space
        # comes from its annotations and each trial renders its own copy
        # of the source into the sandbox before launch
        self.template = template
        self.hooks = hooks
        if template is not None and isinstance(command, (list, tuple)):
            # trials must execute the per-sandbox RENDERED copy, so any
            # absolute reference to the annotated source becomes relative
            # to the trial's cwd (its sandbox)
            tpath = os.path.abspath(template.path)
            command = [os.path.basename(c)
                       if isinstance(c, str) and os.path.abspath(c) == tpath
                       else c for c in command]
        self.command = command
        self.work_dir = os.path.abspath(work_dir or os.getcwd())
        os.makedirs(self.work_dir, exist_ok=True)
        self.parallel = int(parallel if parallel is not None
                            else settings["parallel-factor"])
        self.test_limit = int(test_limit if test_limit is not None
                              else settings["test-limit"])
        self.runtime_limit = (runtime_limit if runtime_limit is not None
                              else settings["runtime-limit"])
        self.timeout = (timeout if timeout is not None
                        else settings["timeout"])
        self.interval = float(settings["async-interval"])
        self.technique = (technique if technique is not None
                          else settings["technique"])
        self.seed = int(seed if seed is not None else settings["seed"])
        # `ut --num-hosts N` (or a real pod launch) makes each process
        # an INDEPENDENT search replica over the same program
        # (multi-start): program-mode tuning has no cross-process
        # exchange — the jax.distributed sharded-engine plane is the
        # library-mode story (parallel/).  Diverge the replica seeds,
        # and give non-coordinator replicas their own archive/best
        # files so N appenders never interleave one jsonl (compare
        # afterwards with `ut-stats ut.archive*.jsonl`).
        pid = int(os.environ.get("UT_PROCESS_ID", "0") or 0)
        nproc = int(os.environ.get("UT_NUM_PROCESSES", "1") or 1)
        self.host_tag = f".h{pid}" if (nproc > 1 and pid > 0) else ""
        if nproc > 1:
            self.seed += pid
        self.params_file = params_file
        self.archive = archive if archive is not None else os.path.join(
            self.work_dir, f"ut.archive{self.host_tag}.jsonl")
        self.resume = resume
        self.seed_configs = list(seed_configs or [])
        if surrogate is None:
            # same flags > ut.config() > defaults layering as the
            # sibling parameters above; the settings key holds a kind
            # list (the reference's learning-model list, __init__.py:53)
            m = settings["learning-model"]
            models = [m] if isinstance(m, str) else list(m or [])
            surrogate = models[0] if models else None
            if len(models) > 1:
                log.warning(
                    "[ut] only one surrogate runs per tuner; using %r "
                    "and ignoring %r (the mlp kind is itself an "
                    "ensemble)", surrogate, models[1:])
        self.surrogate = surrogate
        # by-name surrogates get the calibrated defaults (BENCHREPORT
        # settings) unless the caller overrides
        if isinstance(surrogate, str):
            from ..calibrated import CALIBRATED_OPTS
            self.surrogate_opts = {**CALIBRATED_OPTS,
                                   **(surrogate_opts or {})}
            # async surrogate plane (docs/PERF.md): flag > ut.config >
            # default ON for program mode — builds give the background
            # refit wall-clock to hide behind, exactly like prefetch.
            # An explicit surrogate_opts['async_refit'] (library use)
            # wins over the settings default; the explicit
            # --surrogate-async flag wins over everything
            sa = (surrogate_async if surrogate_async is not None
                  else settings["surrogate-async"])
            on = str(sa).lower() not in ("off", "false", "0") \
                if sa is not None else True
            if surrogate_async is not None:
                self.surrogate_opts["async_refit"] = on
            else:
                self.surrogate_opts.setdefault("async_refit", on)
        else:
            self.surrogate_opts = surrogate_opts
            if surrogate is None and surrogate_opts:
                log.warning(
                    "[ut] surrogate options %s have no effect: no "
                    "learning model is enabled (pass --learning-models "
                    "/ ut.config learning-model)",
                    sorted(surrogate_opts))
        self.env_extra = dict(env or {})
        # children (analysis run + sandboxed eval workers) must be able
        # to `import uptune_tpu` even from a plain checkout with no
        # `pip install -e .` (utils/pypath.py)
        from ..utils.pypath import child_pythonpath
        self.env_extra["PYTHONPATH"] = child_pythonpath(
            self.env_extra.get("PYTHONPATH"))
        self.use_sandbox = sandbox
        self.status_interval = (status_interval if status_interval
                                is not None else max(1, self.parallel))
        # async ticket prefetch: keep `prefetch` trials proposed AHEAD
        # of free worker slots, so the device propose+dedup+config
        # materialization runs while every slot is still busy building
        # and a freed slot is refilled instantly (0 = the old lockstep
        # behavior: propose only when a slot is already free).  Default
        # is the pool width — one build wave of lookahead.
        pf = (prefetch if prefetch is not None
              else settings["prefetch-depth"])
        self.prefetch = int(pf if pf is not None else self.parallel)
        self.compile_cache_dir = (
            compile_cache_dir if compile_cache_dir is not None
            else settings["compile-cache-dir"])
        # content-addressed results store (uptune_tpu/store/,
        # docs/STORE.md): consulted before every build — a hit serves
        # the recorded QoR through tell() without launching anything;
        # results land back in it as they are measured, and concurrent
        # instances sharing one directory — or one tcp:// store server
        # (ISSUE 18, docs/STORE.md "Remote store") — exchange them.
        # None resolves to <work_dir>/ut.temp/store; the literal 'off'
        # disables.
        self.store_dir = (store_dir if store_dir is not None
                          else settings["store-dir"])
        self.warm_start = bool(warm_start if warm_start is not None
                               else settings["warm-start"])
        # cooperative-search knobs (ISSUE 18): `federate` feeds sibling
        # (config, qor) rows into the local surrogate's training set at
        # exchange time (K hosts train one surrogate's worth of
        # evidence); `exchange_interval` is the migration cadence —
        # it becomes the store's refresh_interval, the single gate both
        # the elite-migration and federated-rows flows tick on
        self.federate = bool(federate if federate is not None
                             else settings["federate"])
        self.exchange_interval = float(
            exchange_interval if exchange_interval is not None
            else settings["exchange-interval"])
        self.store = None
        self.store_hits = 0        # builds eliminated by cache hits
        self.exchange_injected = 0  # sibling-instance bests ingested
        self.federated_rows = 0    # sibling rows fed to the surrogate
        # observability: speculative trials withdrawn after a tell()
        # landed a new best (their tickets were proposed around the
        # stale incumbent)
        self.spec_cancelled = 0

        self.params: Optional[List[List[Dict[str, Any]]]] = None
        self.default_qor: Optional[float] = None
        self.sense = "min"
        self.tuner: Optional[Tuner] = None
        self.pool: Optional[WorkerPool] = None
        self.stage = 0
        self._results_seen = 0
        self._host_history: List[Tuple[Dict[str, Any], float]] = []

    # ------------------------------------------------------------------
    def analyze(self, force: bool = False) -> List[List[Dict[str, Any]]]:
        """Space discovery: reuse an existing ut.params.json (the
        reference's --params short-circuit, async_task_scheduler.py:21-32)
        or run the profiling subprocess."""
        if self.template is not None:
            # template mode: the space comes from the annotations; run the
            # default-rendered program once for the default QoR + sense
            self.params = [self.template.records]
            self.template.write_params(
                os.path.join(self.work_dir, PARAMS_FILE))
            name = os.path.basename(self.template.path)
            dflt = os.path.join(self.work_dir, name)
            if os.path.abspath(dflt) != os.path.abspath(
                    self.template.path):
                self.template.render_to(dflt)
            env = dict(os.environ)
            env.update(self.env_extra)
            env.pop("UT_TUNE_START", None)
            env.update({"UT_BEFORE_RUN_PROFILE": "On",
                        "UT_WORK_DIR": self.work_dir})
            call_program(self.command, limit=self.runtime_limit, env=env,
                         cwd=self.work_dir)
            self._read_default_qor()
            return self.params

        path = self.params_file or os.path.join(self.work_dir, PARAMS_FILE)
        if not force and os.path.isfile(path):
            with open(path) as f:
                self.params = json.load(f)
        else:
            env = dict(os.environ)
            env.update(self.env_extra)
            env.pop("UT_TUNE_START", None)
            env.update({"UT_BEFORE_RUN_PROFILE": "On",
                        "UT_WORK_DIR": self.work_dir})
            res = call_program(self.command, limit=self.runtime_limit,
                               env=env, cwd=self.work_dir)
            ppath = os.path.join(self.work_dir, PARAMS_FILE)
            if res["returncode"] != 0 or not os.path.isfile(ppath):
                raise AnalysisError(
                    f"analysis run failed (rc={res['returncode']}, "
                    f"timeout={res['timeout']}): "
                    f"{res['stderr'].strip()[-500:]}")
            with open(ppath) as f:
                self.params = json.load(f)
        if not self.params or not any(self.params):
            raise AnalysisError("analysis recorded no tunable parameters")
        self._read_default_qor()
        return self.params

    def _read_default_qor(self) -> None:
        dq_path = os.path.join(self.work_dir, DEFAULT_QOR_FILE)
        if os.path.isfile(dq_path):
            try:
                with open(dq_path) as f:
                    dq = json.load(f)
                self.default_qor = float(dq["qor"])
                self.sense = dq.get("trend", "min")
            except (json.JSONDecodeError, KeyError, TypeError, ValueError):
                pass

    # ------------------------------------------------------------------
    def _enable_compile_cache(self, space) -> None:
        """Persistent XLA compilation cache for the driver's device
        programs, keyed by the space signature: repeated tunes of the
        same program load their propose/dedup/commit executables from
        disk instead of paying first-step compiles (~seconds each).
        Set the base dir via ut.config({'compile-cache-dir': ...}) /
        `ut --compile-cache-dir`; the literal value 'off' disables."""
        base = self.compile_cache_dir
        if isinstance(base, str) and base.lower() in ("off", "none"):
            return
        import hashlib

        from ..utils.platform_guard import enable_compile_cache
        sig = hashlib.sha1("\n".join(
            repr(s) for s in space.specs).encode()).hexdigest()[:16]
        enable_compile_cache(base, subdir=sig)

    def _make_tuner(self, space) -> Tuner:
        filt = (REGISTRY.check_config if REGISTRY.rules else None)
        return Tuner(space, None, technique=self.technique,
                     seed=self.seed, sense=self.sense,
                     archive=self.archive, resume=self.resume,
                     surrogate=self.surrogate,
                     surrogate_opts=self.surrogate_opts,
                     config_filter=filt,
                     hooks=self.hooks)

    def _maybe_new_best(self, stats) -> None:
        if stats is not None and stats.was_new_best:
            res = self.tuner.result()
            write_best(res.best_config, res.best_qor,
                       work_dir=self.work_dir,
                       filename=(f"best{self.host_tag}.json"
                                 if self.host_tag else None))
            log.info("[ut] new best qor=%.6g after %d evals",
                     res.best_qor, res.evals)

    def _status(self, last_qor: Optional[float]) -> None:
        self._results_seen += 1
        if self._results_seen % self.status_interval:
            return
        res = self.tuner.result()
        lw = "fail" if last_qor is None else f"{last_qor:.6g}"
        log.info("[ut] evals=%d best(GB)=%.6g last(LW)=%s pending=%d "
                 "replaced=%d", res.evals, res.best_qor, lw,
                 self.pool.busy_count, self.pool.replaced)

    @staticmethod
    def _cancel_speculative(queue, tuner: Tuner) -> int:
        """Withdraw queued-but-unlaunched trials whose ticket came from
        a technique arm (or the bandit-arbitrated surrogate plane):
        they were proposed around the now-stale incumbent.  cancel()
        guarantees no archive row, no history insert, and — when a
        ticket loses ALL its trials — no observe() and no bandit credit
        (driver._finalize `withdrawn`), so a cancelled pull is an
        unknown outcome, not a penalty.  Externally-provided trials
        (seed configs, @ut.model proposals, random saturation top-ups)
        are kept: their value does not depend on the incumbent."""
        kept, n = [], 0
        while queue:
            tr = queue.popleft()
            tk = tr.ticket
            # injected covers seed/model AND the random saturation
            # top-up (arm set, injected=True) — all incumbent-agnostic;
            # the bandit-arbitrated surrogate pull (credit_virtual) is
            # injected too but IS proposed around the incumbent
            if (not tk.injected) or tk.credit_virtual:
                tuner.cancel(tr)
                n += 1
            else:
                kept.append(tr)
        queue.extend(kept)
        if n:
            # count trials actually withdrawn, not new-best sweeps — a
            # sweep that keeps everything invalidated nothing
            obs.count("driver.spec_invalidations", n)
        return n

    # ------------------------------------------------------------------
    def _open_store(self, space):
        """Open the results store for this (space, command, stage)
        scope, or return None when disabled ('off').  A ``tcp://``
        base opens a `RemoteStore` on a cooperative store server
        (ISSUE 18); anything else a filesystem `ResultStore`."""
        base = self.store_dir
        if isinstance(base, str) and base.lower() in ("off", "none"):
            return None
        if base is None or (isinstance(base, str)
                            and base.lower() in ("on", "default")):
            base = os.path.join(self.work_dir, "ut.temp", "store")
        from ..store import open_store
        extra = ([self.template.path] if self.template is not None
                 else None)
        return open_store(base, [repr(s) for s in space.specs],
                          self.command, stage=self.stage,
                          extra_files=extra, env=self.env_extra,
                          refresh_interval=self.exchange_interval)

    @staticmethod
    def _verdict(qor: Optional[float],
                 config: Dict[str, Any]) -> Optional[float]:
        """USER-oriented QoR -> the tell() verdict: an @ut.constraint
        violation becomes a failure (None).  The ONE rule shared by
        the poll loop, the wall-limit drain, store-hit serving, and
        the profiled seed default."""
        if qor is not None and REGISTRY.constraints and \
                not REGISTRY.check_qor(qor, config):
            return None
        return qor

    def _record_result(self, trial: Trial, qor: Optional[float],
                       dur: float, info: Dict[str, Any]) -> None:
        """Measured trial -> store row.  The RAW QoR is recorded (the
        @ut.constraint verdict is session policy, re-applied at serve
        time); timeouts are not recorded at all — they depend on this
        run's --runtime-limit and another instance with a wider limit
        may succeed."""
        if self.store is None or info.get("timeout"):
            return
        tk = trial.ticket
        self.store.record(
            trial.config, qor, dur, u=tk.u_np[trial.slot],
            perms=[p[trial.slot] for p in tk.perms_np])

    def _serve_hit(self, trial: Trial, row: Dict[str, Any],
                   queue) -> None:
        """A store hit: synthesize the trial result and tell() it
        immediately — no build, but FULL accounting (told/evals budget,
        archive row, surrogate observation, bandit credit) and the same
        new-best speculative invalidation a pool result triggers."""
        t0 = time.perf_counter()
        qor = self._verdict(row.get("qor"), trial.config)
        stats = self.tuner.tell(trial, qor, float(row.get("dur", 0.0)))
        if obs.journal.enabled():
            # store-hit attribution for the search-quality stream: the
            # tell row above records the outcome, this row records that
            # it cost no build (docs/OBSERVABILITY.md, ISSUE 12)
            obs.journal.emit(
                "store_hit", gid=trial.gid,
                qor=None if qor is None else round(float(qor), 6),
                dur=round(float(row.get("dur", 0.0)), 6))
        if obs.enabled():
            # the bypass lane: a served ticket's gid shows up HERE and
            # never on a worker-N build lane
            obs.complete_span("store.serve_hit", t0=t0,
                              dur=time.perf_counter() - t0,
                              track="store", gid=trial.gid)
            obs.observe("store.serve_ms",
                        (time.perf_counter() - t0) * 1e3)
        if qor is not None:
            self._host_history.append((trial.config, qor))
        if stats is not None and stats.was_new_best and self.prefetch:
            self.spec_cancelled += self._cancel_speculative(
                queue, self.tuner)
        self._maybe_new_best(stats)
        self._status(qor)

    def _warm_start_from_store(self) -> int:
        """Preload the store's recorded rows for this scope into the
        tuner: best-so-far + dedup history + surrogate training set,
        with no budget/archive impact (Tuner.preload).  Rows carrying
        exact unit vectors replay bit-exactly; legacy rows without them
        are re-encoded from their configs (close enough for warm-start
        dedup — a boundary float that re-encodes differently just gets
        re-measured once)."""
        store, tuner = self.store, self.tuner
        rows = store.scope_rows()
        if REGISTRY.constraints:
            # stored rows carry the RAW QoR; @ut.constraint is session
            # policy and must gate here exactly as it gates serve-time
            # hits — otherwise a violating row becomes an unbeatable
            # preloaded best and the tune reports a forbidden config
            rows = [r for r in rows
                    if REGISTRY.check_qor(r["qor"], r["cfg"])]
        if not rows:
            return 0
        n = tuner.preload_rows(rows)
        res = tuner.result()
        log.info("[ut] warm start: %d stored trials preloaded "
                 "(best=%.6g)", n, res.best_qor)
        return n

    def _maybe_exchange_best(self, queue) -> None:
        """Multi-instance exchange: when refresh() brings in sibling
        rows, inject the incoming best as an 'exchange' trial if it
        beats our incumbent.  It will be a store hit at launch time —
        entering this instance's history/best/archive with full
        accounting and zero build cost (the reference's SQLite-sync
        new-best propagation, api.py SQLAlchemy plane).

        Acts ONLY on the store's fresh-foreign delta feed
        (`pop_fresh_rows`): rows already present at store open are a
        previous run's results — importing those up front would steer
        the techniques around them and break the exact cache replay of
        a repeated tune (the BENCH_CACHE protocol).  Cross-RUN
        propagation is `--warm-start`'s job.  A sibling's raw best may
        also violate THIS session's @ut.constraint — such rows are
        dropped, never injected (serving one would just burn a budget
        trial as a failure)."""
        rows = self.store.pop_fresh_rows()
        if REGISTRY.constraints:
            rows = [r for r in rows
                    if REGISTRY.check_qor(r["qor"], r["cfg"])]
        if not rows:
            return
        tuner = self.tuner
        pick = min if self.sense == "min" else max
        row = pick(rows, key=lambda r: float(r["qor"]))
        injected = []
        if tuner.sign * float(row["qor"]) < float(tuner.best.qor):
            injected = tuner.inject([row["cfg"]], source="exchange")
        if injected:
            self.exchange_injected += len(injected)
            obs.event("store.exchange", qor=float(row["qor"]))
            obs.count("store.exchange_injected", len(injected))
            if obs.journal.enabled():
                obs.journal.emit("exchange",
                                 qor=round(float(row["qor"]), 6))
            # serve ahead of speculative technique work
            queue.extendleft(reversed(injected))
        if self.federate:
            # federated surrogate rows (ISSUE 18): the injected elite
            # re-enters through its store-hit tell with full
            # accounting, so feed the REST of the delta to the
            # surrogate/dedup planes only — K cooperating hosts train
            # on one pooled evidence set without burning budget trials
            self._federate_rows([r for r in rows
                                 if not (injected and r is row)])

    def _federate_rows(self, rows) -> None:
        """Sibling (config, qor) rows -> the tuner's dedup history +
        surrogate training set (Tuner.preload_rows): no budget, no
        archive rows, no bandit credit — foreign evidence, not this
        run's work.  Refit stays at the surrogate's own versioned-
        snapshot watermark (maybe_refit): migration cadence must not
        force a refit storm on K hosts at once."""
        if not rows:
            return
        n = self.tuner.preload_rows(rows, refit=False)
        if not n:
            return
        self.federated_rows += n
        obs.count("store.federated_rows", n)
        sm = self.tuner.surrogate
        if sm is not None:
            sm.maybe_refit()
        if obs.journal.enabled():
            obs.journal.emit("federate", rows=n)

    def _host_proposals(self, space) -> List[Trial]:
        """Ask @ut.model proposal sources for one config each."""
        trials: List[Trial] = []
        for fn in registered_models():
            try:
                cfg = fn(list(self._host_history), space)
            except Exception as e:  # user code: isolate failures
                log.warning("[ut] custom model %s failed: %s",
                            getattr(fn, "_ut_model_name", fn), e)
                continue
            if isinstance(cfg, dict):
                trials.extend(self.tuner.inject(
                    [cfg], source=getattr(fn, "_ut_model_name", "model")))
        return trials

    # ------------------------------------------------------------------
    def run(self, test_limit: Optional[int] = None,
            time_limit: Optional[float] = None) -> TuneResult:
        """Tune end-to-end; returns the Tuner's TuneResult."""
        if self.params is None:
            with obs.span("controller.analyze"):
                self.analyze()
        limit = int(test_limit if test_limit is not None
                    else self.test_limit)
        wall_limit = (time_limit if time_limit is not None
                      else self.timeout)
        records = self.params[self.stage]
        space = space_from_params(records)
        self._enable_compile_cache(space)
        store = self.store = self._open_store(space)
        self.tuner = tuner = self._make_tuner(space)
        # the CLI drives ask/tell (not Tuner.run), so the run-budget
        # surrogate rule is applied here where the limit is known
        tuner._apply_budget_rule(limit)
        if store is not None:
            if self.resume and os.path.exists(self.archive):
                # the replayed archive doubles as store rows, so runs
                # recorded before the store existed (or whose store dir
                # was lost) still never re-execute an archived config
                store.ingest_archive(self.archive)
            if self.warm_start:
                with obs.span("controller.warm_start") as sp:
                    sp.set(rows=self._warm_start_from_store())

        queue: collections.deque = collections.deque()
        # seed trial: the program's declared defaults; its QoR was already
        # measured by the profiling run, so tell() it without a subprocess
        seed_trials = tuner.inject([default_config(records)], "seed")
        # the default itself may violate a QoR constraint
        dq = self._verdict(self.default_qor, default_config(records))
        if seed_trials and dq is not None:
            for tr in seed_trials:
                # the profiling run measured the defaults: that is a
                # real result, record it for sibling/future tunes
                self._record_result(tr, dq, 0.0, {})
                self._maybe_new_best(tuner.tell(tr, dq))
        else:
            queue.extend(seed_trials)
        # user-provided seed configurations (--seed-configuration):
        # merged over the declared defaults (a partial file is valid,
        # like the reference's manipulator load), injected as 'seed'
        # trials and evaluated ahead of any technique batch
        if self.seed_configs:
            defaults = default_config(records)
            merged = []
            for cfg in self.seed_configs:
                unknown = sorted(set(cfg) - set(defaults))
                if unknown:
                    log.warning("[ut] seed configuration: ignoring "
                                "unknown parameter(s) %s", unknown)
                merged.append({**defaults,
                               **{k: v for k, v in cfg.items()
                                  if k in defaults}})
            queue.extend(tuner.inject(merged, "seed"))
        queue.extend(self._host_proposals(space))
        pre_launch = None
        if self.template is not None:
            name = os.path.basename(self.template.path)
            tpl = self.template

            def pre_launch(sb, index, trial):
                tpl.render_to(os.path.join(sb, name), trial.config)

        t0 = time.time()
        dry_asks = 0
        # gid of a queue head already looked up and missed while every
        # slot was busy: don't re-hash it each poll iteration (reset
        # when a refresh merges new rows — the answer may have changed)
        miss_gid = -1
        with WorkerPool(self.command, self.work_dir, self.parallel,
                        runtime_limit=self.runtime_limit,
                        env=self.env_extra,
                        sandbox=self.use_sandbox,
                        pre_launch=pre_launch,
                        # multi-host replicas share work_dir: namespace
                        # the sandbox slots (and thereby the per-slot
                        # config hand-off files) per replica, or two
                        # replicas' workers read each other's configs
                        slot_prefix=(f"{self.host_tag[1:]}."
                                     if self.host_tag else "")) as pool:
            self.pool = pool
            while True:
                # 1. refill freed slots INSTANTLY from the prefetched
                # queue — no device work on this path.  Gate on told
                # (per-trial), not evals (per-ticket): a wide in-flight
                # ticket must still count against the budget, or a
                # --test-limit 25 run launches 50+ trials.  A trial
                # whose config the store already holds is served INLINE
                # (no slot, no build): the recorded QoR flows through
                # tell() with full accounting, and the loop keeps
                # draining — store hits don't wait for free slots
                while queue and tuner.told + pool.busy_count < limit:
                    head = queue[0]
                    hit = (store.lookup(head.config)
                           if store is not None and head.gid != miss_gid
                           else None)
                    if hit is not None:
                        self.store_hits += 1
                        self._serve_hit(queue.popleft(), hit, queue)
                        continue
                    if not pool.n_free:
                        miss_gid = head.gid
                        break
                    pool.submit(queue.popleft(), stage=self.stage)
                # 2. speculative prefetch: top the queue back up to
                # `prefetch` trials while every slot is busy building,
                # so the propose+dedup device programs and config
                # materialization hide entirely behind build wall-clock
                outstanding = pool.busy_count + len(queue)
                depth = max(self.prefetch, pool.n_free)
                if (tuner.told + outstanding < limit
                        and len(queue) < depth
                        and dry_asks < 8):
                    want = min(depth - len(queue),
                               limit - tuner.told - outstanding)
                    asked = tuner.ask(min_trials=want)
                    queue.extend(asked)
                    obs.gauge("prefetch.depth", len(queue))
                    dry_asks = 0 if asked else dry_asks + 1
                    if asked and pool.n_free:
                        continue  # launch the fresh trials before polling
                # multi-instance exchange: pick up sibling instances'
                # freshly appended rows (time-gated re-scan) and pull
                # in their best when it beats ours
                if store is not None and store.maybe_refresh():
                    miss_gid = -1   # new rows: head may hit now
                    self._maybe_exchange_best(queue)
                if pool.busy_count == 0:
                    if tuner.told >= limit:
                        break
                    if not queue and dry_asks >= 8:
                        break  # space saturated: nothing left to propose
                for trial, qor, dur, info in pool.poll(self.interval):
                    self._record_result(trial, qor, dur, info)
                    qor = self._verdict(qor, trial.config)
                    stats = tuner.tell(trial, qor, dur)
                    if qor is not None:
                        self._host_history.append((trial.config, qor))
                    if stats is not None and stats.was_new_best \
                            and self.prefetch:
                        # a new best invalidates speculative technique
                        # tickets proposed around the stale incumbent:
                        # withdraw the un-launched ones so the refill
                        # proposes against the new best instead
                        # (prefetch=0 keeps the legacy fire-everything
                        # behavior)
                        self.spec_cancelled += self._cancel_speculative(
                            queue, tuner)
                    self._maybe_new_best(stats)
                    self._status(qor)
                if wall_limit and time.time() - t0 > wall_limit:
                    for trial, qor, dur, info in pool.drain(
                            timeout=self.runtime_limit):
                        self._record_result(trial, qor, dur, info)
                        tuner.tell(trial, self._verdict(
                            qor, trial.config), dur)
                    break
            # withdraw trials still queued (never launched): no archive
            # rows, no failure penalty — the limit simply arrived first
            while queue:
                tuner.cancel(queue.popleft())
            # the async-pipeline scoreboard (docs/PERF.md): slot-seconds
            # spent building vs driver overhead the prefetch failed to
            # hide behind them
            log.info(
                "[ut] pool utilization=%.2f (driver t_propose=%.2fs "
                "t_dedup=%.2fs behind t_eval_wait=%.1fs; speculative "
                "cancels=%d)", pool.utilization(),
                tuner.t_propose_total, tuner.t_dedup_total,
                tuner.t_eval_wait_total, self.spec_cancelled)
            if store is not None:
                log.info(
                    "[ut] store: %d build(s) eliminated by cache hits, "
                    "%d launched, %d exchange trial(s) ingested, %d "
                    "row(s) federated (%s)",
                    self.store_hits, pool.launched,
                    self.exchange_injected, self.federated_rows,
                    store.stats())
        res = tuner.result()
        if res.best_config:
            write_best(res.best_config, res.best_qor,
                       work_dir=self.work_dir)
        tuner.close()
        if store is not None:
            store.close()
        return res
