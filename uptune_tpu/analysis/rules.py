"""The ut-lint rule pack: the five JAX hazards that cost this codebase
TPU throughput.  See docs/LINT.md for the full rationale per rule.

R001 host-sync-under-jit      device->host transfer inside traced code
R002 prng-key-reuse           a PRNG key consumed twice without split
R003 traced-control-flow      Python if/while on traced values under jit
R004 side-effect-under-jit    print/file-IO/global mutation under jit
R005 retrace-churn            jit wrappers constructed per call/iteration
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .core import FUNCTION_NODES, ModuleCtx, Rule, function_body, \
    register, shallow_walk

# ---------------------------------------------------------------------
_HOST_CASTS = {"float", "int", "bool", "complex"}
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_NUMPY_PULLS = {"numpy.asarray", "numpy.array", "numpy.ascontiguousarray"}
_DEVICE_GET = {"jax.device_get"}


@register
class HostSyncUnderJit(Rule):
    id = "R001"
    name = "host-sync-under-jit"
    short = ("device->host transfer (float()/.item()/np.asarray/"
             "device_get) inside a traced function")
    why = ("Each sync serializes the XLA stream: the fused engine's "
           "~10^5 acq/s collapses to host roundtrip rate. Keep values "
           "on device (jnp ops) or sync outside the jitted region.")

    def check(self, mod: ModuleCtx) -> Iterator:
        jit = mod.jit
        for fn in jit.reachable:
            for node in shallow_walk(function_body(fn)):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                # float(x) / int(x) / bool(x) on a traced value
                if isinstance(f, ast.Name) and f.id in _HOST_CASTS \
                        and len(node.args) == 1 \
                        and jit.is_tainted_expr(fn, node.args[0]):
                    yield node, (
                        f"{f.id}() on a traced value forces a host sync "
                        f"under jit; keep it a jnp array (or compute the "
                        f"scalar outside the traced region)")
                    continue
                # x.item() / x.tolist() / x.block_until_ready()
                if isinstance(f, ast.Attribute) \
                        and f.attr in _SYNC_METHODS \
                        and jit.is_tainted_expr(fn, f.value):
                    yield node, (
                        f".{f.attr}() on a traced value forces a host "
                        f"sync under jit")
                    continue
                d = mod.dotted(f)
                if d in _NUMPY_PULLS and node.args \
                        and jit.is_tainted_expr(fn, node.args[0]):
                    yield node, (
                        f"{d}() materializes a traced value on the host "
                        f"under jit; use jnp.asarray / keep the array on "
                        f"device")
                elif d in _DEVICE_GET and node.args \
                        and jit.is_tainted_expr(fn, node.args[0]):
                    yield node, (
                        "jax.device_get() inside a traced function is a "
                        "host sync; move it outside the jitted region")


# ---------------------------------------------------------------------
# jax.random functions that READ a key (first positional argument).
# split() counts: feeding one key to two split() calls yields identical
# child streams — the same corruption as sampler reuse.  fold_in() does
# NOT: it derives a stream decorrelated by explicit extra data, and
# `fold_in(key, i)` across loop indices is the standard idiom (the
# multi-chip scorer's per-shard keys depend on it).
_KEY_FACTORY = {"PRNGKey", "key"}
_KEY_NONCONSUMING = {"fold_in", "key_data", "wrap_key_data", "clone",
                     "key_impl", "default_prng_impl"}


class _KeyState:
    FRESH, CONSUMED = 0, 1


@register
class PRNGKeyReuse(Rule):
    id = "R002"
    name = "prng-key-reuse"
    short = "a PRNG key consumed twice without an intervening split"
    why = ("Reused keys give technique populations identical "
           "perturbations: arms stop being independent and the bandit "
           "credits correlated noise. Always split (or fold_in) before "
           "each consumer.")

    def check(self, mod: ModuleCtx) -> Iterator:
        # module scope first: scripts consume keys at top level, and a
        # module-level reuse replays streams across the whole process
        yield from self._check_stmts(mod, list(mod.tree.body))
        for fn in mod.jit.functions:
            if isinstance(fn, ast.Lambda):
                continue
            yield from self._check_stmts(mod, function_body(fn))

    # -- helpers ------------------------------------------------------
    def _random_attr(self, mod: ModuleCtx, func) -> Optional[str]:
        """'split' / 'uniform' / ... when `func` is jax.random.<attr>."""
        d = mod.dotted(func)
        if d is None or not d.startswith("jax.random."):
            return None
        return d.rsplit(".", 1)[-1]

    def _consumed_key(self, mod: ModuleCtx, call: ast.Call
                      ) -> Optional[ast.AST]:
        attr = self._random_attr(mod, call.func)
        if attr is None or attr in _KEY_FACTORY \
                or attr in _KEY_NONCONSUMING:
            return None
        if call.args:
            return call.args[0]
        for kw in call.keywords:
            if kw.arg == "key":
                return kw.value
        return None

    def _is_key_factory(self, mod: ModuleCtx, node) -> bool:
        """A PRNGKey(...) call with a CONSTANT seed.  PRNGKey(seed)
        over a parameter/attribute yields a different stream per
        caller — the canonical `split(PRNGKey(seed))` init idiom must
        not be flagged."""
        if not isinstance(node, ast.Call):
            return False
        attr = self._random_attr(mod, node.func)
        if attr not in _KEY_FACTORY:
            return False
        vals = list(node.args) + [k.value for k in node.keywords]
        return bool(vals) and all(isinstance(v, ast.Constant)
                                  for v in vals)

    # -- the tiny abstract interpreter --------------------------------
    def _check_stmts(self, mod: ModuleCtx, stmts: List[ast.AST]
                     ) -> Iterator:
        findings: List[Tuple[ast.AST, str]] = []
        state: Dict[str, int] = {}

        def consume(name: str, node: ast.AST) -> None:
            if state.get(name) == _KeyState.CONSUMED:
                findings.append((node, (
                    f"PRNG key '{name}' is consumed again without an "
                    f"intervening jax.random.split/fold_in — identical "
                    f"random streams")))
            state[name] = _KeyState.CONSUMED

        def rebind(target: ast.AST) -> None:
            for n in ast.walk(target):
                d = mod.plain_dotted(n)
                if d is not None and d in state:
                    state[d] = _KeyState.FRESH

        def consume_calls(nodes: List[ast.AST]) -> None:
            for node in shallow_walk(nodes):
                if not isinstance(node, ast.Call):
                    continue
                key_arg = self._consumed_key(mod, node)
                if key_arg is not None:
                    d = mod.plain_dotted(key_arg)
                    if d is not None:
                        consume(d, node)

        def visit_expr(expr: ast.AST) -> None:
            comps: List[ast.AST] = []
            for node in shallow_walk([expr]):
                if isinstance(node, (ast.ListComp, ast.SetComp,
                                     ast.GeneratorExp, ast.DictComp)):
                    comps.append(node)
                if not isinstance(node, ast.Call):
                    continue
                key_arg = self._consumed_key(mod, node)
                if key_arg is not None:
                    d = mod.plain_dotted(key_arg)
                    if d is not None:
                        consume(d, node)
                # constant key consumed inline: PRNGKey(..) as a direct
                # argument of another call — every invocation of the
                # enclosing function replays the same stream
                for a in list(node.args) + [k.value for k in
                                            node.keywords]:
                    if self._is_key_factory(mod, a):
                        findings.append((a, (
                            "jax.random.PRNGKey(<constant>) consumed "
                            "inline: this code replays the same random "
                            "stream on every execution; split from a "
                            "stored key instead")))
            # second symbolic iteration over each comprehension's
            # per-iteration parts (element + filters): a key consumed
            # in the body but split outside the comprehension surfaces
            # on this pass, same as the two-pass For/While handling.
            # Generator targets rebind first — `for k in split(key, n)`
            # yields a FRESH k each iteration, not reuse.
            for comp in comps:
                for g in comp.generators:
                    rebind(g.target)
                body = ([comp.key, comp.value]
                        if isinstance(comp, ast.DictComp)
                        else [comp.elt])
                body += [i for g in comp.generators for i in g.ifs]
                consume_calls(body)

        def exec_stmts(stmts: List[ast.AST]) -> None:
            for s in stmts:
                if isinstance(s, FUNCTION_NODES + (ast.ClassDef,)):
                    continue
                if isinstance(s, ast.Assign):
                    visit_expr(s.value)
                    for t in s.targets:
                        rebind(t)
                elif isinstance(s, (ast.AnnAssign, ast.AugAssign)):
                    if s.value is not None:
                        visit_expr(s.value)
                    rebind(s.target)
                elif isinstance(s, ast.If):
                    visit_expr(s.test)
                    pre = dict(state)
                    exec_stmts(s.body)
                    after_body = dict(state)
                    state.clear()
                    state.update(pre)
                    exec_stmts(s.orelse)
                    # merge: consumed wins (either path may have run)
                    for k in set(after_body) | set(state):
                        state[k] = max(state.get(k, 0),
                                       after_body.get(k, 0))
                elif isinstance(s, (ast.For, ast.AsyncFor)):
                    visit_expr(s.iter)
                    # two symbolic iterations: reuse across iterations
                    # (a key consumed in the body but split outside the
                    # loop) surfaces on the second pass
                    for _ in range(2):
                        rebind(s.target)
                        exec_stmts(s.body)
                    exec_stmts(s.orelse)
                elif isinstance(s, ast.While):
                    for _ in range(2):
                        visit_expr(s.test)
                        exec_stmts(s.body)
                    exec_stmts(s.orelse)
                elif isinstance(s, ast.Try):
                    exec_stmts(s.body)
                    for h in s.handlers:
                        exec_stmts(h.body)
                    exec_stmts(s.orelse)
                    exec_stmts(s.finalbody)
                elif isinstance(s, (ast.With, ast.AsyncWith)):
                    for item in s.items:
                        visit_expr(item.context_expr)
                    exec_stmts(s.body)
                elif isinstance(s, ast.Return):
                    if s.value is not None:
                        visit_expr(s.value)
                elif isinstance(s, ast.Expr):
                    visit_expr(s.value)
                else:
                    for child in ast.iter_child_nodes(s):
                        if isinstance(child, ast.expr):
                            visit_expr(child)

        exec_stmts(stmts)
        yield from findings


# ---------------------------------------------------------------------
def _is_none_check(test: ast.AST) -> bool:
    """`x is None` / `x is not None`, possibly under not/and/or — the
    standard static-argument dispatch pattern inside jitted bodies."""
    if isinstance(test, ast.Compare):
        return all(isinstance(op, (ast.Is, ast.IsNot))
                   for op in test.ops)
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _is_none_check(test.operand)
    if isinstance(test, ast.BoolOp):
        return all(_is_none_check(v) for v in test.values)
    return False


@register
class TracedControlFlow(Rule):
    id = "R003"
    name = "traced-control-flow"
    short = "Python if/while on a traced value inside a jitted body"
    why = ("Branching on a traced value either raises a "
           "TracerBoolConversionError or — when it slips through via a "
           "concretized aux value — forces a blocking host sync and a "
           "retrace per branch. Use jnp.where / lax.cond / "
           "lax.while_loop.")

    def check(self, mod: ModuleCtx) -> Iterator:
        jit = mod.jit
        jnp_prefixes = ("jax.numpy.", "jax.lax.", "jnp.")
        for fn in jit.reachable:
            for node in shallow_walk(function_body(fn)):
                if isinstance(node, (ast.If, ast.While)):
                    test = node.test
                elif isinstance(node, ast.IfExp):
                    test = node.test
                else:
                    continue
                # strip `x is None` operands out of and/or chains: the
                # static-dispatch half of `if x is None and n:` must not
                # taint the whole test
                operands: List[ast.AST] = []
                todo = [test]
                while todo:
                    t = todo.pop()
                    if isinstance(t, ast.BoolOp):
                        todo.extend(t.values)
                    elif not _is_none_check(t):
                        operands.append(t)
                hazard = False
                for op in operands:
                    # a jnp/lax call in the test is always device-valued
                    for sub in ast.walk(op):
                        if isinstance(sub, ast.Call):
                            d = mod.dotted(sub.func)
                            if d is not None \
                                    and d.startswith(jnp_prefixes):
                                hazard = True
                                break
                    if hazard or jit.is_tainted_expr(fn, op):
                        hazard = True
                        break
                if hazard:
                    kw = ("if" if isinstance(node, (ast.If, ast.IfExp))
                          else "while")
                    yield node, (
                        f"Python `{kw}` on a traced value inside a "
                        f"jitted body; use jnp.where / lax.cond / "
                        f"lax.while_loop (or hoist the decision out of "
                        f"the traced region)")


# ---------------------------------------------------------------------
_LOGGER_NAMES = {"log", "logger", "logging"}
_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "critical",
                "exception"}


@register
class SideEffectUnderJit(Rule):
    id = "R004"
    name = "side-effect-under-jit"
    short = "print / file IO / logging / global mutation under jit"
    why = ("Side effects run at TRACE time only: they silently vanish "
           "on cached executions, and print() on a traced value syncs. "
           "Use jax.debug.print/jax.debug.callback, or move the effect "
           "to the host loop.")

    def check(self, mod: ModuleCtx) -> Iterator:
        for fn in mod.jit.reachable:
            for node in shallow_walk(function_body(fn)):
                if isinstance(node, ast.Global):
                    yield node, (
                        "global mutation inside a jitted body happens "
                        "at trace time only (stale on every cached "
                        "call); return the value instead")
                    continue
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if isinstance(f, ast.Name) and f.id == "print":
                    yield node, (
                        "print() under jit runs only at trace time; "
                        "use jax.debug.print(...)")
                elif isinstance(f, ast.Name) and f.id == "open":
                    yield node, (
                        "file IO under jit runs only at trace time; "
                        "move it to the host loop or use "
                        "jax.debug.callback")
                elif isinstance(f, ast.Attribute) and isinstance(
                        f.value, ast.Name) \
                        and f.value.id in _LOGGER_NAMES \
                        and f.attr in _LOG_METHODS:
                    yield node, (
                        f"{f.value.id}.{f.attr}() under jit runs only "
                        f"at trace time; use jax.debug.print or log "
                        f"from the host loop")


# ---------------------------------------------------------------------
@register
class RetraceChurn(Rule):
    id = "R005"
    name = "retrace-churn"
    short = "a jit wrapper constructed per call / per loop iteration"
    why = ("jax.jit's compile cache keys on the FUNCTION OBJECT: a "
           "wrapper rebuilt each call or iteration never hits the "
           "cache, so every invocation pays a full retrace+compile. "
           "Hoist the jit to definition time, or store it in a keyed "
           "cache (dict/attribute).")

    _wrappers = {"jax.jit", "jax.pmap", "jax.pjit", "jit", "pmap"}

    def _is_jit_call(self, mod: ModuleCtx, node) -> bool:
        if not isinstance(node, ast.Call):
            return False
        d = mod.dotted(node.func)
        return d in self._wrappers

    def check(self, mod: ModuleCtx) -> Iterator:
        jit = mod.jit
        for node in ast.walk(mod.tree):
            if not self._is_jit_call(mod, node):
                continue
            parent = mod.parents.get(node)
            # (c) immediate invocation: jax.jit(f)(x) — a fresh wrapper
            # per execution; at module level it runs once, so only flag
            # inside a function
            if isinstance(parent, ast.Call) and parent.func is node \
                    and mod.enclosing_function(node) is not None:
                yield node, (
                    "jax.jit(f)(...) builds a fresh wrapper per call — "
                    "the compile cache never hits; jit once at "
                    "definition time and reuse the wrapper")
                continue
            # (b) jit construction inside a traced function.  A
            # parameterized decorator `@jax.jit(donate_argnums=0)` is
            # definition-time jitting of the function it decorates —
            # the churn question applies to the function ENCLOSING the
            # decorated def, not the def itself
            fn = mod.enclosing_function(node)
            if fn is not None and any(
                    node is d for d in
                    getattr(fn, "decorator_list", [])):
                fn = mod.enclosing_function(fn)
            if fn is not None and fn in jit.reachable:
                yield node, (
                    "constructing a jit wrapper inside a traced "
                    "function re-traces it on every outer trace; hoist "
                    "it out of the jitted region")
                continue
            # (a) jit in a loop, unless stored under a key (attribute /
            # subscript target = an explicit wrapper cache)
            in_loop = any(isinstance(a, (ast.For, ast.AsyncFor,
                                         ast.While, ast.comprehension))
                          for a in mod.ancestors(node))
            if not in_loop:
                continue
            stored_keyed = False
            for anc in mod.ancestors(node):
                if isinstance(anc, ast.Assign):
                    if all(isinstance(t, (ast.Attribute, ast.Subscript))
                           for t in anc.targets):
                        stored_keyed = True
                    break
                if isinstance(anc, (ast.For, ast.AsyncFor, ast.While,
                                    *FUNCTION_NODES)):
                    break
            if not stored_keyed:
                yield node, (
                    "jit wrapper constructed inside a loop: each "
                    "iteration pays a fresh trace+compile; hoist it out "
                    "of the loop or store it in a keyed cache "
                    "(self._jit[name] = jax.jit(...))")
