"""ut-lint: JAX-hazard static analysis for uptune-tpu, plus the runtime
trace guard that cross-checks it.

Static side (no jax import — runs on any box)::

    python -m uptune_tpu.analysis uptune_tpu/ --format json
    ut-lint --list-rules

Runtime side::

    from uptune_tpu.analysis import TraceGuard
    with TraceGuard(limit=2) as tg:
        ...   # anything jitted in here gets its traces counted

Concurrency side (R101–R106 + the lock sanitizer)::

    UT_LOCK_GUARD=strict python bench.py --serve --quick
    from uptune_tpu.analysis import LockGuard
    with LockGuard(strict=True):
        ...   # locks created in here get order/held-time checked

Rules, suppression syntax, and the throughput rationale: docs/LINT.md.
"""
from .core import Finding, all_rules, lint_paths, lint_source
from .lock_guard import LockGuard, LockOrderError, lock_guard_from_env
from .trace_guard import RetraceError, TraceGuard, guard_from_env

__all__ = ["Finding", "all_rules", "lint_paths", "lint_source",
           "TraceGuard", "RetraceError", "guard_from_env",
           "LockGuard", "LockOrderError", "lock_guard_from_env"]
