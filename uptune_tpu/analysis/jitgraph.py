"""Which functions in a module run under a JAX trace?

Roots are found three ways:

* **decorators** — `@jax.jit`, `@jit`, `@partial(jax.jit, ...)`,
  `@jax.pmap`, `@jax.vmap`, `@jax.checkpoint` / `@jax.remat`;
* **higher-order call sites** — a function object passed to
  `jax.jit(f)`, `jax.lax.scan(f, ...)`, `while_loop`, `fori_loop`,
  `cond`, `switch`, `lax.map`, `associative_scan`, `vmap`, `pmap`,
  `grad` / `value_and_grad`, `shard_map` (name or lambda, local or
  module-level or `self.method`);
* **repo convention** — methods named `propose` / `observe` on any
  class: technique operators are jitted centrally by the driver
  (`driver/driver.py` `_propose_jit`/`_observe_jit`) and run inside the
  fused engine's `lax.scan` step, so a per-file analysis cannot see
  their jit wrapper.  The set is `HOT_METHOD_NAMES`.

Reachability then closes over intra-module calls: plain `g(...)` in the
enclosing scope chain, `self.m(...)` / `cls.m(...)` within the class.
Cross-module calls are invisible (per-file analysis) — the convention
set exists precisely to cover the one cross-module jit seam this repo
has.

Taint: within a traced function, the *parameters* are the traced
values (minus `self`/`cls` and the by-convention static `space` handle),
propagated forward through assignments.  Rules use taint to avoid
flagging host-side math on closure constants inside jitted bodies
(e.g. `float(np.log2(d))` over a static dimension is fine; `float(x)`
over a parameter is a device sync).
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import FUNCTION_NODES, ModuleCtx, function_body, shallow_walk

# canonical dotted callables whose function argument(s) get traced
JIT_WRAPPERS = {
    "jax.jit", "jax.pmap", "jax.vmap", "jax.grad", "jax.value_and_grad",
    "jax.checkpoint", "jax.remat", "jax.pjit",
}
# dotted name -> positional indices of the traced callee(s)
LAX_HOF: Dict[str, Tuple[int, ...]] = {
    "jax.lax.scan": (0,),
    "jax.lax.while_loop": (0, 1),
    "jax.lax.fori_loop": (2,),
    "jax.lax.cond": (1, 2),
    "jax.lax.switch": (1, 2, 3, 4, 5, 6, 7, 8),
    "jax.lax.map": (0,),
    "jax.lax.associative_scan": (0,),
    "jax.experimental.shard_map.shard_map": (0,),
    "jax.checkpoint": (0,),
}
# this repo jits these methods from another module (driver/engine) —
# but only on Technique classes (techniques/ modules, or classes whose
# base name ends in 'Technique'): surrogate managers also have an
# `observe`, and theirs is host-side by design
HOT_METHOD_NAMES = {"propose", "observe"}
# parameters that are host-side handles by convention, not traced values
NONTRACED_PARAMS = {"self", "cls", "space", "mesh"}
# parameter NAMES that are static shape/config scalars by repo
# convention (they parameterize shapes, so they cannot be traced):
# n_cat, n_cont, num_steps, dim, steps, axis, ...
STATIC_PARAM_RE = re.compile(
    r"^(n|num|dim|ndim|size|steps|axis|length|count|rank|width|depth"
    r"|stride|beta|lr|lam|sense)(_\w+)*$")
# attribute reads that yield STATIC metadata even on traced arrays
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "weak_type",
                "sharding", "aval"}
# calls whose result is static regardless of argument taint
STATIC_CALLS = {"len", "isinstance", "type", "hasattr", "getattr",
                "callable", "id", "repr"}


def _last_segment(dotted: str) -> str:
    return dotted.rsplit(".", 1)[-1]


def _is_jit_wrapper(mod: ModuleCtx, node: ast.AST) -> bool:
    d = mod.dotted(node)
    if d is None:
        return False
    if d in JIT_WRAPPERS:
        return True
    # bare `jit`/`pmap` names with no visible import (fixtures, exec'd
    # snippets) still count: a false jit context is cheaper than a
    # missed one for every rule in the pack
    return d in ("jit", "pmap", "pjit")


def _decorator_is_jit(mod: ModuleCtx, dec: ast.AST) -> bool:
    if _is_jit_wrapper(mod, dec):
        return True
    if isinstance(dec, ast.Call):
        # @partial(jax.jit, static_argnums=...) / @jax.jit(...)-style
        fd = mod.dotted(dec.func)
        if fd in ("functools.partial", "partial") and dec.args:
            return _is_jit_wrapper(mod, dec.args[0])
        return _is_jit_wrapper(mod, dec.func)
    return False


class _Scope:
    """One function (or module/class) scope: locally defined functions
    by name, for resolving callees."""

    def __init__(self, node, parent: Optional["_Scope"], cls=None):
        self.node = node
        self.parent = parent
        self.cls = cls                       # enclosing ClassDef if any
        self.local_funcs: Dict[str, ast.AST] = {}

    def resolve(self, name: str):
        s = self
        while s is not None:
            if name in s.local_funcs:
                return s.local_funcs[name]
            s = s.parent
        return None


class JitGraph:
    def __init__(self, mod: ModuleCtx):
        self.mod = mod
        self.functions: List[ast.AST] = []       # all function-like defs
        self.scope_of: Dict[ast.AST, _Scope] = {}
        self.class_of: Dict[ast.AST, Optional[ast.ClassDef]] = {}
        self.methods: Dict[Tuple[int, str], ast.AST] = {}  # (id(cls), name)
        self.roots: Set[ast.AST] = set()
        self._collect()
        self.reachable: Set[ast.AST] = self._close()
        self._taint_cache: Dict[ast.AST, Set[str]] = {}

    # -- collection ---------------------------------------------------
    def _collect(self) -> None:
        mod = self.mod
        module_scope = _Scope(mod.tree, None)

        def visit(node, scope: _Scope, cls, direct):
            # `cls` is the class whose instance `self` refers to here —
            # it flows INTO nested functions (a scan body defined in a
            # method still calls self.step on the same instance);
            # `direct` marks immediate class children (real methods).
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, scope, child, True)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    self._register(child, scope, cls, direct)
                    inner = _Scope(child, scope, cls)
                    self.scope_of[child] = inner
                    visit(child, inner, cls, False)
                elif isinstance(child, ast.Lambda):
                    self.functions.append(child)
                    self.class_of[child] = cls
                    inner = _Scope(child, scope, cls)
                    self.scope_of[child] = inner
                    visit(child, inner, cls, False)
                else:
                    visit(child, scope, cls, direct)

        visit(mod.tree, module_scope, None, False)

        # roots from HOF call sites (after all defs are registered)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                self._roots_from_call(node)

    def _register(self, fn, scope: _Scope, cls, direct: bool) -> None:
        self.functions.append(fn)
        self.class_of[fn] = cls
        scope.local_funcs[fn.name] = fn
        if cls is not None and direct:
            self.methods[(id(cls), fn.name)] = fn
        if any(_decorator_is_jit(self.mod, d) for d in fn.decorator_list):
            self.roots.add(fn)
        if cls is not None and direct and fn.name in HOT_METHOD_NAMES \
                and self._is_technique_class(cls):
            self.roots.add(fn)

    def _is_technique_class(self, cls: ast.ClassDef) -> bool:
        path = self.mod.path.replace("\\", "/")
        if "/techniques/" in path or path.endswith("techniques.py"):
            return True
        for base in cls.bases:
            d = self.mod.plain_dotted(base) or ""
            if d.rsplit(".", 1)[-1].endswith("Technique"):
                return True
        return False

    def _resolve_callable_arg(self, call: ast.Call, arg: ast.AST):
        """A positional arg of a jit/HOF call -> function node if it
        names one we know (local/module function, lambda, self.m)."""
        if isinstance(arg, ast.Lambda):
            return arg
        fn_scope = self._enclosing_scope(call)
        if isinstance(arg, ast.Name) and fn_scope is not None:
            return fn_scope.resolve(arg.id)
        if isinstance(arg, ast.Attribute) and isinstance(
                arg.value, ast.Name) and arg.value.id in ("self", "cls"):
            cls = self._enclosing_class(call)
            if cls is not None:
                return self.methods.get((id(cls), arg.attr))
        return None

    def _roots_from_call(self, call: ast.Call) -> None:
        mod = self.mod
        d = mod.dotted(call.func)
        idxs: Tuple[int, ...] = ()
        if d is not None and (d in JIT_WRAPPERS
                              or _last_segment(d) == "shard_map"):
            idxs = (0,)
        elif d is not None:
            hof = LAX_HOF.get(d)
            if hof is None and d.startswith("lax."):
                hof = LAX_HOF.get("jax." + d)
            if hof is None and _last_segment(d) in (
                    "scan", "while_loop", "fori_loop", "cond", "switch"):
                hof = LAX_HOF.get("jax.lax." + _last_segment(d))
            if hof is not None:
                idxs = hof
        if not idxs:
            return
        for i in idxs:
            if i < len(call.args):
                target = self._resolve_callable_arg(call, call.args[i])
                if target is not None:
                    self.roots.add(target)

    def _enclosing_scope(self, node) -> Optional[_Scope]:
        fn = self.mod.enclosing_function(node)
        if fn is None:
            return None
        return self.scope_of.get(fn)

    def _enclosing_class(self, node) -> Optional[ast.ClassDef]:
        for anc in self.mod.ancestors(node):
            if isinstance(anc, ast.ClassDef):
                return anc
            if isinstance(anc, FUNCTION_NODES):
                cls = self.class_of.get(anc)
                if cls is not None:
                    return cls
        return None

    # -- reachability -------------------------------------------------
    def _callees(self, fn) -> Set[ast.AST]:
        out: Set[ast.AST] = set()
        scope = self.scope_of.get(fn)
        cls = self.class_of.get(fn)
        for node in shallow_walk(function_body(fn)):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name) and scope is not None:
                t = scope.resolve(f.id)
                if t is not None:
                    out.add(t)
            elif isinstance(f, ast.Attribute) and isinstance(
                    f.value, ast.Name) and f.value.id in ("self", "cls") \
                    and cls is not None:
                t = self.methods.get((id(cls), f.attr))
                if t is not None:
                    out.add(t)
        return out

    def _close(self) -> Set[ast.AST]:
        seen: Set[ast.AST] = set()
        todo = list(self.roots)
        while todo:
            fn = todo.pop()
            if fn in seen:
                continue
            seen.add(fn)
            todo.extend(self._callees(fn) - seen)
        return seen

    # -- taint --------------------------------------------------------
    def tainted_names(self, fn) -> Set[str]:
        """Names holding (potentially) traced values inside `fn`:
        parameters minus the by-convention host handles and static
        shape/config scalars, closed forward over assignments.  Reads
        that yield static metadata (`x.shape`, `x.ndim`, `len(x)`) do
        NOT propagate taint — shape math on traced arrays is host-side
        by construction."""
        cached = self._taint_cache.get(fn)
        if cached is not None:
            return cached
        args = fn.args
        params = [a.arg for a in (
            list(getattr(args, "posonlyargs", [])) + args.args
            + args.kwonlyargs)]
        for extra in (args.vararg, args.kwarg):
            if extra is not None:
                params.append(extra.arg)
        tainted = {p for p in params
                   if p not in NONTRACED_PARAMS
                   and not STATIC_PARAM_RE.match(p)}

        def target_names(t) -> Set[str]:
            return {n.id for n in ast.walk(t)
                    if isinstance(n, ast.Name)}

        def assign(value, targets) -> bool:
            if value is None or not _expr_tainted(value, tainted):
                return False
            new: Set[str] = set()
            for t in targets:
                new |= target_names(t) - tainted
            if new:
                tainted.update(new)
                return True
            return False

        def for_pairs(node):
            """(target, source-expr) pairs: zip/enumerate iterate
            positionally, so only targets fed by tainted iterables
            become tainted."""
            it, tgt = node.iter, node.target
            if isinstance(it, ast.Call) and isinstance(it.func, ast.Name):
                if it.func.id == "enumerate" and it.args \
                        and isinstance(tgt, ast.Tuple) \
                        and len(tgt.elts) == 2:
                    return [(tgt.elts[1], it.args[0])]
                if it.func.id == "zip" \
                        and isinstance(tgt, ast.Tuple) \
                        and len(tgt.elts) == len(it.args) \
                        and not any(isinstance(a, ast.Starred)
                                    for a in it.args):
                    return list(zip(tgt.elts, it.args))
            return [(tgt, it)]

        body = function_body(fn)
        for _ in range(20):
            changed = False
            for node in shallow_walk(body):
                if isinstance(node, ast.Assign):
                    changed |= assign(node.value, node.targets)
                elif isinstance(node, (ast.AnnAssign, ast.AugAssign,
                                       ast.NamedExpr)):
                    changed |= assign(node.value, [node.target])
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    for t, src in for_pairs(node):
                        changed |= assign(src, [t])
            if not changed:
                break
        self._taint_cache[fn] = tainted
        return tainted

    def is_tainted_expr(self, fn, expr) -> bool:
        return _expr_tainted(expr, self.tainted_names(fn))


def _expr_tainted(expr: ast.AST, tainted: Set[str]) -> bool:
    """Does `expr` reference a tainted name OUTSIDE static-metadata
    contexts (`.shape`/`.ndim`/... attribute reads, `len()` etc.)?"""
    if isinstance(expr, ast.Attribute) and expr.attr in STATIC_ATTRS:
        return False
    if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Name) \
            and expr.func.id in STATIC_CALLS:
        return False
    if isinstance(expr, ast.Name):
        return expr.id in tainted
    if isinstance(expr, FUNCTION_NODES):
        return False
    return any(_expr_tainted(c, tainted)
               for c in ast.iter_child_nodes(expr))
