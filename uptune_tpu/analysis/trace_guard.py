"""Runtime cross-check for the static rules: count jax.jit traces.

Static analysis says "this pattern CAN retrace"; the TraceGuard says
"this run DID retrace N times".  Inside the guard's scope every
function handed to `jax.jit` is shimmed so its *Python* body — which
executes only while JAX is tracing — bumps a per-function counter.
Cached executions never enter the Python body, so the counter is
exactly the trace count.

    with TraceGuard(limit=2) as tg:
        run_benchmark()
    tg.check()          # warns (or raises, strict=True) on excess

Scope notes:

* only `jax.jit` wrappers CREATED inside the scope are counted — a
  function jitted before entering the guard keeps its original shim-less
  body (wrap long-lived tuners inside the guard, as bench.py does);
* call sites must resolve `jax.jit` at call time (the `jax.jit(...)` /
  `@jax.jit` attribute style this repo uses everywhere); `from jax
  import jit` binds early and escapes the patch — such wrappers are
  simply not counted;
* an expected-trace budget of `limit` per function: 1 for a single
  shape, +1 per distinct input shape/dtype/static-arg combination you
  intend to run.  Anything above is the retrace churn R005 hunts.
  Wrappers REBUILT from an already-traced function are budgeted too
  (each rebuild is a fresh compile even though every individual
  wrapper traces once), so `jax.jit(f)(x)` in a loop is caught.
"""
from __future__ import annotations

import functools
import threading
import warnings
from typing import Dict, Optional

__all__ = ["TraceGuard", "RetraceError"]

_lock = threading.Lock()


class RetraceError(RuntimeError):
    """Raised by TraceGuard(strict=True) when a function exceeded its
    trace budget."""


class TraceGuard:
    def __init__(self, limit: int = 2, strict: bool = False,
                 name: str = "trace-guard", enabled: bool = True):
        self.limit = int(limit)
        self.strict = strict
        self.name = name
        self.enabled = enabled   # False = inert context, jit untouched
        self.counts: Dict[str, int] = {}
        self.rebuilds: Dict[str, int] = {}
        self._orig_jit = None
        self._label_seen: Dict[str, int] = {}
        # code objects that traced at least once, kept by strong ref so
        # ids cannot be recycled by the GC mid-guard
        self._traced_codes: Dict[int, object] = {}

    # -- bookkeeping --------------------------------------------------
    def record(self, label: str) -> None:
        with _lock:
            self.counts[label] = self.counts.get(label, 0) + 1
            n = self.counts[label]
        # every trace is also an instant on the obs timeline (no-op
        # when tracing is off), so retrace churn shows up IN the
        # exported Perfetto trace next to the spans it stalls instead
        # of only in a separate end-of-run report; excess=True marks
        # the ones over budget
        from .. import obs
        obs.event("jit.trace", fn=label, n=n,
                  excess=n > self.limit)
        obs.count("jit.traces")

    def excess(self) -> Dict[str, int]:
        """{function label: count} for functions over the limit —
        either traces of one wrapper, or wrappers rebuilt from the same
        code object after it already traced (churn: the compile cache
        keys on the wrapper, so every rebuild pays a fresh compile)."""
        ex = {k: v for k, v in self.counts.items() if v > self.limit}
        for k, v in self.rebuilds.items():
            if v > self.limit:
                ex[f"{k} (rebuilt after trace)"] = v
        return ex

    def report(self) -> Dict[str, object]:
        return {"limit": self.limit, "traces": dict(self.counts),
                "rebuilds": dict(self.rebuilds),
                "excess": self.excess()}

    def check(self) -> None:
        """Warn (or raise, strict=True) if any function re-traced or
        was rebuilt past the budget."""
        ex = self.excess()
        if not ex:
            return
        detail = ", ".join(f"{k}: {v}" for k, v in sorted(ex.items()))
        msg = (f"{self.name}: unexpected recompiles (limit "
               f"{self.limit}) — {detail}. Likely causes: unhashed "
               f"Python scalars in static args, shape-varying inputs, "
               f"or a jit wrapper rebuilt per call (ut-lint R005).")
        if self.strict:
            raise RetraceError(msg)
        warnings.warn(msg, RuntimeWarning, stacklevel=2)

    # -- the patch ----------------------------------------------------
    def _counting_jit(self, fun=None, **jit_kwargs):
        if fun is None:
            # jax.jit(static_argnums=...)(f) keyword-only usage
            return lambda f: self._counting_jit(f, **jit_kwargs)
        base = getattr(fun, "__qualname__",
                       getattr(fun, "__name__", repr(fun)))
        # the TRACE budget is per WRAPPER, not per qualname: the driver
        # jits one <lambda> per technique arm, and aggregating those
        # would read as retrace churn when each wrapper traced exactly
        # once.  Churn from wrappers REBUILT per call is caught
        # separately: constructing another wrapper from a code object
        # that already traced counts toward the same budget (building a
        # fleet of wrappers up-front, before anything runs, does not).
        code = getattr(fun, "__code__", None)
        with _lock:
            n = self._label_seen.get(base, 0)
            self._label_seen[base] = n + 1
            if code is not None and id(code) in self._traced_codes:
                self.rebuilds[base] = self.rebuilds.get(base, 0) + 1
        label = f"{base}#{n + 1}" if n else base

        @functools.wraps(fun)
        def traced(*args, **kwargs):
            if code is not None:
                with _lock:
                    self._traced_codes[id(code)] = code
            self.record(label)
            return fun(*args, **kwargs)

        return self._orig_jit(traced, **jit_kwargs)

    def __enter__(self) -> "TraceGuard":
        if not self.enabled:
            return self
        import jax
        # pre-load the lazily-imported jax.scipy submodule: its module
        # body builds internal shape-polymorphic jit wrappers
        # (_cho_solve, _solve_triangular) that would otherwise be
        # created — and counted — inside the guard the first time a
        # guarded region imports the surrogate stack.  The guard
        # measures THIS repo's programs, not jax library internals
        import jax.scipy.linalg  # noqa: F401
        self._jax = jax
        self._orig_jit = jax.jit
        jax.jit = self._counting_jit
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if not self.enabled:
            return
        self._jax.jit = self._orig_jit
        if exc_type is None:
            self.check()


def guard_from_env(env: Optional[dict] = None) -> TraceGuard:
    """TraceGuard configured from UT_TRACE_GUARD[_LIMIT/_STRICT] env
    vars — the bench.py / `ut` CLI hook.  Always returns a guard; when
    the env var is unset it is an inert context (enabled=False, jit
    untouched), so call sites are a plain `with guard_from_env() as g`
    plus an `if g.enabled` around reporting."""
    import os
    e = os.environ if env is None else env
    if e.get("UT_TRACE_GUARD", "") not in ("1", "true", "yes", "warn",
                                           "strict"):
        return TraceGuard(enabled=False)
    return TraceGuard(
        limit=int(e.get("UT_TRACE_GUARD_LIMIT", "2")),
        strict=(e.get("UT_TRACE_GUARD", "") == "strict"
                or e.get("UT_TRACE_GUARD_STRICT", "") == "1"))
