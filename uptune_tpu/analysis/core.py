"""ut-lint core: module context, rule registry, suppressions, findings.

The analyzer is pure-AST (no jax import, no code execution) so it runs
anywhere — CI boxes without an accelerator, pre-commit hooks, editors.
Repo-specific knowledge lives in two places: `jitgraph.py` decides which
functions are device-traced (the scope where host-sync / control-flow /
side-effect hazards actually cost throughput), and `rules.py` holds the
rule pack.  This module is the machinery both stand on.

Suppression syntax (per line)::

    x = float(q)          # ut-lint: disable=R001
    # ut-lint: disable-next=R001,R004
    x = float(q)

`disable=all` silences every rule on that line.  Suppressed findings are
still collected (reporters can show them; the CLI exit code ignores
them), so an audit of intentional hazards is one `--show-suppressed`
away.
"""
from __future__ import annotations

import ast
import dataclasses
import hashlib
import os
import re
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set

SUPPRESS_RE = re.compile(
    r"#\s*ut-lint:\s*(disable|disable-next)\s*=\s*"
    r"(all|[A-Z]\d+(?:\s*,\s*[A-Z]\d+)*)")


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int          # 1-based
    col: int           # 0-based (ast convention)
    message: str
    snippet: str = ""
    suppressed: bool = False
    occurrence: int = 0  # ordinal among same-(rule, snippet) findings

    def fingerprint(self) -> str:
        """Stable identity for baselines: path + rule + the stripped
        source line + occurrence ordinal, NOT the line number —
        findings survive unrelated edits above them.  For textually
        IDENTICAL findings the semantics are count-based: with N
        baselined occurrences, the first N (in file order) match the
        baseline and any extras are reported.  A new identical hazard
        therefore always surfaces as exactly one fresh finding, but
        which of the N+1 sites is flagged is positional (the last
        one), not necessarily the one most recently written."""
        key = (f"{self.path}::{self.rule}::{self.snippet.strip()}"
               f"::{self.occurrence}")
        return hashlib.sha1(key.encode("utf-8", "replace")).hexdigest()

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "suppressed": self.suppressed,
                "fingerprint": self.fingerprint()}


class Rule:
    """One lint rule.  Subclasses set `id`/`name`/`short`/`why` and
    implement check(mod) yielding (node, message) pairs."""

    id: str = ""
    name: str = ""
    short: str = ""      # one-line description (SARIF shortDescription)
    why: str = ""        # TPU-throughput rationale (docs/LINT.md)

    def check(self, mod: "ModuleCtx") -> Iterator:
        raise NotImplementedError


class PackageRule(Rule):
    """A rule whose verdict needs EVERY linted module at once (the
    lock-order inversion check: the two halves of an inverted pair
    usually live in different files).  `lint_paths` calls
    `check_package` exactly once over the whole module set; linting a
    single file degrades gracefully to that one module — full coverage
    comes from the repo-wide gate run (scripts/lint.sh)."""

    def check(self, mod: "ModuleCtx") -> Iterator:
        for m, node, message in self.check_package([mod]):
            if m is mod:
                yield node, message

    def check_package(self, mods: Sequence["ModuleCtx"]) -> Iterator:
        """Yield (mod, node, message) triples across all modules."""
        raise NotImplementedError


_REGISTRY: Dict[str, Rule] = {}


def register(cls):
    """Class decorator adding a rule to the global registry."""
    inst = cls()
    if inst.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {inst.id}")
    _REGISTRY[inst.id] = inst
    return cls


def all_rules() -> Dict[str, Rule]:
    from . import rules as _rules  # noqa: F401  (registration side effect)
    from . import conc_rules as _conc  # noqa: F401  (R101–R106)
    return dict(_REGISTRY)


# ---------------------------------------------------------------------
def _parse_suppressions(lines: Sequence[str]) -> Dict[int, Set[str]]:
    out: Dict[int, Set[str]] = {}
    for i, text in enumerate(lines, 1):
        for m in SUPPRESS_RE.finditer(text):
            kind, ids = m.group(1), m.group(2)
            ruleset = ({"all"} if ids == "all"
                       else {r.strip() for r in ids.split(",")})
            target = i if kind == "disable" else i + 1
            out.setdefault(target, set()).update(ruleset)
    return out


class ModuleCtx:
    """Parsed module + the shared analyses rules draw on."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.suppressions = _parse_suppressions(self.lines)
        self.aliases = self._collect_import_aliases(self.tree)
        self.parents = self._build_parents(self.tree)
        from .jitgraph import JitGraph
        self.jit = JitGraph(self)
        self._locks = None

    @property
    def locks(self):
        """Lazy LockGraph (the concurrency pass; `lockgraph.py`) —
        built on first use so jit-only tooling pays nothing for it."""
        if self._locks is None:
            from .lockgraph import LockGraph
            self._locks = LockGraph(self)
        return self._locks

    # -- imports ------------------------------------------------------
    @staticmethod
    def _collect_import_aliases(tree: ast.AST) -> Dict[str, str]:
        """Local name -> canonical dotted path (`jnp` -> `jax.numpy`,
        `random` -> `jax.random` after `from jax import random`, ...)."""
        out: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    out[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    out[a.asname or a.name] = f"{node.module}.{a.name}"
        return out

    @staticmethod
    def _build_parents(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
        parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        return parents

    # -- shared helpers ----------------------------------------------
    def dotted(self, node: ast.AST) -> Optional[str]:
        """Attribute/Name chain -> canonical dotted string, resolving
        import aliases at the root; None for non-chain expressions."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.aliases.get(node.id, node.id)
        parts.append(root)
        return ".".join(reversed(parts))

    def plain_dotted(self, node: ast.AST) -> Optional[str]:
        """Like dotted() but WITHOUT alias resolution — for value
        expressions like `self.key` / `state.key` where the root is a
        variable, not an import."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        return ".".join(reversed(parts))

    def is_suppressed(self, rule_id: str, line: int) -> bool:
        ids = self.suppressions.get(line)
        return bool(ids) and ("all" in ids or rule_id in ids)

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""

    def enclosing_function(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return cur
            cur = self.parents.get(cur)
        return None

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)


FUNCTION_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def shallow_walk(roots: Iterable[ast.AST]) -> Iterator[ast.AST]:
    """Walk nodes without descending into nested function-like nodes
    (each reachable function is analyzed once, under its own scope)."""
    todo = list(roots)
    while todo:
        node = todo.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, FUNCTION_NODES):
                continue
            todo.append(child)


def function_body(fn) -> List[ast.AST]:
    if isinstance(fn, ast.Lambda):
        return [fn.body]
    return list(fn.body)


# ---------------------------------------------------------------------
def _mk_finding(mod: ModuleCtx, rid: str, node, message: str) -> Finding:
    line = getattr(node, "lineno", 1)
    col = getattr(node, "col_offset", 0)
    return Finding(rid, mod.path, line, col, message,
                   snippet=mod.snippet(line),
                   suppressed=mod.is_suppressed(rid, line))


def _finalize(findings: List[Finding]) -> List[Finding]:
    """Per-module finishing: position sort, one finding per
    (rule, line, col) — loop double-execution in the key-reuse
    interpreter can emit duplicates — and occurrence ordinals for the
    count-based fingerprint semantics."""
    seen: Set[tuple] = set()
    out = []
    for f in sorted(findings, key=lambda f: (f.line, f.col, f.rule)):
        k = (f.rule, f.line, f.col)
        if k not in seen:
            seen.add(k)
            out.append(f)
    counts: Dict[tuple, int] = {}
    for f in out:
        fk = (f.rule, f.snippet.strip())
        f.occurrence = counts.get(fk, 0)
        counts[fk] = f.occurrence + 1
    return out


def lint_source(path: str, source: str,
                select: Optional[Set[str]] = None) -> List[Finding]:
    """Lint one module's source; returns findings INCLUDING suppressed
    ones (marked), sorted by position.  Syntax errors yield a single
    parse-error finding under rule id 'E000'.  Package rules see just
    this module (their single-module fallback)."""
    try:
        mod = ModuleCtx(path, source)
    except SyntaxError as e:
        return [Finding("E000", path, e.lineno or 1, e.offset or 0,
                        f"syntax error: {e.msg}", snippet="")]
    findings: List[Finding] = []
    for rid, rule in sorted(all_rules().items()):
        if select is not None and rid not in select:
            continue
        for node, message in rule.check(mod):
            findings.append(_mk_finding(mod, rid, node, message))
    return _finalize(findings)


def iter_py_files(paths: Sequence[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        else:
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def lint_paths(paths: Sequence[str],
               select: Optional[Set[str]] = None) -> List[Finding]:
    """Lint a path set.  Per-module rules run module by module;
    PackageRules run ONCE over every successfully parsed module (the
    lock-order inversion pair may span files).  Output order and the
    per-module fingerprint semantics match the old per-file path."""
    by_path: Dict[str, List[Finding]] = {}
    order: List[str] = []
    mods: List[ModuleCtx] = []
    for fp in iter_py_files(paths):
        rel = os.path.relpath(fp)
        if rel in by_path:
            continue
        order.append(rel)
        by_path[rel] = []
        try:
            with open(fp, encoding="utf-8") as f:
                src = f.read()
        except (OSError, UnicodeDecodeError) as e:
            by_path[rel].append(Finding("E000", rel, 1, 0,
                                        f"unreadable: {e}"))
            continue
        try:
            mods.append(ModuleCtx(rel, src))
        except SyntaxError as e:
            by_path[rel].append(Finding(
                "E000", rel, e.lineno or 1, e.offset or 0,
                f"syntax error: {e.msg}", snippet=""))
    rules = sorted(all_rules().items())
    for mod in mods:
        for rid, rule in rules:
            if select is not None and rid not in select:
                continue
            if isinstance(rule, PackageRule):
                continue
            for node, message in rule.check(mod):
                by_path[mod.path].append(
                    _mk_finding(mod, rid, node, message))
    for rid, rule in rules:
        if select is not None and rid not in select:
            continue
        if not isinstance(rule, PackageRule):
            continue
        for mod, node, message in rule.check_package(mods):
            by_path[mod.path].append(_mk_finding(mod, rid, node, message))
    return [f for rel in order for f in _finalize(by_path[rel])]
