"""ut-lint CLI: `python -m uptune_tpu.analysis [paths...]`.

Exit codes: 0 clean (no non-suppressed, non-baselined findings),
1 findings, 2 usage error.  `--write-baseline` grandfathers the current
findings so `scripts/lint.sh` fails only on NEW hazards.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import List, Optional, Set

from .core import Finding, all_rules, lint_paths
from .reporters import format_json, format_sarif, format_text


def _load_baseline(path: str) -> Set[str]:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    return set(doc.get("fingerprints", []))


def _write_baseline(path: str, findings: List[Finding]) -> int:
    # E000 (parse error) is never baselined: its fingerprint is
    # location-independent, so grandfathering one syntax error would
    # exempt the file from every rule forever
    broken = sorted({f.path for f in findings if f.rule == "E000"})
    if broken:
        print(f"ut-lint: refusing to baseline unparseable file(s): "
              f"{broken} — fix the syntax errors first",
              file=sys.stderr)
    fps = sorted({f.fingerprint() for f in findings
                  if not f.suppressed and f.rule != "E000"})
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"tool": "ut-lint", "fingerprints": fps}, f, indent=1)
        f.write("\n")
    return len(fps)


def _git_changed(base: str) -> Optional[List[str]]:
    """Changed (vs ``base``) plus untracked ``*.py`` files, as
    cwd-relative paths — or None when git is unavailable or the ref is
    bad, so the caller can fall back to a full lint rather than
    silently passing an unlinted change."""
    def run(*a: str) -> "subprocess.CompletedProcess[str]":
        return subprocess.run(["git", *a], capture_output=True,
                              text=True)
    try:
        top = run("rev-parse", "--show-toplevel")
        diff = run("diff", "--name-only", "--diff-filter=d", base,
                   "--", "*.py")
        extra = run("ls-files", "--others", "--exclude-standard",
                    "--", "*.py")
    except OSError:
        return None
    if top.returncode or diff.returncode or extra.returncode:
        return None
    root = top.stdout.strip()
    names = (set(diff.stdout.splitlines())
             | set(extra.stdout.splitlines()))
    out = []
    for n in sorted(n for n in names if n.strip()):
        p = os.path.relpath(os.path.join(root, n))
        if os.path.exists(p):
            out.append(p)
    return out


def _in_scope(path: str, roots: List[str]) -> bool:
    ap = os.path.abspath(path)
    for r in roots:
        ar = os.path.abspath(r)
        if ap == ar or ap.startswith(ar + os.sep):
            return True
    return False


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="ut-lint",
        description="JAX-hazard static analysis for uptune-tpu "
                    "(see docs/LINT.md)")
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to lint "
                         "(default: uptune_tpu/)")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text")
    ap.add_argument("--select", metavar="R001,R002",
                    help="run only these rule ids")
    ap.add_argument("--disable", metavar="R00X,...",
                    help="skip these rule ids")
    ap.add_argument("--baseline", metavar="FILE",
                    help="ignore findings whose fingerprint is in this "
                         "baseline file (grandfathered)")
    ap.add_argument("--write-baseline", metavar="FILE",
                    help="write current findings as the new baseline "
                         "and exit 0")
    ap.add_argument("--changed", action="store_true",
                    help="lint only files changed vs --changed-base "
                         "(git diff + untracked), intersected with "
                         "the requested paths; falls back to a full "
                         "lint if git fails")
    ap.add_argument("--changed-base", metavar="REF", default="HEAD",
                    help="base ref for --changed (default: HEAD)")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="include '# ut-lint: disable' findings in "
                         "text/json output")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        for rid, r in sorted(rules.items()):
            print(f"{rid}  {r.name:24s} {r.short}")
        return 0

    select: Optional[Set[str]] = None
    if args.select:
        select = {r.strip() for r in args.select.split(",") if r.strip()}
        unknown = select - set(rules)
        if unknown:
            print(f"ut-lint: unknown rule id(s): {sorted(unknown)}",
                  file=sys.stderr)
            return 2
    if args.disable:
        disabled = {r.strip() for r in args.disable.split(",")
                    if r.strip()}
        unknown = disabled - set(rules)
        if unknown:
            print(f"ut-lint: unknown rule id(s): {sorted(unknown)}",
                  file=sys.stderr)
            return 2
        select = (select or set(rules)) - disabled

    paths = args.paths or ["uptune_tpu"]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"ut-lint: no such path(s): {missing}", file=sys.stderr)
        return 2

    if args.changed:
        changed = _git_changed(args.changed_base)
        if changed is None:
            # better to lint everything than to green-light a change
            # the diff scoping could not see
            print("ut-lint: --changed: git unavailable or bad ref "
                  f"{args.changed_base!r}; falling back to full lint",
                  file=sys.stderr)
        else:
            scoped = [c for c in changed if _in_scope(c, paths)]
            print(f"ut-lint: --changed vs {args.changed_base}: "
                  f"{len(scoped)} file(s) in scope", file=sys.stderr)
            # note: package-wide rules (R101) only see the changed
            # modules under --changed; the full gate still runs them
            # repo-wide
            paths = scoped

    findings = lint_paths(paths, select)

    if args.write_baseline:
        n = _write_baseline(args.write_baseline, findings)
        print(f"ut-lint: baseline with {n} fingerprint(s) written to "
              f"{args.write_baseline}", file=sys.stderr)
        return 0

    if args.baseline and os.path.exists(args.baseline):
        grandfathered = _load_baseline(args.baseline)
        findings = [f for f in findings
                    if f.rule == "E000"       # parse errors never pass
                    or f.suppressed
                    or f.fingerprint() not in grandfathered]

    if args.format == "text":
        print(format_text(findings, args.show_suppressed))
    elif args.format == "json":
        print(format_json(findings, args.show_suppressed))
    else:
        print(format_sarif(findings))

    return 1 if any(not f.suppressed for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main())
