"""Finding reporters: text (human / pre-commit), json (scripts,
baselines), sarif (code-scanning UIs — GitHub, VS Code SARIF viewer)."""
from __future__ import annotations

import json
from typing import Dict, List

from .core import Finding, all_rules


def _summary(findings: List[Finding]) -> Dict:
    active = [f for f in findings if not f.suppressed]
    by_rule: Dict[str, int] = {}
    for f in active:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    return {"total": len(active),
            "suppressed": sum(1 for f in findings if f.suppressed),
            "by_rule": dict(sorted(by_rule.items()))}


def format_text(findings: List[Finding],
                show_suppressed: bool = False) -> str:
    rules = all_rules()
    out = []
    for f in findings:
        if f.suppressed and not show_suppressed:
            continue
        tag = " (suppressed)" if f.suppressed else ""
        name = rules[f.rule].name if f.rule in rules else "parse-error"
        out.append(f"{f.path}:{f.line}:{f.col + 1}: {f.rule} "
                   f"{f.message} [{name}]{tag}")
    s = _summary(findings)
    out.append(f"ut-lint: {s['total']} finding(s)"
               + (f", {s['suppressed']} suppressed"
                  if s["suppressed"] else ""))
    return "\n".join(out)


def format_json(findings: List[Finding],
                show_suppressed: bool = False) -> str:
    rows = [f.to_dict() for f in findings
            if show_suppressed or not f.suppressed]
    return json.dumps({"tool": "ut-lint", "findings": rows,
                       "summary": _summary(findings)}, indent=1)


def format_sarif(findings: List[Finding]) -> str:
    rules = all_rules()
    rule_meta = [{
        "id": rid,
        "name": r.name,
        "shortDescription": {"text": r.short},
        "fullDescription": {"text": r.why},
        "helpUri": "docs/LINT.md",
    } for rid, r in sorted(rules.items())]
    results = [{
        "ruleId": f.rule,
        "level": "warning" if f.suppressed else "error",
        "message": {"text": f.message},
        "suppressions": ([{"kind": "inSource"}] if f.suppressed else []),
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.path.replace("\\", "/")},
                "region": {"startLine": f.line,
                           "startColumn": f.col + 1},
            },
        }],
        "partialFingerprints": {"utLint/v1": f.fingerprint()},
    } for f in findings]
    doc = {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "ut-lint",
                "informationUri":
                    "https://github.com/cornell-zhang/uptune",
                "rules": rule_meta,
            }},
            "results": results,
        }],
    }
    return json.dumps(doc, indent=1)
