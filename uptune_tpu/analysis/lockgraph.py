"""Which code in a module runs holding which lock?

The concurrency sibling of `jitgraph.py`: where JitGraph answers "does
this function run under a JAX trace?", LockGraph answers "does this
statement run under a threading lock, and which one?".  It feeds the
R101–R106 rule pack (`conc_rules.py`).

What it resolves (pure AST, per module):

* **lock-typed attributes** — ``self.X = threading.Lock()`` /
  ``RLock()`` / ``Condition()`` in any method of a class, plus
  module-level ``NAME = threading.Lock()``.  Thread/Event/Queue-typed
  attributes are collected too (rules use the kinds to type `.join()`
  receivers and to exclude inherently thread-safe fields).
* **held regions** — ``with self._lock:`` blocks.  Every node walked
  inside one is annotated with the tuple of held lock ids
  (`held_at`); nested acquisitions record directed ``outer -> inner``
  edges (`nest_edges`) for the package-wide inversion check.
* **thread entry points** — functions referenced by
  ``threading.Thread(target=...)`` plus their intra-class call
  closure: the code that runs concurrently with the main thread.

Lock identity is *syntactic*: ``ClassName.attr_path`` for instance
attributes (``Session._ckpt_lock``, ``Session.group.lock``) and
``modstem.NAME`` for module-level locks (``metrics._LOCK`` and
``journal._LOCK`` stay distinct).  Two classes with the same name in
different modules therefore conflate — a documented over-approximation
the inversion rule inherits (its message names both sites, so a false
pair is cheap to triage).  Locks held through *local variables pulled
from containers* (``klock = self._glocks.setdefault(...)`` in
serve/server.py) are unresolvable per-file and deliberately skipped:
a missed edge is cheaper than a stream of wrong-identity ones.

A ``with self.foo.lock:`` whose attribute was never assigned a
``threading.*`` factory in this module (a *foreign* lock, e.g. the
session's ``group.lock``) still counts as a held region when its final
segment looks lock-ish (``lock``/``mutex``/``cv``/``cond``) — a with
statement on such a name is a lock acquisition in every idiom this
repo uses, and missing those regions would blind R101/R102 to the one
cross-object nesting the serving plane actually has.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import FUNCTION_NODES, ModuleCtx, function_body, shallow_walk

# canonical dotted factory -> kind
LOCK_FACTORIES: Dict[str, str] = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "threading.Condition": "condition",
    "threading.Event": "event",
    "threading.Semaphore": "semaphore",
    "threading.BoundedSemaphore": "semaphore",
    "threading.Barrier": "barrier",
    "threading.Thread": "thread",
    "threading.Timer": "thread",
    "multiprocessing.Lock": "lock",
    "multiprocessing.RLock": "rlock",
    "queue.Queue": "queue",
    "queue.SimpleQueue": "queue",
    "queue.LifoQueue": "queue",
    "queue.PriorityQueue": "queue",
    "collections.deque": "queue",
    "concurrent.futures.ThreadPoolExecutor": "executor",
    "concurrent.futures.ProcessPoolExecutor": "executor",
}
# kinds whose `with x:` acquires a mutual-exclusion region
HELD_KINDS = {"lock", "rlock", "condition"}
# kinds that are synchronization objects, not shared data (R103 skips)
SYNC_KINDS = {"lock", "rlock", "condition", "event", "semaphore",
              "barrier", "thread", "queue", "executor"}
# a with-context attribute that smells like a foreign lock
_LOCKISH_RE = re.compile(r"(?:^|_)(?:\w*lock|mutex|cv|cond)$", re.I)


def _mod_stem(path: str) -> str:
    return os.path.basename(path).rsplit(".py", 1)[0]


class LockGraph:
    """Per-module lock/thread analysis; built lazily by ModuleCtx."""

    def __init__(self, mod: ModuleCtx):
        self.mod = mod
        self.jit = mod.jit          # reuse its scopes/classes/methods
        self._stem = _mod_stem(mod.path)
        # id(ClassDef) -> {attr path after self. : kind}
        self.class_kinds: Dict[int, Dict[str, str]] = {}
        # module-level NAME -> kind
        self.module_kinds: Dict[str, str] = {}
        # fn node -> {local name: kind}
        self.local_kinds: Dict[ast.AST, Dict[str, str]] = {}
        # node -> tuple of held lock ids (outermost first); absent = bare
        self.held_at: Dict[ast.AST, Tuple[str, ...]] = {}
        # (lock_id, with_node, fn) per resolved acquisition
        self.regions: List[Tuple[str, ast.AST, ast.AST]] = []
        # (outer_id, inner_id, with_node, fn) per nested acquisition
        self.nest_edges: List[Tuple[str, str, ast.AST, ast.AST]] = []
        # every threading.Thread(...) creation: (call, enclosing fn|None)
        self.thread_creations: List[Tuple[ast.Call, Optional[ast.AST]]] = []
        # function nodes referenced as Thread targets
        self.thread_entries: Set[ast.AST] = set()
        # callee fn -> [(call node, caller fn)] for intra-module calls
        self.call_sites: Dict[ast.AST, List[Tuple[ast.Call, ast.AST]]] = {}
        self._collect_kinds()
        self._collect_threads()
        for fn in self.jit.functions:
            self._walk_fn(fn)
        self._collect_call_sites()

    # -- kind collection ----------------------------------------------
    def _factory_kind(self, value: ast.AST) -> Optional[str]:
        if not isinstance(value, ast.Call):
            return None
        d = self.mod.dotted(value.func)
        if d is None:
            return None
        kind = LOCK_FACTORIES.get(d)
        if kind is None and "." in d:
            # `futures.ThreadPoolExecutor` etc: match by final segment
            # for the unambiguous factory names only
            last = d.rsplit(".", 1)[-1]
            if last in ("ThreadPoolExecutor", "ProcessPoolExecutor"):
                kind = "executor"
        return kind

    def _collect_kinds(self) -> None:
        mod = self.mod
        # module level
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                kind = self._factory_kind(stmt.value)
                if kind:
                    self.module_kinds[stmt.targets[0].id] = kind
        # self.* attrs (any method) and function locals
        for fn in self.jit.functions:
            cls = self.jit.class_of.get(fn)
            locals_ = self.local_kinds.setdefault(fn, {})
            for node in shallow_walk(function_body(fn)):
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign) and node.value:
                    targets, value = [node.target], node.value
                else:
                    continue
                kind = self._factory_kind(value)
                if not kind:
                    continue
                for t in targets:
                    p = mod.plain_dotted(t)
                    if p is None:
                        continue
                    if p.startswith("self.") and cls is not None:
                        self.class_kinds.setdefault(
                            id(cls), {})[p[5:]] = kind
                    elif "." not in p:
                        locals_[p] = kind

    # -- thread entry points ------------------------------------------
    @staticmethod
    def _thread_target(call: ast.Call) -> Optional[ast.AST]:
        for kw in call.keywords:
            if kw.arg == "target":
                return kw.value
        # Thread(group, target, ...)
        if len(call.args) >= 2:
            return call.args[1]
        return None

    def _resolve_fn_ref(self, at_node: ast.AST,
                        ref: ast.AST) -> Optional[ast.AST]:
        """A function reference (Name / self.m / lambda) -> def node."""
        if isinstance(ref, ast.Lambda):
            return ref
        fn = self.mod.enclosing_function(at_node)
        if isinstance(ref, ast.Name):
            scope = self.jit.scope_of.get(fn) if fn is not None else None
            if scope is not None:
                return scope.resolve(ref.id)
            for stmt in self.mod.tree.body:       # module-level call
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and stmt.name == ref.id:
                    return stmt
            return None
        if isinstance(ref, ast.Attribute) and isinstance(
                ref.value, ast.Name) and ref.value.id in ("self", "cls"):
            cls = self.jit.class_of.get(fn) if fn is not None else None
            if cls is not None:
                return self.jit.methods.get((id(cls), ref.attr))
        return None

    def _collect_threads(self) -> None:
        for node in ast.walk(self.mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if self.mod.dotted(node.func) != "threading.Thread":
                continue
            fn = self.mod.enclosing_function(node)
            self.thread_creations.append((node, fn))
            tgt = self._thread_target(node)
            if tgt is not None:
                t = self._resolve_fn_ref(node, tgt)
                if t is not None:
                    self.thread_entries.add(t)

    def thread_reachable(self) -> Set[ast.AST]:
        """Thread entry points closed over intra-class/local calls —
        the code that runs off the creating thread."""
        seen: Set[ast.AST] = set()
        todo = list(self.thread_entries)
        while todo:
            fn = todo.pop()
            if fn in seen:
                continue
            seen.add(fn)
            todo.extend(self.jit._callees(fn) - seen)
        return seen

    # -- lock resolution ----------------------------------------------
    def resolve_lock(self, fn: Optional[ast.AST],
                     expr: ast.AST) -> Optional[str]:
        """A with-context expression -> lock id, or None if it is not
        (recognizably) a lock.  Locals are skipped: their identity is
        unknowable per-file (see module docstring)."""
        p = self.mod.plain_dotted(expr)
        if p is None:
            return None
        cls = self.jit.class_of.get(fn) if fn is not None else None
        if p.startswith("self.") or p.startswith("cls."):
            path = p.split(".", 1)[1]
            kind = None
            if cls is not None:
                kind = self.class_kinds.get(id(cls), {}).get(path)
            if kind is None:
                # foreign lock heuristic (e.g. `self.group.lock`)
                if _LOCKISH_RE.search(path.rsplit(".", 1)[-1]):
                    kind = "lock"
                else:
                    return None
            if kind not in HELD_KINDS:
                return None
            cname = cls.name if cls is not None else "?"
            return f"{cname}.{path}"
        if "." in p:
            return None
        if fn is not None and p in self.local_kinds.get(fn, {}):
            return None                       # local lock: identityless
        kind = self.module_kinds.get(p)
        if kind in HELD_KINDS:
            return f"{self._stem}.{p}"
        return None

    def kind_of(self, fn: Optional[ast.AST],
                expr: ast.AST) -> Optional[str]:
        """The collected kind of an attribute/name expression (for
        typing `.join()` / `.wait()` receivers), or None."""
        p = self.mod.plain_dotted(expr)
        if p is None:
            return None
        cls = self.jit.class_of.get(fn) if fn is not None else None
        if p.startswith("self.") or p.startswith("cls."):
            if cls is None:
                return None
            return self.class_kinds.get(id(cls), {}).get(p.split(".", 1)[1])
        if "." not in p:
            if fn is not None:
                k = self.local_kinds.get(fn, {}).get(p)
                if k:
                    return k
            return self.module_kinds.get(p)
        return None

    # -- held-region walk ---------------------------------------------
    def _walk_fn(self, fn: ast.AST) -> None:
        held: List[str] = []

        def visit(node: ast.AST) -> None:
            if isinstance(node, FUNCTION_NODES) and node is not fn:
                return          # nested defs run later, not under held
            if isinstance(node, (ast.With, ast.AsyncWith)):
                entered = 0
                for item in node.items:
                    lid = self.resolve_lock(fn, item.context_expr)
                    if lid is None:
                        continue
                    self.regions.append((lid, node, fn))
                    for outer in held:
                        if outer != lid:
                            self.nest_edges.append((outer, lid, node, fn))
                    held.append(lid)
                    entered += 1
                if held:
                    self.held_at[node] = tuple(held)
                for child in ast.iter_child_nodes(node):
                    visit(child)
                if entered:
                    del held[-entered:]
                return
            if held:
                self.held_at[node] = tuple(held)
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in function_body(fn):
            visit(stmt)

    # -- intra-module call sites --------------------------------------
    def _collect_call_sites(self) -> None:
        for caller in self.jit.functions:
            scope = self.jit.scope_of.get(caller)
            cls = self.jit.class_of.get(caller)
            for node in shallow_walk(function_body(caller)):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                target = None
                if isinstance(f, ast.Name) and scope is not None:
                    target = scope.resolve(f.id)
                elif isinstance(f, ast.Attribute) and isinstance(
                        f.value, ast.Name) \
                        and f.value.id in ("self", "cls") \
                        and cls is not None:
                    target = self.jit.methods.get((id(cls), f.attr))
                if target is not None:
                    self.call_sites.setdefault(target, []).append(
                        (node, caller))
