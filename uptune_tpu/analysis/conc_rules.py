"""ut-lint rule pack, concurrency pass: R101–R106.

The serving/store planes are thread-heavy (`serve/wire.py` handler
threads, `obs/ship.py` shipper loop, `store/store.py` cross-process
segments) and about to be replicated across K processes (ROADMAP items
1–2), where today's latent lock-order inversion or ack-before-durable
reordering becomes a fleet-wide outage.  These rules lint the lock
discipline statically from `lockgraph.py`'s per-module lock/thread
graph; `lock_guard.py` is the runtime cross-check (the TraceGuard/R005
pairing).

Scope notes shared by the pack:

* Lock identity is syntactic (`ClassName.attr`) — see lockgraph.py for
  the documented over/under-approximations.
* Buffered-file ``write``/``flush``/``readline`` and ``os.write`` are
  NOT "blocking" for R102: the repo's append discipline (one complete
  line per O_APPEND write) and its protocol framing (`serve/client.py`
  serializes request/response pairs under its lock BY DESIGN) live on
  exactly those calls.  The rule targets the calls that stall a lock
  for device-unbounded time: fsync, socket transfers, subprocess,
  sleep, thread joins.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import (ModuleCtx, PackageRule, Rule, function_body, register,
                   shallow_walk)
from .lockgraph import SYNC_KINDS

# -- R101 -------------------------------------------------------------


@register
class LockOrderInversion(PackageRule):
    id = "R101"
    name = "lock-order-inversion"
    short = ("Two locks are acquired in opposite nesting orders "
             "somewhere in the linted set")
    why = ("An A->B nesting in one thread and B->A in another is a "
           "textbook deadlock: each thread holds the lock the other "
           "needs.  Per-process it is a hung server; replicated across "
           "a fleet it is a correlated outage.  The check is package-"
           "wide because the two halves usually live in different "
           "files (the session plane nests into the group plane).")

    def check_package(self, mods):
        edges: Dict[Tuple[str, str],
                    List[Tuple[ModuleCtx, ast.AST]]] = {}
        for mod in mods:
            for outer, inner, node, _fn in mod.locks.nest_edges:
                edges.setdefault((outer, inner), []).append((mod, node))
        for (a, b), sites in sorted(edges.items()):
            rev = edges.get((b, a))
            if not rev or a >= b:       # report each pair once, at the
                continue                # sites of BOTH directions
            other = rev[0]
            for mod, node in sites:
                yield (mod, node,
                       f"lock order inversion: {a} -> {b} here but "
                       f"{b} -> {a} at {other[0].path}:"
                       f"{other[1].lineno} — one consistent order or "
                       f"a deadlock")
            here = sites[0]
            for mod, node in rev:
                yield (mod, node,
                       f"lock order inversion: {b} -> {a} here but "
                       f"{a} -> {b} at {here[0].path}:"
                       f"{here[1].lineno} — one consistent order or "
                       f"a deadlock")


# -- R102 -------------------------------------------------------------

# dotted calls that block for device/disk/process-unbounded time
_BLOCKING_DOTTED = {
    "os.fsync", "os.fdatasync", "time.sleep",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
    "select.select",
}
# attribute calls that block regardless of receiver spelling
_BLOCKING_ATTRS = {"fsync", "sendall", "recv", "recv_into", "accept"}
_CLOSURE_DEPTH = 4      # intra-class call closure for hidden blocking


@register
class BlockingCallUnderLock(Rule):
    id = "R102"
    name = "blocking-call-under-lock"
    short = "A blocking call (fsync/socket/subprocess/sleep/join) runs inside a held-lock region"
    why = ("A lock held across fsync, a socket transfer, a subprocess "
           "or a sleep serializes every other thread behind a latency "
           "the lock's critical section does not need: the serving "
           "plane's tail latency becomes the disk's.  Move the "
           "blocking call outside the critical section (snapshot under "
           "the lock, block outside — the store/durable pattern).")

    def _direct(self, mod: ModuleCtx, fn) -> List[Tuple[ast.Call, str]]:
        out: List[Tuple[ast.Call, str]] = []
        lg = mod.locks
        for node in shallow_walk(function_body(fn)):
            if not isinstance(node, ast.Call):
                continue
            d = mod.dotted(node.func)
            if d is not None and (d in _BLOCKING_DOTTED
                                  or d.startswith("subprocess.")):
                out.append((node, f"{d}()"))
                continue
            if isinstance(node.func, ast.Attribute):
                a = node.func.attr
                if a in _BLOCKING_ATTRS:
                    out.append((node, f".{a}()"))
                elif a == "join" and lg.kind_of(
                        fn, node.func.value) == "thread":
                    out.append((node, f".{a}()"))
        return out

    def _transitive(self, mod: ModuleCtx, fn, depth: int,
                    seen: Set) -> Optional[str]:
        """First blocking call reachable through intra-class/local
        callees of `fn` (the store's `record -> _append -> fsync`
        seam), as a description string, or None."""
        if depth <= 0 or fn in seen:
            return None
        seen.add(fn)
        direct = self._direct(mod, fn)
        if direct:
            node, desc = direct[0]
            return f"{desc} at line {node.lineno}"
        for callee in mod.jit._callees(fn):
            sub = self._transitive(mod, callee, depth - 1, seen)
            if sub is not None:
                name = getattr(callee, "name", "<lambda>")
                return f"{name}() -> {sub}"
        return None

    def check(self, mod: ModuleCtx):
        lg = mod.locks
        if not lg.regions:
            return
        for fn in mod.jit.functions:
            scope = mod.jit.scope_of.get(fn)
            cls = mod.jit.class_of.get(fn)
            for node in shallow_walk(function_body(fn)):
                if not isinstance(node, ast.Call):
                    continue
                held = lg.held_at.get(node)
                if not held:
                    continue
                hl = ", ".join(dict.fromkeys(held))
                d = mod.dotted(node.func)
                if d is not None and (d in _BLOCKING_DOTTED
                                      or d.startswith("subprocess.")):
                    yield (node, f"blocking call {d}() while holding "
                                 f"{hl}")
                    continue
                f = node.func
                if isinstance(f, ast.Attribute):
                    if f.attr in _BLOCKING_ATTRS:
                        yield (node, f"blocking call .{f.attr}() while "
                                     f"holding {hl}")
                        continue
                    if f.attr == "join" and lg.kind_of(
                            fn, f.value) == "thread":
                        yield (node, f"Thread.join() while holding "
                                     f"{hl}")
                        continue
                # intra-class/local callee that blocks internally
                target = None
                if isinstance(f, ast.Name) and scope is not None:
                    target = scope.resolve(f.id)
                elif isinstance(f, ast.Attribute) and isinstance(
                        f.value, ast.Name) \
                        and f.value.id in ("self", "cls") \
                        and cls is not None:
                    target = mod.jit.methods.get((id(cls), f.attr))
                if target is not None:
                    desc = self._transitive(mod, target,
                                            _CLOSURE_DEPTH, set())
                    if desc is not None:
                        name = getattr(target, "name", "<lambda>")
                        yield (node,
                               f"call to {name}() performs blocking "
                               f"{desc} while holding {hl}")


# -- R103 -------------------------------------------------------------


@register
class UnguardedSharedField(Rule):
    id = "R103"
    name = "unguarded-shared-field"
    short = ("A self.* field is accessed under a lock in one method "
             "but bare in thread-entry code")
    why = ("A field the class bothers to lock in one place is shared "
           "state; touching it without the lock from code that runs on "
           "another thread (a Thread target or its callees) is a data "
           "race — torn reads of compound updates, lost increments, "
           "iteration over a list mid-mutation.  Either take the lock "
           "at the bare site or make the field single-owner (never "
           "touch it under a lock at all).")

    def check(self, mod: ModuleCtx):
        lg = mod.locks
        if not lg.thread_entries:
            return
        jit = mod.jit
        # attr (first segment after self.) -> guard lock ids, per class
        guarded: Dict[int, Dict[str, Set[str]]] = {}
        for fn in jit.functions:
            cls = jit.class_of.get(fn)
            if cls is None:
                continue
            for node in shallow_walk(function_body(fn)):
                if not (isinstance(node, ast.Attribute)
                        and isinstance(node.value, ast.Name)
                        and node.value.id == "self"):
                    continue
                held = lg.held_at.get(node)
                if held:
                    guarded.setdefault(id(cls), {}).setdefault(
                        node.attr, set()).update(held)
        if not guarded:
            return
        thread_fns = lg.thread_reachable()
        # a method whose EVERY intra-class call site sits inside a held
        # region effectively runs locked (obs/flight.py `_rotate`)
        lock_ctx = set()
        for fn in jit.functions:
            sites = lg.call_sites.get(fn)
            if sites and all(lg.held_at.get(call)
                             for call, _caller in sites):
                lock_ctx.add(fn)
        for fn in thread_fns:
            cls = jit.class_of.get(fn)
            if cls is None or fn in lock_ctx:
                continue
            cls_guarded = guarded.get(id(cls))
            if not cls_guarded:
                continue
            kinds = lg.class_kinds.get(id(cls), {})
            init = jit.methods.get((id(cls), "__init__"))
            if fn is init:
                continue            # runs before any thread starts
            for node in shallow_walk(function_body(fn)):
                if not (isinstance(node, ast.Attribute)
                        and isinstance(node.value, ast.Name)
                        and node.value.id == "self"):
                    continue
                attr = node.attr
                locks = cls_guarded.get(attr)
                if not locks:
                    continue
                if kinds.get(attr) in SYNC_KINDS:
                    continue        # the lock/event itself, not data
                if (id(cls), attr) in jit.methods:
                    continue        # a method reference, not a field
                if lg.held_at.get(node):
                    continue        # this access IS under a lock
                ll = ", ".join(sorted(locks))
                yield (node,
                       f"self.{attr} accessed without a lock in "
                       f"thread-entry code but guarded by {ll} "
                       f"elsewhere in {cls.name}")


# -- R104 -------------------------------------------------------------


@register
class AckBeforeDurable(Rule):
    id = "R104"
    name = "ack-before-durable"
    short = ("A serving path returns a reply after committing state "
             "without draining it to the checkpoint log first")
    why = ("The durability contract (serve/durable.py) is that any "
           "`committed: true` a client ever observed survives a crash: "
           "the commit record must be appended BEFORE the reply is "
           "written.  A handler that commits and returns a value "
           "without a drain/append between loses exactly the epochs "
           "clients believe are safe.  Split-phase appliers follow the "
           "repo's `*_locked` convention: a method named `*_locked` "
           "that commits is the under-lock half (the caller holds the "
           "lock and owns the reply), so the drain obligation moves to "
           "its call sites — each call to such a method counts as a "
           "commit in the calling function.")

    _COMMIT_ATTRS = {"_commit", "commit"}
    _DRAIN_ATTRS = {"_drain_ckpt", "drain_ckpt"}
    _LOCKED_SUFFIX = "_locked"

    def _commit_carriers(self, mod: ModuleCtx):
        """Names of `*_locked` methods whose body commits: the locked
        half of a split-phase tell.  Exempt from the in-function check
        (they return apply results to a lock-holding caller, not a
        wire reply) — but calls TO them are commits, so every caller
        inherits the drain-before-ack obligation."""
        carriers = set()
        for fn in mod.jit.functions:
            name = getattr(fn, "name", "")
            if not name.endswith(self._LOCKED_SUFFIX):
                continue
            for node in shallow_walk(function_body(fn)):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in self._COMMIT_ATTRS:
                    rec = mod.plain_dotted(node.func.value) or ""
                    if rec == "self" or rec.startswith("self."):
                        carriers.add(name)
                        break
        return carriers

    @staticmethod
    def _in_scope(mod: ModuleCtx) -> bool:
        for alias, target in mod.aliases.items():
            if alias == "durable" or target.endswith(".durable") \
                    or target == "durable":
                return True
        return "_drain_ckpt" in mod.source

    def check(self, mod: ModuleCtx):
        if not self._in_scope(mod):
            return
        carriers = self._commit_carriers(mod)
        for fn in mod.jit.functions:
            name = getattr(fn, "name", "")
            if name in self._COMMIT_ATTRS:
                continue            # the commit primitive itself
            if name in carriers:
                continue            # locked half; callers own the drain
            commits: List[ast.Call] = []
            drains: List[ast.Call] = []
            returns: List[ast.Return] = []
            for node in shallow_walk(function_body(fn)):
                if isinstance(node, ast.Return) and node.value is not None \
                        and not (isinstance(node.value, ast.Constant)
                                 and node.value.value is None):
                    returns.append(node)
                    continue
                if not isinstance(node, ast.Call) \
                        or not isinstance(node.func, ast.Attribute):
                    continue
                rec = mod.plain_dotted(node.func.value) or ""
                a = node.func.attr
                if (a in self._COMMIT_ATTRS or a in carriers) and (
                        rec == "self" or rec.startswith("self.")):
                    commits.append(node)
                elif a in self._DRAIN_ATTRS:
                    drains.append(node)
                elif a == "append" and ("durable" in rec
                                        or "ckpt" in rec):
                    drains.append(node)
            for c in commits:
                acked = any(r.lineno > c.lineno for r in returns)
                drained = any(d.lineno > c.lineno for d in drains)
                if acked and not drained:
                    yield (c,
                           "commit is acknowledged (value returned) "
                           "with no checkpoint drain/append after it — "
                           "a crash here loses a committed epoch the "
                           "client saw")


# -- R105 -------------------------------------------------------------


@register
class ThreadWithoutJoin(Rule):
    id = "R105"
    name = "daemon-thread-no-stop"
    short = ("A Thread is created with no reachable join() on its "
             "handle (or a container it is tracked in)")
    why = ("An untracked thread outlives shutdown: it races teardown "
           "(writing to closed sockets/files), holds the process open, "
           "and under the fleet plane turns one process's exit into a "
           "hang.  Track the handle and join it (bounded) in stop(); "
           "a genuinely fire-and-forget daemon gets a suppression with "
           "its justification.")

    @staticmethod
    def _join_evidence(mod: ModuleCtx):
        """Module-wide join coverage: dotted receiver paths of
        `.join()` calls, plus for-loop iterables whose loop variable is
        joined in the body (`for t in self._threads: t.join()`), with
        one local-alias hop (`ts = list(self._threads)`)."""
        joined: Set[str] = set()
        # local name -> dotted source, per function (alias hop)
        aliases: Dict[Tuple[int, str], str] = {}
        for fn in mod.jit.functions:
            key = id(fn)
            for node in shallow_walk(function_body(fn)):
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    v = node.value
                    if isinstance(v, ast.Call) and isinstance(
                            v.func, ast.Name) \
                            and v.func.id in ("list", "tuple", "sorted") \
                            and len(v.args) == 1:
                        v = v.args[0]
                    src = mod.plain_dotted(v)
                    if src is not None:
                        aliases[(key, node.targets[0].id)] = src
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute) and node.func.attr == "join":
                p = mod.plain_dotted(node.func.value)
                if p is not None:
                    joined.add(p)
            elif isinstance(node, (ast.For, ast.AsyncFor)) \
                    and isinstance(node.target, ast.Name):
                it = node.iter
                if isinstance(it, ast.Call) and isinstance(
                        it.func, ast.Name) \
                        and it.func.id in ("list", "tuple", "sorted") \
                        and len(it.args) == 1:
                    it = it.args[0]
                p = mod.plain_dotted(it)
                if p is None:
                    continue
                tname = node.target.id
                body_joins = any(
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "join"
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id == tname
                    for b in node.body for n in ast.walk(b))
                if body_joins:
                    joined.add(p)
                    fn = mod.enclosing_function(node)
                    if fn is not None and "." not in p:
                        src = aliases.get((id(fn), p))
                        if src:
                            joined.add(src)
        return joined

    @staticmethod
    def _handle(mod: ModuleCtx, call: ast.Call):
        """(kind, path) for the Thread's handle: ('name', p) for a
        direct assignment target, ('container', p) when appended/
        stored into a container, (None, None) when untracked."""
        node, parent = call, mod.parents.get(call)
        while parent is not None:
            if isinstance(parent, ast.Assign) \
                    and len(parent.targets) == 1:
                p = mod.plain_dotted(parent.targets[0])
                if p is not None:
                    return "name", p
                return None, None
            if isinstance(parent, ast.Call) and isinstance(
                    parent.func, ast.Attribute) \
                    and parent.func.attr in ("append", "add") \
                    and node in parent.args:
                p = mod.plain_dotted(parent.func.value)
                if p is not None:
                    return "container", p
                return None, None
            if isinstance(parent, (ast.ListComp, ast.List, ast.Tuple,
                                   ast.Starred, ast.IfExp)):
                node, parent = parent, mod.parents.get(parent)
                continue
            if isinstance(parent, ast.Attribute):
                # Thread(...).start() chain: no handle survives
                return None, None
            break
        return None, None

    def check(self, mod: ModuleCtx):
        lg = mod.locks
        if not lg.thread_creations:
            return
        joined = self._join_evidence(mod)

        # containers that thread handles are appended to, per handle
        def appended_to(fn, hname: str) -> List[str]:
            out = []
            if fn is None:
                return out
            for node in shallow_walk(function_body(fn)):
                if isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Attribute) \
                        and node.func.attr in ("append", "add") \
                        and any(isinstance(a, ast.Name)
                                and a.id == hname for a in node.args):
                    p = mod.plain_dotted(node.func.value)
                    if p is not None:
                        out.append(p)
            return out

        msg = ("Thread started without a reachable join(): track the "
               "handle and join it on shutdown (or suppress with the "
               "daemon's lifecycle justification)")
        for call, fn in lg.thread_creations:
            kind, path = self._handle(mod, call)
            if kind == "name":
                if path in joined:
                    continue
                if any(c in joined for c in appended_to(fn, path)):
                    continue
                yield (call, msg)
            elif kind == "container":
                if path not in joined:
                    yield (call, msg)
            else:
                yield (call, msg)


# -- R106 -------------------------------------------------------------


@register
class ConditionWaitNoPredicate(Rule):
    id = "R106"
    name = "condition-wait-no-predicate"
    short = "Condition.wait() is called outside a while loop"
    why = ("Condition waits wake spuriously and notify_all() wakes "
           "every waiter for a predicate only one can consume: a "
           "wait() not re-checked in a `while predicate:` loop "
           "proceeds on state that is not there.  `wait_for()` "
           "carries its own predicate and is exempt.")

    def check(self, mod: ModuleCtx):
        lg = mod.locks
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "wait"):
                continue
            fn = mod.enclosing_function(node)
            if lg.kind_of(fn, node.func.value) != "condition":
                continue            # Event.wait / unknown receivers
            in_while = False
            for anc in mod.ancestors(node):
                if isinstance(anc, ast.While):
                    in_while = True
                    break
                if anc is fn:
                    break
            if not in_while:
                yield (node,
                       "Condition.wait() outside a while-predicate "
                       "loop: spurious wakeups proceed on a predicate "
                       "that does not hold (use `while pred: cv.wait()`"
                       " or cv.wait_for(pred))")
