"""Runtime lock sanitizer: the dynamic half of the concurrency pass.

The static rules (R101–R106) see one module at a time and syntactic
lock identity; LockGuard watches the *process*: with
``UT_LOCK_GUARD=1|strict`` it wraps ``threading.Lock``/``RLock`` via a
plain module-attribute patch (no sitecustomize) so every lock created
afterwards records into one acquisition-order graph keyed by
allocation site.  It detects

* **cycles** — site A acquired while holding B somewhere, and B while
  holding A somewhere else: the dynamic would-deadlock signal R101
  approximates statically;
* **held-too-long** — a lock held past ``UT_LOCK_GUARD_MS``
  milliseconds (0 = threshold off, the default: the serving plane
  deliberately holds its per-key lock across a compile wall, so a
  fixed default would cry wolf; ``held_max_ms`` is always reported).

The TraceGuard pattern throughout: ``lock_guard_from_env()`` returns
an inert guard when the env var is unset (zero overhead, no patching),
detections are *recorded* at acquire/release and only raised from
``check()`` on clean exit (never mid-critical-section), strict mode
raises ``LockOrderError``, warn mode emits a RuntimeWarning, and every
detection lands in the obs metrics/event families
(``lockguard.cycles`` / ``lockguard.held_too_long``).

Scope and honesty notes: only locks created AFTER ``install()``
through the ``threading`` module attributes are wrapped (``from
threading import Lock`` binds the raw factory at import time; the repo
always spells ``threading.Lock()``).  Bookkeeping is guarded by a raw
``_thread.allocate_lock`` plus a thread-local re-entrancy flag so the
guard's own obs calls cannot recurse into it.  Per-acquire overhead is
a thread-local append and a monotonic read — `bench.py --serve` prices
it at ≥ 0.95x the unguarded throughput and fails the run otherwise.
"""
from __future__ import annotations

import _thread
import os
import sys
import threading
import time
import warnings
from typing import Any, Dict, List, Optional, Set, Tuple

from .. import obs

__all__ = ["LockGuard", "LockOrderError", "lock_guard_from_env"]

_PATCH_LOCK = _thread.allocate_lock()   # serializes install/uninstall
_mono = time.monotonic                  # hot-path alias


class LockOrderError(RuntimeError):
    """Strict-mode verdict: the process built a cyclic lock-order
    graph (would deadlock under the right interleave) or held a lock
    past the configured threshold."""


def _caller_site() -> str:
    """Allocation site of a Lock()/RLock() call, as `dir/file.py:NN`,
    skipping frames inside threading.py itself (Condition() allocates
    its RLock from there — the user call site is what identifies the
    lock)."""
    tfile = getattr(threading, "__file__", "")
    f = sys._getframe(2)
    while f is not None and f.f_code.co_filename == tfile:
        f = f.f_back
    if f is None:
        return "<unknown>:0"
    fn = f.f_code.co_filename
    parts = fn.replace("\\", "/").rsplit("/", 2)
    short = "/".join(parts[-2:]) if len(parts) > 1 else fn
    return f"{short}:{f.f_lineno}"


class _LockProxy:
    """Wraps a raw lock; reports acquire/release to the guard.

    The guard bookkeeping is INLINED here rather than delegated to
    LockGuard methods: plain-Lock acquire/release is the sanitizer's
    hot path (every `with self._lock:` in the serving/store planes),
    and on the bench box each avoided Python call is a measurable
    slice of the >= 0.95x overhead budget."""

    __slots__ = ("_g", "_lk", "_site", "_acq", "_rel")

    def __init__(self, guard: "LockGuard", raw, site: str):
        self._g = guard
        self._lk = raw
        self._site = site
        self._acq = raw.acquire     # bound-method cache: one fewer
        self._rel = raw.release     # attribute hop per hot-path call

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._acq(blocking, timeout)
        if ok:
            g = self._g
            if g._active:
                tls = g._tls
                try:
                    stack = tls.stack
                except AttributeError:
                    stack = tls.stack = []
                    tls.busy = False
                if not tls.busy:
                    g.acquires += 1     # telemetry; races lose counts
                    if stack:
                        site = self._site
                        edges = g._edges
                        for hp, _t0 in stack:
                            h = hp._site
                            # lock-free probe: edges are only added,
                            # so a hit is definitive; first-seen pairs
                            # go through the locked slow path
                            if (h != site
                                    and site not in edges.get(h, ())):
                                g._add_edges(tls, stack, site)
                                break
                    stack.append((self, _mono()))
        return ok

    __enter__ = acquire

    def release(self) -> None:
        g = self._g
        tls = g._tls
        stack = getattr(tls, "stack", None)
        if stack and not tls.busy:
            if stack[-1][0] is self:        # LIFO: the common case
                t0 = stack.pop()[1]
            else:
                t0 = None
                for i in range(len(stack) - 2, -1, -1):
                    if stack[i][0] is self:
                        t0 = stack.pop(i)[1]
                        break
            if t0 is not None and g._active:
                ms = (_mono() - t0) * 1e3
                site = self._site
                if ms > g._held_max.get(site, 0.0):
                    g._held_max[site] = ms  # racy max: telemetry
                if 0.0 < g.held_ms < ms:
                    g._note_held(tls, site, ms)
        self._rel()

    def locked(self) -> bool:
        return self._lk.locked()

    def __exit__(self, *exc) -> None:
        self.release()

    def _at_fork_reinit(self) -> None:
        self._lk._at_fork_reinit()

    def __repr__(self) -> str:
        return f"<guarded {self._lk!r} @ {self._site}>"


class _RLockProxy:
    """Reentrant variant: only the outermost acquire/release touch the
    guard, and the `_release_save`/`_acquire_restore`/`_is_owned`
    protocol is forwarded so Condition(RLock()) keeps working.
    Bookkeeping inlined for the same hot-path reason as _LockProxy
    (the session server's per-key lock is an RLock)."""

    __slots__ = ("_g", "_lk", "_site", "_count", "_acq", "_rel")

    def __init__(self, guard: "LockGuard", raw, site: str):
        self._g = guard
        self._lk = raw
        self._site = site
        self._count = 0
        self._acq = raw.acquire
        self._rel = raw.release

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._acq(blocking, timeout)
        if ok:
            self._count += 1            # owner-only mutation: safe
            if self._count == 1:
                g = self._g
                if g._active:
                    tls = g._tls
                    try:
                        stack = tls.stack
                    except AttributeError:
                        stack = tls.stack = []
                        tls.busy = False
                    if not tls.busy:
                        g.acquires += 1
                        if stack:
                            site = self._site
                            edges = g._edges
                            for hp, _t0 in stack:
                                h = hp._site
                                if (h != site and site
                                        not in edges.get(h, ())):
                                    g._add_edges(tls, stack, site)
                                    break
                        stack.append((self, _mono()))
        return ok

    __enter__ = acquire

    def release(self) -> None:
        if self._count == 1:
            self._g._on_release(self)
        self._count -= 1
        self._rel()

    def __exit__(self, *exc) -> None:
        self.release()

    # Condition support ------------------------------------------------
    def _release_save(self):
        self._g._on_release(self)
        n, self._count = self._count, 0
        return (n, self._lk._release_save())

    def _acquire_restore(self, state) -> None:
        n, inner = state
        self._lk._acquire_restore(inner)
        self._count = n
        self._g._on_acquire(self)

    def _is_owned(self) -> bool:
        return self._lk._is_owned()

    def _at_fork_reinit(self) -> None:
        self._lk._at_fork_reinit()
        self._count = 0

    def __repr__(self) -> str:
        return f"<guarded {self._lk!r} @ {self._site}>"


class LockGuard:
    def __init__(self, *, strict: bool = False, held_ms: float = 0.0,
                 enabled: bool = True, name: str = "lock-guard"):
        self.strict = bool(strict)
        self.held_ms = float(held_ms)
        self.enabled = bool(enabled)
        self.name = name
        self.locks = 0           # proxies created
        self.acquires = 0        # approximate (unlocked counter)
        self._raw = _thread.allocate_lock()     # guards the edge graph
        self._tls = threading.local()
        # site -> set of sites acquired while it was held
        self._edges: Dict[str, Set[str]] = {}
        self._cycles: List[Tuple[str, ...]] = []
        self._held_long: List[Tuple[str, float]] = []
        self._held_max: Dict[str, float] = {}
        self._orig: Optional[tuple] = None
        self._active = False

    # -- bookkeeping ---------------------------------------------------
    # the acquire/release fast paths are deliberately lock-free: the
    # held stack is thread-local, `_edges` membership probes are plain
    # GIL-atomic dict reads (edges are only ever added), and the graph
    # lock + re-entrancy flag are taken only for FIRST-SEEN edges and
    # detections — steady state pays a tls read, a counter, a list
    # append and a monotonic stamp (priced by the bench's >= 0.95x gate)
    def _on_acquire(self, proxy) -> None:
        if not self._active:
            return
        tls = self._tls
        try:
            stack = tls.stack
        except AttributeError:
            stack = tls.stack = []
            tls.busy = False
        if tls.busy:
            return
        self.acquires += 1          # telemetry; races lose counts
        if stack:
            site = proxy._site
            novel = False
            for held_proxy, _t0 in stack:
                h = held_proxy._site
                if h != site and site not in self._edges.get(h, ()):
                    novel = True
                    break
            if novel:
                self._add_edges(tls, stack, site)
        stack.append((proxy, time.monotonic()))

    def _on_release(self, proxy) -> None:
        tls = self._tls
        stack = getattr(tls, "stack", None)
        if not stack or tls.busy:
            return
        if stack[-1][0] is proxy:           # LIFO: the common case
            t0 = stack.pop()[1]
        else:
            t0 = None
            for i in range(len(stack) - 2, -1, -1):
                if stack[i][0] is proxy:
                    t0 = stack.pop(i)[1]
                    break
            if t0 is None:
                return
        if self._active:
            ms = (time.monotonic() - t0) * 1e3
            site = proxy._site
            if ms > self._held_max.get(site, 0.0):
                self._held_max[site] = ms   # racy max: telemetry
            if 0.0 < self.held_ms < ms:
                self._note_held(tls, site, ms)

    def _note_held(self, tls, site: str, ms: float) -> None:
        tls.busy = True         # obs may touch proxied locks
        try:
            with self._raw:
                self._held_long.append((site, round(ms, 3)))
            obs.count("lockguard.held_too_long")
            obs.event("lockguard.held", site=site, ms=round(ms, 3),
                      limit_ms=self.held_ms)
        finally:
            tls.busy = False

    def _add_edges(self, tls, stack, site: str) -> None:
        """Slow path: at least one (held -> site) pair is new.  Edge
        insertion + cycle search under the graph lock; obs emission
        after it (obs may itself acquire proxied locks — busy makes
        that re-entrancy a no-op, and emitting outside `_raw` keeps
        the graph lock leaf-level)."""
        tls.busy = True
        try:
            cycles = []
            with self._raw:
                for held_proxy, _t0 in stack:
                    h = held_proxy._site
                    if h == site:
                        continue
                    dests = self._edges.setdefault(h, set())
                    if site not in dests:
                        dests.add(site)
                        c = self._find_cycle(h, site)
                        if c:
                            self._cycles.append(c)
                            cycles.append(c)
            for c in cycles:
                obs.count("lockguard.cycles")
                obs.event("lockguard.cycle", path=list(c))
        finally:
            tls.busy = False

    def _find_cycle(self, a: str,
                    b: str) -> Optional[Tuple[str, ...]]:
        """Called under self._raw right after adding edge a->b: if a is
        reachable from b, the graph just closed a cycle."""
        seen = {b}
        todo = [b]
        parent: Dict[str, str] = {}
        found = False
        while todo and not found:
            x = todo.pop()
            for y in self._edges.get(x, ()):
                if y == a:
                    parent[y] = x
                    found = True
                    break
                if y not in seen:
                    seen.add(y)
                    parent[y] = x
                    todo.append(y)
        if not found:
            return None
        path = [a]
        cur: Optional[str] = a
        # walk parents back from a to b, then close with a->b
        while cur != b:
            cur = parent.get(cur)
            if cur is None:
                break
            path.append(cur)
        path.reverse()          # b ... a
        return tuple([a] + path)

    # -- install/uninstall --------------------------------------------
    def install(self) -> "LockGuard":
        if not self.enabled or self._active:
            return self
        with _PATCH_LOCK:
            self._orig = (threading.Lock, threading.RLock)
            guard = self
            orig_rlock = self._orig[1]

            def Lock():
                guard.locks += 1
                return _LockProxy(guard, _thread.allocate_lock(),
                                  _caller_site())

            def RLock():
                guard.locks += 1
                return _RLockProxy(guard, orig_rlock(), _caller_site())

            threading.Lock = Lock
            threading.RLock = RLock
            self._active = True
        return self

    def uninstall(self) -> None:
        if not self._active:
            return
        with _PATCH_LOCK:
            self._active = False
            if self._orig is not None:
                # tolerate a nested guard having re-patched after us:
                # only restore what is still ours to restore
                threading.Lock, threading.RLock = self._orig
                self._orig = None

    # -- verdicts ------------------------------------------------------
    def report(self) -> Dict[str, Any]:
        with self._raw:
            cycles = [list(c) for c in self._cycles]
            held_long = list(self._held_long)
            n_edges = sum(len(v) for v in self._edges.values())
        held_max = max(self._held_max.values(), default=0.0)
        return {"name": self.name, "strict": self.strict,
                "held_ms_limit": self.held_ms, "locks": self.locks,
                "acquires": self.acquires, "edges": n_edges,
                "cycles": cycles, "held_too_long": held_long,
                "held_max_ms": round(held_max, 3)}

    def ok(self) -> bool:
        return not self._cycles and not self._held_long

    def check(self) -> None:
        """Raise (strict) or warn on recorded problems — called on
        clean exit only, never mid-critical-section."""
        if not self.enabled or self.ok():
            return
        rep = self.report()
        parts = []
        if rep["cycles"]:
            parts.append(f"{len(rep['cycles'])} lock-order cycle(s): "
                         + "; ".join(" -> ".join(c)
                                     for c in rep["cycles"][:3]))
        if rep["held_too_long"]:
            worst = max(rep["held_too_long"], key=lambda s: s[1])
            parts.append(f"{len(rep['held_too_long'])} held-too-long "
                         f"event(s), worst {worst[0]} at {worst[1]}ms "
                         f"(limit {self.held_ms}ms)")
        msg = f"[{self.name}] " + "; ".join(parts)
        if self.strict:
            raise LockOrderError(msg)
        warnings.warn(msg, RuntimeWarning, stacklevel=2)

    def __enter__(self) -> "LockGuard":
        return self.install()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.uninstall()
        if exc_type is None:
            self.check()


def lock_guard_from_env(name: str = "lock-guard") -> LockGuard:
    """UT_LOCK_GUARD=1|true|yes|warn -> warn mode; =strict -> raise;
    unset -> inert guard (no patching, no overhead).
    UT_LOCK_GUARD_MS sets the held-too-long threshold in milliseconds
    (default 0 = off: held_max_ms is still reported)."""
    v = os.environ.get("UT_LOCK_GUARD", "").strip().lower()
    enabled = v in ("1", "true", "yes", "warn", "strict")
    strict = v == "strict" or os.environ.get(
        "UT_LOCK_GUARD_STRICT", "").strip().lower() in ("1", "true",
                                                        "yes")
    try:
        held_ms = float(os.environ.get("UT_LOCK_GUARD_MS", "0") or 0)
    except ValueError:
        held_ms = 0.0
    return LockGuard(strict=strict and enabled, held_ms=held_ms,
                     enabled=enabled, name=name)
