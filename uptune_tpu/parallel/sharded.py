"""Multi-chip scale-out of the fused engine over a `jax.sharding.Mesh`.

The reference scales search by running one OpenTuner instance per parallel
slot and epoch-wise syncing their results through a global SQLite table
(`/root/reference/python/uptune/api.py:596-607,725-726` and
`opentuner/api.py:87-104`), and scales evaluation by Ray actors.  The
TPU-native design maps both axes onto the device mesh:

* **`search` axis** — independent search replicas (own technique states,
  own RNG streams, own dedup history: the per-instance DB equivalent),
  exchanging the global best every step via ICI collectives (`pmin` +
  one-hot `psum` broadcast) instead of SQL row exchange;
* **`eval` axis** — each replica's candidate batch is sharded for
  objective / surrogate scoring; per-shard QoR is `all_gather`-ed back so
  technique `observe` sees its full population.  Proposal generation is
  replicated within an eval group (same key -> same proposals), which
  costs nothing at these shapes and keeps technique state exact.

Everything runs inside one `shard_map`-ped `lax.scan` program: the whole
multi-replica tuning run is a single XLA executable with all cross-chip
traffic on ICI.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.8 top-level; older releases keep it in experimental
    from jax import shard_map as _shard_map  # type: ignore
    _REP_KW = "check_vma"
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map
    _REP_KW = "check_rep"


def shard_map(fn, **kw):
    """Version-compat wrapper: the replication-check kwarg was renamed
    check_rep -> check_vma when shard_map moved to the jax top level."""
    kw[_REP_KW] = kw.pop("check_rep", False)
    return _shard_map(fn, **kw)

from ..engine.fused import EngineState, FusedEngine
from ..techniques.base import Best


def make_mesh(n_search: Optional[int] = None, n_eval: int = 1,
              devices=None) -> Mesh:
    """Build a ('search', 'eval') mesh over the available devices."""
    devices = list(devices if devices is not None else jax.devices())
    if n_search is None:
        n_search = len(devices) // n_eval
    n = n_search * n_eval
    assert n <= len(devices), (n_search, n_eval, len(devices))
    arr = np.array(devices[:n]).reshape(n_search, n_eval)
    return Mesh(arr, ("search", "eval"))


class ShardedEngine:
    """A FusedEngine replicated over mesh['search'] with eval sharding
    over mesh['eval']."""

    def __init__(self, engine: FusedEngine, mesh: Mesh):
        self.engine = engine
        self.mesh = mesh
        self.n_search = mesh.shape["search"]
        self.n_eval = mesh.shape["eval"]
        if engine.total_batch % self.n_eval:
            raise ValueError(
                f"total batch {engine.total_batch} not divisible by "
                f"eval-axis size {self.n_eval}")
        self._compiled: dict = {}

    # -- state management ---------------------------------------------------
    def init(self, key: jax.Array) -> EngineState:
        """Per-replica engine states stacked on a leading [n_search] axis
        and device_put onto the mesh."""
        keys = jax.random.split(key, self.n_search)
        state = jax.vmap(self.engine.init)(keys)
        spec = P("search")
        sharding = jax.sharding.NamedSharding(self.mesh, spec)
        return jax.tree.map(
            lambda x: jax.device_put(x, sharding), state)

    # -- collectives --------------------------------------------------------
    def _exchange(self, best: Best) -> Best:
        """Global-best broadcast across the search axis: lexicographic
        (qor, replica-index) argmin, one-hot psum broadcast."""
        qmin = jax.lax.pmin(best.qor, "search")
        idx = jax.lax.axis_index("search")
        big = jnp.asarray(1 << 30, jnp.int32)
        winner = jax.lax.pmin(
            jnp.where(best.qor == qmin, idx, big), "search")
        i_am = (idx == winner) & jnp.isfinite(qmin)
        u = jax.lax.psum(jnp.where(i_am, best.u, 0.0), "search")
        perms = tuple(
            jax.lax.psum(jnp.where(i_am, p, 0), "search")
            for p in best.perms)
        # keep the local best when nothing finite exists yet
        return Best(
            jnp.where(jnp.isfinite(qmin), u, best.u),
            tuple(jnp.where(jnp.isfinite(qmin), p, lp)
                  for p, lp in zip(perms, best.perms)),
            qmin)

    def _sharded_eval(self, cands) -> jax.Array:
        """Evaluate only this device's slice of the batch, all_gather the
        QoR back to the full batch."""
        eng = self.engine
        shard = eng.total_batch // self.n_eval
        i = jax.lax.axis_index("eval")
        lo = i * shard
        u = jax.lax.dynamic_slice_in_dim(cands.u, lo, shard, axis=0)
        perms = tuple(jax.lax.dynamic_slice_in_dim(p, lo, shard, axis=0)
                      for p in cands.perms)
        q = eng.objective(eng.space.decode_scalars(u), perms)
        return jax.lax.all_gather(q, "eval", axis=0, tiled=True)

    # -- compiled programs --------------------------------------------------
    def _local(self, n_steps: int):
        eng = self.engine

        def local_run(state_block: EngineState) -> EngineState:
            state = jax.tree.map(lambda x: x[0], state_block)
            state = eng.run(state, n_steps, eval_fn=self._sharded_eval,
                            exchange=self._exchange)
            return jax.tree.map(lambda x: x[None], state)

        return local_run

    def run(self, state: EngineState, n_steps: int) -> EngineState:
        """n_steps sharded steps as one shard_map-ed scan program.

        The compiled program is memoized per n_steps — jax.jit caches by
        function identity, so rebuilding the closure each call would
        recompile the whole multi-replica program every invocation."""
        fn = self._compiled.get(n_steps)
        if fn is None:
            fn = jax.jit(shard_map(
                self._local(n_steps), mesh=self.mesh,
                in_specs=(P("search"),), out_specs=P("search"),
                check_rep=False))
            self._compiled[n_steps] = fn
        return fn(state)

    # -- host-side results --------------------------------------------------
    def best(self, state: EngineState) -> Tuple[dict, float]:
        qors = np.asarray(state.best.qor)
        i = int(np.argmin(qors))
        cands = jax.tree.map(lambda x: x[i], state.best)
        cfg = self.engine.space.to_configs(
            Best(cands.u, cands.perms, cands.qor).as_batch(1))[0]
        return cfg, float(self.engine.sign * qors[i])
