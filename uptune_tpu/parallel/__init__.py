from .multihost import (distributed_config, initialize,  # noqa: F401
                        is_coordinator, make_multihost_mesh)
from .sharded import ShardedEngine, make_mesh  # noqa: F401
from .surrogate_shard import sharded_gp_score  # noqa: F401
