from .sharded import ShardedEngine, make_mesh  # noqa: F401
