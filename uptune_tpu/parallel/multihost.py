"""Multi-host (DCN) scale-out: jax.distributed bootstrap + hybrid
meshes.

The reference reaches multiple machines through a Ray cluster + shared
FS / S3 (`/root/reference/cluster/config.yaml:1-60`,
`api.py:831-848` node workdir discovery,
`async_task_scheduler.py:340-353` S3 publish).  The TPU-native
equivalent is the standard JAX multi-process model: every host runs the
same program, `jax.distributed.initialize` wires the processes over
DCN, and the ('search', 'eval') mesh of `uptune_tpu.parallel.sharded`
is laid out so that the *search* axis (the best-exchange collective,
tiny payloads, latency-tolerant) spans hosts over DCN while the *eval*
axis (per-replica batch sharding, bandwidth-sensitive) stays inside
each host's ICI island — the layout recipe of the scaling playbook:
fast collectives ride ICI, slow ones ride DCN.

Environment-variable bootstrap mirrors the reference's settings-dict
override layering (flags > env > defaults): UT_COORDINATOR,
UT_NUM_PROCESSES, UT_PROCESS_ID.
"""
from __future__ import annotations

import os
from typing import Optional, Sequence

from jax.sharding import Mesh


def distributed_config(coordinator: Optional[str] = None,
                       num_processes: Optional[int] = None,
                       process_id: Optional[int] = None) -> dict:
    """Resolve the jax.distributed bootstrap triple from args > UT_* env
    > single-process defaults; validates before any network call."""
    coordinator = coordinator or os.environ.get("UT_COORDINATOR")
    if num_processes is None:
        env = os.environ.get("UT_NUM_PROCESSES")
        num_processes = int(env) if env else 1
    if process_id is None:
        env = os.environ.get("UT_PROCESS_ID")
        process_id = int(env) if env else 0
    if num_processes < 1:
        raise ValueError(f"num_processes must be >= 1, got {num_processes}")
    if not 0 <= process_id < num_processes:
        raise ValueError(
            f"process_id {process_id} outside [0, {num_processes})")
    if num_processes > 1 and not coordinator:
        raise ValueError(
            "multi-process run needs a coordinator address "
            "(UT_COORDINATOR=host:port or coordinator=...)")
    return {"coordinator_address": coordinator,
            "num_processes": num_processes, "process_id": process_id}


def initialize(coordinator: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> dict:
    """Bootstrap jax.distributed for a multi-host tuning run; no-op for
    a single process.  Returns the resolved config."""
    cfg = distributed_config(coordinator, num_processes, process_id)
    if cfg["num_processes"] > 1:
        import jax
        jax.distributed.initialize(
            coordinator_address=cfg["coordinator_address"],
            num_processes=cfg["num_processes"],
            process_id=cfg["process_id"])
    return cfg


def make_multihost_mesh(n_eval_per_host: int = 1,
                        devices: Optional[Sequence] = None) -> Mesh:
    """('search', 'eval') mesh spanning every process's devices.

    Layout contract: devices of one host stay CONTIGUOUS along the
    search axis and the eval axis never crosses a host boundary, so the
    eval all_gather runs on ICI and only the (scalar) best-exchange
    crosses DCN.  jax.devices() in a multi-process run returns all
    global devices grouped by process, which gives exactly that
    ordering."""
    import jax
    import numpy as np

    if devices is None:
        # the eval axis must fit inside one host's ICI island
        local = jax.local_device_count()
        if local % n_eval_per_host:
            raise ValueError(
                f"eval width {n_eval_per_host} does not divide the "
                f"{local} local devices — the eval all_gather would "
                f"cross a host boundary onto DCN")
        devices = list(jax.devices())
    else:
        devices = list(devices)
    n = len(devices)
    if n % n_eval_per_host:
        raise ValueError(
            f"{n} global devices not divisible by eval width "
            f"{n_eval_per_host}")
    arr = np.array(devices).reshape(n // n_eval_per_host, n_eval_per_host)
    return Mesh(arr, ("search", "eval"))


def is_coordinator() -> bool:
    """True on the process that should own host-side IO (archive writes,
    best.json, logging) — process_id 0."""
    import jax
    return jax.process_index() == 0
