"""Multi-chip GP acquisition scoring: candidates sharded over a mesh
axis, history (the fitted GPState) replicated.

SURVEY §5.7 maps the reference's "long context" axis onto candidate-batch
scale: at 10^5-10^6 pool candidates per acquisition the [B, N]
cross-kernel dominates, and it is embarrassingly parallel over B.  Each
device scores its slice of the batch against the full (replicated)
training set — the blockwise-GP shape where per-device traffic is only
the [B/n] score slice on ICI, no psum in the hot path.

Single-chip companions: `surrogate/gp.py` (plain XLA, B up to ~10^5)
and `surrogate/pallas_score.py` (fused Pallas kernel for the
million-candidate regime).  This module spreads either regime across
the mesh — and picks between them PER SHARD: once a device's slice
reaches PALLAS_MIN_POOL candidates, mean/ei/lcb route through the
fused mean+variance kernel instead of gp.predict (override with
`use_pallas=`).

The reference has no analogue — its XGBoost surrogate scores candidate
dicts one batch per process (`/root/reference/python/uptune/
src/multi_stage.py:8-22`); cross-machine scale meant more Ray actors,
never a faster surrogate.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..surrogate import gp as gp_mod
from ..surrogate import pallas_score
from ..surrogate.gp import GPState
from .sharded import shard_map

SCORES = ("mean", "ei", "lcb", "thompson")


def sharded_gp_score(mesh, axis: str, state: GPState, feats: jax.Array,
                     kind: str = "ei",
                     best_y: Optional[float] = None,
                     key: Optional[jax.Array] = None,
                     beta: float = 2.0,
                     n_cont: Optional[int] = None,
                     n_cat: int = 0,
                     use_pallas: Optional[bool] = None) -> jax.Array:
    """[B, F] candidate features -> [B] acquisition scores, with B
    sharded over `mesh.shape[axis]` devices and the GPState replicated.

    kind='mean' returns the predictive mean, 'ei' expected improvement
    over `best_y` (higher = better), 'lcb' the lower confidence bound
    (lower = better), 'thompson' one posterior sample per point (needs
    `key`; per-shard key folding keeps draws independent).

    `n_cont`/`n_cat` are the mixed-kernel split (Space.n_cont_features /
    Space.n_cat) and MUST match what the state was fitted with: a state
    fitted over surrogate_transform features scored without them would
    silently treat the one-hot block as continuous coordinates and drop
    the fitted ls_cat — multi-chip scores would diverge from
    single-chip scores on exactly the categorical-heavy spaces the
    mixed kernel exists for.
    """
    if kind not in SCORES:
        raise ValueError(f"unknown score {kind!r}; known: {SCORES}")
    if kind == "ei" and best_y is None:
        raise ValueError("kind='ei' needs best_y (incumbent QoR)")
    if kind == "thompson" and key is None:
        raise ValueError("kind='thompson' needs a PRNG key")
    n = mesh.shape[axis]
    b = feats.shape[0]
    if b % n:
        raise ValueError(f"batch {b} not divisible by mesh axis "
                         f"{axis!r} of size {n}")

    best_arr = jnp.asarray(0.0 if best_y is None else best_y,
                           jnp.float32)
    key_arr = jax.random.PRNGKey(0) if key is None else key
    # per-shard regime choice (static: shard size is b // n at trace
    # time): large slices use the fused Pallas mean+variance kernel,
    # small ones keep plain XLA; thompson always uses gp.predict (its
    # draw needs the same moments, but stays off the fused path so the
    # per-shard key folding below remains the only RNG difference)
    if use_pallas is None:
        use_pallas = (b // n) >= pallas_score.PALLAS_MIN_POOL
    if use_pallas and state.kinv is None and kind != "thompson":
        # attach the premasked K^-1 ONCE here — inside the shard the
        # fallback would re-run the O(N^3) solve per call on every
        # device (r5 review)
        state = gp_mod.precompute_kinv(state)

    def local(state, best_arr, key_arr, shard):
        if use_pallas and kind in ("mean", "ei", "lcb"):
            mu, sd = pallas_score.gp_mean_var_scores(
                state, shard, n_cont=n_cont, n_cat=n_cat)
        elif kind != "thompson":
            mu, sd = gp_mod.predict(state, shard, n_cont, n_cat)
        if kind == "mean":
            return mu
        if kind == "ei":
            return gp_mod.ei_from_moments(mu, sd, best_arr)
        if kind == "lcb":
            return mu - beta * sd
        k = jax.random.fold_in(key_arr, jax.lax.axis_index(axis))
        return gp_mod.thompson(state, shard, k, n_cont, n_cat)

    rep = P()  # replicated
    fn = shard_map(local, mesh=mesh,
                   in_specs=(rep, rep, rep, P(axis)),
                   out_specs=P(axis), check_rep=False)
    return fn(state, best_arr, key_arr, feats)
