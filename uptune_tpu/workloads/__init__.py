from .synthetic import (  # noqa: F401
    beale_device, make_host_objective, random_tsp_distances,
    rosenbrock_device, rosenbrock_objective, rosenbrock_space, sphere_device,
    tsp_device, tsp_objective, tsp_space)
