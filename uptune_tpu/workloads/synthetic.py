"""Synthetic benchmark objectives: the reference's own framework-test
fixtures (`/root/reference/samples/rosenbrock/rosenbrock.py:1-60` functions
rosenbrock / sphere / beale; `/root/reference/samples/tsp/tsp.py:1-19`
permutation tour length), in batched form.

Each objective provides:
* `space(...)` -> a Space
* a host callable `(list[config dict]) -> np.ndarray` for the Tuner
* a pure-JAX `*_device(u_decoded or perm)` used by the fused on-device
  engine and the bench harness.
"""
from __future__ import annotations

from typing import Dict, List

import jax.numpy as jnp
import numpy as np

from ..space.params import FloatParam, IntParam, PermParam
from ..space.spec import Space


# -- rosenbrock family ------------------------------------------------------
def rosenbrock_space(dims: int = 2, lo: float = -30.0, hi: float = 30.0,
                     as_int: bool = False) -> Space:
    mk = IntParam if as_int else FloatParam
    return Space([mk(f"x{i}", lo, hi) for i in range(dims)])


def rosenbrock_device(x: jnp.ndarray) -> jnp.ndarray:
    """[..., D] -> [...] classic Rosenbrock value."""
    a, b = x[..., :-1], x[..., 1:]
    return (100.0 * (b - a * a) ** 2 + (1.0 - a) ** 2).sum(axis=-1)


def sphere_device(x: jnp.ndarray) -> jnp.ndarray:
    return (x * x).sum(axis=-1)


def beale_device(x: jnp.ndarray) -> jnp.ndarray:
    a, b = x[..., 0], x[..., 1]
    return ((1.5 - a + a * b) ** 2
            + (2.25 - a + a * b ** 2) ** 2
            + (2.625 - a + a * b ** 3) ** 2)


def _configs_to_x(cfgs: List[Dict], dims: int) -> np.ndarray:
    return np.asarray([[c[f"x{i}"] for i in range(dims)] for c in cfgs],
                      np.float64)


def make_host_objective(fn_device, dims: int):
    def objective(cfgs: List[Dict]) -> np.ndarray:
        x = _configs_to_x(cfgs, dims)
        return np.asarray(fn_device(jnp.asarray(x)))
    return objective


def rosenbrock_objective(dims: int = 2):
    return make_host_objective(rosenbrock_device, dims)


# -- tsp --------------------------------------------------------------------
def tsp_space(n_cities: int) -> Space:
    return Space([PermParam("tour", list(range(n_cities)))])


def random_tsp_distances(n_cities: int, seed: int = 0) -> np.ndarray:
    rng = np.random.RandomState(seed)
    pts = rng.rand(n_cities, 2)
    d = np.sqrt(((pts[:, None, :] - pts[None, :, :]) ** 2).sum(-1))
    return d


def tsp_device(perm: jnp.ndarray, dist: jnp.ndarray) -> jnp.ndarray:
    """perm [..., N] int32 city order -> [...] closed-tour length.

    Deliberate variant: the reference scores the *open* path
    (samples/tsp/tsp.py:8-13); we use the standard closed tour, whose
    optimum is rotation-invariant — values are not directly comparable
    to the reference's."""
    nxt = jnp.roll(perm, -1, axis=-1)
    return dist[perm, nxt].sum(axis=-1)


def tsp_objective(dist: np.ndarray):
    djnp = jnp.asarray(dist)

    def objective(cfgs: List[Dict]) -> np.ndarray:
        perm = jnp.asarray([c["tour"] for c in cfgs], jnp.int32)
        return np.asarray(tsp_device(perm, djnp))
    return objective
