"""`ut` — the command-line entry point.

Mirrors the reference CLI (`/root/reference/python/uptune/on.py:8-55` +
the aggregated argparsers, `python/uptune/__init__.py:122-141`):

    ut prog.py -pf 4 --test-limit 200
    ut prog.py --technique de --technique pso
    ut --list-techniques
    ut prog.py --apply-best          # re-run with the best found config

Flag precedence is flags > ut.config(...) > defaults
(tests/python/test_async_execute.py:5-14 contract): any flag left unset
falls back to the session settings dict.  Mode selection is automatic
(async_task_scheduler.py:465-474): template annotations in the script
select template mode; >1 stage in ut.params.json selects multi-stage.
"""
from __future__ import annotations

import argparse
import json
import logging
import os
import sys
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ut", description="uptune-tpu: TPU-native program autotuner")
    p.add_argument("script", nargs="?", help="program to tune")
    p.add_argument("script_args", nargs="*",
                   help="arguments passed through to the program")
    p.add_argument("-pf", "--parallel-factor", type=int, default=None,
                   help="parallel evaluation width")
    p.add_argument("--test-limit", type=int, default=None,
                   help="number of trials")
    p.add_argument("--timeout", type=float, default=None,
                   help="total tuning wall-clock limit (s)")
    p.add_argument("--runtime-limit", type=float, default=None,
                   help="per-trial wall-clock limit (s)")
    p.add_argument("-t", "--technique", action="append", default=None,
                   help="search technique (repeatable); default: AUC "
                        "bandit portfolio")
    p.add_argument("--generate-bandit-technique", type=int, default=None,
                   metavar="SEED",
                   help="use a seeded random AUC-bandit portfolio "
                        "instead of --technique")
    p.add_argument("--learning-models", action="append", default=None,
                   choices=("gp", "mlp"),
                   help="enable the surrogate plane (EI top-k pruning "
                        "+ pool proposals, calibrated defaults); the "
                        "reference's --learning-models flag")
    p.add_argument("--surrogate-arbitration", default=None,
                   choices=("schedule", "bandit", "bandit-small-budget"),
                   help="how the surrogate proposal plane gets "
                        "acquisitions: 'schedule' fires every Nth "
                        "acquisition (with the run-budget passivation "
                        "rule); 'bandit' registers it as a "
                        "credit-earning arm of the AUC bandit, which "
                        "starves it per-run when its pulls stop "
                        "producing new bests; 'bandit-small-budget' is "
                        "the measured recipe for eval budgets at or "
                        "below the parameter count (bandit arbitration "
                        "+ affordable 8-eval pulls, no passivation — "
                        "0.88x baseline on gcc-real at 30 seeds, "
                        "BENCHREPORT.md)")
    p.add_argument("--surrogate-async", choices=("on", "off"),
                   default=None,
                   help="async surrogate plane (default on): 'on' runs "
                        "the O(N^3) GP refit + hyperparameter sweep on "
                        "a background worker publishing versioned "
                        "snapshots — ask/tell never blocks on learning "
                        "and new observations fold into the model via "
                        "O(N^2) incremental Cholesky updates; 'off' "
                        "restores the synchronous inline refit")
    p.add_argument("--surrogate-screen", action="append", default=None,
                   metavar="ARCHIVE",
                   help="cross-payload transfer: driver jsonl trial "
                        "archive(s) from OTHER workloads over the SAME "
                        "space (repeatable).  The surrogate restricts "
                        "its model to the feature lanes that measurably "
                        "moved QoR there and biases its pool mutations "
                        "toward them (surrogate/screen.py) — the "
                        "measured fix for budget<params runs where an "
                        "unscreened GP stays prior-dominated")
    p.add_argument("--surrogate-screen-top", default="16,24",
                   metavar="CONT,CAT",
                   help="screen sizes: continuous lanes, categorical "
                        "groups kept (default 16,24; hard mode only)")
    p.add_argument("--surrogate-screen-mode", default="hard",
                   choices=("hard", "soft"),
                   help="'hard' restricts the model to the top-k lanes; "
                        "'soft' keeps full width and scales each lane "
                        "by its transferred sensitivity (per-lane ARD)")
    p.add_argument("--surrogate-flip-bias", default=None,
                   choices=("none", "online"),
                   help="'online' re-ranks categorical params by "
                        "|corr| with QoR over THIS run's observations "
                        "at each refit and biases the proposal plane's "
                        "flip moves toward them (75%% sensitivity / "
                        "25%% uniform) — guides the bold moves without "
                        "narrowing the model")
    p.add_argument("--seed-configuration", action="append", default=None,
                   metavar="JSON",
                   help="JSON file with a known-good configuration (or "
                        "a list of them) injected as 'seed' trials at "
                        "startup, evaluated before any technique batch "
                        "— warm-starts expensive runs from prior bests "
                        "(repeatable; partial configs are merged over "
                        "the declared defaults).  The reference's "
                        "--seed-configuration flag")
    p.add_argument("--seed", type=int, default=None, help="RNG seed")
    p.add_argument("--prefetch", type=int, default=None, metavar="N",
                   help="async ticket prefetch depth: keep N trials "
                        "proposed ahead of free worker slots so device "
                        "propose+dedup hides behind build wall-clock "
                        "(default: the parallel factor; 0 = lockstep "
                        "propose-only-when-a-slot-is-free)")
    p.add_argument("--compile-cache-dir", default=None, metavar="DIR",
                   help="persistent XLA compilation cache base dir "
                        "(jax_compilation_cache_dir), keyed per space "
                        "signature so repeated tunes of the same "
                        "program skip first-step compiles (default: "
                        ".xla_cache at the repo root / "
                        "~/.cache/uptune_tpu/xla; pass 'off' to "
                        "disable)")
    p.add_argument("--store-dir", default=None, metavar="DIR",
                   help="content-addressed trial results store "
                        "(docs/STORE.md): consulted before every build "
                        "— a previously measured config is served its "
                        "recorded QoR without launching the program, "
                        "and N concurrent ut processes sharing one "
                        "store directory exchange results and "
                        "new-bests (default: ut.temp/store under the "
                        "work dir; pass 'off' to disable)")
    p.add_argument("--store", default=None, metavar="MODE|ADDR",
                   help="'on'/'off' forces the results store regardless "
                        "of --store-dir ('off' wins over any "
                        "directory); tcp://HOST:PORT joins a `ut "
                        "store` cooperative store server instead of a "
                        "directory — N tuning processes pointed at one "
                        "server share results, exchange new-bests and "
                        "pool surrogate evidence over TCP "
                        "(docs/STORE.md \"Remote store\")")
    p.add_argument("--federate", choices=("on", "off"), default=None,
                   help="feed sibling instances' (config, qor) rows "
                        "into the local surrogate at exchange time "
                        "(default on; elite migration runs either way)")
    p.add_argument("--exchange-interval", type=float, default=None,
                   metavar="SECONDS",
                   help="migration cadence: minimum seconds between "
                        "store refreshes gating elite migration and "
                        "the federated surrogate feed (default 2)")
    p.add_argument("--warm-start", action="store_true", default=None,
                   help="preload this (space, program)'s stored trials "
                        "before the first acquisition: best-so-far, "
                        "dedup history (recorded configs are never "
                        "re-proposed) and the surrogate training set "
                        "all start warm — spend the whole budget on "
                        "NEW configs instead of replaying a cached "
                        "stream")
    p.add_argument("--params", default=None,
                   help="reuse an existing ut.params.json")
    p.add_argument("--resume", action="store_true",
                   help="resume from the trial archive")
    p.add_argument("--work-dir", default=None,
                   help="work directory (default: cwd)")
    p.add_argument("--no-sandbox", action="store_true",
                   help="run trials directly in the work dir")
    p.add_argument("--apply-best", action="store_true",
                   help="run the program once with the best config")
    p.add_argument("--list-techniques", action="store_true",
                   help="list registered search techniques and exit")
    p.add_argument("--print-search-space-size", action="store_true",
                   help="analyze, print log10(space size) and exit")
    p.add_argument("--print-params", action="store_true",
                   help="analyze, print the param records and exit")
    p.add_argument("--cfg", action="store_true",
                   help="print the resolved configuration")
    p.add_argument("--num-hosts", type=int, default=None,
                   help="run the same command in N local processes (the "
                        "analogue of the reference's Ray cluster "
                        "provisioning, cluster/config.yaml). In program "
                        "mode each process is an INDEPENDENT search "
                        "replica (multi-start: seeds diverge, replica "
                        "i>0 writes ut.archive.hi.jsonl / best.hi.json; "
                        "the launcher promotes the best replica to "
                        "best.json at the end). The UT_COORDINATOR / "
                        "UT_NUM_PROCESSES / UT_PROCESS_ID env is also "
                        "wired, so library-mode programs can call "
                        "uptune_tpu.parallel.initialize() for the "
                        "jax.distributed sharded-engine plane")
    p.add_argument("--trace", default=None, metavar="OUT.json",
                   help="unified observability plane "
                        "(docs/OBSERVABILITY.md): record cross-plane "
                        "spans (ticket lifecycle, worker-slot build "
                        "lanes + their subprocess sidecar spans, "
                        "background refit, store hits) and write a "
                        "Perfetto-viewable Chrome trace JSON here, "
                        "plus OUT.json.metrics.jsonl with the flight "
                        "recorder's periodic metrics timeline.  The "
                        "trace and metrics tail are also flushed on "
                        "SIGINT/SIGTERM, so an interrupted run keeps "
                        "its telemetry.  Also reachable via "
                        "UT_TRACE=<path> or ut.config({'trace': ...}); "
                        "'off' disables")
    p.add_argument("--journal", default=None, metavar="OUT.jsonl",
                   help="tuning journal (docs/OBSERVABILITY.md "
                        "'Search-quality telemetry'): an append-only "
                        "JSONL stream of search decisions — arm pulls "
                        "with dedup/prune verdicts, every tell joined "
                        "with the surrogate's propose-time mu/sigma, "
                        "store hits, snapshot publishes — plus live "
                        "convergence/calibration gauges and stall/"
                        "miscalibration alerts derived from it.  "
                        "Render post-hoc with `ut report OUT.jsonl`.  "
                        "Also reachable via UT_JOURNAL or "
                        "ut.config({'journal': ...}); 'off' disables")
    p.add_argument("--metrics-interval", type=float, default=None,
                   metavar="SECONDS",
                   help="flight-recorder cadence for the traced run's "
                        "metrics timeline (default 1.0; 0 disables "
                        "the background thread and restores the "
                        "single end-of-run metrics snapshot).  Only "
                        "meaningful with --trace/UT_TRACE")
    p.add_argument("--metrics-rotate", type=int, default=None,
                   metavar="N",
                   help="flight-recorder rotation depth: generations "
                        "kept past the row cap (<file>.1 … <file>.N; "
                        "default 1).  `ut top --metrics` and the "
                        "fleet hub read through the whole chain")
    p.add_argument("--telemetry", default=None, metavar="HOST:PORT",
                   help="fleet telemetry (docs/OBSERVABILITY.md "
                        "'Fleet telemetry'): ship this process's "
                        "metrics window snapshots, journal rows, "
                        "alerts and health rollups to a running "
                        "`ut hub` collector over a bounded "
                        "never-blocking queue with reconnect/backoff "
                        "and explicit drop accounting.  --num-hosts "
                        "replicas each ship under their own "
                        "(host, pid, role.hN) source key.  Also "
                        "reachable via UT_TELEMETRY or "
                        "ut.config({'telemetry': ...}); 'off' "
                        "disables")
    p.add_argument("--device-trace", default=None, metavar="DIR",
                   help="programmatic jax.profiler capture for the "
                        "whole run (docs/OBSERVABILITY.md 'Device "
                        "telemetry'): the XPlane dump lands under "
                        "DIR/plugins/profile/ and, when --trace is "
                        "also on, is referenced from the Chrome-trace "
                        "export (otherData.device_trace) so host "
                        "spans and XLA kernels open side by side in "
                        "Perfetto.  Also reachable via "
                        "UT_DEVICE_TRACE=<dir>; 'off' disables")
    p.add_argument("--device", choices=("cpu", "accel"), default="cpu",
                   help="platform for the search engine (default cpu: "
                        "black-box evals dominate; 'accel' trusts the "
                        "environment's accelerator config)")
    p.add_argument("-v", "--verbose", action="store_true")
    return p


def _configure_logging(verbose: bool) -> None:
    logging.basicConfig(
        level=logging.DEBUG if verbose else logging.INFO,
        format="[%(relativeCreated)7.0fms] %(levelname)s %(message)s")


def _launch_hosts(n: int, argv: Optional[List[str]],
                  work_dir: Optional[str] = None) -> int:
    """`ut --num-hosts N ...`: run the SAME ut command in N local
    processes — the single-machine analogue of the reference's cluster
    provisioning (cluster/config.yaml spins Ray head + workers).  On a
    real pod each host runs the same command with UT_COORDINATOR
    pointing at host 0; this flag exists so the multi-process path can
    be exercised anywhere.

    PROGRAM-mode semantics are multi-start: each replica tunes
    independently with a diverged seed and its own archive/best files
    (ProgramTuner.host_tag), and the launcher promotes the best
    replica's result to best.json afterwards — there is no cross-host
    exchange in the subprocess evaluation plane.  The jax.distributed
    coordinator env is still wired for library-mode programs that build
    the sharded engine (parallel/ is the plane with real ICI/DCN
    collectives).

    Children inherit everything else from the parent command line; their
    output is line-prefixed with [hN].  Exit code is the first nonzero
    child code."""
    import socket
    import subprocess
    import threading

    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()

    base = [a for a in (argv if argv is not None else sys.argv[1:])]
    # strip the flag (both --num-hosts N and --num-hosts=N spellings)
    cleaned, skip = [], False
    for a in base:
        if skip:
            skip = False
            continue
        if a == "--num-hosts":
            skip = True
            continue
        if a.startswith("--num-hosts="):
            continue
        cleaned.append(a)

    # children must import uptune_tpu regardless of their cwd (checkout
    # use without pip install -e — same seam as ProgramTuner.env_extra)
    from .utils.pypath import child_pythonpath
    pp = child_pythonpath()
    procs = []
    for pid in range(n):
        env = dict(os.environ,
                   PYTHONPATH=pp,
                   UT_COORDINATOR=f"localhost:{port}",
                   UT_NUM_PROCESSES=str(n),
                   UT_PROCESS_ID=str(pid))
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "uptune_tpu.cli", *cleaned], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))

    def _pump(i, p):
        for line in p.stdout:
            sys.stdout.write(f"[h{i}] {line}")
            sys.stdout.flush()

    threads = [threading.Thread(target=_pump, args=(i, p), daemon=True)
               for i, p in enumerate(procs)]
    for t in threads:
        t.start()
    rc = 0
    for p in procs:
        code = p.wait()
        rc = rc or code
    for t in threads:
        t.join(timeout=5)
    _merge_replica_bests(cleaned, n, work_dir)
    return rc


def _merge_replica_bests(cleaned: List[str], n: int,
                         work_dir: Optional[str] = None) -> None:
    """Promote the best replica's result to best.json (best-effort: the
    work dir is the launcher's --work-dir when given, else derived from
    the script positional — matching main()'s own resolution; silently
    skipped for non-tuning invocations like --list-techniques)."""
    import json as _json

    script = next((a for a in cleaned
                   if not a.startswith("-") and os.path.isfile(a)
                   and a.endswith((".py", ".tpl"))), None)
    if script is None:
        return
    if work_dir:
        work_dir = os.path.abspath(work_dir)
    else:
        work_dir = os.path.dirname(os.path.abspath(script)) or os.getcwd()
    # orientation comes from the program's declared trend (ut.target)
    sense = "min"
    try:
        with open(os.path.join(work_dir, "ut.default_qor.json")) as f:
            sense = _json.load(f).get("trend", "min")
    except (OSError, ValueError):
        pass
    sign = 1.0 if sense == "min" else -1.0
    cands = []
    for pid in range(n):
        tag = f".h{pid}" if pid else ""
        path = os.path.join(work_dir, f"best{tag}.json")
        if not os.path.isfile(path):
            continue
        try:
            with open(path) as f:
                rec = _json.load(f)
            cands.append((sign * float(rec["qor"]), pid, rec))
        except (ValueError, KeyError, OSError):
            continue
    if not cands:
        return
    skey, pid, rec = min(cands)
    qor = sign * skey
    dst = os.path.join(work_dir, "best.json")
    if pid != 0:
        with open(dst, "w") as f:
            _json.dump(rec, f, indent=1)
    print(f"[ut] best across {len(cands)} replicas: qor={qor:.6g} "
          f"(replica h{pid}) -> {dst}")


# `ut <name> ...` subcommands, each deferring to its own module (and
# flag set) — one table so dispatch, the misplaced-subcommand hint and
# future additions stay in lockstep:
#   serve   the tuning-as-a-service session server (docs/SERVING.md)
#   route   the sharded front tier: consistent-hash router over K
#           shard processes (docs/SERVING.md "Sharded front tier")
#   top     live terminal dashboard over a running server/router or a
#           flight-recorder metrics JSONL (docs/OBSERVABILITY.md)
#   report  render a tuning journal into a search-quality report
#   hub     the fleet-telemetry collector --telemetry ships to
#   store   the cooperative results-store server tuning processes
#           join with --store tcp://HOST:PORT (docs/STORE.md)
SUBCOMMANDS = {
    "serve": ("uptune_tpu.serve.cli", "main"),
    "route": ("uptune_tpu.serve.router", "main"),
    "top": ("uptune_tpu.obs.top", "main"),
    "report": ("uptune_tpu.obs.report", "main"),
    "hub": ("uptune_tpu.obs.hub", "main"),
    "store": ("uptune_tpu.store.server", "main"),
}


def main(argv: Optional[List[str]] = None) -> int:
    raw = list(argv if argv is not None else sys.argv[1:])
    if raw and raw[0] in SUBCOMMANDS:
        import importlib
        mod_name, attr = SUBCOMMANDS[raw[0]]
        sub_main = getattr(importlib.import_module(mod_name), attr)
        return sub_main(raw[1:])
    first_pos = next((a for a in raw if not a.startswith("-")), None) \
        if raw and raw[0].startswith("-") else None
    if first_pos in SUBCOMMANDS:
        # `ut -v serve` / `ut -v top` fall through and try to TUNE a
        # program file literally named like the subcommand.  A hint
        # only — never abort: the word may legitimately be a flag
        # VALUE (arity is the parser's business), and the tuning
        # parser's own error follows if it really was a misplaced
        # subcommand
        print(f"[ut] hint: the {first_pos!r} subcommand must come "
              f"first: ut {first_pos} [flags]", file=sys.stderr)
    args = build_parser().parse_args(argv)
    _configure_logging(args.verbose)
    log = logging.getLogger("uptune_tpu")
    if args.num_hosts is not None and args.num_hosts > 1 \
            and "UT_PROCESS_ID" not in os.environ:
        return _launch_hosts(args.num_hosts, argv, args.work_dir)
    if args.device == "cpu":
        # the proposal engine is cheap next to black-box evals; default
        # to the (hang-proof) host platform unless --device accel
        from .utils.platform_guard import force_cpu
        force_cpu(1)

    if args.list_techniques:
        from .techniques.base import all_technique_names, is_experimental
        for name in all_technique_names():
            # [experimental] = measured BEHIND the default portfolio on
            # the reference fixtures (AB_PORTFOLIO.md) — selectable,
            # not recommended
            print(f"{name}  [experimental]" if is_experimental(name)
                  else name)
        return 0
    if not args.script:
        print("ut: a script to tune is required", file=sys.stderr)
        return 2

    script = os.path.abspath(args.script)
    if not os.path.isfile(script):
        print(f"ut: no such file {script}", file=sys.stderr)
        return 2
    work_dir = os.path.abspath(args.work_dir or os.path.dirname(script)
                               or os.getcwd())

    if args.apply_best:
        from .exec.measure import call_program
        env = dict(os.environ)
        env.update({"BEST": "True", "UPTUNE": "True",
                    "UT_WORK_DIR": work_dir})
        res = call_program([sys.executable, script] + args.script_args,
                           env=env, cwd=work_dir, capture=False)
        return res["returncode"]

    from .api.session import settings
    from .exec.controller import ProgramTuner
    from .exec.template import detect_template

    template = None
    if script.endswith((".py", ".tpl")):
        try:
            template = detect_template(script)
        except ValueError as e:
            print(f"ut: {e}", file=sys.stderr)
            return 2

    technique = args.technique
    if technique is not None and len(technique) == 1:
        technique = technique[0]
    if args.generate_bandit_technique is not None:
        if technique is not None:
            print("ut: --generate-bandit-technique conflicts with "
                  "--technique; pass one or the other", file=sys.stderr)
            return 2
        from .techniques.banditmutation import generate_bandit_technique
        technique = generate_bandit_technique(
            args.generate_bandit_technique)

    # the flag is this layer's override; when absent, ProgramTuner
    # itself falls back to the ut.config 'learning-model' setting (the
    # same flags > settings > defaults layering as its sibling params)
    models = args.learning_models
    surrogate = models[0] if models else None
    if models and len(models) > 1:
        log.warning("[ut] only one surrogate runs per tuner; using "
                    "%r and ignoring %r (the mlp kind is itself an "
                    "ensemble)", surrogate, models[1:])

    if args.surrogate_arbitration == "bandit-small-budget":
        from .calibrated import BUDGET_CONSTRAINED_OPTS
        sopts = dict(BUDGET_CONSTRAINED_OPTS)
    elif args.surrogate_arbitration:
        sopts = {"arbitration": args.surrogate_arbitration}
    else:
        sopts = None
    if args.surrogate_screen:
        try:
            c, k = (int(x) for x in args.surrogate_screen_top.split(","))
        except ValueError:
            print("ut: --surrogate-screen-top must be 'CONT,CAT' "
                  "integers", file=sys.stderr)
            return 2
        sopts = dict(sopts or {})
        sopts["screen"] = {"archives": list(args.surrogate_screen),
                           "top_cont": c, "top_cat": k}
        sopts["screen_mode"] = args.surrogate_screen_mode
    if args.surrogate_flip_bias:
        sopts = dict(sopts or {})
        sopts["flip_bias"] = args.surrogate_flip_bias
    seed_cfgs = []
    for path in (args.seed_configuration or []):
        try:
            with open(path) as f:
                loaded = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"ut: --seed-configuration {path}: {e}",
                  file=sys.stderr)
            return 2
        if isinstance(loaded, dict):
            loaded = [loaded]
        if not (isinstance(loaded, list)
                and all(isinstance(c, dict) for c in loaded)):
            print(f"ut: --seed-configuration {path}: expected a JSON "
                  f"object or list of objects", file=sys.stderr)
            return 2
        seed_cfgs.extend(loaded)

    store_dir = args.store_dir
    if args.store == "off":
        store_dir = "off"
    elif args.store == "on" and store_dir is None:
        # force-enable ONLY overrides a disabled config: a store-dir
        # configured via ut.config keeps winning (--store on means
        # "make sure it runs", not "ignore where it runs)"
        cfg_dir = settings["store-dir"]
        if cfg_dir is None or (isinstance(cfg_dir, str)
                               and cfg_dir.lower() in ("off", "none")):
            store_dir = "default"   # ut.temp/store under the work dir
    elif args.store is not None and args.store != "on":
        # tcp://HOST:PORT joins a cooperative store server; the addr
        # IS the store base (wins over any directory — a process
        # cannot be in two stores)
        if not args.store.startswith("tcp://"):
            print(f"ut: --store must be on, off or tcp://HOST:PORT, "
                  f"got {args.store!r}", file=sys.stderr)
            return 2
        from .store.remote import parse_addr
        try:
            parse_addr(args.store)
        except ValueError as e:
            print(f"ut: {e}", file=sys.stderr)
            return 2
        store_dir = args.store
    pt = ProgramTuner(
        [sys.executable, script] + args.script_args, work_dir,
        parallel=args.parallel_factor, test_limit=args.test_limit,
        runtime_limit=args.runtime_limit, timeout=args.timeout,
        technique=technique, seed=args.seed, params_file=args.params,
        resume=args.resume, sandbox=not args.no_sandbox,
        surrogate=surrogate, surrogate_opts=sopts,
        surrogate_async=args.surrogate_async, template=template,
        seed_configs=seed_cfgs, prefetch=args.prefetch,
        compile_cache_dir=args.compile_cache_dir,
        store_dir=store_dir, warm_start=args.warm_start,
        federate=(None if args.federate is None
                  else args.federate == "on"),
        exchange_interval=args.exchange_interval)

    if args.cfg:
        for k in sorted(settings):
            print(f"  {k} = {settings[k]}")

    params = pt.analyze()
    if args.print_params:
        print(json.dumps(params, indent=1))
        return 0
    if args.print_search_space_size:
        import math
        from .exec.space_io import stage_spaces
        for s, space in enumerate(stage_spaces(params)):
            size = space.search_space_size()
            print(f"stage {s}: log10(size) = "
                  f"{math.log10(size) if size else 0:.2f}")
        return 0

    # observability plane (docs/OBSERVABILITY.md): flag > UT_TRACE env
    # > ut.config('trace').  Enabled BEFORE the tune so analysis, warm
    # start, and every ticket land on the timeline; exported after.
    from . import obs
    trace_path = args.trace
    if trace_path is None:
        trace_path = obs.maybe_enable_from_env()
        if trace_path is None and not obs.enabled():
            cfg_trace = settings["trace"]
            if cfg_trace and str(cfg_trace).lower() not in ("off",
                                                            "none"):
                trace_path = str(cfg_trace)
    elif trace_path.lower() in ("off", "none"):
        trace_path = None
    pid_env = os.environ.get("UT_PROCESS_ID")
    if trace_path and pid_env and pid_env != "0":
        # --num-hosts replicas each trace their own file (same rule as
        # ut.archive.hN.jsonl: N appenders never share one path)
        root, ext = os.path.splitext(trace_path)
        trace_path = f"{root}.h{pid_env}{ext}"
    if trace_path and not obs.enabled():
        obs.enable()
    if trace_path:
        # graceful telemetry (docs/OBSERVABILITY.md): a ^C'd or
        # SIGTERM'd run still flushes a valid truncated trace + the
        # metrics timeline's tail; the flight recorder turns the
        # end-of-run metrics snapshot into a periodic timeline
        obs.install_exit_flush(trace_path, extra={"process": "ut-driver"})
        mi = (args.metrics_interval if args.metrics_interval is not None
              else 1.0)
        if mi > 0:
            obs.start_flight_recorder(
                trace_path, interval=mi,
                rotate=(args.metrics_rotate
                        if args.metrics_rotate is not None
                        else obs.flight.DEFAULT_ROTATE))

    # fleet telemetry (docs/OBSERVABILITY.md "Fleet telemetry"): flag
    # > UT_TELEMETRY env > ut.config('telemetry').  Started BEFORE the
    # tune so warm-start and every ticket's windows reach the hub;
    # --num-hosts replicas inherit UT_TELEMETRY and suffix their role
    shipper = None
    telemetry = args.telemetry
    if telemetry is None:
        # an env value — INCLUDING 'off' — wins over ut.config, the
        # same layering as serve/cli.py and the journal above
        telemetry = os.environ.get("UT_TELEMETRY", "").strip() or None
        if telemetry is None:
            cfg_t = settings["telemetry"]
            if not obs.ship.disabled_token(cfg_t):
                telemetry = str(cfg_t)
    if obs.ship.disabled_token(telemetry):
        telemetry = None
    if telemetry:
        role = ("ut-driver" if not pid_env or pid_env == "0"
                else f"ut-driver.h{pid_env}")
        shipper = obs.ship.start(telemetry, role=role)
        # telemetry without trace/journal must still hook
        # SIGINT/SIGTERM: the exit flush's ship.stop() is what ships
        # the final=true terminal window when a supervisor kills the
        # run (idempotent when --trace already installed it)
        obs.install_exit_flush(None)

    # device-plane profiler capture (ISSUE 13): flag > UT_DEVICE_TRACE
    # env; independent of --trace (the XPlane dump stands alone in
    # Perfetto), but a traced run's export references the dump dir
    dtrace = args.device_trace
    if dtrace is None:
        dtrace = obs.device.maybe_trace_from_env()
    elif dtrace.lower() in ("off", "none"):
        dtrace = None
    else:
        dtrace = obs.device.start_trace(dtrace)

    # tuning journal (docs/OBSERVABILITY.md "Search-quality
    # telemetry"): flag > UT_JOURNAL env > ut.config('journal').
    # Resolved BEFORE starting so --num-hosts replicas suffix their
    # path first (same .hN rule as the trace/archive files)
    journal_path = args.journal
    if journal_path is None:
        journal_path = os.environ.get("UT_JOURNAL", "").strip() or None
        if journal_path is None:
            cfg_j = settings["journal"]
            if cfg_j:
                journal_path = str(cfg_j)
    if journal_path and obs.journal.disabled_token(journal_path):
        journal_path = None
    if journal_path and pid_env and pid_env != "0":
        root, ext = os.path.splitext(journal_path)
        journal_path = f"{root}.h{pid_env}{ext}"
    jmon = None
    if journal_path:
        jmon = obs.start_journal(
            journal_path,
            meta={"process": "ut-driver",
                  "script": os.path.basename(script)})
        if not trace_path:
            # journal without trace: the graceful SIGINT/SIGTERM
            # flush must still cover the journal's buffered tail
            obs.install_exit_flush(None)

    from .analysis.trace_guard import guard_from_env
    from .exec.multistage import run_auto
    # UT_TRACE_GUARD=1|strict: count per-function jit traces over the
    # whole tune (docs/LINT.md) — the proposal plane must compile once
    # per technique, not once per step
    try:
        with guard_from_env() as guard:
            res = run_auto(pt)   # single / multi-stage / decouple
    finally:
        if dtrace:
            # settle the XPlane dump BEFORE the trace export so the
            # referenced profile is complete when the document is
            # written — including on a raising run (the obs exit
            # flush also stops a still-active capture on SIGINT/
            # SIGTERM paths that bypass this finally)
            obs.device.stop_trace()
            log.info("[ut] device profile captured under %s (open "
                     "the xplane.pb in Perfetto next to the --trace "
                     "export)", dtrace)
    if journal_path:
        # settle the journal BEFORE the trace export: detaching
        # finalizes the quality gauges into the metrics registry, so
        # the flight recorder's final row (written by obs.finish)
        # carries the run's terminal search.* values even when the
        # run was shorter than the publication cadence
        for alert in (jmon.alerts if jmon is not None else []):
            log.warning("[ut] search alert: %s", json.dumps(alert))
        obs.stop_journal(jmon)
        log.info("[ut] journal written to %s (render with "
                 "`ut report %s`)", journal_path, journal_path)
    if obs.enabled():
        # the trace-guard retrace report ships INSIDE the obs export
        # (and every individual trace is already an instant event on
        # the timeline) instead of as a separate stderr report
        extra = {"process": "ut-driver"}
        if guard.enabled:
            extra["trace_guard"] = guard.report()
        if trace_path:
            obs.finish(trace_path, extra=extra)
            log.info("[ut] trace written to %s (open in "
                     "https://ui.perfetto.dev; metrics in %s)",
                     trace_path, trace_path + ".metrics.jsonl")
        elif guard.enabled:
            # recording without an output path (UT_TRACE=1): there is
            # no trace document for the report to ride in, so keep the
            # stderr line
            log.info("[ut] trace-guard: %s", json.dumps(guard.report()))
        for line in obs.text_summary().splitlines():
            log.info("[ut] %s", line)
    elif guard.enabled:
        log.info("[ut] trace-guard: %s", json.dumps(guard.report()))
    if shipper is not None:
        # final window + drain: the hub's last row for this source
        # carries the run's terminal counters (the exactness contract
        # BENCH_FLEET asserts against the flight-recorder finals)
        shipper.stop()
        st = shipper.stats()
        log.info("[ut] telemetry shipped to %s:%s (%d rows acked, "
                 "%d dropped)", shipper.addr[0], shipper.addr[1],
                 st["acked"], st["dropped"])
    log.info("[ut] done: best qor=%.6g evals=%d", res.best_qor, res.evals)
    print(json.dumps({"best_config": res.best_config,
                      "best_qor": res.best_qor, "evals": res.evals}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
