"""Pallas TPU kernel: fused Matérn-5/2 cross-kernel + posterior-mean
scoring.

Scoring a candidate batch against GP history is the acquisition
hot path at north-star batch sizes: mu = K(xq, X) @ alpha needs the
[B, N] cross-kernel, which at B=10^5 candidates x N=1024 history rows
is a ~400 MB HBM intermediate if materialized (the pure-XLA
`gp.predict` path builds it).  This kernel tiles the candidate axis:
each grid step computes one [T, N] kernel tile in VMEM — distances via
an MXU dot using the |a-b|^2 = |a|^2+|b|^2-2ab^T identity, Matérn
transform on the VPU — contracts it with alpha immediately, and writes
only the [T] mean scores.  Nothing of size B x N ever touches HBM.

Live call sites (r4 verdict next-step #2): `SurrogateManager`'s
proposal-pool scoring routes here whenever the pool reaches
`PALLAS_MIN_POOL` candidates (surrogate/manager.py _build_pool_fn), and
`parallel/surrogate_shard.py` routes each device's shard here in the
same regime.  `interpret=True` keeps every path testable on the CPU
mesh.

The VARIANCE path tiles too, despite the triangular solve in
`gp.predict`: with K^-1 precomputed once per call (one cho_solve
against I, O(N^3) but B-independent and N <= max_points) the predictive
variance is 1 + noise - rowsum((k @ K^-1) * k) — two MXU matmuls per
tile, nothing of size B x N in HBM.  Padding is folded in by masking
K^-1 rows/cols (the mask-adjusted K is block-diagonal, so the masked
quadratic form equals the unpadded one exactly).  That makes EI and
LCB — not just the mean — exact in the fused regime.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

LANES = 256         # output row width (multiple of 128)
ROWS = 8            # output rows per grid step (sublane minimum)
TILE = LANES * ROWS  # candidate rows per grid step (2048)

# mean+variance tiles are smaller: each grid step holds TWO [T, N]
# intermediates (k and k @ K^-1) plus the [N, N] K^-1 in VMEM
VLANES = 128
VTILE = VLANES * ROWS  # 1024

# pool size at which the manager/shard layers switch from plain-XLA
# gp.predict to this kernel (below it the [B, N] intermediate is small
# enough that XLA's fusion wins on dispatch overhead)
PALLAS_MIN_POOL = 4096


def _tile_d2(a, b):
    d2 = ((a * a).sum(axis=1, keepdims=True)
          + (b * b).sum(axis=1)[None, :]
          - 2.0 * jnp.dot(a, b.T, preferred_element_type=jnp.float32))
    return jnp.maximum(d2, 0.0)


def _matern_tile(d2):
    d = jnp.sqrt(d2 + 1e-12)
    s5d = math.sqrt(5.0) * d
    return (1.0 + s5d + (5.0 / 3.0) * d2) * jnp.exp(-s5d)


def _score_kernel(xq_ref, x_ref, alpha_ref, out_ref):
    """One tile: out[T] = matern52(xq_tile, X) @ alpha.

    Padded history rows need no masking here: the mean contracts with
    alpha, and the caller zeroes alpha on padded rows."""
    a = xq_ref[:]                        # [T, F]  (pre-scaled by 1/ls)
    b = x_ref[:]                         # [N, F]
    k = _matern_tile(_tile_d2(a, b))     # [T, N]
    out_ref[:] = (k @ alpha_ref[:]).reshape(ROWS, LANES)


def _score_kernel_mixed(xq_c_ref, xq_k_ref, x_c_ref, x_k_ref, alpha_ref,
                        out_ref):
    """Mixed-kernel tile: Matérn over the continuous block × an
    exponential-Hamming factor over the categorical one-hot block (the
    gp.py product kernel).  Both raw-distance tiles ride the MXU; the
    caller pre-scales the cont block by 1/ls and the cat block by
    sqrt(1/(n_cat·ls_cat)), so here k = matern(d2c) · exp(-d2k)."""
    k = _matern_tile(_tile_d2(xq_c_ref[:], x_c_ref[:]))
    k = k * jnp.exp(-_tile_d2(xq_k_ref[:], x_k_ref[:]))
    out_ref[:] = (k @ alpha_ref[:]).reshape(ROWS, LANES)


def _score_kernel_expham(xq_k_ref, x_k_ref, alpha_ref, out_ref):
    """Pure exponential-Hamming tile for ALL-categorical spaces
    (n_cont == 0): a zero-width continuous BlockSpec would not lower
    through Mosaic, so the Matérn factor — identically 1 there — is
    omitted instead."""
    k = jnp.exp(-_tile_d2(xq_k_ref[:], x_k_ref[:]))
    out_ref[:] = (k @ alpha_ref[:]).reshape(ROWS, LANES)


def _mu_q_tiles(k, alpha_ref, kinv_ref, mu_ref, q_ref):
    """Shared tail of every mean+variance kernel: contract one [T, N]
    kernel tile with alpha (mean) and with the premasked K^-1
    (variance quadratic term q = diag(k K^-1 k^T))."""
    mu_ref[:] = (k @ alpha_ref[:]).reshape(ROWS, VLANES)
    w = jnp.dot(k, kinv_ref[:], preferred_element_type=jnp.float32)
    q_ref[:] = (w * k).sum(axis=1).reshape(ROWS, VLANES)


def _var_kernel(xq_ref, x_ref, alpha_ref, kinv_ref, mu_ref, q_ref):
    k = _matern_tile(_tile_d2(xq_ref[:], x_ref[:]))
    _mu_q_tiles(k, alpha_ref, kinv_ref, mu_ref, q_ref)


def _var_kernel_mixed(xq_c_ref, xq_k_ref, x_c_ref, x_k_ref, alpha_ref,
                      kinv_ref, mu_ref, q_ref):
    k = _matern_tile(_tile_d2(xq_c_ref[:], x_c_ref[:]))
    k = k * jnp.exp(-_tile_d2(xq_k_ref[:], x_k_ref[:]))
    _mu_q_tiles(k, alpha_ref, kinv_ref, mu_ref, q_ref)


def _var_kernel_expham(xq_k_ref, x_k_ref, alpha_ref, kinv_ref, mu_ref,
                       q_ref):
    k = jnp.exp(-_tile_d2(xq_k_ref[:], x_k_ref[:]))
    _mu_q_tiles(k, alpha_ref, kinv_ref, mu_ref, q_ref)


def _pl_setup():
    from jax.experimental import pallas as pl
    try:
        from jax.experimental.pallas import tpu as pltpu
        vmem = pltpu.VMEM
    except ImportError:  # pragma: no cover
        vmem = None

    def spec(shape, index_map=None):
        kw = {"memory_space": vmem} if vmem is not None else {}
        return pl.BlockSpec(shape, index_map, **kw)

    return pl, spec


@functools.partial(jax.jit, static_argnames=("interpret",))
def _mean_scores_padded(xq_scaled, x_scaled, alpha, interpret: bool):
    pl, spec = _pl_setup()
    B, F = xq_scaled.shape
    N = x_scaled.shape[0]

    # 2D [B/LANES, LANES] output in (ROWS, LANES) blocks: 1D f32 outputs
    # trip a Mosaic/XLA tile-layout mismatch (observed: XLA {0:T(1024)}
    # vs Mosaic {0:T(256)}) and sublane blocks must be multiples of 8
    out = pl.pallas_call(
        _score_kernel,
        out_shape=jax.ShapeDtypeStruct((B // LANES, LANES), jnp.float32),
        grid=(B // TILE,),
        in_specs=[
            spec((TILE, F), lambda i: (i, 0)),
            spec((N, F), lambda i: (0, 0)),
            spec((N,), lambda i: (0,)),
        ],
        out_specs=spec((ROWS, LANES), lambda i: (i, 0)),
        interpret=interpret,
    )(xq_scaled, x_scaled, alpha)
    return out.reshape(B)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _mean_scores_padded_expham(xq_k, x_k, alpha, interpret: bool):
    pl, spec = _pl_setup()
    B, Fk = xq_k.shape
    N = x_k.shape[0]
    out = pl.pallas_call(
        _score_kernel_expham,
        out_shape=jax.ShapeDtypeStruct((B // LANES, LANES), jnp.float32),
        grid=(B // TILE,),
        in_specs=[
            spec((TILE, Fk), lambda i: (i, 0)),
            spec((N, Fk), lambda i: (0, 0)),
            spec((N,), lambda i: (0,)),
        ],
        out_specs=spec((ROWS, LANES), lambda i: (i, 0)),
        interpret=interpret,
    )(xq_k, x_k, alpha)
    return out.reshape(B)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _mean_scores_padded_mixed(xq_c, xq_k, x_c, x_k, alpha,
                              interpret: bool):
    pl, spec = _pl_setup()
    B, Fc = xq_c.shape
    Fk = xq_k.shape[1]
    N = x_c.shape[0]
    out = pl.pallas_call(
        _score_kernel_mixed,
        out_shape=jax.ShapeDtypeStruct((B // LANES, LANES), jnp.float32),
        grid=(B // TILE,),
        in_specs=[
            spec((TILE, Fc), lambda i: (i, 0)),
            spec((TILE, Fk), lambda i: (i, 0)),
            spec((N, Fc), lambda i: (0, 0)),
            spec((N, Fk), lambda i: (0, 0)),
            spec((N,), lambda i: (0,)),
        ],
        out_specs=spec((ROWS, LANES), lambda i: (i, 0)),
        interpret=interpret,
    )(xq_c, xq_k, x_c, x_k, alpha)
    return out.reshape(B)


def _var_out(B):
    s = jax.ShapeDtypeStruct((B // VLANES, VLANES), jnp.float32)
    return (s, s)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _mean_var_padded(xq_scaled, x_scaled, alpha, kinv, interpret: bool):
    pl, spec = _pl_setup()
    B, F = xq_scaled.shape
    N = x_scaled.shape[0]
    ospec = spec((ROWS, VLANES), lambda i: (i, 0))
    mu, q = pl.pallas_call(
        _var_kernel,
        out_shape=_var_out(B),
        grid=(B // VTILE,),
        in_specs=[
            spec((VTILE, F), lambda i: (i, 0)),
            spec((N, F), lambda i: (0, 0)),
            spec((N,), lambda i: (0,)),
            spec((N, N), lambda i: (0, 0)),
        ],
        out_specs=(ospec, ospec),
        interpret=interpret,
    )(xq_scaled, x_scaled, alpha, kinv)
    return mu.reshape(B), q.reshape(B)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _mean_var_padded_expham(xq_k, x_k, alpha, kinv, interpret: bool):
    pl, spec = _pl_setup()
    B, Fk = xq_k.shape
    N = x_k.shape[0]
    ospec = spec((ROWS, VLANES), lambda i: (i, 0))
    mu, q = pl.pallas_call(
        _var_kernel_expham,
        out_shape=_var_out(B),
        grid=(B // VTILE,),
        in_specs=[
            spec((VTILE, Fk), lambda i: (i, 0)),
            spec((N, Fk), lambda i: (0, 0)),
            spec((N,), lambda i: (0,)),
            spec((N, N), lambda i: (0, 0)),
        ],
        out_specs=(ospec, ospec),
        interpret=interpret,
    )(xq_k, x_k, alpha, kinv)
    return mu.reshape(B), q.reshape(B)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _mean_var_padded_mixed(xq_c, xq_k, x_c, x_k, alpha, kinv,
                           interpret: bool):
    pl, spec = _pl_setup()
    B, Fc = xq_c.shape
    Fk = xq_k.shape[1]
    N = x_c.shape[0]
    ospec = spec((ROWS, VLANES), lambda i: (i, 0))
    mu, q = pl.pallas_call(
        _var_kernel_mixed,
        out_shape=_var_out(B),
        grid=(B // VTILE,),
        in_specs=[
            spec((VTILE, Fc), lambda i: (i, 0)),
            spec((VTILE, Fk), lambda i: (i, 0)),
            spec((N, Fc), lambda i: (0, 0)),
            spec((N, Fk), lambda i: (0, 0)),
            spec((N,), lambda i: (0,)),
            spec((N, N), lambda i: (0, 0)),
        ],
        out_specs=(ospec, ospec),
        interpret=interpret,
    )(xq_c, xq_k, x_c, x_k, alpha, kinv)
    return mu.reshape(B), q.reshape(B)


def gp_mean_var_scores(state, xq: jax.Array,
                       interpret: bool = None,
                       n_cont=None, n_cat: int = 0):
    """Posterior (mean [B], std [B]) in original target units, fused —
    numerically equivalent to gp.predict(state, xq, n_cont, n_cat)
    without the [B, N] cross-kernel in HBM (see module docstring for
    the K^-1 quadratic-form tiling).  `n_cont`/`n_cat` MUST match the
    fit, exactly as in gp_mean_scores."""
    if interpret is None:
        from ..ops import routing as _routing
        interpret = _routing.interpret_default()
    B, F = xq.shape
    pad = (-B) % VTILE
    xq32 = jnp.asarray(xq, jnp.float32)
    if pad:
        xq32 = jnp.concatenate([xq32, jnp.zeros((pad, F), jnp.float32)])
    x32 = jnp.asarray(state.x, jnp.float32)
    alpha = jnp.asarray(state.alpha, jnp.float32) * state.mask
    # premasked K^-1 (gp.precompute_kinv rationale): prefer the one
    # attached at fit time — recomputing the O(N^3) solve per scoring
    # call doubles the per-pull cost for nothing (r5 review)
    if state.kinv is not None:
        kinv = jnp.asarray(state.kinv, jnp.float32)
    else:
        from . import gp as _gp
        kinv = jnp.asarray(_gp.precompute_kinv(state).kinv, jnp.float32)
    mixed = n_cont is not None and n_cat and n_cont < F
    if mixed:
        cat_s = jnp.sqrt(1.0 / (float(n_cat) * state.ls_cat))
        if n_cont == 0:
            mu_n, q = _mean_var_padded_expham(
                xq32 * cat_s, x32 * cat_s, alpha, kinv, bool(interpret))
        else:
            mu_n, q = _mean_var_padded_mixed(
                xq32[:, :n_cont] / state.lengthscale,
                xq32[:, n_cont:] * cat_s,
                x32[:, :n_cont] / state.lengthscale,
                x32[:, n_cont:] * cat_s,
                alpha, kinv, bool(interpret))
    else:
        mu_n, q = _mean_var_padded(xq32 / state.lengthscale,
                                   x32 / state.lengthscale,
                                   alpha, kinv, bool(interpret))
    if pad:
        mu_n, q = mu_n[:B], q[:B]
    var = jnp.maximum(1.0 + state.noise - q, 1e-9)
    return (mu_n * state.y_std + state.y_mean,
            jnp.sqrt(var) * state.y_std)


def gp_mean_scores(state, xq: jax.Array,
                   interpret: bool = None,
                   n_cont=None, n_cat: int = 0) -> jax.Array:
    """Posterior mean for a [B, F] query batch against a fitted GPState,
    without materializing the [B, N] cross-kernel in HBM.

    Numerically equivalent to gp.predict(state, xq, n_cont, n_cat)[0];
    `n_cont`/`n_cat` MUST match the fit (a mixed-kernel state scored
    without them would treat one-hot flag lanes as continuous
    coordinates and drop ls_cat).  `interpret` defaults to True off-TPU
    (pallas CPU path) and False on TPU, via the shared routing knob
    (`ops/routing.py` — UT_PALLAS=interpret forces True anywhere)."""
    if interpret is None:
        from ..ops import routing as _routing
        interpret = _routing.interpret_default()
    B, F = xq.shape
    pad = (-B) % TILE
    xq32 = jnp.asarray(xq, jnp.float32)
    if pad:
        xq32 = jnp.concatenate([xq32, jnp.zeros((pad, F), jnp.float32)])
    x32 = jnp.asarray(state.x, jnp.float32)
    alpha = jnp.asarray(state.alpha, jnp.float32) * state.mask
    mixed = n_cont is not None and n_cat and n_cont < F
    if mixed:
        # cont block scaled by 1/ls (Matérn); cat one-hot block scaled
        # by sqrt(1/(n_cat·ls_cat)) so its raw squared distance is
        # already the exponent of the Hamming factor
        cat_s = jnp.sqrt(1.0 / (float(n_cat) * state.ls_cat))
        if n_cont == 0:
            # all-categorical space: a zero-width continuous block
            # cannot lower through Mosaic; the Matérn factor is 1
            mu_n = _mean_scores_padded_expham(
                xq32 * cat_s, x32 * cat_s, alpha, bool(interpret))
        else:
            mu_n = _mean_scores_padded_mixed(
                xq32[:, :n_cont] / state.lengthscale,
                xq32[:, n_cont:] * cat_s,
                x32[:, :n_cont] / state.lengthscale,
                x32[:, n_cont:] * cat_s,
                alpha, bool(interpret))
    else:
        mu_n = _mean_scores_padded(xq32 / state.lengthscale,
                                   x32 / state.lengthscale,
                                   alpha, bool(interpret))
    mu = mu_n[:B] if pad else mu_n
    return mu * state.y_std + state.y_mean
