"""Pallas TPU kernel: fused Matérn-5/2 cross-kernel + posterior-mean
scoring.

Scoring a candidate batch against GP history is the acquisition
hot path at north-star batch sizes: mu = K(xq, X) @ alpha needs the
[B, N] cross-kernel, which at B=10^5 candidates x N=1024 history rows
is a ~400 MB HBM intermediate if materialized (the pure-XLA
`gp.predict` path builds it).  This kernel tiles the candidate axis:
each grid step computes one [T, N] kernel tile in VMEM — distances via
an MXU dot using the |a-b|^2 = |a|^2+|b|^2-2ab^T identity, Matérn
transform on the VPU — contracts it with alpha immediately, and writes
only the [T] mean scores.  Nothing of size B x N ever touches HBM.

Used by SurrogateManager's top-k selection for very large batches;
`interpret=True` keeps it testable on the CPU mesh.  The variance path
stays in XLA (`gp.predict`): it needs a triangular solve against the
Cholesky factor, which does not tile this way.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

LANES = 256         # output row width (multiple of 128)
ROWS = 8            # output rows per grid step (sublane minimum)
TILE = LANES * ROWS  # candidate rows per grid step (2048)


def _score_kernel(xq_ref, x_ref, alpha_ref, out_ref):
    """One tile: out[T] = matern52(xq_tile, X) @ alpha.

    Padded history rows need no masking here: the mean contracts with
    alpha, and the caller zeroes alpha on padded rows."""
    a = xq_ref[:]                        # [T, F]  (pre-scaled by 1/ls)
    b = x_ref[:]                         # [N, F]
    d2 = ((a * a).sum(axis=1, keepdims=True)
          + (b * b).sum(axis=1)[None, :]
          - 2.0 * jnp.dot(a, b.T, preferred_element_type=jnp.float32))
    d2 = jnp.maximum(d2, 0.0)
    d = jnp.sqrt(d2 + 1e-12)
    s5d = math.sqrt(5.0) * d
    k = (1.0 + s5d + (5.0 / 3.0) * d2) * jnp.exp(-s5d)   # [T, N]
    out_ref[:] = (k @ alpha_ref[:]).reshape(ROWS, LANES)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _mean_scores_padded(xq_scaled, x_scaled, alpha, interpret: bool):
    from jax.experimental import pallas as pl
    try:
        from jax.experimental.pallas import tpu as pltpu
        vmem = pltpu.VMEM
    except ImportError:  # pragma: no cover
        vmem = None

    B, F = xq_scaled.shape
    N = x_scaled.shape[0]
    grid = (B // TILE,)

    def spec(shape, index_map=None):
        kw = {"memory_space": vmem} if vmem is not None else {}
        return pl.BlockSpec(shape, index_map, **kw)

    # 2D [B/LANES, LANES] output in (ROWS, LANES) blocks: 1D f32 outputs
    # trip a Mosaic/XLA tile-layout mismatch (observed: XLA {0:T(1024)}
    # vs Mosaic {0:T(256)}) and sublane blocks must be multiples of 8
    out = pl.pallas_call(
        _score_kernel,
        out_shape=jax.ShapeDtypeStruct((B // LANES, LANES), jnp.float32),
        grid=grid,
        in_specs=[
            spec((TILE, F), lambda i: (i, 0)),
            spec((N, F), lambda i: (0, 0)),
            spec((N,), lambda i: (0,)),
        ],
        out_specs=spec((ROWS, LANES), lambda i: (i, 0)),
        interpret=interpret,
    )(xq_scaled, x_scaled, alpha)
    return out.reshape(B)


def gp_mean_scores(state, xq: jax.Array,
                   interpret: bool = None) -> jax.Array:
    """Posterior mean for a [B, F] query batch against a fitted GPState,
    without materializing the [B, N] cross-kernel in HBM.

    Numerically equivalent to gp.predict(state, xq)[0]; `interpret`
    defaults to True off-TPU (pallas CPU path) and False on TPU."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, F = xq.shape
    pad = (-B) % TILE
    xq_scaled = (jnp.asarray(xq, jnp.float32) / state.lengthscale)
    if pad:
        xq_scaled = jnp.concatenate(
            [xq_scaled, jnp.zeros((pad, F), jnp.float32)])
    x_scaled = jnp.asarray(state.x, jnp.float32) / state.lengthscale
    alpha = jnp.asarray(state.alpha, jnp.float32) * state.mask
    mu_n = _mean_scores_padded(xq_scaled, x_scaled, alpha,
                               bool(interpret))
    mu = mu_n[:B] if pad else mu_n
    return mu * state.y_std + state.y_mean
