"""Pallas TPU kernel: fused Matérn-5/2 cross-kernel + posterior-mean
scoring.

Scoring a candidate batch against GP history is the acquisition
hot path at north-star batch sizes: mu = K(xq, X) @ alpha needs the
[B, N] cross-kernel, which at B=10^5 candidates x N=1024 history rows
is a ~400 MB HBM intermediate if materialized (the pure-XLA
`gp.predict` path builds it).  This kernel tiles the candidate axis:
each grid step computes one [T, N] kernel tile in VMEM — distances via
an MXU dot using the |a-b|^2 = |a|^2+|b|^2-2ab^T identity, Matérn
transform on the VPU — contracts it with alpha immediately, and writes
only the [T] mean scores.  Nothing of size B x N ever touches HBM.

Used by SurrogateManager's top-k selection for very large batches;
`interpret=True` keeps it testable on the CPU mesh.  The variance path
stays in XLA (`gp.predict`): it needs a triangular solve against the
Cholesky factor, which does not tile this way.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

LANES = 256         # output row width (multiple of 128)
ROWS = 8            # output rows per grid step (sublane minimum)
TILE = LANES * ROWS  # candidate rows per grid step (2048)


def _tile_d2(a, b):
    d2 = ((a * a).sum(axis=1, keepdims=True)
          + (b * b).sum(axis=1)[None, :]
          - 2.0 * jnp.dot(a, b.T, preferred_element_type=jnp.float32))
    return jnp.maximum(d2, 0.0)


def _matern_tile(d2):
    d = jnp.sqrt(d2 + 1e-12)
    s5d = math.sqrt(5.0) * d
    return (1.0 + s5d + (5.0 / 3.0) * d2) * jnp.exp(-s5d)


def _score_kernel(xq_ref, x_ref, alpha_ref, out_ref):
    """One tile: out[T] = matern52(xq_tile, X) @ alpha.

    Padded history rows need no masking here: the mean contracts with
    alpha, and the caller zeroes alpha on padded rows."""
    a = xq_ref[:]                        # [T, F]  (pre-scaled by 1/ls)
    b = x_ref[:]                         # [N, F]
    k = _matern_tile(_tile_d2(a, b))     # [T, N]
    out_ref[:] = (k @ alpha_ref[:]).reshape(ROWS, LANES)


def _score_kernel_mixed(xq_c_ref, xq_k_ref, x_c_ref, x_k_ref, alpha_ref,
                        out_ref):
    """Mixed-kernel tile: Matérn over the continuous block × an
    exponential-Hamming factor over the categorical one-hot block (the
    gp.py product kernel).  Both raw-distance tiles ride the MXU; the
    caller pre-scales the cont block by 1/ls and the cat block by
    sqrt(1/(n_cat·ls_cat)), so here k = matern(d2c) · exp(-d2k)."""
    k = _matern_tile(_tile_d2(xq_c_ref[:], x_c_ref[:]))
    k = k * jnp.exp(-_tile_d2(xq_k_ref[:], x_k_ref[:]))
    out_ref[:] = (k @ alpha_ref[:]).reshape(ROWS, LANES)


def _score_kernel_expham(xq_k_ref, x_k_ref, alpha_ref, out_ref):
    """Pure exponential-Hamming tile for ALL-categorical spaces
    (n_cont == 0): a zero-width continuous BlockSpec would not lower
    through Mosaic, so the Matérn factor — identically 1 there — is
    omitted instead."""
    k = jnp.exp(-_tile_d2(xq_k_ref[:], x_k_ref[:]))
    out_ref[:] = (k @ alpha_ref[:]).reshape(ROWS, LANES)


def _pl_setup():
    from jax.experimental import pallas as pl
    try:
        from jax.experimental.pallas import tpu as pltpu
        vmem = pltpu.VMEM
    except ImportError:  # pragma: no cover
        vmem = None

    def spec(shape, index_map=None):
        kw = {"memory_space": vmem} if vmem is not None else {}
        return pl.BlockSpec(shape, index_map, **kw)

    return pl, spec


@functools.partial(jax.jit, static_argnames=("interpret",))
def _mean_scores_padded(xq_scaled, x_scaled, alpha, interpret: bool):
    pl, spec = _pl_setup()
    B, F = xq_scaled.shape
    N = x_scaled.shape[0]

    # 2D [B/LANES, LANES] output in (ROWS, LANES) blocks: 1D f32 outputs
    # trip a Mosaic/XLA tile-layout mismatch (observed: XLA {0:T(1024)}
    # vs Mosaic {0:T(256)}) and sublane blocks must be multiples of 8
    out = pl.pallas_call(
        _score_kernel,
        out_shape=jax.ShapeDtypeStruct((B // LANES, LANES), jnp.float32),
        grid=(B // TILE,),
        in_specs=[
            spec((TILE, F), lambda i: (i, 0)),
            spec((N, F), lambda i: (0, 0)),
            spec((N,), lambda i: (0,)),
        ],
        out_specs=spec((ROWS, LANES), lambda i: (i, 0)),
        interpret=interpret,
    )(xq_scaled, x_scaled, alpha)
    return out.reshape(B)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _mean_scores_padded_expham(xq_k, x_k, alpha, interpret: bool):
    pl, spec = _pl_setup()
    B, Fk = xq_k.shape
    N = x_k.shape[0]
    out = pl.pallas_call(
        _score_kernel_expham,
        out_shape=jax.ShapeDtypeStruct((B // LANES, LANES), jnp.float32),
        grid=(B // TILE,),
        in_specs=[
            spec((TILE, Fk), lambda i: (i, 0)),
            spec((N, Fk), lambda i: (0, 0)),
            spec((N,), lambda i: (0,)),
        ],
        out_specs=spec((ROWS, LANES), lambda i: (i, 0)),
        interpret=interpret,
    )(xq_k, x_k, alpha)
    return out.reshape(B)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _mean_scores_padded_mixed(xq_c, xq_k, x_c, x_k, alpha,
                              interpret: bool):
    pl, spec = _pl_setup()
    B, Fc = xq_c.shape
    Fk = xq_k.shape[1]
    N = x_c.shape[0]
    out = pl.pallas_call(
        _score_kernel_mixed,
        out_shape=jax.ShapeDtypeStruct((B // LANES, LANES), jnp.float32),
        grid=(B // TILE,),
        in_specs=[
            spec((TILE, Fc), lambda i: (i, 0)),
            spec((TILE, Fk), lambda i: (i, 0)),
            spec((N, Fc), lambda i: (0, 0)),
            spec((N, Fk), lambda i: (0, 0)),
            spec((N,), lambda i: (0,)),
        ],
        out_specs=spec((ROWS, LANES), lambda i: (i, 0)),
        interpret=interpret,
    )(xq_c, xq_k, x_c, x_k, alpha)
    return out.reshape(B)


def gp_mean_scores(state, xq: jax.Array,
                   interpret: bool = None,
                   n_cont=None, n_cat: int = 0) -> jax.Array:
    """Posterior mean for a [B, F] query batch against a fitted GPState,
    without materializing the [B, N] cross-kernel in HBM.

    Numerically equivalent to gp.predict(state, xq, n_cont, n_cat)[0];
    `n_cont`/`n_cat` MUST match the fit (a mixed-kernel state scored
    without them would treat one-hot flag lanes as continuous
    coordinates and drop ls_cat).  `interpret` defaults to True off-TPU
    (pallas CPU path) and False on TPU."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, F = xq.shape
    pad = (-B) % TILE
    xq32 = jnp.asarray(xq, jnp.float32)
    if pad:
        xq32 = jnp.concatenate([xq32, jnp.zeros((pad, F), jnp.float32)])
    x32 = jnp.asarray(state.x, jnp.float32)
    alpha = jnp.asarray(state.alpha, jnp.float32) * state.mask
    mixed = n_cont is not None and n_cat and n_cont < F
    if mixed:
        # cont block scaled by 1/ls (Matérn); cat one-hot block scaled
        # by sqrt(1/(n_cat·ls_cat)) so its raw squared distance is
        # already the exponent of the Hamming factor
        cat_s = jnp.sqrt(1.0 / (float(n_cat) * state.ls_cat))
        if n_cont == 0:
            # all-categorical space: a zero-width continuous block
            # cannot lower through Mosaic; the Matérn factor is 1
            mu_n = _mean_scores_padded_expham(
                xq32 * cat_s, x32 * cat_s, alpha, bool(interpret))
        else:
            mu_n = _mean_scores_padded_mixed(
                xq32[:, :n_cont] / state.lengthscale,
                xq32[:, n_cont:] * cat_s,
                x32[:, :n_cont] / state.lengthscale,
                x32[:, n_cont:] * cat_s,
                alpha, bool(interpret))
    else:
        mu_n = _mean_scores_padded(xq32 / state.lengthscale,
                                   x32 / state.lengthscale,
                                   alpha, bool(interpret))
    mu = mu_n[:B] if pad else mu_n
    return mu * state.y_std + state.y_mean
