"""Cross-payload feature screening for budget-constrained surrogates.

The r4 gcc-real diagnosis (BENCHREPORT.md "Why the surrogate does not
beat the bandit on gcc-real"): at <=80 observations over ~1,100 one-hot
lanes the GP's marginal-likelihood hyperparameter fit stays
prior-dominated — every lengthscale grid point explains the data about
equally well, so the posterior mean barely ranks candidates.  The fix
measured here (r4 verdict next-step #3) is SUPERVISED SCREENING: rank
feature lanes by their observed effect on QoR in archives from OTHER
payloads over the SAME space (the per-flag sensitivity transfer — gcc
flags that never move runtime on three payloads rarely move it on a
fourth), and restrict the SURROGATE — not the search techniques — to
the top-k lanes.  The bandit arms keep proposing in the full space;
only the model's view narrows, which is exactly the regime split the
budget rule already encodes.

The reference has no analogue: its XGBoost surrogate
(/root/reference/python/uptune/plugins/xgbregressor.py:9-84) relies on
tree splits to ignore dead features, which needs far more rows than an
80-eval budget provides; archives were only replayed for resume
(api.py:328-363), never mined across workloads.

Representation contract (Space.surrogate_transform, space/spec.py):
`[cont block: numeric lanes + perm position lanes | cat block: n_cat
one-hot groups x cat_max_codes]`.  A screen keeps whole groups — a flag
is either visible to the GP (all its code columns) or not — so the
screened layout is again `[cont' | cat']` and the mixed
Matérn x exponential-Hamming kernel applies unchanged with
`n_cont=screen.n_cont, n_cat=screen.n_cat`.
"""
from __future__ import annotations

import json
import os
from typing import List, NamedTuple, Optional, Sequence, Tuple

import numpy as np


class FeatureScreen(NamedTuple):
    """A static restriction of the surrogate feature representation.

    idx        : [K] int lane indices into the FULL surrogate rep
                 (cont lanes first, then whole one-hot groups, both in
                 their original order — the kernel split survives).
    n_cont     : width of the kept continuous block.
    n_cat      : number of kept categorical groups.
    cat_weight : [n_scalar] float lane weights over SCALAR lanes
                 (categorical lanes carry their group sensitivity,
                 numeric + dropped lanes 0) — the proposal plane uses
                 it to bias flip moves toward flags that measurably
                 moved QoR on the source payloads.
    scores     : [n_full] per-lane sensitivity over the full rep
                 (introspection / ut-stats).
    lane_weight: [n_full] float in [floor, 1] — the SOFT alternative to
                 hard restriction: scaling the surrogate features by
                 this vector is per-lane ARD (a high-sensitivity lane
                 keeps its resolution, a dead lane's distances shrink
                 toward zero instead of being cut).  Used when the
                 manager runs with screen_mode='soft'.
    """
    idx: np.ndarray
    n_cont: int
    n_cat: int
    cat_weight: np.ndarray
    scores: np.ndarray
    lane_weight: np.ndarray

    def apply(self, feats):
        """Project [B, n_full] surrogate features onto the kept lanes.
        Works on numpy and jax arrays (fancy-index on the last axis)."""
        return feats[..., self.idx]


def lane_sensitivity(feats: np.ndarray, qor: np.ndarray) -> np.ndarray:
    """[N, F] surrogate features x [N] QoR -> [F] |Pearson r| per lane.

    Non-finite QoR rows (failed builds) are dropped — they carry
    "crashed" signal, not magnitude.  Zero-variance lanes score 0.
    """
    feats = np.asarray(feats, np.float64)
    qor = np.asarray(qor, np.float64).reshape(-1)
    ok = np.isfinite(qor)
    feats, qor = feats[ok], qor[ok]
    if len(qor) < 4:
        return np.zeros(feats.shape[1])
    fc = feats - feats.mean(axis=0)
    yc = qor - qor.mean()
    fs = np.sqrt((fc * fc).sum(axis=0))
    ys = np.sqrt((yc * yc).sum())
    denom = fs * ys
    with np.errstate(invalid="ignore", divide="ignore"):
        r = np.where(denom > 0, (fc * yc[:, None]).sum(axis=0) / denom,
                     0.0)
    return np.abs(np.nan_to_num(r))


def build_screen(space, sources: Sequence[Tuple[np.ndarray, np.ndarray]],
                 top_cont: int = 16, top_cat: int = 24) -> FeatureScreen:
    """Aggregate per-lane sensitivity over `sources` (list of
    (surrogate_feats [N,F], qor [N]) pairs — one per source payload) and
    keep the `top_cont` continuous lanes + `top_cat` categorical groups.

    Aggregation is the MEAN of per-source |Pearson r| — correlation is
    scale-free, so payloads with different absolute runtimes contribute
    equally; a lane must move QoR consistently across payloads to rank.
    """
    n_full = space.n_surrogate_features
    n_cont = space.n_cont_features
    per = [lane_sensitivity(f, q) for f, q in sources]
    if not per:
        raise ValueError("build_screen needs at least one source")
    scores = np.mean(np.stack(per), axis=0)
    assert scores.shape[0] == n_full, (scores.shape, n_full)

    # continuous block: straight top-k lanes (order preserved)
    kc = min(max(1, int(top_cont)), n_cont) if n_cont else 0
    cont_rank = np.argsort(-scores[:n_cont])[:kc] if n_cont else []
    cont_keep = np.sort(np.asarray(cont_rank, int))

    # categorical block: score per GROUP = max over its code columns
    # (a flag whose "off" column correlates is as real as one whose
    # "on" column does); keep whole groups
    ncat, width = space.n_cat, space.cat_max_codes
    if ncat:
        gs = scores[n_cont:].reshape(ncat, width).max(axis=1)
        kg = min(max(1, int(top_cat)), ncat)
        grp_keep = np.sort(np.argsort(-gs)[:kg])
        cat_idx = (n_cont + (grp_keep[:, None] * width
                             + np.arange(width)[None, :])).reshape(-1)
    else:
        gs = np.zeros(0)
        grp_keep = np.zeros(0, int)
        cat_idx = np.zeros(0, int)

    idx = np.concatenate([cont_keep, cat_idx]).astype(np.int32)

    # flip-move weights over scalar lanes: kept groups carry their
    # (normalized) sensitivity, everything else 0
    cat_weight = np.zeros(space.n_scalar)
    if ncat and len(grp_keep):
        w = gs[grp_keep]
        w = w / w.max() if w.max() > 0 else np.ones_like(w)
        cat_weight[np.asarray(space.cat_lane_idx)[grp_keep]] = w

    # soft ARD weights over the FULL rep: normalize by a high quantile
    # (not the max — one spiky lane must not flatten the rest), floor
    # at 0.1 so no lane is invisible; group lanes share their group's
    # sensitivity so a flag's one-hot columns scale together
    ref = float(np.quantile(scores[scores > 0], 0.9)) \
        if (scores > 0).any() else 1.0
    lane_scores = scores.copy()
    if ncat:
        lane_scores[n_cont:] = np.repeat(gs, width)
    lane_weight = np.clip(lane_scores / max(ref, 1e-12), 0.1, 1.0)

    return FeatureScreen(idx=idx, n_cont=int(len(cont_keep)),
                         n_cat=int(len(grp_keep)),
                         cat_weight=cat_weight, scores=scores,
                         lane_weight=lane_weight)


def archive_rows(space, path: str):
    """Read one driver jsonl archive -> (surrogate_feats [N,F], qor [N]).

    Archives store the exact unit vectors (`u`) and permutations the
    driver evaluated (driver/driver.py _log_trial), so features are
    rebuilt bit-identically to what a live run would have observed.
    Raises on a space-signature mismatch: sensitivities transferred
    across DIFFERENT spaces would be silently meaningless.
    """
    import jax.numpy as jnp

    from ..space.spec import CandBatch

    us: List[List[float]] = []
    perms: List[List[List[int]]] = []
    qors: List[float] = []
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "space_sig" in rec:
                sig = [repr(s) for s in space.specs]
                if rec["space_sig"] != sig:
                    raise ValueError(
                        f"archive {path} was recorded for a different "
                        f"space; cross-space screening is meaningless")
                continue
            if "u" not in rec or "qor" not in rec:
                continue
            pm_rec = rec.get("perms", [])
            if (len(pm_rec) != len(space.perm_sizes)
                    or any(len(p) != s
                           for p, s in zip(pm_rec, space.perm_sizes))):
                # a row lacking (or short on) its perm blocks cannot be
                # reassembled into a CandBatch on a permutation space —
                # skip it like any other malformed row instead of
                # raising IndexError at stacking time (ADVICE r5)
                continue
            us.append(rec["u"])
            perms.append(pm_rec)
            qors.append(float(rec["qor"]))
    if not us:
        return (np.zeros((0, space.n_surrogate_features), np.float32),
                np.zeros(0, np.float32))
    u = jnp.asarray(np.asarray(us, np.float32))
    pm = tuple(jnp.asarray(np.asarray([p[i] for p in perms], np.int32))
               for i in range(len(space.perm_sizes)))
    cands = CandBatch(u, pm)
    feats = np.asarray(space.surrogate_transform(space.features(cands)))
    return feats, np.asarray(qors, np.float32)


def screen_from_archives(space, paths: Sequence[str],
                         top_cont: int = 16,
                         top_cat: int = 24) -> Optional[FeatureScreen]:
    """Build a FeatureScreen from driver archives of OTHER payloads over
    the same space (the CLI's --surrogate-screen flag).  Archives that
    are missing or empty are skipped; returns None when no source
    contributed rows."""
    sources = []
    for p in paths:
        if not os.path.exists(p):
            continue
        feats, qor = archive_rows(space, p)
        if len(qor) >= 4:
            sources.append((feats, qor))
    if not sources:
        return None
    return build_screen(space, sources, top_cont=top_cont,
                        top_cat=top_cat)
