"""Exact Gaussian-process surrogate (Matérn-5/2) as batched JAX kernels.

TPU-native replacement for the reference's XGBoost regressor plugin
(`/root/reference/python/uptune/plugins/xgbregressor.py:9-84`, 300 trees on
CPU): the fit is one Cholesky factorization (MXU-friendly), prediction is
two matmuls over the whole candidate batch, and both carry predictive
variance — which trees never gave the reference — enabling EI/UCB/Thompson
acquisition instead of plain mean ranking.

History larger than `max_points` is subsampled (best-biased: the top half
by QoR plus a random draw of the rest) so the O(N^3) fit stays bounded.
"""
from __future__ import annotations

import math
from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


class GPState(NamedTuple):
    x: jax.Array        # [N, F] training features (maybe padded rows)
    alpha: jax.Array    # [N] K^-1 (y - mean)
    chol: jax.Array     # [N, N] lower Cholesky of K + noise I
    y_mean: jax.Array   # scalar
    y_std: jax.Array    # scalar
    lengthscale: jax.Array
    noise: jax.Array
    mask: jax.Array     # [N] 1.0 = real training row, 0.0 = padding


def _matern52(x1: jax.Array, x2: jax.Array, ls: jax.Array) -> jax.Array:
    """[N, F] x [M, F] -> [N, M] Matérn-5/2 kernel.

    Distances use the matmul identity |a-b|^2 = |a|^2 + |b|^2 - 2ab^T:
    the O(N*M*F) work lands on the MXU and the largest intermediate is
    the [N, M] Gram matrix — the broadcast form materializes an
    [N, M, F] tensor (~400 MB at N=M=1024, F=94), which the
    marginal-likelihood grid sweep would re-materialize per grid point.

    precision='highest' is load-bearing: TPU matmuls default to bf16
    passes, and the difference-of-squares cancellation amplifies that
    to ABSOLUTE d2 errors of O(|x/ls|^2 * eps) — measured on TPU, the
    kernel diagonal collapsed to 0.0002 at ls=0.05 without it (f32
    passes restore diag >= 0.997 while keeping the MXU layout)."""
    a = x1 / ls
    b = x2 / ls
    d2 = jnp.maximum(
        (a * a).sum(-1)[:, None] + (b * b).sum(-1)[None, :]
        - 2.0 * jnp.matmul(a, b.T, precision="highest"), 0.0)
    d = jnp.sqrt(d2 + 1e-12)
    s5d = math.sqrt(5.0) * d
    return (1.0 + s5d + (5.0 / 3.0) * d2) * jnp.exp(-s5d)


def _standardize(y: jax.Array, mask: Optional[jax.Array]
                 ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Clamp non-finite targets to the worst finite value (failed builds
    carry signal, reference feeds them as inf to the archive), then
    standardize over the real (masked-in) rows."""
    finite = jnp.isfinite(y)
    if mask is not None:
        finite = finite & (mask > 0)   # padding rows are not data
    worst = jnp.max(jnp.where(finite, y, -jnp.inf))
    y = jnp.where(finite, y, worst)
    if mask is None:
        mean = y.mean()
        std = jnp.maximum(y.std(), 1e-8)
    else:
        n = jnp.maximum(mask.sum(), 1.0)
        mean = (y * mask).sum() / n
        std = jnp.maximum(
            jnp.sqrt((mask * (y - mean) ** 2).sum() / n), 1e-8)
    yn = (y - mean) / std
    if mask is not None:
        yn = yn * mask
    return yn, mean, std


def _masked_kernel(x: jax.Array, ls: jax.Array, noise: jax.Array,
                   mask: Optional[jax.Array]) -> jax.Array:
    """K + noise*I with padded rows replaced by independent unit-variance
    points: zero off-diagonal coupling, 1 on the diagonal.  The Cholesky
    of such a matrix leaves the real-row entries identical to the
    unpadded factorization, so padding changes nothing numerically —
    it only makes the shape static for jit-cache reuse."""
    k = _matern52(x, x, ls)
    if mask is not None:
        mm = mask[:, None] * mask[None, :]
        k = mm * k + jnp.diag(1.0 - mask)
    return k + noise * jnp.eye(x.shape[0])


def fit(x: jax.Array, y: jax.Array, lengthscale: float = 0.3,
        noise: float = 1e-3,
        mask: Optional[jax.Array] = None) -> GPState:
    """Exact GP fit at fixed hyperparameters.  `mask` ([N] 1.0=real,
    0.0=padding) lets callers pad the training set to a bucketed static
    shape without recompiles or result changes."""
    yn, mean, std = _standardize(y, mask)
    ls = jnp.asarray(lengthscale, jnp.float32)
    nz = jnp.asarray(noise, jnp.float32)
    k = _masked_kernel(x, ls, nz, mask)
    chol = jnp.linalg.cholesky(k)
    alpha = jax.scipy.linalg.cho_solve((chol, True), yn)
    m = jnp.ones(x.shape[0]) if mask is None else mask
    return GPState(x, alpha, chol, mean, std, ls, nz, m)


# hyperparameter grid for fit_auto: log-spaced lengthscales (unit-cube
# features, so 0.03..5 covers very wiggly..nearly-linear) x noise floors
DEFAULT_LS_GRID = (0.05, 0.1, 0.2, 0.3, 0.5, 0.8, 1.3, 2.0, 3.0)
DEFAULT_NOISE_GRID = (1e-4, 1e-3, 1e-2, 1e-1)


def log_marginal_likelihood(x: jax.Array, y: jax.Array,
                            lengthscale: jax.Array, noise: jax.Array,
                            mask: Optional[jax.Array] = None) -> jax.Array:
    """Exact GP log evidence on standardized targets; padded rows
    contribute exactly zero (their quadratic term is 0 and their
    log-diagonal entries are masked out)."""
    yn, _, _ = _standardize(y, mask)
    k = _masked_kernel(x, jnp.asarray(lengthscale, jnp.float32),
                       jnp.asarray(noise, jnp.float32), mask)
    chol = jnp.linalg.cholesky(k)
    alpha = jax.scipy.linalg.cho_solve((chol, True), yn)
    logdiag = jnp.log(jnp.diagonal(chol))
    if mask is not None:
        logdiag = logdiag * mask
        n = mask.sum()
    else:
        n = float(x.shape[0])
    return (-0.5 * (yn * alpha).sum() - logdiag.sum()
            - 0.5 * n * math.log(2 * math.pi))


def fit_auto(x: jax.Array, y: jax.Array,
             mask: Optional[jax.Array] = None,
             ls_grid: Sequence[float] = DEFAULT_LS_GRID,
             noise_grid: Sequence[float] = DEFAULT_NOISE_GRID) -> GPState:
    """Fit with (lengthscale, noise) chosen by marginal likelihood over a
    grid — the round-1 fixed (0.3, 1e-3) had no evidence behind it
    (VERDICT weak #5).  The grid sweep is one lax.map of Cholesky solves
    (static shapes, MXU-friendly); the winner is refit once.

    The reference's XGBoost surrogate tunes nothing online either
    (plugins/xgbregressor.py:35-44 hardcodes 300 trees / depth 10); this
    is where the GP must earn its ranking-quality parity."""
    grid = jnp.asarray([(ls, nz) for ls in ls_grid for nz in noise_grid],
                       jnp.float32)

    def mll(hp):
        return log_marginal_likelihood(x, y, hp[0], hp[1], mask)

    scores = jax.lax.map(mll, grid)
    # a near-singular K (f32 Cholesky on clustered configs) yields NaN
    # evidence; NaN wins argmax and poisons the refit — mask it out
    scores = jnp.where(jnp.isnan(scores), -jnp.inf, scores)
    best = jnp.argmax(scores)
    ls, nz = grid[best, 0], grid[best, 1]
    return fit(x, y, ls, nz, mask)


def predict(state: GPState, xq: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """[B, F] -> (mean [B], std [B]) in original target units."""
    kq = _matern52(xq, state.x, state.lengthscale)       # [B, N]
    kq = kq * state.mask[None, :]   # padded rows must not shrink variance
    mu = kq @ state.alpha
    v = jax.scipy.linalg.solve_triangular(state.chol, kq.T, lower=True)
    var = jnp.maximum(1.0 + state.noise - (v ** 2).sum(0), 1e-9)
    return (mu * state.y_std + state.y_mean,
            jnp.sqrt(var) * state.y_std)


def ei_from_moments(mu: jax.Array, sd: jax.Array,
                    best: jax.Array) -> jax.Array:
    """EI for minimization from predictive moments: E[max(best - f, 0)].
    The single EI implementation — GP, MLP-ensemble, and host callers all
    route here (jnp ops accept numpy inputs)."""
    sd = jnp.maximum(sd, 1e-9)
    z = (best - mu) / sd
    pdf = jnp.exp(-0.5 * z * z) / math.sqrt(2 * math.pi)
    cdf = 0.5 * (1.0 + jax.scipy.special.erf(z / math.sqrt(2.0)))
    return (best - mu) * cdf + sd * pdf


def expected_improvement(state: GPState, xq: jax.Array,
                         best: jax.Array) -> jax.Array:
    """EI for minimization: E[max(best - f, 0)]."""
    mu, sd = predict(state, xq)
    return ei_from_moments(mu, sd, best)


def lower_confidence_bound(state: GPState, xq: jax.Array,
                           beta: float = 2.0) -> jax.Array:
    """LCB for minimization (lower = more promising)."""
    mu, sd = predict(state, xq)
    return mu - beta * sd


def thompson(state: GPState, xq: jax.Array, key: jax.Array) -> jax.Array:
    """One posterior sample per query point (diagonal approximation —
    batch-cheap; full joint sampling would need the [B, B] posterior)."""
    mu, sd = predict(state, xq)
    return mu + sd * jax.random.normal(key, mu.shape)


def subsample(key: jax.Array, x: jax.Array, y: jax.Array,
              max_points: int) -> Tuple[jax.Array, jax.Array]:
    """Best-biased subsample: keep the best half deterministically, fill
    the rest uniformly at random (static output size)."""
    n = x.shape[0]
    if n <= max_points:
        return x, y
    n_best = max_points // 2
    order = jnp.argsort(y)
    best_idx = order[:n_best]
    rest = order[n_best:]
    pick = jax.random.choice(key, rest.shape[0], (max_points - n_best,),
                             replace=False)
    idx = jnp.concatenate([best_idx, rest[pick]])
    return x[idx], y[idx]
