"""Exact Gaussian-process surrogate (Matérn-5/2) as batched JAX kernels.

TPU-native replacement for the reference's XGBoost regressor plugin
(`/root/reference/python/uptune/plugins/xgbregressor.py:9-84`, 300 trees on
CPU): the fit is one Cholesky factorization (MXU-friendly), prediction is
two matmuls over the whole candidate batch, and both carry predictive
variance — which trees never gave the reference — enabling EI/UCB/Thompson
acquisition instead of plain mean ranking.

History larger than `max_points` is subsampled (best-biased: the top half
by QoR plus a random draw of the rest) so the O(N^3) fit stays bounded.
"""
from __future__ import annotations

import math
from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
# eager, not attribute-lazy: jax loads the scipy submodule (and builds
# its internal _cho_solve/_solve_triangular jit wrappers) on first
# attribute access — inside a TraceGuard scope those library wrappers
# would be counted against the guarded run's budget
import jax.scipy.linalg  # noqa: F401


class GPState(NamedTuple):
    x: jax.Array        # [N, F] training features (maybe padded rows)
    alpha: jax.Array    # [N] K^-1 (y - mean)
    chol: jax.Array     # [N, N] lower Cholesky of K + noise I
    y_mean: jax.Array   # scalar
    y_std: jax.Array    # scalar
    lengthscale: jax.Array
    noise: jax.Array
    mask: jax.Array     # [N] 1.0 = real training row, 0.0 = padding
    # categorical-block lengthscale.  The default MUST be a plain python
    # float, not jnp.float32(1.0): jnp dtype calls return DEVICE arrays,
    # and a device array in a class body initializes the XLA backend at
    # import — which breaks jax.distributed.initialize() in every
    # multi-process run (and hangs outright on a wedged axon tunnel).
    ls_cat: float = 1.0
    # optional premasked K^-1 for the fused Pallas variance path
    # (pallas_score.gp_mean_var_scores).  None by default: it costs an
    # extra O(N^3) solve, so only callers that will score large pools
    # attach it — once per (re)fit via precompute_kinv(), not once per
    # scoring call (r5 review).
    kinv: Optional[jax.Array] = None


def _raw_d2(x1: jax.Array, x2: jax.Array) -> jax.Array:
    """[N, F] x [M, F] -> [N, M] squared euclidean distances.

    Uses the matmul identity |a-b|^2 = |a|^2 + |b|^2 - 2ab^T: the
    O(N*M*F) work lands on the MXU and the largest intermediate is the
    [N, M] Gram matrix — the broadcast form materializes an [N, M, F]
    tensor (~400 MB at N=M=1024, F=94), which the marginal-likelihood
    grid sweep would re-materialize per grid point.

    precision='highest' is load-bearing: TPU matmuls default to bf16
    passes, and the difference-of-squares cancellation amplifies that
    to ABSOLUTE d2 errors of O(|x|^2 * eps) — measured on TPU, the
    kernel diagonal collapsed to 0.0002 at ls=0.05 without it (f32
    passes restore diag >= 0.997 while keeping the MXU layout)."""
    return jnp.maximum(
        (x1 * x1).sum(-1)[:, None] + (x2 * x2).sum(-1)[None, :]
        - 2.0 * jnp.matmul(x1, x2.T, precision="highest"), 0.0)


def _matern52_from_d2(d2: jax.Array) -> jax.Array:
    """Matérn-5/2 from ALREADY lengthscale-scaled squared distances."""
    d = jnp.sqrt(d2 + 1e-12)
    s5d = math.sqrt(5.0) * d
    return (1.0 + s5d + (5.0 / 3.0) * d2) * jnp.exp(-s5d)


def _matern52(x1: jax.Array, x2: jax.Array, ls: jax.Array) -> jax.Array:
    """[N, F] x [M, F] -> [N, M] Matérn-5/2 kernel (continuous lanes)."""
    return _matern52_from_d2(_raw_d2(x1 / ls, x2 / ls))


def _kernel_from_d2(d2c: jax.Array, ham, ls, ls_cat,
                    n_cat: int) -> jax.Array:
    """Product kernel from precomputed raw distance blocks.

    `d2c`: raw (unit-lengthscale) squared distances over the continuous
    block; `ham`: Hamming counts over the categorical block (or None),
    which the 1/sqrt(2)-scaled one-hot encoding in
    Space.surrogate_transform makes equal to ITS raw squared distances.

        k = Matérn52(d2c / ls²) · exp(-(ham / n_cat) / ls_cat)

    The exponential-Hamming factor is the categorical half the r3
    verdict asked for: an isotropic Matérn over one-hot lanes imposes a
    single shared lengthscale, letting 232 flag lanes drown the 95
    numeric ones; the product form gives each block its own scale,
    selected by marginal likelihood.  Both factors are 1 at distance 0,
    so the prior variance stays 1 and predict()'s variance algebra is
    unchanged."""
    k = _matern52_from_d2(d2c / (ls * ls))
    if ham is not None and n_cat:
        k = k * jnp.exp(-(ham / float(n_cat)) / ls_cat)
    return k


def _d2_blocks(x1: jax.Array, x2: jax.Array, n_cont):
    """Split features at static column `n_cont` and return the two raw
    distance blocks (continuous d2, categorical Hamming-count)."""
    if n_cont is None or n_cont >= x1.shape[-1]:
        return _raw_d2(x1, x2), None
    return (_raw_d2(x1[:, :n_cont], x2[:, :n_cont]),
            _raw_d2(x1[:, n_cont:], x2[:, n_cont:]))


def _standardize(y: jax.Array, mask: Optional[jax.Array]
                 ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Clamp non-finite targets to the worst finite value (failed builds
    carry signal, reference feeds them as inf to the archive), then
    standardize over the real (masked-in) rows."""
    finite = jnp.isfinite(y)
    if mask is not None:
        finite = finite & (mask > 0)   # padding rows are not data
    worst = jnp.max(jnp.where(finite, y, -jnp.inf))
    y = jnp.where(finite, y, worst)
    if mask is None:
        mean = y.mean()
        std = jnp.maximum(y.std(), 1e-8)
    else:
        n = jnp.maximum(mask.sum(), 1.0)
        mean = (y * mask).sum() / n
        std = jnp.maximum(
            jnp.sqrt((mask * (y - mean) ** 2).sum() / n), 1e-8)
    yn = (y - mean) / std
    if mask is not None:
        yn = yn * mask
    return yn, mean, std


def _mask_adjust(k: jax.Array, noise: jax.Array,
                 mask: Optional[jax.Array]) -> jax.Array:
    """K + noise*I with padded rows replaced by independent unit-variance
    points: zero off-diagonal coupling, 1 on the diagonal.  The Cholesky
    of such a matrix leaves the real-row entries identical to the
    unpadded factorization, so padding changes nothing numerically —
    it only makes the shape static for jit-cache reuse."""
    if mask is not None:
        mm = mask[:, None] * mask[None, :]
        k = mm * k + jnp.diag(1.0 - mask)
    return k + noise * jnp.eye(k.shape[0])


def fit(x: jax.Array, y: jax.Array, lengthscale: float = 0.3,
        noise: float = 1e-3,
        mask: Optional[jax.Array] = None,
        n_cont: Optional[int] = None, n_cat: int = 0,
        ls_cat: float = 1.0) -> GPState:
    """Exact GP fit at fixed hyperparameters.  `mask` ([N] 1.0=real,
    0.0=padding) lets callers pad the training set to a bucketed static
    shape without recompiles or result changes.  `n_cont`/`n_cat`
    (static) activate the mixed continuous×categorical kernel over
    Space.surrogate_transform features; the defaults reproduce the pure
    Matérn behavior exactly."""
    yn, mean, std = _standardize(y, mask)
    ls = jnp.asarray(lengthscale, jnp.float32)
    nz = jnp.asarray(noise, jnp.float32)
    lc = jnp.asarray(ls_cat, jnp.float32)
    d2c, ham = _d2_blocks(x, x, n_cont)
    k = _mask_adjust(_kernel_from_d2(d2c, ham, ls, lc, n_cat), nz, mask)
    chol = jnp.linalg.cholesky(k)
    alpha = jax.scipy.linalg.cho_solve((chol, True), yn)
    m = jnp.ones(x.shape[0]) if mask is None else mask
    return GPState(x, alpha, chol, mean, std, ls, nz, m, lc)


# hyperparameter grid for fit_auto: log-spaced lengthscales (unit-cube
# features, so 0.03..5 covers very wiggly..nearly-linear) x noise floors
DEFAULT_LS_GRID = (0.05, 0.1, 0.2, 0.3, 0.5, 0.8, 1.3, 2.0, 3.0)
DEFAULT_NOISE_GRID = (1e-4, 1e-3, 1e-2, 1e-1)
# categorical lengthscales: ls_cat ~ the Hamming FRACTION over which
# correlation decays by 1/e — 0.02 ≈ "a handful of flag flips decorrelate"
# up to 2.0 ≈ "flags barely matter"
DEFAULT_LS_CAT_GRID = (0.02, 0.05, 0.1, 0.2, 0.5, 1.0, 2.0)


def log_marginal_likelihood(x: jax.Array, y: jax.Array,
                            lengthscale: jax.Array, noise: jax.Array,
                            mask: Optional[jax.Array] = None,
                            n_cont: Optional[int] = None, n_cat: int = 0,
                            ls_cat=1.0) -> jax.Array:
    """Exact GP log evidence on standardized targets; padded rows
    contribute exactly zero (their quadratic term is 0 and their
    log-diagonal entries are masked out)."""
    yn, _, _ = _standardize(y, mask)
    d2c, ham = _d2_blocks(x, x, n_cont)
    k = _mask_adjust(
        _kernel_from_d2(d2c, ham, jnp.asarray(lengthscale, jnp.float32),
                        jnp.asarray(ls_cat, jnp.float32), n_cat),
        jnp.asarray(noise, jnp.float32), mask)
    return _mll_from_k(k, yn, mask, x.shape[0])


def _mll_from_k(k, yn, mask, n_rows) -> jax.Array:
    chol = jnp.linalg.cholesky(k)
    alpha = jax.scipy.linalg.cho_solve((chol, True), yn)
    logdiag = jnp.log(jnp.diagonal(chol))
    if mask is not None:
        logdiag = logdiag * mask
        n = mask.sum()
    else:
        n = float(n_rows)
    return (-0.5 * (yn * alpha).sum() - logdiag.sum()
            - 0.5 * n * math.log(2 * math.pi))


def fit_auto(x: jax.Array, y: jax.Array,
             mask: Optional[jax.Array] = None,
             ls_grid: Sequence[float] = DEFAULT_LS_GRID,
             noise_grid: Sequence[float] = DEFAULT_NOISE_GRID,
             n_cont: Optional[int] = None, n_cat: int = 0,
             ls_cat_grid: Sequence[float] = DEFAULT_LS_CAT_GRID
             ) -> GPState:
    """Fit with (lengthscale, noise[, ls_cat]) chosen by marginal
    likelihood over a grid — the round-1 fixed (0.3, 1e-3) had no
    evidence behind it (VERDICT weak #5).  The raw distance blocks are
    computed ONCE (two MXU matmuls) and shared across the whole grid;
    the lax.map sweep is then pure elementwise-transform + Cholesky per
    point (static shapes), and the winner is refit once.

    The reference's XGBoost surrogate tunes nothing online either
    (plugins/xgbregressor.py:35-44 hardcodes 300 trees / depth 10); this
    is where the GP must earn its ranking-quality parity.

    With categoricals the hyperparameter space is 3-D; the full product
    grid would be 9×4×7 = 252 Cholesky factorizations per refit — and
    the O(N³) Cholesky, not the (shared) distance matmuls, dominates at
    N≳512.  Instead: coordinate descent — sweep (ls, noise) at the
    middle ls_cat, then sweep ls_cat at that winner (36 + 7 = 43
    factorizations, ~6× cheaper)."""
    has_cat = n_cat > 0 and n_cont is not None and n_cont < x.shape[-1]
    yn, _, _ = _standardize(y, mask)
    d2c, ham = _d2_blocks(x, x, n_cont)

    def mll(hp):
        k = _mask_adjust(_kernel_from_d2(d2c, ham, hp[0], hp[2], n_cat),
                         hp[1], mask)
        return _mll_from_k(k, yn, mask, x.shape[0])

    def sweep(grid):
        scores = jax.lax.map(mll, grid)
        # a near-singular K (f32 Cholesky on clustered configs) yields
        # NaN evidence; NaN wins argmax and poisons the refit — mask it
        scores = jnp.where(jnp.isnan(scores), -jnp.inf, scores)
        return grid[jnp.argmax(scores)]

    cat_grid = tuple(ls_cat_grid)
    mid = cat_grid[len(cat_grid) // 2] if has_cat else 1.0
    g1 = jnp.asarray([(ls, nz, mid) for ls in ls_grid
                      for nz in noise_grid], jnp.float32)
    best = sweep(g1)
    if has_cat:
        g2 = jnp.stack([
            jnp.full((len(cat_grid),), best[0]),
            jnp.full((len(cat_grid),), best[1]),
            jnp.asarray(cat_grid, jnp.float32)], axis=1)
        best = sweep(g2)
    return fit(x, y, best[0], best[1], mask,
               n_cont=n_cont, n_cat=n_cat, ls_cat=best[2])


def bucket_of(n: int, max_points: int) -> int:
    """Static training-shape bucket for `n` rows: the next power of two,
    capped at `max_points` (ADVICE round 1: without bucketing every
    refit below max_points re-traced the O(N^3) fit program)."""
    b = 1
    while b < n:
        b *= 2
    return min(b, max(max_points, n))


def pad_train(x: jax.Array, y: jax.Array, bucket: int
              ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Zero-pad a training set to `bucket` rows; returns (x, y, mask):
    the padded-row convention _mask_adjust expects (zero rows, zero
    mask).  fit_auto_bucketed routes here; SurrogateManager._refit_full
    mirrors the same convention in host numpy (its padding must not
    dispatch device ops from the refit worker) — keep the two in sync
    if the convention ever changes."""
    n = x.shape[0]
    mask = jnp.concatenate(
        [jnp.ones(n), jnp.zeros(bucket - n)]).astype(x.dtype)
    x = jnp.concatenate([x, jnp.zeros((bucket - n, x.shape[1]), x.dtype)])
    y = jnp.concatenate([y, jnp.zeros(bucket - n, y.dtype)])
    return x, y, mask


# fit_auto_bucketed's jit cache: one wrapper per static configuration,
# so the hyperparameter sweep compiles once per BUCKET instead of once
# per growing N (each wrapper traces exactly once; lazily keyed here
# rather than one shape-polymorphic wrapper so a trace guard sees no
# per-shape retraces)
_FIT_AUTO_JIT: dict = {}


def fit_auto_bucketed(x: jax.Array, y: jax.Array, *,
                      max_points: int = 1024,
                      key: Optional[jax.Array] = None,
                      n_cont: Optional[int] = None, n_cat: int = 0,
                      ls_grid: Sequence[float] = DEFAULT_LS_GRID,
                      noise_grid: Sequence[float] = DEFAULT_NOISE_GRID,
                      ls_cat_grid: Sequence[float] = DEFAULT_LS_CAT_GRID
                      ) -> GPState:
    """fit_auto over a padded power-of-two bucket: subsample past
    `max_points` (best-biased; `key` seeds the draw), pad to the
    bucket, and run the jitted sweep — callers that refit as the
    training set grows compile once per bucket, not once per N."""
    if x.shape[0] > max_points:
        x, y = subsample(key if key is not None else jax.random.PRNGKey(0),
                         x, y, max_points)
    grids = (tuple(ls_grid), tuple(noise_grid), tuple(ls_cat_grid))
    bucket = bucket_of(x.shape[0], max_points)
    x, y, mask = pad_train(jnp.asarray(x, jnp.float32),
                           jnp.asarray(y, jnp.float32), bucket)
    sig = (bucket, int(x.shape[1]), n_cont, n_cat, grids)
    fn = _FIT_AUTO_JIT.get(sig)
    if fn is None:
        g = grids
        fn = jax.jit(lambda xx, yy, mm: fit_auto(
            xx, yy, mm, ls_grid=g[0], noise_grid=g[1], n_cont=n_cont,
            n_cat=n_cat, ls_cat_grid=g[2]))
        _FIT_AUTO_JIT[sig] = fn
    return fn(x, y, mask)


def extend(state: GPState, x_row: jax.Array, y_raw: jax.Array,
           slot: jax.Array, n_cont: Optional[int] = None,
           n_cat: int = 0) -> GPState:
    """O(N^2) rank-1 extension of a fitted padded GPState: condition on
    ONE new observation occupying padding row `slot` (traced int32),
    keeping hyperparameters and the target standardization of the last
    full fit.  Exact GP conditioning at fixed hyperparameters — the
    result equals `fit()` on the extended training set with the same
    (lengthscale, noise, ls_cat, y_mean, y_std), because _mask_adjust
    keeps padded rows decoupled (unit diagonal, zero off-diagonal):
    rewriting row `slot` of the factor leaves every other row of L
    untouched, so no O(N^3) refactorization is needed.

    Shapes are static (the padded bucket), so a per-bucket jit wrapper
    traces exactly once; `slot` must be the first padded row (real rows
    occupy a prefix — the manager's invariant), which makes every
    later row's kernel entry zero and the update purely local.

    `y_raw` must already be finite (callers clamp failures to the worst
    finite target, mirroring _standardize's clamping).  When the state
    carries a premasked K^-1 (precompute_kinv), it is extended in
    O(N^2) too, via the bordered-inverse identity
    K_new^-1 = K^-1 + (v - e_slot)(v - e_slot)^T / l_nn^2 with
    v = K^-1 k_vec."""
    xb, chol, mask = state.x, state.chol, state.mask
    d2c, ham = _d2_blocks(x_row[None, :], xb, n_cont)
    kvec = _kernel_from_d2(d2c, ham, state.lengthscale, state.ls_cat,
                           n_cat)[0] * mask               # [N]
    # forward-substitute against the existing factor: entries at padded
    # rows (incl. `slot` itself) come out exactly zero, so the full
    # solve IS the leading-block solve
    w = jax.scipy.linalg.solve_triangular(chol, kvec, lower=True)
    lnn = jnp.sqrt(jnp.maximum(1.0 + state.noise - (w * w).sum(), 1e-12))
    chol_new = chol.at[slot, :].set(w.at[slot].set(lnn))
    # recover the standardized targets from the old factor (K alpha =
    # yn, so yn = L L^T alpha — no stored y needed), splice in the new
    # row, and re-solve against the extended factor
    yn = chol @ (chol.T @ state.alpha)
    yn = yn.at[slot].set((y_raw - state.y_mean) / state.y_std)
    alpha_new = jax.scipy.linalg.cho_solve((chol_new, True), yn)
    kinv_new = state.kinv
    if kinv_new is not None:
        v = kinv_new @ kvec
        r = v.at[slot].add(-1.0)
        kinv_new = kinv_new + jnp.outer(r, r) / (lnn * lnn)
    return state._replace(
        x=xb.at[slot].set(x_row), alpha=alpha_new, chol=chol_new,
        mask=mask.at[slot].set(1.0), kinv=kinv_new)


def precompute_kinv(state: GPState) -> GPState:
    """Attach the premasked K^-1 the fused Pallas variance path needs
    (pallas_score module docstring: the mask-adjusted K is
    block-diagonal, so zeroing padded rows/cols of its inverse makes
    the tile-level quadratic form equal the unpadded solve exactly).
    Call once per (re)fit when large-pool scoring is expected; the
    Pallas path falls back to computing it per call otherwise."""
    n = state.x.shape[0]
    kinv = jax.scipy.linalg.cho_solve(
        (jnp.asarray(state.chol, jnp.float32), True), jnp.eye(n))
    kinv = kinv * state.mask[:, None] * state.mask[None, :]
    return state._replace(kinv=kinv)


def predict(state: GPState, xq: jax.Array,
            n_cont: Optional[int] = None, n_cat: int = 0
            ) -> Tuple[jax.Array, jax.Array]:
    """[B, F] -> (mean [B], std [B]) in original target units."""
    d2c, ham = _d2_blocks(xq, state.x, n_cont)
    kq = _kernel_from_d2(d2c, ham, state.lengthscale, state.ls_cat,
                         n_cat)                           # [B, N]
    kq = kq * state.mask[None, :]   # padded rows must not shrink variance
    mu = kq @ state.alpha
    v = jax.scipy.linalg.solve_triangular(state.chol, kq.T, lower=True)
    var = jnp.maximum(1.0 + state.noise - (v ** 2).sum(0), 1e-9)
    return (mu * state.y_std + state.y_mean,
            jnp.sqrt(var) * state.y_std)


def ei_from_moments(mu: jax.Array, sd: jax.Array,
                    best: jax.Array) -> jax.Array:
    """EI for minimization from predictive moments: E[max(best - f, 0)].
    The single EI implementation — GP, MLP-ensemble, and host callers all
    route here (jnp ops accept numpy inputs)."""
    sd = jnp.maximum(sd, 1e-9)
    z = (best - mu) / sd
    pdf = jnp.exp(-0.5 * z * z) / math.sqrt(2 * math.pi)
    cdf = 0.5 * (1.0 + jax.scipy.special.erf(z / math.sqrt(2.0)))
    return (best - mu) * cdf + sd * pdf


def expected_improvement(state: GPState, xq: jax.Array,
                         best: jax.Array,
                         n_cont: Optional[int] = None,
                         n_cat: int = 0) -> jax.Array:
    """EI for minimization: E[max(best - f, 0)]."""
    mu, sd = predict(state, xq, n_cont, n_cat)
    return ei_from_moments(mu, sd, best)


def lower_confidence_bound(state: GPState, xq: jax.Array,
                           beta: float = 2.0,
                           n_cont: Optional[int] = None,
                           n_cat: int = 0) -> jax.Array:
    """LCB for minimization (lower = more promising)."""
    mu, sd = predict(state, xq, n_cont, n_cat)
    return mu - beta * sd


def thompson(state: GPState, xq: jax.Array, key: jax.Array,
             n_cont: Optional[int] = None, n_cat: int = 0) -> jax.Array:
    """One posterior sample per query point (diagonal approximation —
    batch-cheap; full joint sampling would need the [B, B] posterior)."""
    mu, sd = predict(state, xq, n_cont, n_cat)
    return mu + sd * jax.random.normal(key, mu.shape)


def score_flat(state: GPState, xq: jax.Array, kind: str = "mean",
               best_y=None, beta: float = 2.0,
               n_cont: Optional[int] = None, n_cat: int = 0,
               interpret: bool = None,
               pallas_min: Optional[int] = None) -> jax.Array:
    """Score a query batch of ANY leading shape [..., F] as ONE flat
    [prod(leading), F] pass — the fused-scoring entry the batched
    multi-instance engine uses: N instances' candidate batches reshape
    to a single cross-kernel matmul (filling the MXU) instead of N
    per-instance dispatches, and past PALLAS_MIN_POOL flat rows the
    Pallas tiled kernel scores without the [B, N] HBM intermediate.

    kind: 'mean' (posterior mean), 'ei' (expected improvement vs
    `best_y` — required), or 'lcb' (mu - beta*sd).  Returns scores in
    the leading shape of `xq`; `n_cont`/`n_cat` MUST match the fit."""
    lead = xq.shape[:-1]
    flat = xq.reshape((-1, xq.shape[-1]))
    from . import pallas_score  # local: pallas_score imports gp lazily
    from ..ops import routing as _routing
    if pallas_min is None:
        pallas_min = pallas_score.PALLAS_MIN_POOL
    # the historical bare `>= PALLAS_MIN_POOL` gate, now routed through
    # the shared UT_PALLAS knob: 'off' forces the predict path at any
    # size, 'interpret' forces the fused kernels (interpret mode) at
    # any size, 'auto' keeps the size gate
    route = _routing.decide(flat.shape[0], min_rows=pallas_min,
                            cpu_ok=True)
    fused = route != _routing.XLA
    if fused and interpret is None:
        interpret = _routing.interpret_flag(route)
    if kind == "mean":
        out = (pallas_score.gp_mean_scores(
                   state, flat, interpret, n_cont, n_cat) if fused
               else predict(state, flat, n_cont, n_cat)[0])
    elif kind in ("ei", "lcb"):
        mu, sd = (pallas_score.gp_mean_var_scores(
                      state, flat, interpret, n_cont, n_cat) if fused
                  else predict(state, flat, n_cont, n_cat))
        if kind == "ei":
            if best_y is None:
                raise ValueError("kind='ei' needs best_y")
            out = ei_from_moments(mu, sd, jnp.float32(best_y))
        else:
            out = mu - beta * sd
    else:
        raise ValueError(f"unknown kind {kind!r}")
    return out.reshape(lead)


def subsample(key: jax.Array, x: jax.Array, y: jax.Array,
              max_points: int) -> Tuple[jax.Array, jax.Array]:
    """Best-biased subsample: keep the best half deterministically, fill
    the rest uniformly at random (static output size)."""
    n = x.shape[0]
    if n <= max_points:
        return x, y
    n_best = max_points // 2
    order = jnp.argsort(y)
    best_idx = order[:n_best]
    rest = order[n_best:]
    pick = jax.random.choice(key, rest.shape[0], (max_points - n_best,),
                             replace=False)
    idx = jnp.concatenate([best_idx, rest[pick]])
    return x[idx], y[idx]
