"""Exact Gaussian-process surrogate (Matérn-5/2) as batched JAX kernels.

TPU-native replacement for the reference's XGBoost regressor plugin
(`/root/reference/python/uptune/plugins/xgbregressor.py:9-84`, 300 trees on
CPU): the fit is one Cholesky factorization (MXU-friendly), prediction is
two matmuls over the whole candidate batch, and both carry predictive
variance — which trees never gave the reference — enabling EI/UCB/Thompson
acquisition instead of plain mean ranking.

History larger than `max_points` is subsampled (best-biased: the top half
by QoR plus a random draw of the rest) so the O(N^3) fit stays bounded.
"""
from __future__ import annotations

import math
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


class GPState(NamedTuple):
    x: jax.Array        # [N, F] training features
    alpha: jax.Array    # [N] K^-1 (y - mean)
    chol: jax.Array     # [N, N] lower Cholesky of K + noise I
    y_mean: jax.Array   # scalar
    y_std: jax.Array    # scalar
    lengthscale: jax.Array
    noise: jax.Array


def _matern52(x1: jax.Array, x2: jax.Array, ls: jax.Array) -> jax.Array:
    """[N, F] x [M, F] -> [N, M] Matérn-5/2 kernel."""
    d2 = jnp.maximum(
        ((x1[:, None, :] - x2[None, :, :]) / ls) ** 2, 0.0).sum(-1)
    d = jnp.sqrt(d2 + 1e-12)
    s5d = math.sqrt(5.0) * d
    return (1.0 + s5d + (5.0 / 3.0) * d2) * jnp.exp(-s5d)


def fit(x: jax.Array, y: jax.Array, lengthscale: float = 0.3,
        noise: float = 1e-3) -> GPState:
    """Fit on standardized targets; non-finite targets are clamped to the
    worst finite value (failed builds carry signal, reference feeds them
    as inf to the archive)."""
    finite = jnp.isfinite(y)
    worst = jnp.max(jnp.where(finite, y, -jnp.inf))
    y = jnp.where(finite, y, worst)
    mean = y.mean()
    std = jnp.maximum(y.std(), 1e-8)
    yn = (y - mean) / std
    ls = jnp.asarray(lengthscale, jnp.float32)
    k = _matern52(x, x, ls) + noise * jnp.eye(x.shape[0])
    chol = jnp.linalg.cholesky(k)
    alpha = jax.scipy.linalg.cho_solve((chol, True), yn)
    return GPState(x, alpha, chol, mean, std,
                   ls, jnp.asarray(noise, jnp.float32))


def predict(state: GPState, xq: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """[B, F] -> (mean [B], std [B]) in original target units."""
    kq = _matern52(xq, state.x, state.lengthscale)       # [B, N]
    mu = kq @ state.alpha
    v = jax.scipy.linalg.solve_triangular(state.chol, kq.T, lower=True)
    var = jnp.maximum(1.0 + state.noise - (v ** 2).sum(0), 1e-9)
    return (mu * state.y_std + state.y_mean,
            jnp.sqrt(var) * state.y_std)


def expected_improvement(state: GPState, xq: jax.Array,
                         best: jax.Array) -> jax.Array:
    """EI for minimization: E[max(best - f, 0)]."""
    mu, sd = predict(state, xq)
    z = (best - mu) / sd
    pdf = jnp.exp(-0.5 * z * z) / math.sqrt(2 * math.pi)
    cdf = 0.5 * (1.0 + jax.scipy.special.erf(z / math.sqrt(2.0)))
    return (best - mu) * cdf + sd * pdf


def lower_confidence_bound(state: GPState, xq: jax.Array,
                           beta: float = 2.0) -> jax.Array:
    """LCB for minimization (lower = more promising)."""
    mu, sd = predict(state, xq)
    return mu - beta * sd


def thompson(state: GPState, xq: jax.Array, key: jax.Array) -> jax.Array:
    """One posterior sample per query point (diagonal approximation —
    batch-cheap; full joint sampling would need the [B, B] posterior)."""
    mu, sd = predict(state, xq)
    return mu + sd * jax.random.normal(key, mu.shape)


def subsample(key: jax.Array, x: jax.Array, y: jax.Array,
              max_points: int) -> Tuple[jax.Array, jax.Array]:
    """Best-biased subsample: keep the best half deterministically, fill
    the rest uniformly at random (static output size)."""
    n = x.shape[0]
    if n <= max_points:
        return x, y
    n_best = max_points // 2
    order = jnp.argsort(y)
    best_idx = order[:n_best]
    rest = order[n_best:]
    pick = jax.random.choice(key, rest.shape[0], (max_points - n_best,),
                             replace=False)
    idx = jnp.concatenate([best_idx, rest[pick]])
    return x[idx], y[idx]
