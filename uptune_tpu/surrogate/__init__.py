from . import gp, mlp  # noqa: F401
from .manager import KINDS, SurrogateManager  # noqa: F401
