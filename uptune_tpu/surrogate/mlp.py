"""MLP-ensemble surrogate: E independently-initialized regressors trained
in parallel with vmap — the JAX counterpart of the reference's surrogate
*ensemble* (`/root/reference/python/uptune/plugins/models.py:54-72`
discovers N model plugins and averages their scores; here the ensemble is
one vmapped train/predict program and disagreement across members doubles
as an uncertainty signal for multivoting pruning, api.py:307-326).
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class MLPEnsembleState(NamedTuple):
    params: Tuple       # pytree with leading ensemble axis [E, ...]
    x_mean: jax.Array   # [F]
    x_std: jax.Array    # [F]
    y_mean: jax.Array
    y_std: jax.Array


def _init_params(key: jax.Array, sizes) -> Tuple:
    params = []
    for din, dout in zip(sizes[:-1], sizes[1:]):
        key, kw = jax.random.split(key)
        w = jax.random.normal(kw, (din, dout)) * jnp.sqrt(2.0 / din)
        params.append((w, jnp.zeros((dout,))))
    return tuple(params)


def _forward(params, x):
    for i, (w, b) in enumerate(params):
        x = x @ w + b
        if i < len(params) - 1:
            x = jax.nn.gelu(x)
    return x[..., 0]


def fit(key: jax.Array, x: jax.Array, y: jax.Array, n_members: int = 4,
        width: int = 64, steps: int = 300, lr: float = 3e-3,
        mask: jax.Array = None) -> MLPEnsembleState:
    """Train the whole ensemble with vmapped full-batch Adam.  `mask`
    ([N] 1.0=real, 0.0=padding) weights the loss and the normalization
    stats so callers can pad to bucketed static shapes (jit-cache
    reuse) without biasing the fit."""
    if mask is None:
        w = jnp.ones(x.shape[0])
    else:
        w = mask
    finite = jnp.isfinite(y) & (w > 0)   # padding rows are not data
    worst = jnp.max(jnp.where(finite, y, -jnp.inf))
    y = jnp.where(finite, y, worst)
    n = jnp.maximum(w.sum(), 1.0)
    x_mean = (x * w[:, None]).sum(0) / n
    x_std = jnp.maximum(
        jnp.sqrt((w[:, None] * (x - x_mean) ** 2).sum(0) / n), 1e-8)
    y_mean = (y * w).sum() / n
    y_std = jnp.maximum(
        jnp.sqrt((w * (y - y_mean) ** 2).sum() / n), 1e-8)
    xn = (x - x_mean) / x_std
    yn = (y - y_mean) / y_std
    sizes = (x.shape[1], width, width, 1)

    def train_one(k):
        params = _init_params(k, sizes)
        # inline Adam (no optax dependency in the hot path)
        m = jax.tree.map(jnp.zeros_like, params)
        v = jax.tree.map(jnp.zeros_like, params)

        def loss_fn(p):
            pred = _forward(p, xn)
            return (w * (pred - yn) ** 2).sum() / n

        def body(carry, i):
            params, m, v = carry
            g = jax.grad(loss_fn)(params)
            m = jax.tree.map(lambda a, b: 0.9 * a + 0.1 * b, m, g)
            v = jax.tree.map(lambda a, b: 0.999 * a + 0.001 * b * b, v, g)
            t = i + 1
            mh = jax.tree.map(lambda a: a / (1 - 0.9 ** t), m)
            vh = jax.tree.map(lambda a: a / (1 - 0.999 ** t), v)
            params = jax.tree.map(
                lambda p, a, b: p - lr * a / (jnp.sqrt(b) + 1e-8),
                params, mh, vh)
            return (params, m, v), None

        (params, _, _), _ = jax.lax.scan(
            body, (params, m, v), jnp.arange(steps))
        return params

    params = jax.vmap(train_one)(jax.random.split(key, n_members))
    return MLPEnsembleState(params, x_mean, x_std, y_mean, y_std)


def predict_members(state: MLPEnsembleState,
                    xq: jax.Array) -> jax.Array:
    """[B, F] -> [E, B] per-member predictions in original units."""
    xn = (xq - state.x_mean) / state.x_std
    preds = jax.vmap(lambda p: _forward(p, xn))(state.params)
    return preds * state.y_std + state.y_mean


def predict(state: MLPEnsembleState,
            xq: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """[B, F] -> (mean [B], std-across-members [B])."""
    preds = predict_members(state, xq)
    return preds.mean(0), preds.std(0)
