"""Surrogate lifecycle + multivoting prune for the host driver.

Mirrors the reference's controller-side surrogate plumbing: offline init
from training data + online re-fit cadence (`/root/reference/python/uptune/
api.py:291-304`, `src/multi_stage.py:157-162`) and the `multivoting`
proposal filter (`api.py:307-326`: each ensemble member votes on every
candidate; losers are dropped before evaluation).

Votes here: a member votes FOR a candidate when its predicted QoR lands in
the best `keep_quantile` of observed history.  A candidate survives with
>= `majority` of votes, and an `explore_frac` random share of the batch
always survives (the reference's random-pick-outside-top-split serves the
same anti-myopia role, multi_stage.py:109-117).
"""
from __future__ import annotations

import threading
import time
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..space.spec import CandBatch, Space
from . import gp as gp_mod
from . import mlp as mlp_mod
from . import pallas_score

KINDS = ("gp", "mlp")


class SurrogateSnapshot(NamedTuple):
    """One immutable published model state.  Everything scoring reads —
    the fitted state, the prune threshold, the incumbent — travels
    together, so a reader that grabbed `manager._snap` once can never
    observe a half-updated model: publication is a single reference
    rebind (atomic under the GIL) of a fully-built snapshot.

    `version` is the monotonic publication counter (full refits AND
    incremental extensions bump it); `n_rows` is the train-row
    watermark — observations [0, n_rows) of the manager's training set
    are conditioned into `state`.  `exact` marks that those rows occupy
    the padded bucket verbatim in training order (no best-biased
    subsample ran), which is what makes O(N^2) rank-1 extension of row
    `in_bucket` valid between full refits."""
    state: Any
    version: int
    n_rows: int
    threshold: Optional[float]
    best_y: Optional[float]
    exact: bool = True
    in_bucket: int = 0


def _screen_feats(feats, sidx, sw):
    """Apply a FeatureScreen's view to surrogate features: hard lane
    selection (`sidx`), soft ARD scaling (`sw`), or neither.  The ONE
    projection implementation — SurrogateManager._sx wraps it for host
    paths and pool_fn captures it in its jit closure, so the model and
    every query batch stay in the same representation by construction."""
    if sidx is not None:
        return feats[..., sidx]
    if sw is not None:
        return feats * sw
    return feats

# re-exported for callers that already import the manager; the source
# of truth is jax-import-free (see uptune_tpu/calibrated.py)
from ..calibrated import CALIBRATED_OPTS  # noqa: E402,F401


class SurrogateManager:
    def __init__(self, space: Space, kind: str = "gp", *,
                 min_points: int = 64, refit_interval: int = 64,
                 keep_quantile: float = 0.5, majority: float = 0.5,
                 explore_frac: float = 0.1, max_points: int = 1024,
                 n_members: int = 4, seed: int = 0,
                 hyper_fit: bool = True, select: str = "threshold",
                 keep_frac: float = 0.25, score: str = "lcb",
                 propose_batch: int = 0, propose_every: int = 2,
                 pool_mult: int = 32,
                 min_model_points: Optional[int] = None,
                 auto_passive: bool = True,
                 arbitration: str = "schedule",
                 propose_batch_parity: bool = True,
                 screen=None, screen_mode: str = "hard",
                 flip_bias: str = "none",
                 async_refit: bool = False, incremental: bool = True):
        if kind not in KINDS:
            raise ValueError(f"unknown surrogate {kind!r}; known: {KINDS}")
        if arbitration not in ("schedule", "bandit"):
            raise ValueError(f"unknown arbitration {arbitration!r}; "
                             f"known: schedule, bandit")
        if select not in ("threshold", "topk"):
            raise ValueError(f"unknown select mode {select!r}")
        if score not in ("lcb", "ei"):
            raise ValueError(f"unknown score {score!r}; known: lcb, ei")
        # select='threshold': drop candidates predicted worse than the
        # keep_quantile of history (the reference's multivoting,
        # api.py:307-326).  select='topk': keep only the best keep_frac
        # of each BATCH by acquisition score — BO-style concentration,
        # much more selective than an absolute threshold when the
        # proposal stream is already decent.
        self.select = select
        self.keep_frac = keep_frac
        # score='lcb' ranks candidates by mean - 2*std; 'ei' by expected
        # improvement over the incumbent — better calibrated exploration
        # when topk concentration is aggressive (keep_frac < 0.5)
        self.score_kind = score
        # propose_batch > 0 turns on the surrogate PROPOSAL plane: every
        # `propose_every`-th acquisition the manager emits its own
        # EI-maximizing batch from an oversampled pool (see propose_pool)
        # instead of only filtering technique batches
        self.propose_batch = propose_batch
        self.propose_every = propose_every
        # arbitration='schedule': the plane fires every propose_every-th
        # acquisition unconditionally (plus the run-budget passivation
        # rule).  arbitration='bandit': the plane is a credit-earning
        # VIRTUAL ARM in the driver's AUC bandit — pulled when its AUC
        # score wins, starved when its pulls stop producing new bests.
        # Self-correcting where the schedule is unconditional; measured
        # tradeoff in BENCHREPORT.md ("Bandit-arbitrated plane").
        # Passivation stays orthogonal: the run-budget rule gates
        # whether the plane is ACTIVE, arbitration only decides WHEN an
        # active plane pulls.
        self.arbitration = arbitration
        # Under bandit arbitration the pool batch is raised by the
        # driver to the median technique-arm batch (pull-size parity,
        # propose_batch_parity=False opts out).  Measured (r4,
        # exp_bandit_batch.jsonl): 8-eval pool pulls inflate the AUC
        # use_count ~4x faster per evaluation than ~32-eval technique
        # batches, so once new bests thin out near the optimum the
        # exploration term sqrt(2*log2(n)/use_count) ranks the plane
        # last exactly when its refinement would finish the run —
        # rosenbrock-4d censored 4/10 at batch 8, 4/10 at 16, 2/10 at
        # 32 (median 2436 -> 1470 -> 414 vs scheduled 346).
        self.propose_batch_parity = propose_batch_parity
        self.pool_mult = pool_mult
        self._pool_jit = None
        self.space = space
        self.kind = kind
        self.min_points = min_points
        self.refit_interval = refit_interval
        self.keep_quantile = keep_quantile
        self.majority = majority
        self.explore_frac = explore_frac
        self.max_points = max_points
        self.n_members = n_members
        self._xs: list = []
        self._ys: list = []
        self._since_fit = 0
        self._key = jax.random.PRNGKey(seed)

        # --- versioned snapshot plane (docs/PERF.md "Async surrogate
        # plane").  Scoring paths read `self._snap` exactly once per
        # call; learning publishes whole SurrogateSnapshot objects by
        # rebinding it under `_pub_lock` (the lock orders concurrent
        # publishers — the background refit worker vs the driver
        # thread's incremental extensions — readers stay lock-free).
        #
        # async_refit=True moves the O(N^3) full fit (and the fit_auto
        # hyperparameter sweep) onto a single background worker thread:
        # maybe_refit() SUBMITS at the cadence and returns immediately,
        # the worker publishes when ready, and the driver tell path
        # never blocks on learning.  Donation/dispatch stays on the
        # refit thread (JAX dispatch is thread-safe).  force_refit() is
        # forced-sync in both modes — warm-start/preload callers (PR 4)
        # rely on guidance from the very next acquisition.
        #
        # incremental=True keeps the published model FRESH between full
        # refits: each new observation extends the cached Cholesky
        # factor in O(N^2) inside the padded bucket (gp.extend), with
        # full fit_auto hyperparameter re-selection demoted to the
        # refit_interval cadence.
        self.async_refit = bool(async_refit)
        self.incremental = bool(incremental)
        # rank-1 extensions folded per maybe_refit tick: each row is
        # one O(N^2) jitted dispatch (~ms), and a backlog accumulated
        # while a background fit ran would otherwise land on a single
        # tell — the cap amortizes it across ticks at a bounded per-tell
        # cost; the cadence-driven full refit clears any residual lag
        self._ext_per_tick = 8
        # a single device SERIALIZES executions: a background fit
        # running on the driver's device would make every driver
        # dispatch queue behind it — overlap in wall-clock but not on
        # the device.  With >1 local device the fit plane claims the
        # LAST one (driver programs live on device 0) and the published
        # state is copied back to device 0, so scoring never crosses
        # devices; on a 1-device platform fits share the device and the
        # async win reduces to hiding fits behind host/build time
        devs = jax.local_devices()
        self._refit_device = (devs[-1] if self.async_refit
                              and len(devs) > 1 else None)
        self._snap: Optional[SurrogateSnapshot] = None
        self._pub_lock = threading.Lock()
        self._version = 0
        self._refit_exec = None       # lazy single-worker executor
        self._refit_future = None
        self.refits_started = 0       # full fits launched (sync + bg)
        self.refits = 0               # full fits published
        self.incr_updates = 0         # rank-1 extensions applied
        self.t_refit_last = 0.0       # s of the last BLOCKING full fit
        self.t_refit_total = 0.0      # cumulative blocking-fit seconds
        self.t_refit_bg_total = 0.0   # cumulative background-fit seconds

        # surrogate feature representation (Space.surrogate_transform):
        # numeric lanes snapped to their decoded grid, categorical lanes
        # one-hot — static split point for the GP's mixed
        # Matérn×exponential-Hamming kernel (VERDICT r3 next-step #2).
        # An optional FeatureScreen (surrogate/screen.py) restricts the
        # MODEL's view to the lanes that measurably moved QoR on other
        # payloads of the same space (cross-payload transfer, r4 verdict
        # next-step #3): every transform below is followed by the
        # projection, and the kernel split becomes the screened one.
        # The search techniques still propose in the FULL space — only
        # the surrogate narrows.  A dict form defers construction to
        # here, where the space exists: {"archives": [paths],
        # "top_cont": int, "top_cat": int} (the CLI's
        # --surrogate-screen flag arrives this way).
        if isinstance(screen, dict):
            from .screen import screen_from_archives
            paths = list(screen.get("archives", ()))
            screen = screen_from_archives(
                space, paths,
                top_cont=screen.get("top_cont", 16),
                top_cat=screen.get("top_cat", 24))
            if screen is None and paths:
                # a requested screen must never degrade silently: the
                # user would attribute the run's numbers to a transfer
                # that never engaged (r5 review)
                import warnings
                warnings.warn(
                    f"--surrogate-screen: none of {len(paths)} "
                    f"archive(s) contributed rows (missing, empty, or "
                    f"<4 usable trials) — running UNSCREENED",
                    UserWarning)
        if screen_mode not in ("hard", "soft"):
            raise ValueError(f"unknown screen_mode {screen_mode!r}; "
                             f"known: hard, soft")
        if flip_bias not in ("none", "online"):
            raise ValueError(f"unknown flip_bias {flip_bias!r}; "
                             f"known: none, online")
        # flip_bias='online': at each refit, rank categorical groups by
        # |Pearson r| against QoR over THIS RUN's own observations and
        # bias the pool's flip moves toward them (75% sensitivity mass,
        # 25% uniform).  The self-measured cousin of the cross-payload
        # screen's flip weighting — it guides the plane's bold moves
        # without narrowing the model's view (the gcc-real mechanism:
        # bold exploration wins there, so steer the boldness).
        self.flip_bias = flip_bias
        self._online_cat_w = None
        self.screen = screen
        self.screen_mode = screen_mode
        self._screen_idx = None
        self._screen_w = None
        self._n_cont = space.n_cont_features
        self._n_cat = space.n_cat
        # scalar categorical lanes backing the model's cat groups, in
        # group order — the online flip-bias maps refit-time group
        # sensitivities back onto flip probabilities through this
        self._cat_groups = np.arange(space.n_cat)
        if screen is not None:
            if screen_mode == "hard":
                # hard restriction: the model sees only the top-k lanes
                self._n_cont = int(screen.n_cont)
                self._n_cat = int(screen.n_cat)
                self._screen_idx = jnp.asarray(screen.idx, jnp.int32)
                if screen.n_cat and space.cat_max_codes:
                    cat_part = np.asarray(
                        screen.idx[screen.n_cont:], np.int64)
                    self._cat_groups = np.unique(
                        (cat_part - space.n_cont_features)
                        // space.cat_max_codes)
            else:
                # soft ARD: full width, per-lane sensitivity scaling —
                # dead lanes' distances shrink instead of being cut
                self._screen_w = jnp.asarray(screen.lane_weight,
                                             jnp.float32)

        # Two activity guards, both measured (BENCHREPORT "Why the
        # surrogate does not beat the bandit on gcc-real"):
        #
        # * `min_model_points` — observation gate: below this many
        #   points the manager observes and fits but neither prunes nor
        #   proposes.  Defaults to min_points (inert) — gating on
        #   observations alone COSTS evals where guidance from 16
        #   points already pays (gcc-options: 5-seed gated median 1553
        #   vs 1046.5 ungated); it exists as an explicit knob.
        # * `passive` — run-budget rule, set by the driver/controller
        #   when the EVAL BUDGET is smaller than the parameter count
        #   (`auto_passive=False` opts out): on an 80-eval run over 328
        #   params, in-loop guidance displaced scarce bandit diversity
        #   (1.49x iters on gcc-real); on a 6000-eval run over 200
        #   params the same guidance wins 0.33x.  The budget, not the
        #   dimension alone, is the discriminating variable.
        self.min_model_points = (min_points if min_model_points is None
                                 else min_model_points)
        self.auto_passive = auto_passive
        self.passive = False

        # The training bucket grows with N (powers of two up to
        # max_points), and every program whose input carries the padded
        # training state re-traces at each new bucket.  That is the
        # DESIGN (one compile per bucket, never one per N) — but a
        # single shape-polymorphic wrapper would read as retrace churn
        # to a TraceGuard, and lazily building wrappers after their
        # code object traced counts as rebuild churn.  So every
        # bucket-shaped program gets a per-bucket wrapper FLEET, built
        # up-front: each wrapper traces exactly once and
        # UT_TRACE_GUARD=strict stays clean over a whole tune (the
        # bucketed-fit_auto half of ISSUE 5; gp.fit_auto_bucketed is
        # the same idea for standalone callers).
        buckets, b = {self.max_points}, 1
        while b < self.max_points:
            buckets.add(b)
            b *= 2
        self._buckets = sorted(buckets)
        self._ext_jit: dict = {}
        if kind == "gp":
            nc, ncat = self._n_cont, self._n_cat
            if hyper_fit:
                self._fit_jit = {
                    bb: jax.jit(lambda x, y, mask: gp_mod.fit_auto(
                        x, y, mask, n_cont=nc, n_cat=ncat))
                    for bb in self._buckets}
            else:
                self._fit_jit = {
                    bb: jax.jit(lambda x, y, mask: gp_mod.fit(
                        x, y, mask=mask, n_cont=nc, n_cat=ncat))
                    for bb in self._buckets}
            self._score_jit = {
                bb: jax.jit(lambda st, xq: gp_mod.lower_confidence_bound(
                    st, xq, n_cont=nc, n_cat=ncat))
                for bb in self._buckets}
            self._score_ei_jit = {
                bb: jax.jit(lambda st, xq, b: gp_mod.expected_improvement(
                    st, xq, b, n_cont=nc, n_cat=ncat))
                for bb in self._buckets}
            # predictive moments for the tuning journal's calibration
            # join (ISSUE 12): one wrapper per bucket, built up-front
            # like every other fleet so strict trace accounting stays
            # clean — each traces once, on its first journaled ticket
            self._pred_jit = {
                bb: jax.jit(lambda st, xq: gp_mod.predict(
                    st, xq, n_cont=nc, n_cat=ncat))
                for bb in self._buckets}
            if self.incremental:
                self._ext_jit = {
                    bb: jax.jit(lambda st, xr, yr, sl: gp_mod.extend(
                        st, xr, yr, sl, n_cont=nc, n_cat=ncat))
                    for bb in self._buckets}
        else:
            # the mlp ensemble's PARAMS are bucket-independent (only
            # training consumes the padded set), so scoring keeps one
            # wrapper; the fit still gets a per-bucket fleet
            self._fit_jit = {
                bb: jax.jit(lambda k, x, y, mask: mlp_mod.fit(
                    k, x, y, n_members=n_members, mask=mask))
                for bb in self._buckets}
            self._score = jax.jit(mlp_mod.predict_members)

            def _mlp_moments(st, xq):
                preds = mlp_mod.predict_members(st, xq)
                return preds.mean(axis=0), preds.std(axis=0)

            # ensemble params are bucket-independent: one moments
            # wrapper serves every bucket (same rule as _score)
            one_pred = jax.jit(_mlp_moments)
            self._pred_jit = {bb: one_pred for bb in self._buckets}

    # ------------------------------------------------------------------
    def _sx(self, feats):
        """Space features -> surrogate representation, screened when a
        FeatureScreen is installed (observe, the prune mask, and the
        proposal pool all route through _screen_feats)."""
        return _screen_feats(self.space.surrogate_transform(feats),
                             self._screen_idx, self._screen_w)

    @property
    def n_points(self) -> int:
        return len(self._ys)

    @property
    def fitted(self) -> bool:
        return self._snap is not None

    # legacy accessors: the pre-snapshot attributes, now views of the
    # published snapshot (tests and downstream tooling read _state)
    @property
    def _state(self):
        s = self._snap
        return None if s is None else s.state

    @property
    def _threshold(self) -> Optional[float]:
        s = self._snap
        return None if s is None else s.threshold

    @property
    def _best_y(self) -> Optional[float]:
        s = self._snap
        return None if s is None else s.best_y

    @property
    def _use_kinv(self) -> bool:
        """Attach the premasked K^-1 at publish iff pools are large
        enough for the fused Pallas variance path (r5 review: once per
        refit, never per scoring call).  Evaluated per fit because the
        driver's bandit pull-size parity may raise propose_batch after
        construction (before the first fit, so the published pytree
        structure stays stable across a run)."""
        return (self.kind == "gp" and self.propose_batch
                * self.pool_mult >= pallas_score.PALLAS_MIN_POOL)

    @property
    def snapshot_version(self) -> int:
        """Monotonic publication counter (0 = never fitted)."""
        s = self._snap
        return 0 if s is None else s.version

    @property
    def refit_lag_rows(self) -> int:
        """Staleness bound: observed training rows the published
        snapshot has not conditioned on yet (= n_points when unfitted).
        Bounded by refit_interval + the rows observed while one
        background fit runs; 0 whenever incremental extension keeps
        up."""
        s = self._snap
        return self.n_points - (0 if s is None else s.n_rows)

    def observe(self, feats: np.ndarray, qor: np.ndarray) -> None:
        """Record evaluated (features, engine-oriented QoR) rows.
        `feats` is the Space.features() representation (what the driver
        hands over); it is re-encoded to the surrogate representation
        (snapped numeric lanes + one-hot categoricals) on the way in."""
        sf = np.asarray(self._sx(jnp.asarray(feats, jnp.float32)))
        for f, q in zip(sf, np.asarray(qor)):
            self._xs.append(np.asarray(f, np.float32))
            self._ys.append(float(q))
            self._since_fit += 1

    def maybe_refit(self) -> bool:
        """Advance the learning plane one tick.  Sync mode: run the full
        fit inline when the cadence is due (the pre-PR-5 behavior).
        Async mode: SUBMIT the full fit to the background worker and
        return immediately — the worker publishes the snapshot when
        ready.  In both modes, observations past the published
        watermark are folded in via O(N^2) incremental Cholesky
        extension (gp.extend) so scoring stays fresh between full fits.
        Returns True iff a full fit was PUBLISHED during this call."""
        published = self._poll_refit()
        if self.n_points >= self.min_points:
            due = self._refit_future is None and (
                not self.fitted or self._since_fit >= self.refit_interval)
            if due:
                args = self._refit_args()
                if self.async_refit:
                    if self._refit_exec is None:
                        from concurrent.futures import ThreadPoolExecutor
                        self._refit_exec = ThreadPoolExecutor(
                            max_workers=1,
                            thread_name_prefix="ut-surrogate-refit")
                    obs.event("surrogate.submit", n_rows=self.n_points)
                    self._refit_future = self._refit_exec.submit(
                        self._refit_full, *args, background=True)
                else:
                    self._refit_full(*args)
                    published = True
        if self.fitted and not published and self._refit_future is None:
            # no extension while a fit is in flight: the submitted fit
            # already covers those rows (marginal freshness), and even
            # on a dedicated refit device the CPU execution threadpool
            # is shared — measured ~30-100 ms/row queueing behind the
            # running fit, exactly the tell-path stall the plane
            # removes.  Post-submission rows fold in (capped per tick)
            # from the tick after publish.
            self._maybe_extend()
        return published

    def _refit_args(self):
        """Snapshot the training set + keys on the CALLER's thread so a
        background fit sees a frozen watermark (rows observed after
        submission belong to the next fit / the incremental path) and
        the key stream stays identical between sync and async modes."""
        self.refits_started += 1
        self._since_fit = 0
        self._key, ks, kf = jax.random.split(self._key, 3)
        return (np.stack(self._xs),
                np.asarray(self._ys, np.float32), ks, kf)

    def fit_bucket(self, n: Optional[int] = None) -> int:
        """The padded training bucket a full fit over `n` rows (default:
        the current training set) compiles for: power-of-two, capped at
        max_points, with one refit_interval of padding headroom
        reserved so incremental extension has slots to fold new rows
        into even when n lands exactly on a power of two."""
        n = min(self.n_points if n is None else n, self.max_points)
        headroom = (self.refit_interval
                    if self.incremental and self.kind == "gp" else 0)
        target = min(n + headroom, max(self.max_points, n))
        return gp_mod.bucket_of(target, self.max_points)

    @staticmethod
    def _host_subsample(xs_np, ys_np, ks, max_points):
        """gp.subsample's best-biased draw, in HOST numpy: keep the best
        half deterministically, fill the rest at random (seeded off the
        fit key).  On host because it runs before every full fit with a
        DIFFERENT n — the device version's internal ops would re-trace
        per n on the refit worker, and that Python-heavy tracing holds
        the GIL against the driver thread (the stall the async plane
        exists to remove)."""
        n = len(ys_np)
        if n <= max_points:
            return xs_np, ys_np
        n_best = max_points // 2
        order = np.argsort(ys_np)
        rest = order[n_best:]
        rng = np.random.RandomState(int(np.asarray(ks)[-1]) & 0x7fffffff)
        pick = rng.choice(len(rest), max_points - n_best, replace=False)
        idx = np.concatenate([order[:n_best], rest[pick]])
        return xs_np[idx], ys_np[idx]

    def _refit_full(self, xs_np, ys_np, ks, kf,
                    background: bool = False) -> None:
        """The full fit: host-side subsample + zero-pad to the bucket
        (numpy — no device dispatch, no per-n tracing), then ONE jitted
        program per bucket (fit_auto hyperparameter sweep when
        hyper_fit), then publish one immutable snapshot."""
        t0 = time.perf_counter()
        # the fit span lands on the CALLING thread's lane: the refit
        # worker under async_refit (rendering as its own Perfetto lane
        # overlapping driver ticket spans), the driver thread for
        # forced-sync fits
        sp_obs = obs.span("surrogate.fit", background=background,
                          n_rows=len(ys_np))
        sp_obs.__enter__()
        try:
            self._refit_full_body(xs_np, ys_np, ks, kf, background,
                                  t0, sp_obs)
        finally:
            # a failed fit (the PR 5 warn + re-arm path) must still
            # close its span: the refit-worker lane has to show WHERE
            # the time went, not go blank on the runs being debugged
            sp_obs.__exit__(None, None, None)

    def _refit_full_body(self, xs_np, ys_np, ks, kf, background,
                         t0, sp_obs) -> None:
        n_total = len(ys_np)
        xs_sub, ys_sub = self._host_subsample(xs_np, ys_np,
                                              ks, self.max_points)
        n = len(ys_sub)
        bucket = self.fit_bucket(n_total)
        pad = bucket - n
        xp = np.concatenate(
            [xs_sub, np.zeros((pad, xs_sub.shape[1]), np.float32)])
        yp = np.concatenate([ys_sub, np.zeros(pad, np.float32)])
        mp = np.concatenate(
            [np.ones(n, np.float32), np.zeros(pad, np.float32)])
        dev = self._refit_device
        if dev is not None:
            x = jax.device_put(xp, dev)
            y = jax.device_put(yp, dev)
            mask = jax.device_put(mp, dev)
            kf = jax.device_put(kf, dev)
        else:
            x, y, mask = jnp.asarray(xp), jnp.asarray(yp), jnp.asarray(mp)
        fit = self._fit_jit[bucket]
        if self.kind == "gp":
            state = fit(x, y, mask)
            if self._use_kinv:
                # large pools score through the fused Pallas variance
                # path; attach the premasked K^-1 ONCE per publish
                # rather than once per pool pull (r5 review)
                state = gp_mod.precompute_kinv(state)
        else:
            state = fit(kf, x, y, mask)
        if dev is not None:
            # bring the fitted state home to the driver's device so
            # scoring/extension never execute on (or transfer from) the
            # refit device; O(bucket^2) bytes, trivial next to the fit
            state = jax.device_put(state, jax.local_devices()[0])
        # a published snapshot must be DONE computing: the first reader
        # on the driver thread must never pay this fit's device work
        state = jax.block_until_ready(state)
        finite = ys_np[np.isfinite(ys_np)]
        thr = (float(np.quantile(finite, self.keep_quantile))
               if len(finite) else None)
        besty = float(finite.min()) if len(finite) else None
        if self.flip_bias == "online" and self._n_cat:
            # per-group |Pearson r| over this run's own rows -> flip
            # weights on the backing scalar lanes (see __init__)
            from .screen import lane_sensitivity
            scores = lane_sensitivity(xs_np, ys_np.astype(np.float64))
            width = self.space.cat_max_codes
            gs = scores[self._n_cont:].reshape(
                self._n_cat, width).max(axis=1)
            w = np.zeros(self.space.n_scalar)
            lanes = np.asarray(self.space.cat_lane_idx)[self._cat_groups]
            w[lanes] = gs / gs.max() if gs.max() > 0 else 1.0
            self._online_cat_w = w
        with self._pub_lock:
            self._version += 1
            self._snap = SurrogateSnapshot(
                state, self._version, n_total, thr, besty,
                exact=n_total <= self.max_points, in_bucket=n)
            self.refits += 1
        obs.event("surrogate.publish", version=self._version,
                  n_rows=n_total, bucket=bucket)
        obs.gauge("surrogate.refits_published", self.refits)
        if obs.journal.enabled():
            obs.journal.emit("snapshot", version=self._version,
                             n_rows=int(n_total), bucket=int(bucket))
        ext = self._ext_jit.get(bucket)
        if ext is not None and n < bucket and n_total <= self.max_points:
            # warm the extension wrapper for THIS bucket on the refit
            # thread (throwaway call, result discarded): its first-use
            # trace+compile otherwise lands on whichever driver tell
            # next folds a row in — the exact latency spike the async
            # plane exists to remove
            jax.block_until_ready(ext(
                state, jnp.zeros(state.x.shape[1], jnp.float32),
                jnp.float32(besty if besty is not None else 0.0),
                jnp.int32(n)))
        dt = time.perf_counter() - t0
        sp_obs.set(bucket=bucket)
        if background:
            self.t_refit_bg_total += dt
        else:
            self.t_refit_last = dt
            self.t_refit_total += dt

    def _poll_refit(self) -> bool:
        """Consume a FINISHED background fit without blocking: True when
        one published since the last poll.  A failed fit warns and
        re-arms the cadence so the next tick retries."""
        f = self._refit_future
        if f is None or not f.done():
            return False
        self._refit_future = None
        exc = f.exception()
        if exc is None:
            return True
        import warnings
        warnings.warn(
            f"background surrogate refit failed: {exc!r}; the last "
            f"published snapshot stays live, retrying at the next "
            f"cadence", RuntimeWarning)
        self._since_fit = max(self._since_fit, self.refit_interval)
        return False

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until any in-flight background refit has published (or
        failed); True when nothing is left in flight.  The sync
        barrier: tests pin publication points with it, Tuner.close()
        uses it so no worker outlives the run, and bench protocols call
        it between matched-seed phases."""
        f = self._refit_future
        if f is None:
            return True
        from concurrent.futures import TimeoutError as _FTimeout
        try:
            f.exception(timeout)   # waits; does not raise the fit's exc
        except _FTimeout:
            return False
        self._poll_refit()
        return True

    def close(self) -> None:
        """Let an in-flight background refit publish, then shut the
        worker thread down.  Without this each async manager leaves one
        idle non-daemon 'ut-surrogate-refit' thread for the process
        lifetime; maybe_refit() lazily recreates the executor if the
        manager is used again."""
        self.drain()
        if self._refit_exec is not None:
            self._refit_exec.shutdown(wait=True)
            self._refit_exec = None

    def _maybe_extend(self) -> int:
        """Fold observations past the published watermark into the
        snapshot via rank-1 Cholesky extension: O(N^2) per row inside
        the padded bucket (static shapes — the per-bucket wrapper from
        __init__ traces once), at the hyperparameters and target
        standardization of the last full fit.  Skipped when the last
        fit subsampled (row slots no longer align) or the bucket is
        full; the cadence-driven full refit covers those regimes.
        Returns the rows folded in."""
        snap = self._snap
        if (not self.incremental or self.kind != "gp" or snap is None
                or not snap.exact):
            return 0
        n = self.n_points
        bucket = int(snap.state.x.shape[0])
        fn = self._ext_jit.get(bucket)
        if n <= snap.n_rows or snap.in_bucket >= bucket or fn is None:
            return 0
        ys = self._ys
        worst = max((v for v in ys if np.isfinite(v)), default=None)
        if worst is None:
            return 0
        st, rows, i = snap.state, 0, snap.n_rows
        t0_obs = time.perf_counter()
        while i < n and snap.in_bucket + rows < bucket \
                and rows < self._ext_per_tick:
            q = ys[i] if np.isfinite(ys[i]) else worst
            st = fn(st, jnp.asarray(self._xs[i], jnp.float32),
                    jnp.float32(q), jnp.int32(snap.in_bucket + rows))
            rows += 1
            i += 1
        fin = np.asarray([v for v in ys[:i] if np.isfinite(v)],
                         np.float32)
        thr = (float(np.quantile(fin, self.keep_quantile))
               if len(fin) else None)
        besty = float(fin.min()) if len(fin) else None
        with self._pub_lock:
            if self._snap is not snap:
                # a background full fit published mid-extension: it is
                # the newer model (fresh hyperparameters) — discard the
                # extension; the next tick re-extends from ITS watermark
                return 0
            self._version += 1
            self._snap = snap._replace(
                state=st, version=self._version, n_rows=i,
                threshold=thr, best_y=besty,
                in_bucket=snap.in_bucket + rows)
        self.incr_updates += rows
        if rows:
            obs.complete_span("surrogate.extend", t0=t0_obs,
                              dur=time.perf_counter() - t0_obs,
                              rows=rows, version=self._version)
        return rows

    def force_refit(self) -> bool:
        """Fit NOW if the point count allows, ignoring the
        `refit_interval` cadence — the warm-start hook: after a bulk
        ingestion of stored trials the model should guide from the very
        first live acquisition instead of waiting out the online
        cadence.  Forced-SYNC even under async_refit (after draining
        any in-flight background fit): PR 4 preload semantics and
        exact replay depend on the model being ready on return."""
        self.drain()
        self._since_fit = max(self._since_fit, self.refit_interval)
        if self.n_points < self.min_points:
            return False
        self._refit_full(*self._refit_args())
        return True

    def warm_start(self, feats: np.ndarray, qor: np.ndarray) -> bool:
        """Bulk-ingest externally-recorded (features, engine-oriented
        QoR) rows — the results store's cross-tune training set
        (docs/STORE.md) — and fit immediately.  Returns True when the
        model came out fitted."""
        self.observe(feats, qor)
        return self.force_refit()

    def _flip_probs(self) -> jax.Array:
        """[n_scalar] per-lane probability weights for the pool's
        categorical flip moves: uniform by default; with an online
        flip-bias or a transferred screen, 75% of the mass follows the
        sensitivity ranking and 25% stays uniform so every flag remains
        reachable."""
        space = self.space
        n_cat = space.n_cat
        u = np.zeros(space.n_scalar)
        if n_cat:
            u[np.asarray(space.cat_lane_idx)] = 1.0 / n_cat
        w = None
        if self.flip_bias == "online":
            w = self._online_cat_w
        elif self.screen is not None:
            w = self.screen.cat_weight
        if w is None or not n_cat or float(np.sum(w)) <= 0:
            return jnp.asarray(u, jnp.float32)
        w = np.asarray(w, np.float64) / float(np.sum(w))
        return jnp.asarray(0.75 * w + 0.25 * u, jnp.float32)

    def predict_cands(self, cands: CandBatch):
        """Predictive moments for a candidate batch against the
        CURRENT published snapshot: ``(mu [B], sd [B], version)`` as
        host numpy arrays (engine-oriented targets), or None when not
        fitted.  The tuning journal's calibration join (ISSUE 12): the
        driver records these at propose time and joins them with the
        measured QoR at tell — call sites gate on the journal flag, so
        an unjournaled run never pays the extra dispatch."""
        snap = self._snap   # one atomic snapshot read (see keep_mask)
        if snap is None:
            return None
        feats = self._sx(self.space.features(cands))
        bucket = (int(snap.state.x.shape[0]) if self.kind == "gp"
                  else self._buckets[0])
        mu, sd = self._pred_jit[bucket](snap.state, feats)
        return np.asarray(mu), np.asarray(sd), snap.version

    # ------------------------------------------------------------------
    def keep_mask(self, cands: CandBatch,
                  candidate_mask: Optional[np.ndarray] = None
                  ) -> Optional[np.ndarray]:
        """[B] bool host mask: True = evaluate. None when not fitted.
        `candidate_mask` marks the rows actually eligible for evaluation
        (novel, non-pending); topk ranks ONLY among those — otherwise
        already-evaluated duplicate rows could fill every top-k slot and
        starve the novel candidates."""
        # ONE read of the published snapshot: state/threshold/incumbent
        # travel together, so a concurrent background publish can never
        # mix model versions inside a single scoring call
        snap = self._snap
        if snap is None or snap.threshold is None:
            return None
        if self.passive or self.n_points < self.min_model_points:
            return None     # guards: see __init__
        feats = self._sx(self.space.features(cands))
        preds = None
        use_ei = (self.select == "topk" and self.score_kind == "ei"
                  and snap.best_y is not None)
        if self.kind == "gp":
            bucket = int(snap.state.x.shape[0])
            if use_ei:
                score = -np.asarray(self._score_ei_jit[bucket](
                    snap.state, feats, jnp.float32(snap.best_y)))
            else:
                score = np.asarray(self._score_jit[bucket](
                    snap.state, feats))
        else:
            preds = np.asarray(self._score(snap.state, feats))  # [E, B]
            score = preds.mean(axis=0)
            if use_ei:
                score = -np.asarray(gp_mod.ei_from_moments(
                    score, preds.std(axis=0), snap.best_y))
        if self.select == "topk":
            b = score.shape[0]
            if candidate_mask is not None:
                n_elig = int(np.asarray(candidate_mask).sum())
                score = np.where(candidate_mask, score, np.inf)
            else:
                n_elig = b
            k = max(1, int(round(n_elig * self.keep_frac)))
            keep = np.zeros(b, bool)
            if n_elig:
                keep[np.argsort(score)[:min(k, n_elig)]] = True
        elif self.kind == "gp":
            keep = score <= snap.threshold
        else:
            votes = (preds <= snap.threshold).mean(axis=0)
            keep = votes >= self.majority
        b = keep.shape[0]
        self._key, ke = jax.random.split(self._key)
        explore = np.asarray(
            jax.random.uniform(ke, (b,))) < self.explore_frac
        if candidate_mask is not None:
            explore = explore & np.asarray(candidate_mask)
        return keep | explore

    # ------------------------------------------------------------------
    # surrogate proposal plane: EI-maximizing batches from an oversampled
    # pool.  Where keep_mask only FILTERS technique batches (the
    # reference's multivoting role), this is BO-style acquisition
    # maximization over a discrete candidate set — scoring thousands of
    # pool points is nearly free on TPU, so the evaluated batch
    # concentrates on the acquisition optimum instead of the best half of
    # whatever one technique happened to propose.
    def _build_pool_fn(self):
        space = self.space
        n_out = self.propose_batch
        pool = max(n_out * self.pool_mult, n_out)
        n_rand = max(pool // 4, 1)       # global exploration share
        n_local = pool - n_rand          # cloud around the incumbent
        # local rows split across three move families, sized by what the
        # space actually contains:
        #   dense   — multi-scale Gaussian on NUMERIC lanes only
        #             (continuous refinement; categorical lanes pinned —
        #             a Gaussian step on a tri-state lane either rounds
        #             back to the incumbent code or is a blind jump)
        #   flip    — 1..k CATEGORICAL lanes re-drawn to a DIFFERENT
        #             code, numeric lanes pinned: mutation in flag space,
        #             the move that carries real compiler-flag tuning
        #             (VERDICT r3 next-step #2)
        #   sparse  — a few lanes of ANY kind re-drawn uniformly
        #             (escape hatch / mixed moves)
        n_num = space.n_scalar - space.n_cat
        if space.n_cat == 0:
            n_dense = n_local // 2
            n_flip = 0
        elif n_num == 0:
            n_dense = 0
            n_flip = n_local // 2
        else:
            n_dense = n_local // 3
            n_flip = n_local // 3
        n_sparse = n_local - n_dense - n_flip
        cat_row = jnp.zeros(space.n_scalar).at[
            jnp.asarray(space.cat_lane_idx, jnp.int32)].set(1.0) \
            if space.n_cat else jnp.zeros(space.n_scalar)
        max_flips = max(2, space.n_cat // 8)
        kind = self.kind
        score_ei = self.score_kind == "ei"
        nc, ncat = self._n_cont, self._n_cat
        sidx = self._screen_idx
        sw = self._screen_w
        # at PALLAS_MIN_POOL+ candidates the [pool, N] cross-kernel is
        # the acquisition hot spot; the fused acquisition pipeline
        # (ops/acquire.py) scores it, applies EI/LCB and selects the
        # n_out winners tile-by-tile without materializing [pool, N]
        # or even the [pool] score vector in HBM.  Routing (UT_PALLAS
        # knob, ops/routing.py) is decided HERE at build time — pool
        # is static — so the jitted pool_fn contains exactly one
        # implementation; XLA-routed pools keep the legacy
        # materialized scoring below, bit-identical to before.
        # cpu_ok=False: auto keeps the legacy path on CPU (the
        # interpret-mode emulation measures slower than it — the
        # ops/acquire.py routing note); UT_PALLAS=interpret still
        # forces the kernel route for parity drives.
        from ..ops import acquire, routing
        from ..ops import perm as perm_ops
        route = (routing.decide(pool,
                                min_rows=pallas_score.PALLAS_MIN_POOL,
                                cpu_ok=False)
                 if kind == "gp" else routing.XLA)

        def pool_fn(state, key, best_u, best_perms, best_y, flip_p):
            kr, kn, ks, kp, km, kv, kw, kf1, kf2, kf3 = \
                jax.random.split(key, 10)
            rand = space.random(kr, n_rand)
            parts = []
            if n_dense:
                # dense: per-row radius log-uniform over [2^-9, 2^-1.5]
                # of the unit cube — a multi-scale cloud (coarse jumps
                # through fine local refinement) on numeric lanes;
                # categorical lanes stay at the incumbent's codes
                r = jnp.exp2(jax.random.uniform(
                    ks, (n_dense, 1), minval=-9.0, maxval=-1.5))
                noise = jax.random.normal(
                    kn, (n_dense, space.n_scalar)) * r * (1.0 - cat_row)
                parts.append(jnp.clip(best_u[None, :] + noise, 0.0, 1.0))
            if n_flip:
                # flip: per-row flip-count log-uniform in [1, max_flips];
                # selected categorical lanes move to a uniformly chosen
                # DIFFERENT code (offset 1..K-1 mod K), all other lanes
                # pinned — the tri-state flag flip
                nf = jnp.exp2(jax.random.uniform(
                    kf1, (n_flip, 1), minval=0.0,
                    maxval=float(np.log2(max_flips))))
                # per-lane probability nf * flip_p, clipped at 1 with
                # the truncated mass redistributed over eligible
                # lanes proportional to their HEADROOM (1 - p): with
                # flip BIAS a high-sensitivity lane can exceed 1 at
                # large nf, and silent saturation would deflate the
                # expected flip count below the nominal nf (ADVICE
                # r5).  Headroom-proportional shares can never
                # re-saturate a lane while headroom remains, so the
                # expected count is preserved EXACTLY whenever
                # over <= total headroom (else every eligible lane
                # saturates — the achievable maximum).  Unsaturated
                # rows pass through bitwise unchanged (over == 0).
                p_flip = nf * flip_p[None, :]
                over = jnp.clip(p_flip - 1.0, 0.0).sum(-1, keepdims=True)
                p_flip = jnp.minimum(p_flip, 1.0)
                room = jnp.where(flip_p[None, :] > 0, 1.0 - p_flip, 0.0)
                p_flip = jnp.minimum(
                    p_flip + over * room
                    / jnp.maximum(room.sum(-1, keepdims=True), 1e-9),
                    1.0)
                sel = (jax.random.uniform(kf2, (n_flip, space.n_scalar))
                       < p_flip) & (cat_row > 0)
                vals = space.decode_scalars(best_u)          # [D] codes
                ncodes = space.vhi + 1.0
                off = 1.0 + jnp.floor(
                    jax.random.uniform(kf3, (n_flip, space.n_scalar))
                    * jnp.maximum(space.vhi, 1.0))
                newc = jnp.mod(vals[None, :] + off, ncodes)
                flipped = jnp.where(sel, newc, vals[None, :])
                parts.append(space.encode_scalars(flipped))
            # sparse: per-row lane-selection rate log-uniform between
            # ~1 lane and a quarter of the lanes; selected lanes re-draw
            # uniformly, the rest stay at the incumbent
            d = max(space.n_scalar, 1)
            lo_rate = -float(np.log2(d))
            rate = jnp.exp2(jax.random.uniform(
                km, (n_sparse, 1),
                minval=lo_rate, maxval=max(-2.0, lo_rate)))
            flip = jax.random.uniform(kv, (n_sparse, d)) < rate
            parts.append(jnp.where(
                flip, jax.random.uniform(kw, (n_sparse, d)),
                best_u[None, :]))
            u_loc = jnp.concatenate(parts, axis=0)
            perms_loc = []
            for i, size in enumerate(space.perm_sizes):
                base = jnp.tile(best_perms[i][None, :], (n_local, 1))
                kp, k1, k2, k3 = jax.random.split(kp, 4)
                mut = perm_ops.small_random_change_batch(
                    k1, base, 2.0 / max(size, 2))
                shuf = perm_ops.shuffle_batch(jax.random.fold_in(k2, i),
                                              base)
                coin = jax.random.uniform(k3, (n_local, 1)) < 0.75
                perms_loc.append(
                    jnp.where(coin, mut, shuf).astype(jnp.int32))
            local = CandBatch(u_loc, tuple(perms_loc))
            cands = space.normalize(rand.concat(local))
            feats = _screen_feats(
                space.surrogate_transform(space.features(cands)),
                sidx, sw)
            if kind == "gp":
                if route != routing.XLA:
                    # fused score+acquisition+top-k in one device
                    # program; argsort(score) ascending == top-k of
                    # the (negated) utility, ties both resolved to
                    # the lowest candidate index
                    _, idx = acquire.acquire_topk(
                        state, feats, n_out,
                        kind=("ei" if score_ei else "lcb"),
                        best_y=best_y, beta=2.0,
                        n_cont=nc, n_cat=ncat, route=route)
                    return cands[idx]
                if score_ei:
                    score = -gp_mod.expected_improvement(
                        state, feats, best_y, n_cont=nc, n_cat=ncat)
                else:
                    score = gp_mod.lower_confidence_bound(
                        state, feats, n_cont=nc, n_cat=ncat)
            else:
                preds = mlp_mod.predict_members(state, feats)
                mu, sd = preds.mean(0), preds.std(0)
                if score_ei:
                    score = -gp_mod.ei_from_moments(mu, sd, best_y)
                else:
                    score = mu - 2.0 * sd
            idx = jnp.argsort(score)[:n_out]
            return cands[idx]

        return pool_fn

    def propose_pool(self, key, best_u, best_perms, best_y):
        """EI-maximizing CandBatch of `propose_batch` candidates, or None
        when disabled / not yet fitted / passive."""
        snap = self._snap   # one atomic snapshot read (see keep_mask)
        if self.propose_batch <= 0 or snap is None:
            return None
        if self.passive or self.n_points < self.min_model_points:
            return None     # guards: see __init__

        if self._pool_jit is None:
            # the whole per-bucket fleet is built at once, BEFORE any
            # wrapper traces (same trace-accounting rationale as the
            # __init__ fleets); the mlp state is bucket-independent so
            # one wrapper serves every bucket there
            fn = self._build_pool_fn()
            if self.kind == "gp":
                self._pool_jit = {bb: jax.jit(fn)
                                  for bb in self._buckets}
            else:
                one = jax.jit(fn)
                self._pool_jit = {bb: one for bb in self._buckets}
        bucket = (int(snap.state.x.shape[0]) if self.kind == "gp"
                  else self._buckets[0])
        return self._pool_jit[bucket](snap.state, key, best_u,
                                      best_perms,
                                      jnp.asarray(best_y, jnp.float32),
                                      self._flip_probs())
