"""Batched pseudo simulated annealing.

Reference: `/root/reference/python/uptune/opentuner/search/
simulatedannealing.py:11-136`.  One annealing chain over a linear cooling
schedule (temps 30 -> 0 over 100 intervals, looped); each round proposes
up/down neighbors of the current state (step scaled by
exp(-(20 + t/100)/(temp+1))), then accepts the `sel`-th best point where
sel is geometric with success probability exp(-1/temp) — plus a switch to
the global best when the temperature is effectively zero.

Batched: instead of enumerating two neighbors for every parameter (2·D
proposals), one step samples `batch` random (parameter, direction) moves —
the same neighborhood distribution at fixed batch shape.  The acceptance
rule is applied branchlessly over the sorted batch.
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..space.spec import CandBatch, Space
from .base import Best, Technique, register
from .common import mutate_perm_random_op


class SAState(NamedTuple):
    cur: CandBatch         # [1, ...] current chain state
    cur_qor: jax.Array     # scalar
    counter: jax.Array     # scalar i32, cooling-schedule position
    key: jax.Array         # acceptance-rule randomness


class PseudoAnnealingSearch(Technique):
    def __init__(self, batch: int = 32, t_hi: float = 30.0, t_lo: float = 0.0,
                 interval: int = 100, scaling: float = 50.0,
                 name: str = "PseudoAnnealingSearch"):
        super().__init__(name)
        self.batch = batch
        self.t_hi = t_hi
        self.t_lo = t_lo
        self.interval = interval
        self.scaling = scaling

    def natural_batch(self, space: Space) -> int:
        return self.batch

    def _temp(self, counter: jax.Array) -> jax.Array:
        """Linear 30 -> 0 schedule over `interval` steps, looping
        (simulatedannealing.py:22-33, 115-117)."""
        c = jnp.mod(counter, self.interval).astype(jnp.float32)
        return self.t_hi + (self.t_lo - self.t_hi) * c / self.interval

    def init_state(self, space: Space, key: jax.Array) -> SAState:
        kc, ka = jax.random.split(key)
        cur = space.random(kc, 1)
        return SAState(cur, jnp.asarray(jnp.inf), jnp.asarray(0, jnp.int32),
                       ka)

    def propose(self, space: Space, state: SAState, key: jax.Array,
                best: Best) -> Tuple[SAState, CandBatch]:
        n = self.batch
        kd, kdir, kstep, *kperm = jax.random.split(
            key, 3 + len(space.perm_sizes))
        temp = self._temp(state.counter)
        step = jnp.exp(-(20.0 + state.counter.astype(jnp.float32) / 100.0)
                       / (temp + 1.0))

        # each row perturbs one random parameter up or down by step*U(0,1)
        P = space.n_scalar + len(space.perm_sizes)
        which = jax.random.randint(kd, (n,), 0, P)
        direction = jnp.where(jax.random.uniform(kdir, (n, 1)) < 0.5, -1.0, 1.0)
        mag = step * jax.random.uniform(kstep, (n, 1))
        base_u = jnp.tile(state.cur.u, (n, 1))
        lane_sel = which[:, None] == jnp.arange(space.n_scalar)[None, :]
        u = jnp.clip(base_u + lane_sel * direction * mag, 0.0, 1.0)
        perms = []
        for k_i, kk in enumerate(kperm):
            pm = jnp.tile(state.cur.perms[k_i], (n, 1))
            sel = which == (space.n_scalar + k_i)
            perms.append(mutate_perm_random_op(kk, pm, sel))
        return state, space.normalize(CandBatch(u, tuple(perms)))

    def observe(self, space: Space, state: SAState, cands: CandBatch,
                qor: jax.Array, best: Best) -> SAState:
        temp = self._temp(state.counter)
        # sort the candidate pool (current state participates,
        # simulatedannealing.py:57-59)
        all_qor = jnp.concatenate([qor, state.cur_qor[None]])
        order = jnp.argsort(all_qor)
        # sel ~ geometric(p) with p = exp(-1/temp): number of coin successes
        # (simulatedannealing.py:105-109), computed in closed form
        p = jnp.exp(-1.0 / jnp.maximum(temp, 1e-6))
        ukey, knext = jax.random.split(state.key)
        usel = jax.random.uniform(ukey, ())
        sel = jnp.where(
            p > 1e-9,
            jnp.floor(jnp.log(jnp.maximum(usel, 1e-30)) /
                      jnp.log(jnp.maximum(p, 1e-30))).astype(jnp.int32),
            0)
        sel = jnp.mod(sel, all_qor.shape[0])
        pick = order[sel]
        B = qor.shape[0]

        def row(x_cands, x_cur):
            stacked = jnp.concatenate([x_cands, x_cur[None]], axis=0)
            return stacked[pick]

        new_u = row(cands.u, state.cur.u[0])
        new_perms = tuple(row(c, p[0])
                          for c, p in zip(cands.perms, state.cur.perms))
        new_qor = all_qor[pick]
        # switch to global best when frozen (simulatedannealing.py:111-113)
        frozen = (p < 1e-4) & (best.qor < new_qor)
        new_u = jnp.where(frozen, best.u, new_u)
        new_perms = tuple(jnp.where(frozen, b, p)
                          for b, p in zip(best.perms, new_perms))
        new_qor = jnp.where(frozen, best.qor, new_qor)
        return SAState(
            CandBatch(new_u[None, :], tuple(p[None, :] for p in new_perms)),
            new_qor, state.counter + 1, knext)


register(PseudoAnnealingSearch())
