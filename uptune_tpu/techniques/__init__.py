"""Batched search techniques (the reference's plugin registry, §2.2 of
SURVEY.md, re-designed as pure JAX state machines)."""
from .base import (Best, Technique, all_technique_names, get_root,
                   get_technique, register)

__all__ = ["Best", "Technique", "all_technique_names", "get_root",
           "get_technique", "register"]
