"""Uniform random search (`PureRandom`, reference
`/root/reference/python/uptune/opentuner/search/technique.py:177-182,303`).
Stateless: every step emits a fresh uniform batch."""
from __future__ import annotations

import jax

from ..space.spec import Space
from .base import Best, Technique, register


class PureRandom(Technique):
    def __init__(self, batch: int = 64, name: str = "PureRandom"):
        super().__init__(name)
        self.batch = batch

    def natural_batch(self, space: Space) -> int:
        return self.batch

    def init_state(self, space: Space, key: jax.Array):
        return ()

    def propose(self, space: Space, state, key: jax.Array, best: Best):
        return state, space.random(key, self.batch)

    def observe(self, space, state, cands, qor, best):
        return state


register(PureRandom())
