"""Meta-techniques: AUC multi-armed bandit and round-robin portfolios.

Reference: `/root/reference/python/uptune/opentuner/search/
bandittechniques.py` and `metatechniques.py`.

The bandit's decision ("which technique proposes next") is inherently host
control flow — it selects which jitted proposal program the driver launches
for the step.  The arms' state is tiny (a window-500 event deque), so it
stays host-side with the reference's exact semantics:

* exploitation = sliding-window AUC credit of was-new-best events
  (`AUCBanditQueue.exploitation_term_fast`, bandittechniques.py:116-146,
  O(1) incremental update with auc_sum/auc_decay);
* exploration = sqrt(2*log2(|history|) / use_count)
  (bandittechniques.py:41-48);
* score = exploit + C * explore, C=0.05, window=500 (:21).

Batched credit assignment: the reference credits one proposal at a time; a
batched step pushes ONE event per step — value = "this step's batch
produced a new global best".  This preserves the AUC ordering semantics
while each arm pull buys a whole candidate batch.

For the fully fused on-device tuning step (bench path), see
`uptune_tpu.engine.fused`: there every arm proposes each step and the
bandit weights determine the per-arm candidate counts.
"""
from __future__ import annotations

import math
import random as _pyrandom
from collections import deque
from typing import Dict, List, Optional, Sequence

from .base import Technique, register


class AUCBanditQueue:
    """Host-side exact port of the reference's AUC bandit credit queue."""

    def __init__(self, keys: Sequence[str], C: float = 0.05,
                 window: int = 500, seed: int = 0):
        self.C = C
        self.window = window
        self.keys = list(keys)
        self.history: deque = deque()
        self.use_counts: Dict[str, int] = {k: 0 for k in keys}
        self.auc_sum: Dict[str, float] = {k: 0.0 for k in keys}
        self.auc_decay: Dict[str, float] = {k: 0.0 for k in keys}
        self.rng = _pyrandom.Random(seed)

    def add_key(self, key: str) -> None:
        """Register a new arm mid-flight (used for virtual arms like the
        surrogate proposal plane).  Starts with zero pulls, so the
        exploration term is +inf and the bandit tries it promptly."""
        if key in self.use_counts:
            return
        self.keys.append(key)
        self.use_counts[key] = 0
        self.auc_sum[key] = 0.0
        self.auc_decay[key] = 0.0

    def exploitation_term(self, key: str) -> float:
        pos = self.use_counts[key]
        if not pos:
            return 0.0
        return self.auc_sum[key] * 2.0 / (pos * (pos + 1.0))

    def exploration_term(self, key: str) -> float:
        if self.use_counts[key] > 0 and len(self.history) > 1:
            return math.sqrt(2.0 * math.log2(len(self.history))
                             / self.use_counts[key])
        return float("inf")

    def bandit_score(self, key: str) -> float:
        return self.exploitation_term(key) + self.C * self.exploration_term(key)

    def ordered_keys(self) -> List[str]:
        """Best-scoring first; ties broken randomly (reference shuffles then
        stable-sorts ascending and iterates reversed)."""
        keys = list(self.keys)
        self.rng.shuffle(keys)
        keys.sort(key=self.bandit_score, reverse=True)
        return keys

    def on_result(self, key: str, value: bool) -> None:
        self.history.append((key, value))
        self.use_counts[key] += 1
        if value:
            self.auc_sum[key] += self.use_counts[key]
            self.auc_decay[key] += 1
        if len(self.history) > self.window:
            k, v = self.history.popleft()
            self.use_counts[k] -= 1
            self.auc_sum[k] -= self.auc_decay[k]
            if v:
                self.auc_decay[k] -= 1


class MetaTechnique(Technique):
    """A technique made of sub-techniques; the driver unrolls it (jitting
    each member) and calls select_order()/credit() host-side per step
    (metatechniques.py:14-76)."""

    def __init__(self, techniques: Sequence[Technique],
                 name: Optional[str] = None):
        super().__init__(name)
        seen = set()
        uniq = []
        for t in techniques:
            nm = t.name
            while nm in seen:
                nm += "~"
            if nm != t.name:
                import copy
                t = copy.copy(t)
                t.name = nm
            seen.add(nm)
            uniq.append(t)
        self.techniques: List[Technique] = uniq

    def select_order(self) -> List[Technique]:
        raise NotImplementedError

    def credit(self, name: str, was_new_best: bool,
               step_best: Optional[float] = None,
               global_best: Optional[float] = None) -> None:
        """Feedback after a pull resolves.  `step_best` is the pull's own
        best QoR (engine orientation), `global_best` the run's best —
        the extra channels exist for quality-aware metas (recycling)."""
        pass

    def poll_restart(self) -> List[str]:
        """Names of members whose device state the driver should
        re-initialize (fresh init_state) before the next acquisition.
        Drained on read; empty for metas that never restart members."""
        return []


class AUCBanditMeta(MetaTechnique):
    def __init__(self, techniques: Sequence[Technique],
                 name: Optional[str] = None, C: float = 0.05,
                 window: int = 500, seed: int = 0):
        super().__init__(techniques, name)
        self.bandit = AUCBanditQueue([t.name for t in self.techniques],
                                     C=C, window=window, seed=seed)
        self._by_name = {t.name: t for t in self.techniques}
        # virtual arms compete in the AUC queue but have no Technique:
        # the driver interprets them itself (e.g. 'surrogate' pulls the
        # EI proposal pool).  select_order() filters them out so callers
        # that only understand Techniques keep working.
        self.virtual_arms: set = set()

    def register_virtual_arm(self, name: str) -> None:
        if name in self._by_name:
            raise ValueError(f"arm name {name!r} already taken by a "
                             f"member technique")
        self.virtual_arms.add(name)
        self.bandit.add_key(name)

    def ordered_names(self) -> List[str]:
        """Full credit-ordered arm-name list, virtual arms included."""
        return self.bandit.ordered_keys()

    def select_order(self) -> List[Technique]:
        return [self._by_name[k] for k in self.bandit.ordered_keys()
                if k in self._by_name]

    def credit(self, name: str, was_new_best: bool,
               step_best: Optional[float] = None,
               global_best: Optional[float] = None) -> None:
        self.bandit.on_result(name, was_new_best)


class RoundRobinMeta(MetaTechnique):
    """metatechniques.py:78-87."""

    def __init__(self, techniques: Sequence[Technique],
                 name: Optional[str] = None):
        super().__init__(techniques, name)
        self._i = 0

    def select_order(self) -> List[Technique]:
        order = self.techniques[self._i:] + self.techniques[:self._i]
        self._i = (self._i + 1) % len(self.techniques)
        return order


class RecyclingMeta(RoundRobinMeta):
    """Restart-underperformers meta (metatechniques.py:89-180),
    re-designed for batched pulls.

    Round-robin between members; every `window` resolved pulls the member
    with the WORST window-best QoR is marked for restart when (a) it also
    completed the previous window (the reference's `old_best_results[w]
    is not None` guard — fresh members get a full window before judgment)
    and (b) the global best strictly beats its window best (reference:
    `objective.lt(driver.best_result, best_results[worst])`).  A restart
    here re-initializes the member's DEVICE state via poll_restart() —
    populations/simplices re-seed while jitted programs stay cached —
    instead of constructing a renamed `.R%d` instance (the reference's
    generators rebuild Python objects; our techniques are stateless
    hyperparameter holders, so identity and archive attribution are
    stable across restarts).  The reference seeds replacements with the
    global best config; here every propose() already receives `best`, so
    the restarted member re-anchors the same way.
    """

    def __init__(self, techniques: Sequence[Technique],
                 name: Optional[str] = None, window: int = 20):
        super().__init__(techniques, name)
        self.window = int(window)
        self._pulls = 0
        inf = float("inf")
        self._win_best: Dict[str, float] = {
            t.name: inf for t in self.techniques}
        self._win_pulls: Dict[str, int] = {
            t.name: 0 for t in self.techniques}
        self._prev_pulls: Dict[str, int] = {}
        self._queued: List[str] = []
        self.restart_count = 0
        self._global = inf

    def credit(self, name: str, was_new_best: bool,
               step_best: Optional[float] = None,
               global_best: Optional[float] = None) -> None:
        self._pulls += 1
        if name in self._win_best:
            self._win_pulls[name] += 1
            if step_best is not None:
                self._win_best[name] = min(self._win_best[name],
                                           float(step_best))
        if global_best is not None:
            self._global = min(self._global, float(global_best))
        if self._pulls % self.window == 0:
            self._recycle()

    def _recycle(self) -> None:
        # judge only members actually PULLED this window: an un-scheduled
        # member keeps its state (the reference judges on window results;
        # restarting healthy members for not being scheduled would
        # discard good populations whenever window < len(techniques)).
        # A pulled member whose window best is +inf (it produced only
        # duplicates / failures) is legitimately worst — that is the
        # stagnated case the restart-meta exists for.
        pulled = [k for k, p in self._win_pulls.items() if p > 0]
        restarted = None
        if pulled:
            worst = max(pulled, key=lambda k: self._win_best[k])
            if (self._prev_pulls.get(worst, 0) > 0
                    and self._global < self._win_best[worst]):
                self._queued.append(worst)
                self.restart_count += 1
                restarted = worst
        self._prev_pulls = dict(self._win_pulls)
        if restarted is not None:
            # the re-seeded member gets one full window of grace before
            # it can be judged again (the reference's replacement starts
            # with old_best_results=None); without this a lagging member
            # would churn through a restart every single window
            self._prev_pulls[restarted] = 0
        self._win_best = {k: float("inf") for k in self._win_best}
        self._win_pulls = {k: 0 for k in self._win_pulls}

    def poll_restart(self) -> List[str]:
        out, self._queued = self._queued, []
        return out


def _portfolio(name: str, members) -> AUCBanditMeta:
    return AUCBanditMeta(members, name=name)


def _register_portfolios():
    from .annealing import PseudoAnnealingSearch
    from .de import DifferentialEvolution
    from .evolutionary import GreedyMutation, GlobalGA
    from .pattern import PatternSearch
    from .pso import PSO
    from .simplex import NelderMead

    def de_alt():
        return DifferentialEvolution(cr=0.2, name="DifferentialEvolutionAlt")

    def ugm(**kw):
        return GreedyMutation(**kw)

    def rnm(name="RandomNelderMead"):
        return NelderMead(init_style="random", name=name)

    # bandittechniques.py:273-320
    register(_portfolio("AUCBanditMetaTechniqueA", [
        de_alt(), ugm(name="UniformGreedyMutation"),
        ugm(sigma=0.1, mutation_rate=0.3, name="NormalGreedyMutation"),
        rnm()]))
    register(_portfolio("AUCBanditMetaTechniqueB", [
        de_alt(), ugm(name="UniformGreedyMutation")]))
    register(_portfolio("AUCBanditMetaTechniqueC", [
        de_alt(), PatternSearch()]))
    register(_portfolio("PSO_GA_Bandit",
        [PSO(crossover=cx) for cx in ("OX3", "OX1", "CX", "PMX", "PX")] +
        [ugm(mutation_rate=0.01, crossover_rate=0.8, crossover=cx,
             name=f"ga-{cx}") for cx in ("OX3", "OX1", "CX", "PX", "PMX")] +
        [ugm(mutation_rate=0.01, name="ga-base")]))
    # TPU-flavored portfolio: portfolio A with the UniformGreedyMutation
    # arm swapped for the beyond-reference CMA-ES (techniques/cmaes.py;
    # both fill the broad-exploration role, CMA-ES adapts its search
    # distribution) under the same AUC bandit — opt-in via --technique,
    # the reference-faithful AUCBanditMetaTechniqueA stays the default.
    # The matched 30-seed A/B (scripts/ab_portfolio.py, AB_PORTFOLIO.md:
    # rosenbrock-4d, thresh 1.0, budget 4000, identical seed lists)
    # has it LOSING to portfolio A — median 3916 vs 2412 iters (1.62x),
    # solve-rate 15/30 vs 16/30.  An earlier 10-seed sample (median
    # 1712) was a lucky draw; this stays opt-in and is NOT recommended
    # as a portfolio-A replacement.  CMAES remains valuable as a
    # standalone arm on smooth continuous spaces (test_cmaes converges
    # 600-eval rosenbrock-2d).
    from .cmaes import CMAES
    register(_portfolio("AUCBanditMetaTechniqueTPU", [
        de_alt(), ugm(sigma=0.1, mutation_rate=0.3,
                      name="NormalGreedyMutation"),
        CMAES(), rnm()]), experimental=True)

    # the generic restart-meta + plain round-robin, registered so
    # --technique can name them (metatechniques.py:78-180; VERDICT r2
    # missing #4) — both over the default portfolio's members
    register(RecyclingMeta([
        de_alt(), ugm(name="UniformGreedyMutation"),
        ugm(sigma=0.1, mutation_rate=0.3, name="NormalGreedyMutation"),
        rnm()], name="RecyclingMetaTechnique"))
    register(RoundRobinMeta([
        de_alt(), ugm(name="UniformGreedyMutation"),
        ugm(sigma=0.1, mutation_rate=0.3, name="NormalGreedyMutation"),
        rnm()], name="RoundRobinMetaSearchTechnique"))
    register(_portfolio("test", [de_alt(), PseudoAnnealingSearch()]))
    register(_portfolio("test2", [
        de_alt(), ugm(name="UniformGreedyMutation"),
        ugm(sigma=0.1, mutation_rate=0.3, name="NormalGreedyMutation"),
        rnm(), PseudoAnnealingSearch()]))
    register(_portfolio("PSO_GA_DE",
        [PSO(crossover=cx) for cx in ("OX1", "PMX", "PX")] +
        [ugm(crossover_rate=0.5, crossover=cx, name=f"ga-{cx}")
         for cx in ("OX1", "PMX", "PX")] +
        [de_alt(),
         GlobalGA(mutation_rate=0.1, sigma=0.1, crossover_rate=0.5,
                  crossover_strength=0.2, name="GGA")]))


_register_portfolios()
