"""Batched particle swarm optimization.

Reference: `/root/reference/python/uptune/opentuner/search/pso.py:11-84` —
N=30 HybridParticles, each holding position, per-parameter velocity, and a
local best; every move calls op3_swarm per parameter with
(c=omega=0.5, phi_g=0.5, phi_l=0.5).

Batched: positions/velocities are [N, D] arrays; one propose() moves every
particle (the reference moves them one per desired_result call — same
trajectory distribution, N× the throughput).  Scalar lanes follow the
float/int op3_swarm velocity form, BOOL lanes the sigmoid-coin form, other
complex lanes the stochastic (current/local/global) mix — see
ops.numeric.swarm.  Permutation blocks follow PermutationParameter.op3_swarm
(manipulator.py:1115-1141): with probability 1-c, cross the position with
the global (phi_g) or local (phi_l) best using the technique's crossover
choice at strength 0.3.

First propose() emits the initial random positions (pso.py:35-37).
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..ops import numeric as nops
from ..ops import perm as pops
from ..space import params as P
from ..space.spec import CandBatch, Space
from .base import Best, Technique, register


class PSOState(NamedTuple):
    pos: CandBatch          # [N, ...] particle positions
    vel: jax.Array          # [N, D] scalar-lane velocities
    lbest: CandBatch        # [N, ...] per-particle best position
    lbest_qor: jax.Array    # [N]
    bootstrapped: jax.Array


class PSO(Technique):
    def __init__(self, crossover: str = "OX1", N: int = 30,
                 omega: float = 0.5, phi_l: float = 0.5, phi_g: float = 0.5,
                 name: str = None):
        super().__init__(name or f"pso-{crossover}")
        self.crossover = crossover
        self.N = N
        self.omega = omega
        self.phi_l = phi_l
        self.phi_g = phi_g

    def natural_batch(self, space: Space) -> int:
        return self.N

    def init_state(self, space: Space, key: jax.Array) -> PSOState:
        pos = space.random(key, self.N)
        return PSOState(pos, jnp.zeros((self.N, space.n_scalar)),
                        pos, jnp.full((self.N,), jnp.inf),
                        jnp.asarray(False))

    def propose(self, space: Space, state: PSOState, key: jax.Array,
                best: Best) -> Tuple[PSOState, CandBatch]:
        N = self.N
        ks, kg, kc1, kc2, *kperm = jax.random.split(
            key, 4 + len(space.perm_sizes))
        have = jnp.isfinite(best.qor)
        gbest_u = jnp.where(have, best.u, state.pos.u[0])
        bool_mask = (space.kind == P.BOOL)[None, :]
        new_u, new_vel = nops.swarm(
            ks, state.pos.u, state.lbest.u, gbest_u[None, :], state.vel,
            space.complex_mask[None, :], bool_mask,
            c=self.omega, c1=self.phi_l, c2=self.phi_g)

        # permutation blocks: probabilistic crossover with local/global best
        perms = []
        coin_move = jax.random.uniform(kc1, (N, 1)) > self.omega
        coin_partner = jax.random.uniform(kc2, (N, 1)) < self.phi_g
        fn = pops.CROSSOVERS[self.crossover]
        for kk, pm, lb, gb, size in zip(
                kperm, state.pos.perms, state.lbest.perms, best.perms,
                space.perm_sizes):
            d = max(1, int(round(size * 0.3)))
            gb_rows = jnp.tile(gb[None, :], (N, 1))
            gb_rows = jnp.where(have, gb_rows, pm)
            keys = jax.random.split(kk, N)
            vm = jax.vmap(lambda k, a, b: fn(k, a, b, d))
            with_g = vm(keys, pm, gb_rows)
            with_l = vm(keys, pm, lb)
            crossed = jnp.where(coin_partner, with_g, with_l)
            perms.append(jnp.where(coin_move, crossed, pm))

        moved = space.normalize(CandBatch(new_u, tuple(perms)))
        boot = state.bootstrapped
        out = CandBatch(
            jnp.where(boot, moved.u, state.pos.u),
            tuple(jnp.where(boot, m, p)
                  for m, p in zip(moved.perms, state.pos.perms)))
        vel = jnp.where(boot, new_vel, state.vel)
        return PSOState(out, vel, state.lbest, state.lbest_qor,
                        jnp.asarray(True)), out

    def observe(self, space: Space, state: PSOState, cands: CandBatch,
                qor: jax.Array, best: Best) -> PSOState:
        better = qor < state.lbest_qor
        lbest = CandBatch(
            jnp.where(better[:, None], cands.u, state.lbest.u),
            tuple(jnp.where(better[:, None], c, p)
                  for c, p in zip(cands.perms, state.lbest.perms)))
        return state._replace(lbest=lbest,
                              lbest_qor=jnp.minimum(state.lbest_qor, qor))


for _cx in ("OX3", "OX1", "PMX", "PX", "CX"):
    register(PSO(crossover=_cx))
