"""Batched simplex techniques: Nelder-Mead and Torczon.

Reference: `/root/reference/python/uptune/opentuner/search/
simplextechniques.py` — sequential generators that evaluate one speculative
point at a time.  The TPU re-design evaluates the *entire* decision tree of
one simplex round speculatively in a single batch:

* Nelder-Mead (:180-318): one round needs at most {reflection, expansion,
  outside contraction, inside contraction} plus the S-1 shrink points.  We
  propose all S+3 together and apply the decision rules (reflection
  comparisons against best/second point, contraction vs its base, shrink
  fallback, :220-280) branchlessly in observe().  The reference needs 1-4
  sequential evaluation rounds per simplex move; we need exactly one.
* Torczon (:320-456): propose reflected+expanded+contracted simplexes
  (3·(S-1) points) at once; observe() picks the winning simplex
  (:352-380).

Simplex geometry lives on the scalar unit lanes only; permutation blocks
ride along from the seed point, matching the reference where complex
parameters are copied from `simplex_points[0]` and `linear_point`'s
randomize-if-differ never fires on identical values.

Initial simplexes (Random/Right/Regular mixins, :100-177) and the
convergence-restart behavior of RecyclingMetaTechnique (Multi* variants,
metatechniques.py:89-180) are built in: on convergence the simplex restarts
around the global best.  alpha=2.0 default as in the reference (:246-254,
degenerate-volume argument).
"""
from __future__ import annotations

import math
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..space.spec import CandBatch, Space
from .base import Best, Technique, register

INIT, LOOP = 0, 1


class SimplexState(NamedTuple):
    pts_u: jax.Array       # [S, D] simplex point unit values
    vals: jax.Array        # [S] measured QoR (+inf before INIT observe)
    perms: Tuple[jax.Array, ...]  # each [s_k] — shared seed ordering
    phase: jax.Array       # scalar i32: INIT or LOOP
    key: jax.Array         # restart randomness
    stale: jax.Array       # scalar i32: rounds without improvement


def _simplex_size(space: Space) -> int:
    return space.n_scalar + 1


class _SimplexBase(Technique):
    def __init__(self, init_style: str, name: str,
                 edge: float = 0.1):
        super().__init__(name)
        self.init_style = init_style
        self.edge = edge

    def supports(self, space: Space) -> bool:
        return space.n_scalar >= 1

    # ---- initial simplex construction (mixins :100-177) -------------------
    def _initial_simplex(self, space: Space, key: jax.Array,
                         seed_u: jax.Array) -> jax.Array:
        D = space.n_scalar
        S = _simplex_size(space)
        if self.init_style == "random":
            others = jax.random.uniform(key, (S - 1, D))
            return jnp.concatenate([seed_u[None, :], others], axis=0)
        if self.init_style == "right":
            shift = jnp.where(seed_u <= 0.5, self.edge, -self.edge)
            others = seed_u[None, :] + jnp.eye(D) * shift[None, :]
            return jnp.concatenate([seed_u[None, :], others], axis=0)
        if self.init_style == "regular":
            # RegularInitialMixin :143-177
            q = ((math.sqrt(D + 1.0) - 1.0) / (D * math.sqrt(2.0))) * self.edge
            p = q + self.edge / math.sqrt(2.0)
            base = jnp.where(jnp.maximum(p, q) + seed_u > 1.0, -seed_u, seed_u)
            others = jnp.abs(base[None, :] + q +
                             jnp.eye(D) * (p - q))
            return jnp.concatenate([seed_u[None, :], others], axis=0)
        raise ValueError(self.init_style)

    def init_state(self, space: Space, key: jax.Array) -> SimplexState:
        S = _simplex_size(space)
        k0, k1, k2, knext = jax.random.split(key, 4)
        seed = space.random(k0, 1)
        pts = self._initial_simplex(space, k1, seed.u[0])
        return SimplexState(
            pts, jnp.full((S,), jnp.inf),
            tuple(p[0] for p in seed.perms),
            jnp.asarray(INIT, jnp.int32), knext,
            jnp.asarray(0, jnp.int32))

    def _restart(self, space: Space, state: SimplexState,
                 best: Best, converged: jax.Array) -> SimplexState:
        """Re-seed the simplex around the global best on convergence — the
        recycling behavior of MultiNelderMead/MultiTorczon
        (metatechniques.py:145-170) fused into the technique."""
        k1, k2, knext = jax.random.split(state.key, 3)
        have_best = jnp.isfinite(best.qor)
        seed_u = jnp.where(have_best, best.u,
                           jax.random.uniform(k2, best.u.shape))
        new_pts = self._initial_simplex(space, k1, seed_u)
        S = state.pts_u.shape[0]
        # adopt the best's permutation blocks too — the reference's
        # recycling re-creates the technique from the FULL best config
        # (metatechniques.py:145-170), not only its scalar part
        perms = tuple(
            jnp.where(converged & have_best, bp, sp)
            for sp, bp in zip(state.perms, best.perms))
        return SimplexState(
            jnp.where(converged, new_pts, state.pts_u),
            jnp.where(converged, jnp.full((S,), jnp.inf), state.vals),
            perms,
            jnp.where(converged, INIT, LOOP).astype(jnp.int32),
            knext,
            jnp.where(converged, 0, state.stale).astype(jnp.int32))

    def _attach_perms(self, state: SimplexState, u: jax.Array) -> CandBatch:
        n = u.shape[0]
        return CandBatch(
            u, tuple(jnp.tile(p[None, :], (n, 1)) for p in state.perms))


class NelderMead(_SimplexBase):
    def __init__(self, init_style: str, name: str, alpha: float = 2.0,
                 gamma: float = 2.0, beta: float = 0.5, sigma: float = 0.5,
                 **kw):
        super().__init__(init_style, name, **kw)
        self.alpha = alpha
        self.gamma = gamma
        self.beta = beta
        self.sigma = sigma

    def natural_batch(self, space: Space) -> int:
        return _simplex_size(space) + 3

    def propose(self, space: Space, state: SimplexState, key: jax.Array,
                best: Best) -> Tuple[SimplexState, CandBatch]:
        S = _simplex_size(space)
        order = jnp.argsort(state.vals)
        pts = state.pts_u[order]
        vals = state.vals[order]
        centroid = jnp.mean(pts, axis=0)  # calculate_centroid averages all
        worst = pts[-1]
        refl = jnp.clip(centroid + self.alpha * (centroid - worst), 0, 1)
        expa = jnp.clip(centroid + self.gamma * (refl - centroid), 0, 1)
        c_out = jnp.clip(centroid + self.beta * (refl - centroid), 0, 1)
        c_in = jnp.clip(centroid + self.beta * (worst - centroid), 0, 1)
        shrink = pts[0][None, :] + self.sigma * (pts[1:] - pts[0][None, :])
        loop_batch = jnp.concatenate(
            [refl[None], expa[None], c_out[None], c_in[None], shrink], axis=0)
        # INIT phase: evaluate the simplex itself (+3 random padding rows)
        pad = jax.random.uniform(key, (3, space.n_scalar))
        init_batch = jnp.concatenate([state.pts_u, pad], axis=0)
        u = jnp.where(state.phase == INIT, init_batch, loop_batch)
        # sorted order must persist into observe: store sorted simplex
        new_state = state._replace(
            pts_u=jnp.where(state.phase == INIT, state.pts_u, pts),
            vals=jnp.where(state.phase == INIT, state.vals, vals))
        return new_state, self._attach_perms(state, u)

    def observe(self, space: Space, state: SimplexState, cands: CandBatch,
                qor: jax.Array, best: Best) -> SimplexState:
        S = _simplex_size(space)
        # ---- INIT: adopt measured simplex values --------------------------
        init_vals = qor[:S]
        # ---- LOOP: NM decision tree (:220-280) ----------------------------
        pts, vals = state.pts_u, state.vals  # sorted by propose
        qr, qe, qoc, qic = qor[0], qor[1], qor[2], qor[3]
        q_shrink = qor[4:4 + S - 1]
        refl, expa, c_out, c_in = (cands.u[0], cands.u[1],
                                   cands.u[2], cands.u[3])
        shrink_pts = cands.u[4:4 + S - 1]

        case_expand = (qr < vals[0]) & (qe < qr)
        case_reflect = (qr < vals[1]) & ~case_expand   # covers both branches
        out_base = qr <= vals[-1]
        q_cont = jnp.where(out_base, qoc, qic)
        cont_pt = jnp.where(out_base, c_out, c_in)
        q_base = jnp.where(out_base, qr, vals[-1])
        case_contract = (~case_expand) & (~case_reflect) & (q_cont <= q_base)
        case_shrink = (~case_expand) & (~case_reflect) & (~case_contract)

        repl_pt = jnp.where(case_expand, expa,
                            jnp.where(case_reflect, refl, cont_pt))
        repl_q = jnp.where(case_expand, qe,
                           jnp.where(case_reflect, qr, q_cont))
        # replace worst (last of the sorted simplex)
        loop_pts = pts.at[-1].set(jnp.where(case_shrink, pts[-1], repl_pt))
        loop_vals = vals.at[-1].set(jnp.where(case_shrink, vals[-1], repl_q))
        # shrink: all but best replaced by measured shrink points
        loop_pts = jnp.where(case_shrink,
                             jnp.concatenate([pts[:1], shrink_pts], axis=0),
                             loop_pts)
        loop_vals = jnp.where(case_shrink,
                              jnp.concatenate([vals[:1], q_shrink]),
                              loop_vals)

        is_init = state.phase == INIT
        new_pts = jnp.where(is_init, pts, loop_pts)
        new_vals = jnp.where(is_init, init_vals, loop_vals)
        improved = jnp.min(new_vals) < jnp.min(vals)
        stale = jnp.where(is_init | improved, 0, state.stale + 1)
        out = SimplexState(new_pts, new_vals, state.perms,
                           jnp.asarray(LOOP, jnp.int32), state.key,
                           stale.astype(jnp.int32))
        # convergence_criterea (:78-86): no novelty for ~3 rounds, or simplex
        # geometrically collapsed
        spread = jnp.max(new_pts, axis=0) - jnp.min(new_pts, axis=0)
        converged = (~is_init) & (
            (out.stale > 3 * S + 1) | (jnp.max(spread) < 1e-6))
        return self._restart(space, out, best, converged)


class Torczon(_SimplexBase):
    def __init__(self, init_style: str, name: str, alpha: float = 1.0,
                 gamma: float = 2.0, beta: float = 0.5, **kw):
        super().__init__(init_style, name, **kw)
        self.alpha = alpha
        self.gamma = gamma
        self.beta = beta

    def natural_batch(self, space: Space) -> int:
        S = _simplex_size(space)
        return max(S, 3 * (S - 1))

    def propose(self, space: Space, state: SimplexState, key: jax.Array,
                best: Best) -> Tuple[SimplexState, CandBatch]:
        S = _simplex_size(space)
        nb = self.natural_batch(space)
        order = jnp.argsort(state.vals)
        pts = state.pts_u[order]
        vals = state.vals[order]
        b = pts[0][None, :]
        rest = pts[1:]

        def scaled(scale):  # scaled_simplex (:382-394)
            return jnp.clip(b + scale * (b - rest), 0.0, 1.0)

        refl = scaled(self.alpha)
        expa = scaled(self.gamma)
        cont = scaled(-self.beta)
        loop_batch = jnp.concatenate([refl, expa, cont], axis=0)
        loop_batch = jnp.concatenate(
            [loop_batch,
             jnp.zeros((nb - loop_batch.shape[0], space.n_scalar))], axis=0)
        pad = jax.random.uniform(key, (max(0, nb - S), space.n_scalar))
        init_batch = jnp.concatenate([state.pts_u, pad], axis=0)[:nb]
        u = jnp.where(state.phase == INIT, init_batch, loop_batch)
        new_state = state._replace(
            pts_u=jnp.where(state.phase == INIT, state.pts_u, pts),
            vals=jnp.where(state.phase == INIT, state.vals, vals))
        return new_state, self._attach_perms(state, u)

    def observe(self, space: Space, state: SimplexState, cands: CandBatch,
                qor: jax.Array, best: Best) -> SimplexState:
        S = _simplex_size(space)
        init_vals = qor[:S]
        pts, vals = state.pts_u, state.vals
        m = S - 1
        qr, qe, qc = qor[:m], qor[m:2 * m], qor[2 * m:3 * m]
        refl, expa, cont = (cands.u[:m], cands.u[m:2 * m],
                            cands.u[2 * m:3 * m])
        min_r = jnp.min(qr)
        use_exp = (min_r < vals[0]) & (jnp.min(qe) < min_r)
        use_ref = (min_r < vals[0]) & ~use_exp
        chosen = jnp.where(use_exp, expa, jnp.where(use_ref, refl, cont))
        chosen_q = jnp.where(use_exp, qe, jnp.where(use_ref, qr, qc))
        loop_pts = jnp.concatenate([pts[:1], chosen], axis=0)
        loop_vals = jnp.concatenate([vals[:1], chosen_q])

        is_init = state.phase == INIT
        new_pts = jnp.where(is_init, pts, loop_pts)
        new_vals = jnp.where(is_init, init_vals, loop_vals)
        improved = jnp.min(new_vals) < jnp.min(vals)
        stale = jnp.where(is_init | improved, 0, state.stale + 1)
        out = SimplexState(new_pts, new_vals, state.perms,
                           jnp.asarray(LOOP, jnp.int32), state.key,
                           stale.astype(jnp.int32))
        spread = jnp.max(new_pts, axis=0) - jnp.min(new_pts, axis=0)
        converged = (~is_init) & (
            (out.stale > 3 * S + 1) | (jnp.max(spread) < 1e-6))
        return self._restart(space, out, best, converged)


class MultiSimplex(Technique):
    """MultiNelderMead / MultiTorczon (RecyclingMetaTechnique over the three
    init styles, simplextechniques.py:423-437).  Since each batched simplex
    already self-restarts from the global best, the Multi variant interleaves
    the three init styles round-robin, advancing one per step."""

    def __init__(self, members, name):
        super().__init__(name)
        self.members = members

    def supports(self, space: Space) -> bool:
        return all(m.supports(space) for m in self.members)

    def natural_batch(self, space: Space) -> int:
        return max(m.natural_batch(space) for m in self.members)

    def init_state(self, space: Space, key: jax.Array):
        keys = jax.random.split(key, len(self.members))
        return (jnp.asarray(0, jnp.int32),
                tuple(m.init_state(space, k)
                      for m, k in zip(self.members, keys)))

    def propose(self, space: Space, state, key: jax.Array, best: Best):
        turn, sub = state
        nb = self.natural_batch(space)

        # advance only the member whose turn it is: lax.switch compiles all
        # branches once but executes one (member states share a structure)
        def branch(i, m):
            def run(operand):
                sub_, key_, best_ = operand
                s2, c = m.propose(space, sub_[i], key_, best_)
                pad = nb - c.u.shape[0]
                if pad:
                    ku = jax.random.fold_in(key_, 7)
                    c = CandBatch(
                        jnp.concatenate(
                            [c.u,
                             jax.random.uniform(ku, (pad, space.n_scalar))]),
                        tuple(jnp.concatenate(
                            [p, jnp.tile(p[:1], (pad, 1))]) for p in c.perms))
                return sub_[:i] + (s2,) + sub_[i + 1:], c
            return run

        branches = [branch(i, m) for i, m in enumerate(self.members)]
        new_sub, cands = jax.lax.switch(turn, branches, (sub, key, best))
        return (turn, new_sub), cands

    def observe(self, space: Space, state, cands, qor, best):
        turn, sub = state

        def branch(i, m):
            def run(operand):
                sub_, cands_, qor_, best_ = operand
                n = m.natural_batch(space)
                s2 = m.observe(space, sub_[i], cands_[:n], qor_[:n], best_)
                return sub_[:i] + (s2,) + sub_[i + 1:]
            return run

        branches = [branch(i, m) for i, m in enumerate(self.members)]
        new_sub = jax.lax.switch(turn, branches, (sub, cands, qor, best))
        nxt = jnp.mod(turn + 1, len(self.members))
        return (nxt, new_sub)


def _mk(cls, style, name, **kw):
    return cls(init_style=style, name=name, **kw)


register(_mk(NelderMead, "random", "RandomNelderMead"))
register(_mk(NelderMead, "right", "RightNelderMead"))
register(_mk(NelderMead, "regular", "RegularNelderMead"))
register(MultiSimplex([_mk(NelderMead, "right", "RightNelderMead_"),
                       _mk(NelderMead, "random", "RandomNelderMead_"),
                       _mk(NelderMead, "regular", "RegularNelderMead_")],
                      name="MultiNelderMead"))
register(_mk(Torczon, "random", "RandomTorczon"))
register(_mk(Torczon, "right", "RightTorczon"))
register(_mk(Torczon, "regular", "RegularTorczon"))
register(MultiSimplex([_mk(Torczon, "right", "RightTorczon_"),
                       _mk(Torczon, "random", "RandomTorczon_"),
                       _mk(Torczon, "regular", "RegularTorczon_")],
                      name="MultiTorczon"))
