"""Batched differential evolution.

Reference: `/root/reference/python/uptune/opentuner/search/
differentialevolution.py:29-151`.  The reference replaces one population
member per `desired_configuration()` call (oldest first); the batched
re-design advances the *whole population* per step: every member proposes
its replacement candidate simultaneously (classic synchronous DE, which is
the natural TPU formulation), with the reference's information-sharing slot
(global best appended to the parent pool, :111-113) and its crossover rule
(per-param coin < cr with n_cross forced, cfg = x1 + F*(x2-x3),
F ~ U(0.5, 1), :117-126).

The first propose() call emits the freshly-randomized initial population
itself (initial_population + submitted bookkeeping, :54-85); observe()
then fills in member QoRs and replacement begins.
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..space.spec import CandBatch, Space
from .base import Best, Technique, register
from .common import de_linear_batch, param_mutation_mask


class DEState(NamedTuple):
    pop: CandBatch        # [P, ...] member configurations
    qor: jax.Array        # [P] member QoR (+inf = not yet measured)
    bootstrapped: jax.Array  # scalar bool: initial population submitted?


class DifferentialEvolution(Technique):
    def __init__(self, population_size: int = 30, cr: float = 0.9,
                 n_cross: int = 1, information_sharing: int = 1,
                 name: str = "DifferentialEvolution"):
        super().__init__(name)
        self.population_size = population_size
        self.cr = cr
        self.n_cross = n_cross
        self.information_sharing = information_sharing

    def natural_batch(self, space: Space) -> int:
        return self.population_size

    def init_state(self, space: Space, key: jax.Array) -> DEState:
        pop = space.random(key, self.population_size)
        return DEState(pop, jnp.full((self.population_size,), jnp.inf),
                       jnp.asarray(False))

    def propose(self, space: Space, state: DEState, key: jax.Array,
                best: Best) -> Tuple[DEState, CandBatch]:
        P = self.population_size
        kpar, kf, kmask, klin = jax.random.split(key, 4)

        # parent pool per member i: the P-1 other members plus
        # `information_sharing` copies of the global best; fall back to the
        # member itself while no best exists (first generation).
        n_pool = P - 1 + self.information_sharing
        picks = jax.vmap(
            lambda k: jax.random.choice(k, n_pool, (3,), replace=False)
        )(jax.random.split(kpar, P))                     # [P, 3] pool indices
        member = jnp.arange(P)[:, None]                  # [P, 1]
        # pool index -> population index (skip self), >= P-1 means "best"
        pop_idx = jnp.where(picks >= member, picks + 1, picks)
        is_best = picks >= (P - 1)
        have_best = jnp.isfinite(best.qor)

        def gather(x_pop, x_best):
            # x_pop: [P, ...]; select parent rows, substituting best
            rows = x_pop[jnp.clip(pop_idx, 0, P - 1)]    # [P, 3, ...]
            bcast = jnp.broadcast_to(
                x_best, (P, 3) + x_best.shape)
            use_best = (is_best & have_best)
            while use_best.ndim < rows.ndim:
                use_best = use_best[..., None]
            return jnp.where(use_best, bcast, rows)

        xs_u = gather(state.pop.u, best.u)               # [P, 3, D]
        xs_perms = tuple(gather(pp, bp)
                         for pp, bp in zip(state.pop.perms, best.perms))

        def parent(j: int) -> CandBatch:
            return CandBatch(xs_u[:, j], tuple(p[:, j] for p in xs_perms))

        f = (jax.random.uniform(kf, (P, 1)) / 2.0 + 0.5)  # U(0.5, 1), :119
        cross = param_mutation_mask(space, kmask, P, self.cr, self.n_cross)
        cands = de_linear_batch(space, klin, state.pop, parent(0), parent(1),
                                parent(2), f, cross)
        cands = space.normalize(cands)

        # bootstrap: emit the unsubmitted initial population instead
        boot = state.bootstrapped
        out = CandBatch(
            jnp.where(boot, cands.u, state.pop.u),
            tuple(jnp.where(boot, c, p)
                  for c, p in zip(cands.perms, state.pop.perms)))
        return state._replace(bootstrapped=jnp.asarray(True)), out

    def observe(self, space: Space, state: DEState, cands: CandBatch,
                qor: jax.Array, best: Best) -> DEState:
        # candidate i replaces member i if strictly better (:133-140);
        # also covers the bootstrap generation (member qor = +inf).
        better = qor < state.qor
        pop = CandBatch(
            jnp.where(better[:, None], cands.u, state.pop.u),
            tuple(jnp.where(better[:, None], c, p)
                  for c, p in zip(cands.perms, state.pop.perms)))
        return DEState(pop, jnp.minimum(state.qor, qor), state.bootstrapped)


register(DifferentialEvolution())
register(DifferentialEvolution(cr=0.2, name="DifferentialEvolutionAlt"))
register(DifferentialEvolution(population_size=100, cr=0.2,
                               name="DifferentialEvolution_20_100"))
