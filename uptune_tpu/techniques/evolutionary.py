"""Greedy evolutionary techniques, batched.

Reference: `/root/reference/python/uptune/opentuner/search/
evolutionarytechniques.py` (local mutation of the global best) and
`globalGA.py` (adds whole-value crossover copy).  Greedy selection always
picks the incumbent global best (GreedySelectionMixin, :85-95), so a batched
step emits N independent mutations of the best configuration — the batch
generalization of N sequential desired_configuration() calls.

Mutation semantics (mutation(), :50-60): shuffle parameter order, mutate the
first `must_mutate_count` unconditionally and each other with probability
`mutation_rate`.  Uniform variant randomizes the chosen parameter
(op1_randomize); Normal variant applies sigma-scaled Gaussian noise to
primitive parameters and a random manipulator to complex ones (:97-115).

GA (CrossoverMixin, :117-133) crosses the permutation blocks of two selected
parents with a named crossover at d = size/3 for blocks larger than 6.  With
greedy selection both parents are the same incumbent, so the cross is an
identity on paper — we keep the call for parity (it matters when the
selection rule is changed) but route it through the same batched kernels.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..space.spec import CandBatch, Space
from .base import Best, Technique, register
from .common import crossover_perms, mutate_batch


class GreedyMutation(Technique):
    """UniformGreedyMutation / NormalGreedyMutation / GA / GGA family."""

    def __init__(self, batch: int = 32, mutation_rate: float = 0.1,
                 crossover_rate: float = 0.0, must_mutate_count: int = 1,
                 sigma: Optional[float] = None,
                 crossover: Optional[str] = None,
                 crossover_strength: float = 1.0 / 3.0,
                 name: str = "GreedyMutation"):
        super().__init__(name)
        self.batch = batch
        self.mutation_rate = mutation_rate
        self.crossover_rate = crossover_rate
        self.must_mutate_count = must_mutate_count
        self.sigma = sigma
        self.crossover = crossover
        self.crossover_strength = crossover_strength

    def natural_batch(self, space: Space) -> int:
        return self.batch

    def init_state(self, space: Space, key: jax.Array):
        return ()

    def propose(self, space: Space, state, key: jax.Array,
                best: Best) -> Tuple[tuple, CandBatch]:
        n = self.batch
        krand, kx, kxsel, kmut = jax.random.split(key, 4)
        # parent = incumbent best tiled; before any result exists every row
        # falls back to an independent random config (GreedySelectionMixin)
        fallback = space.random(krand, n)
        have = jnp.isfinite(best.qor)
        parent = CandBatch(
            jnp.where(have, jnp.tile(best.u[None, :], (n, 1)), fallback.u),
            tuple(jnp.where(have, jnp.tile(p[None, :], (n, 1)), f)
                  for p, f in zip(best.perms, fallback.perms)))
        cands = parent
        if self.crossover is not None and space.perm_sizes:
            crossed = crossover_perms(space, kx, parent, parent, parent,
                                      self.crossover, self.crossover_strength)
            do = jax.random.uniform(kxsel, (n, 1)) < self.crossover_rate
            cands = CandBatch(cands.u, tuple(
                jnp.where(do, c, p)
                for c, p in zip(crossed.perms, cands.perms)))
        cands = mutate_batch(space, kmut, cands, self.mutation_rate,
                             self.must_mutate_count, self.sigma)
        return state, space.normalize(cands)

    def observe(self, space, state, cands, qor, best):
        return state


class GlobalGA(GreedyMutation):
    """globalGA.py: crossover copies `crossover_strength * n_params` random
    parameter values from parent 2 into parent 1 (:68-76) before mutation.
    With greedy selection both parents are the incumbent best so the copy is
    an identity; kept for structural parity."""
    pass


def _register_all():
    for cx in ("OX3", "OX1", "PX", "CX", "PMX"):
        register(GreedyMutation(mutation_rate=0.10, crossover_rate=0.8,
                                crossover=cx, name=f"ga-{cx}"))
    register(GreedyMutation(mutation_rate=0.10, name="ga-base"))
    for rate in (0.05, 0.10, 0.20):
        register(GreedyMutation(mutation_rate=rate,
                                name=f"UniformGreedyMutation{int(rate*100):02d}"))
        register(GreedyMutation(mutation_rate=rate, sigma=0.1,
                                name=f"NormalGreedyMutation{int(rate*100):02d}"))
    register(GlobalGA(mutation_rate=0.1, sigma=0.1, crossover_rate=0.5,
                      crossover_strength=0.2, name="GGA"))


_register_all()
