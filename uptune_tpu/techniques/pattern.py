"""Batched pattern (compass) search.

Reference: `/root/reference/python/uptune/opentuner/search/
patternsearch.py:5-68` — keep a center config and step size, propose
up/down unit-space moves for every primitive parameter (random manipulators
for complex ones), move the center to the best improving point or halve the
step; adopt the global best if another technique found better.

Batched: one step samples `batch` random (parameter, direction) moves at
the current step size (fixed batch shape instead of 2·D proposals), and the
accept/shrink decision runs in observe().
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..space.spec import CandBatch, Space
from .base import Best, Technique, register
from .common import mutate_perm_random_op


class PatternState(NamedTuple):
    center: CandBatch      # [1, ...]
    center_qor: jax.Array  # scalar
    step: jax.Array        # scalar f32


class PatternSearch(Technique):
    def __init__(self, batch: int = 32, initial_step: float = 0.1,
                 name: str = "PatternSearch"):
        super().__init__(name)
        self.batch = batch
        self.initial_step = initial_step

    def natural_batch(self, space: Space) -> int:
        return self.batch

    def init_state(self, space: Space, key: jax.Array) -> PatternState:
        center = space.random(key, 1)
        return PatternState(center, jnp.asarray(jnp.inf),
                            jnp.asarray(self.initial_step, jnp.float32))

    def propose(self, space: Space, state: PatternState, key: jax.Array,
                best: Best) -> Tuple[PatternState, CandBatch]:
        n = self.batch
        kd, kdir, *kperm = jax.random.split(key, 2 + len(space.perm_sizes))
        P = space.n_scalar + len(space.perm_sizes)
        which = jax.random.randint(kd, (n,), 0, P)
        direction = jnp.where(jax.random.uniform(kdir, (n, 1)) < 0.5, -1.0, 1.0)
        base_u = jnp.tile(state.center.u, (n, 1))
        lane_sel = which[:, None] == jnp.arange(space.n_scalar)[None, :]
        u = jnp.clip(base_u + lane_sel * direction * state.step, 0.0, 1.0)
        perms = []
        for k_i, kk in enumerate(kperm):
            pm = jnp.tile(state.center.perms[k_i], (n, 1))
            sel = which == (space.n_scalar + k_i)
            perms.append(mutate_perm_random_op(kk, pm, sel))
        return state, space.normalize(CandBatch(u, tuple(perms)))

    def observe(self, space: Space, state: PatternState, cands: CandBatch,
                qor: jax.Array, best: Best) -> PatternState:
        i = jnp.argmin(qor)
        best_pt_qor = qor[i]
        improved = best_pt_qor < state.center_qor
        # priority: global best found elsewhere > improving point > shrink
        # (patternsearch.py:54-63)
        adopt_global = (best.qor < state.center_qor) & (best.qor < best_pt_qor)
        new_u = jnp.where(adopt_global, best.u,
                          jnp.where(improved, cands.u[i], state.center.u[0]))
        new_perms = tuple(
            jnp.where(adopt_global, b,
                      jnp.where(improved, c[i], p[0]))
            for b, c, p in zip(best.perms, cands.perms, state.center.perms))
        new_qor = jnp.where(adopt_global, best.qor,
                            jnp.minimum(state.center_qor, best_pt_qor))
        shrink = (~improved) & (~adopt_global)
        new_step = jnp.where(shrink, state.step * 0.5, state.step)
        return PatternState(
            CandBatch(new_u[None, :], tuple(p[None, :] for p in new_perms)),
            new_qor, new_step)


register(PatternSearch())
