"""Operator-level bandit mutation + composable DE + random portfolios.

Covers the last three registry gaps vs the reference
(VERDICT round 1, component #18):

* `AUCBanditMutationTechnique` (`/root/reference/python/uptune/opentuner/
  search/bandittechniques.py:204-261`): a bandit over individual
  (parameter, operator) mutators seeded from the global best.  The
  TPU-first redesign keeps the credit ON DEVICE: state carries an EMA
  improvement score per operator; propose() draws one operator per
  batch row from an epsilon-softmax over the credits, applies all
  operator kernels to the whole batch and where-selects — one XLA
  program, no host control flow (the random parameter choice of the
  reference's mutator pairs is folded into the operators themselves).
* `ComposableDiffEvolution` / `ComposableDiffEvolutionCX`
  (`search/composableevolutionarytechniques.py:386-525`): DE whose
  permutation handling is a composable crossover operator instead of
  the default shuffle degeneration.
* `--generate-bandit-technique` (`search/driver.py:71-73`,
  `bandittechniques.py:167-201`): a seeded random AUC-bandit portfolio
  over randomly-hyperparameterized sub-techniques.
"""
from __future__ import annotations

import random as _pyrandom
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..space.spec import CandBatch, Space
from .base import Best, Technique, register
from .bandit import AUCBanditMeta
from .common import crossover_perms, mutate_batch

# operator menu: (sigma, rate) mutation variants; sigma None = uniform
# resample (the reference's op1_randomize), else normal mutation
_OPS = (
    (None, 0.0),      # uniform-resample one param
    (0.01, 0.0),      # fine normal, one param
    (0.05, 0.0),
    (0.15, 0.0),
    (0.30, 0.0),      # coarse normal, one param
    (0.05, 0.25),     # normal over ~quarter of the params
)
N_OPS = len(_OPS)


class BMState(NamedTuple):
    credit: jax.Array     # [N_OPS] EMA of per-op improvement rate
    counts: jax.Array     # [N_OPS] pulls (for reporting)
    last_ops: jax.Array   # [B] op drawn for each row of the last batch


class BanditMutation(Technique):
    """Bandit-credited mutations of the global best configuration."""

    def __init__(self, batch: int = 48, epsilon: float = 0.15,
                 temperature: float = 0.1, decay: float = 0.05,
                 name: str = "AUCBanditMutationTechnique"):
        super().__init__(name)
        self.batch = batch
        self.epsilon = epsilon
        self.temperature = temperature
        self.decay = decay

    def natural_batch(self, space: Space) -> int:
        return self.batch

    def init_state(self, space: Space, key: jax.Array) -> BMState:
        return BMState(jnp.zeros(N_OPS), jnp.zeros(N_OPS, jnp.int32),
                       jnp.zeros(self.batch, jnp.int32))

    def propose(self, space: Space, state: BMState, key: jax.Array,
                best: Best) -> Tuple[BMState, CandBatch]:
        B = self.batch
        kop, krand, *kmut = jax.random.split(key, 2 + N_OPS)

        # seed from the global best; pure random until one exists
        # (bandittechniques.py:236-244 falls back the same way)
        have_best = jnp.isfinite(best.qor)
        seed_batch = best.as_batch(B)
        rand_batch = space.random(krand, B)
        base = CandBatch(
            jnp.where(have_best, seed_batch.u, rand_batch.u),
            tuple(jnp.where(have_best, s, r) for s, r in
                  zip(seed_batch.perms, rand_batch.perms)))

        # epsilon-softmax draw of one operator per row
        logits = state.credit / self.temperature
        probs = ((1.0 - self.epsilon) * jax.nn.softmax(logits)
                 + self.epsilon / N_OPS)
        ops = jax.random.categorical(
            kop, jnp.log(probs)[None, :].repeat(B, 0))      # [B]

        variants_u = []
        variants_p = []
        for i, (sigma, rate) in enumerate(_OPS):
            v = mutate_batch(space, kmut[i], base, rate=rate, must=1,
                             sigma=sigma)
            variants_u.append(v.u)
            variants_p.append(v.perms)
        vu = jnp.stack(variants_u)                           # [O, B, D]
        u = jnp.take_along_axis(
            vu, ops[None, :, None].astype(jnp.int32), axis=0)[0]
        perms = []
        for k_i in range(len(space.perm_sizes)):
            vp = jnp.stack([p[k_i] for p in variants_p])     # [O, B, s]
            perms.append(jnp.take_along_axis(
                vp, ops[None, :, None].astype(jnp.int32), axis=0)[0])
        counts = state.counts.at[ops].add(1)
        return (BMState(state.credit, counts, ops.astype(jnp.int32)),
                space.normalize(CandBatch(u, tuple(perms))))

    def observe(self, space: Space, state: BMState, cands: CandBatch,
                qor: jax.Array, best: Best) -> BMState:
        # `best` is already updated with this batch, so a row that SET
        # the new best satisfies qor <= best.qor
        improved = (qor <= best.qor) & jnp.isfinite(qor)
        # per-op improvement rate of this batch
        onehot = jax.nn.one_hot(state.last_ops, N_OPS)       # [B, O]
        pulls = onehot.sum(0)
        wins = (onehot * improved[:, None]).sum(0)
        rate = jnp.where(pulls > 0, wins / jnp.maximum(pulls, 1), 0.0)
        touched = pulls > 0
        credit = jnp.where(
            touched,
            (1.0 - self.decay) * state.credit + self.decay * rate,
            state.credit)
        return BMState(credit, state.counts, state.last_ops)


# ----------------------------------------------------------------------
class ComposableDE(Technique):
    """DE with a composable permutation-crossover operator: numeric lanes
    follow the standard x1 + F(x2-x3) rule via the parent class machinery;
    permutation blocks cross parents with PX/PMX/CX/OX1/OX3 instead of
    degenerating to a shuffle (composableevolutionarytechniques.py:386-443
    RandomThreeParentsComposableTechnique)."""

    def __init__(self, crossover: str = "OX1", population_size: int = 30,
                 cr: float = 0.9, name: str = None):
        super().__init__(name or f"ComposableDE-{crossover}")
        from .de import DifferentialEvolution
        self._de = DifferentialEvolution(
            population_size=population_size, cr=cr, name=self.name + "~de")
        self.crossover = crossover

    def natural_batch(self, space: Space) -> int:
        return self._de.natural_batch(space)

    def init_state(self, space: Space, key: jax.Array):
        return self._de.init_state(space, key)

    def propose(self, space: Space, state, key: jax.Array, best: Best):
        kde, kx = jax.random.split(key)
        state, cands = self._de.propose(space, state, kde, best)
        if space.perm_sizes:
            # cross the proposal's perms with the current population's
            # (child x parent crossover, the composable operator slot)
            cands = crossover_perms(space, kx, cands, cands, state.pop,
                                    self.crossover)
            cands = space.normalize(cands)
        return state, cands

    def observe(self, space: Space, state, cands: CandBatch,
                qor: jax.Array, best: Best):
        return self._de.observe(space, state, cands, qor, best)


# ----------------------------------------------------------------------
def generate_bandit_technique(seed: int = 0,
                              n_arms: int = None) -> AUCBanditMeta:
    """Seeded random AUC-bandit portfolio (`--generate-bandit-technique`,
    bandittechniques.py:167-201: random sub-technique count and random
    hyperparameters)."""
    from .annealing import PseudoAnnealingSearch
    from .de import DifferentialEvolution
    from .evolutionary import GlobalGA, GreedyMutation
    from .pattern import PatternSearch
    from .pso import PSO
    from .simplex import NelderMead, Torczon

    rng = _pyrandom.Random(seed)
    n = n_arms or rng.randint(2, 5)
    makers = [
        lambda i: DifferentialEvolution(
            population_size=rng.choice([15, 30, 50, 100]),
            cr=rng.choice([0.2, 0.5, 0.9]), name=f"rand-de-{i}"),
        lambda i: GreedyMutation(
            mutation_rate=rng.choice([0.01, 0.1, 0.3]),
            sigma=rng.choice([None, 0.05, 0.1, 0.3]),
            crossover=rng.choice([None, "OX1", "PMX", "CX"]),
            crossover_rate=rng.choice([0.0, 0.5, 0.8]),
            name=f"rand-gm-{i}"),
        lambda i: PSO(crossover=rng.choice(["OX1", "OX3", "PMX", "CX",
                                            "PX"]),
                      omega=rng.uniform(0.3, 0.8), name=f"rand-pso-{i}"),
        lambda i: NelderMead(init_style=rng.choice(["random", "right"]),
                             name=f"rand-nm-{i}"),
        lambda i: Torczon(init_style=rng.choice(["random", "right"]),
                          name=f"rand-tz-{i}"),
        lambda i: PseudoAnnealingSearch(name=f"rand-sa-{i}"),
        lambda i: PatternSearch(name=f"rand-ps-{i}"),
        lambda i: BanditMutation(name=f"rand-bm-{i}"),
    ]
    members = [rng.choice(makers)(i) for i in range(n)]
    return AUCBanditMeta(members, name=f"RandomBandit-{seed}",
                         seed=seed)


register(BanditMutation())
register(ComposableDE("OX1", name="ComposableDiffEvolution"))
register(ComposableDE("CX", name="ComposableDiffEvolutionCX"))
