"""Shared batched building blocks used by several techniques.

These encode the reference's per-parameter mutation dispatch
(`evolutionarytechniques.py:50-115`) over the flat encoding: a "parameter"
is either one scalar lane or one permutation block, and a mutation pass
picks, per candidate row, one forced parameter plus a Bernoulli subset of
the rest (mutation() at evolutionarytechniques.py:50-60).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops import numeric as nops
from ..ops import perm as pops
from ..space.spec import CandBatch, Space


def param_mutation_mask(space: Space, key: jax.Array, n: int,
                        rate: float, must: int = 1) -> jax.Array:
    """[n, n_params] bool: per row, `must` forced params (random, distinct)
    plus coin < rate on the others.  Param order = scalar lanes then perm
    blocks."""
    P = space.n_scalar + len(space.perm_sizes)
    kf, kc = jax.random.split(key)
    # `must` forced distinct params per row via random scores' top-k
    scores = jax.random.uniform(kf, (n, P))
    forced_idx = jnp.argsort(scores, axis=1)[:, :max(0, must)]
    forced = jnp.zeros((n, P), bool)
    if must > 0:
        forced = forced.at[jnp.arange(n)[:, None], forced_idx].set(True)
    coins = jax.random.uniform(kc, (n, P)) < rate
    return forced | coins


def mutate_perm_random_op(key: jax.Array, pm: jax.Array,
                          mask: jax.Array) -> jax.Array:
    """Apply one random permutation manipulator per masked row — the
    batched `random.choice(param.manipulators(cfg))(cfg)` of
    evolutionarytechniques.py:113-115.  Ops: shuffle, small random change,
    random swap, invert (d = n//4 min 1)."""
    n = pm.shape[1]
    ks, kc, kw, ki, kp = jax.random.split(key, 5)
    variants = jnp.stack([
        pops.shuffle_batch(ks, pm),
        pops.small_random_change_batch(kc, pm),
        pops.random_swap_batch(kw, pm),
        pops.random_invert_batch(ki, pm, max(1, n // 4)),
    ])  # [4, B, n]
    pick = jax.random.randint(kp, (pm.shape[0],), 0, 4)
    chosen = jnp.take_along_axis(
        variants, pick[None, :, None].astype(jnp.int32), axis=0)[0]
    return jnp.where(mask[:, None], chosen, pm)


def mutate_batch(space: Space, key: jax.Array, cands: CandBatch,
                 rate: float, must: int = 1,
                 sigma: Optional[float] = None) -> CandBatch:
    """One evolutionary mutation pass over a batch.

    sigma=None  -> uniform mutation (op1_randomize per selected param,
                   UniformGreedyMutation semantics)
    sigma=float -> normal mutation on primitive lanes, random manipulator
                   on complex/permutation params (NormalGreedyMutation)
    """
    n = cands.batch
    kmask, kmut, *kperm = jax.random.split(key, 2 + len(space.perm_sizes))
    mask = param_mutation_mask(space, kmask, n, rate, must)
    scal_mask = mask[:, :space.n_scalar]
    if sigma is None:
        u = nops.randomize(kmut, cands.u, scal_mask)
    else:
        u = nops.normal_mutation(kmut, cands.u, sigma,
                                 space.complex_mask[None, :], scal_mask)
    perms = []
    for k_i, (kk, pm) in enumerate(zip(kperm, cands.perms)):
        pmask = mask[:, space.n_scalar + k_i]
        if sigma is None:
            shuf = pops.shuffle_batch(kk, pm)
            perms.append(jnp.where(pmask[:, None], shuf, pm))
        else:
            perms.append(mutate_perm_random_op(kk, pm, pmask))
    return CandBatch(u, tuple(perms))


def perm_codes_equal(p1: jax.Array, p2: jax.Array) -> jax.Array:
    """[B] bool: rows equal (same_value for permutation blocks)."""
    return jnp.all(p1 == p2, axis=-1)


def de_linear_batch(space: Space, key: jax.Array,
                    base: CandBatch, x1: CandBatch, x2: CandBatch,
                    x3: CandBatch, f: jax.Array,
                    cross_mask: jax.Array) -> CandBatch:
    """The DE candidate construction: per selected param,
    cfg = x1 + f*(x2 - x3) (`differentialevolution.py:117-126`).

    Scalar lanes use op4_set_linear math with complex-lane
    randomize-if-differ degeneration (manipulator.py:523-542, 866-917);
    permutation blocks copy x1 and reshuffle iff x2 != x3
    (ComplexParameter.add_difference, manipulator.py:903-917).

    cross_mask: [B, n_params] bool (which params the DE crossover touches);
    unselected params keep `base` (the member being replaced).
    f: [B, 1] scale factor.
    """
    kc, *kperm = jax.random.split(key, 1 + len(space.perm_sizes))
    codes2 = space.decode_scalars(x2.u)
    codes3 = space.decode_scalars(x3.u)
    u = nops.set_linear(
        kc, x1.u, x2.u, x3.u, 1.0, f, -f,
        space.complex_mask[None, :], codes2 == codes3,
        mask=cross_mask[:, :space.n_scalar], base=base.u)
    perms = []
    for k_i, kk in enumerate(kperm):
        pmask = cross_mask[:, space.n_scalar + k_i]
        differ = ~perm_codes_equal(x2.perms[k_i], x3.perms[k_i])
        shuffled = pops.shuffle_batch(kk, x1.perms[k_i])
        new = jnp.where(differ[:, None], shuffled, x1.perms[k_i])
        perms.append(jnp.where(pmask[:, None], new, base.perms[k_i]))
    return CandBatch(u, tuple(perms))


def crossover_perms(space: Space, key: jax.Array, child: CandBatch,
                    a: CandBatch, b: CandBatch, op: str,
                    strength: float = 1.0 / 3.0,
                    min_size: int = 7) -> CandBatch:
    """Apply permutation crossover `op` (PX/PMX/CX/OX1/OX3) between parents
    a and b on every perm block of size >= min_size, writing into `child`'s
    perm slots (GA CrossoverMixin, evolutionarytechniques.py:117-133:
    only perm params with size > 6, d = size/3)."""
    if not space.perm_sizes:
        return child
    fn = pops.CROSSOVERS[op]
    keys = jax.random.split(key, len(space.perm_sizes))
    perms = []
    for kk, pa, pb, size in zip(keys, a.perms, b.perms, space.perm_sizes):
        if size >= min_size:
            d = max(1, int(round(size * strength)))
            vm = jax.vmap(lambda k, x, y: fn(k, x, y, d))
            perms.append(vm(jax.random.split(kk, pa.shape[0]), pa, pb))
        else:
            perms.append(pa)
    return CandBatch(child.u, tuple(perms))
