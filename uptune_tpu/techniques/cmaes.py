"""Batched CMA-ES: covariance-matrix-adaptation evolution strategy.

BEYOND-REFERENCE technique (the reference's portfolio stops at DE/GA/
PSO/simplex, search/technique.py:287-331): CMA-ES is the strongest
general-purpose continuous black-box optimizer in its class and maps
exceptionally well onto the TPU — the per-generation work is a [D, D]
eigendecomposition plus [λ, D] matmuls (MXU food), and the whole update
is one jitted program with static shapes.  Standard (μ/μ_w, λ) CMA-ES
with rank-1 + rank-μ covariance updates and cumulative step-size
adaptation (Hansen's tutorial formulation), operating in the unit cube
of `Space`'s scalar lanes.

Supports scalar-lane spaces only (no permutation blocks): the covariance
model has no meaning over permutations, so portfolios drop the arm on
such spaces via supports().
"""
from __future__ import annotations

import math
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..space.spec import CandBatch, Space
from .base import Best, Technique, register


class CMAState(NamedTuple):
    mean: jax.Array      # [D]
    cov: jax.Array       # [D, D]
    sigma: jax.Array     # scalar step size
    p_sigma: jax.Array   # [D] step-size evolution path
    p_c: jax.Array       # [D] covariance evolution path
    gen: jax.Array       # scalar i32 generation counter
    # cached eigendecomposition of `cov` (refreshed whenever cov
    # changes): one O(D^3) eigh per generation instead of two
    eig_b: jax.Array     # [D, D] eigenvector basis
    eig_sq: jax.Array    # [D] sqrt(eigenvalues)
    eig_isq: jax.Array   # [D] 1/sqrt(eigenvalues)


class CMAES(Technique):
    def __init__(self, population_size: int = 32,
                 sigma0: float = 0.3, name: str = "CMAES"):
        super().__init__(name)
        self.population_size = int(population_size)
        self.sigma0 = float(sigma0)

    def natural_batch(self, space: Space) -> int:
        return self.population_size

    def supports(self, space: Space) -> bool:
        return space.n_scalar >= 2 and not space.perm_sizes

    # -- strategy constants (depend only on D and λ: static under jit,
    #    computed with NumPy so tracing never sees them as arrays) --
    def _consts(self, d: int):
        import numpy as np

        lam = self.population_size
        mu = lam // 2
        w_np = np.log(mu + 0.5) - np.log(np.arange(1, mu + 1))
        w_np = w_np / w_np.sum()                      # [mu], sums to 1
        w = jnp.asarray(w_np, jnp.float32)
        mu_eff = 1.0 / float((w_np ** 2).sum())
        c_sigma = (mu_eff + 2.0) / (d + mu_eff + 5.0)
        d_sigma = (1.0 + 2.0 * max(0.0, math.sqrt((mu_eff - 1.0)
                                                  / (d + 1.0)) - 1.0)
                   + c_sigma)
        c_c = (4.0 + mu_eff / d) / (d + 4.0 + 2.0 * mu_eff / d)
        c_1 = 2.0 / ((d + 1.3) ** 2 + mu_eff)
        c_mu = min(1.0 - c_1,
                   2.0 * (mu_eff - 2.0 + 1.0 / mu_eff)
                   / ((d + 2.0) ** 2 + mu_eff))
        # E||N(0, I_d)||
        chi_d = math.sqrt(d) * (1.0 - 1.0 / (4.0 * d)
                                + 1.0 / (21.0 * d * d))
        return mu, w, mu_eff, c_sigma, d_sigma, c_c, c_1, c_mu, chi_d

    @staticmethod
    def _eig(cov: jax.Array):
        """Symmetric eigendecomposition with clamped spectrum: returns
        (B, sqrt_diag, inv_sqrt_diag)."""
        cov = 0.5 * (cov + cov.T)
        lam, b = jnp.linalg.eigh(cov)
        lam = jnp.clip(lam, 1e-10, 1e6)
        return b, jnp.sqrt(lam), 1.0 / jnp.sqrt(lam)

    def init_state(self, space: Space, key: jax.Array) -> CMAState:
        d = space.n_scalar
        return CMAState(
            jnp.full((d,), 0.5, jnp.float32),
            jnp.eye(d, dtype=jnp.float32),
            jnp.asarray(self.sigma0, jnp.float32),
            jnp.zeros((d,), jnp.float32),
            jnp.zeros((d,), jnp.float32),
            jnp.asarray(0, jnp.int32),
            jnp.eye(d, dtype=jnp.float32),
            jnp.ones((d,), jnp.float32),
            jnp.ones((d,), jnp.float32))

    def propose(self, space: Space, state: CMAState, key: jax.Array,
                best: Best) -> Tuple[CMAState, CandBatch]:
        lam = self.population_size
        d = space.n_scalar
        z = jax.random.normal(key, (lam, d), jnp.float32)
        y = (z * state.eig_sq[None, :]) @ state.eig_b.T  # ~ N(0, C)
        u = jnp.clip(state.mean[None, :] + state.sigma * y, 0.0, 1.0)
        cands = space.normalize(CandBatch(u, ()))
        return state, cands

    def observe(self, space: Space, state: CMAState, cands: CandBatch,
                qor: jax.Array, best: Best) -> CMAState:
        d = space.n_scalar
        (mu, w, mu_eff, c_sigma, d_sigma, c_c, c_1, c_mu,
         chi_d) = self._consts(d)

        # selection: μ best of the generation (failures rank last)
        q = jnp.where(jnp.isfinite(qor), qor, 1e30)
        order = jnp.argsort(q)[:mu]
        # y recovered from the evaluated candidates (includes the boundary
        # clip — the standard repair-and-update treatment)
        y_sel = (cands.u[order] - state.mean[None, :]) / state.sigma
        y_w = w @ y_sel                                       # [D]

        mean = state.mean + state.sigma * y_w
        b, isq = state.eig_b, state.eig_isq
        inv_sqrt_y = (y_w @ b) * isq @ b.T                    # C^-1/2 y_w
        p_sigma = ((1.0 - c_sigma) * state.p_sigma
                   + math.sqrt(c_sigma * (2.0 - c_sigma) * mu_eff)
                   * inv_sqrt_y)
        gen = state.gen + 1
        ps_norm = jnp.linalg.norm(p_sigma)
        # stalled-path indicator (Hansen's h_sigma)
        denom = jnp.sqrt(1.0 - (1.0 - c_sigma) ** (2.0 * gen))
        h_sigma = (ps_norm / denom
                   < (1.4 + 2.0 / (d + 1.0)) * chi_d).astype(jnp.float32)
        p_c = ((1.0 - c_c) * state.p_c
               + h_sigma * math.sqrt(c_c * (2.0 - c_c) * mu_eff) * y_w)

        rank1 = jnp.outer(p_c, p_c) \
            + (1.0 - h_sigma) * c_c * (2.0 - c_c) * state.cov
        rank_mu = (y_sel * w[:, None]).T @ y_sel              # Σ w y yᵀ
        cov = ((1.0 - c_1 - c_mu) * state.cov
               + c_1 * rank1 + c_mu * rank_mu)
        sigma = state.sigma * jnp.exp(
            (c_sigma / d_sigma) * (ps_norm / chi_d - 1.0))
        sigma = jnp.clip(sigma, 1e-8, 1.0)
        nb, nsq, nisq = self._eig(cov)   # the generation's one eigh
        return CMAState(mean, cov, sigma, p_sigma, p_c, gen,
                        nb, nsq, nisq)


register(CMAES())
