"""Technique framework: batched search strategies as pure JAX step functions.

The reference drives techniques one proposal at a time through generator
objects (`/root/reference/python/uptune/opentuner/search/technique.py:33-363`).
Here a technique is a *batched state machine*: it owns a pytree of device
arrays and two pure functions —

    state            = t.init_state(space, key)
    state, cands     = t.propose(space, state, key, best)     # jittable
    state            = t.observe(space, state, cands, qor, best)  # jittable

`propose` emits a whole CandBatch (the technique's `natural_batch(space)`
candidates) per step instead of one config per call; `observe` feeds the
measured QoR batch back.  Both are wrapped in `jax.jit` by the driver, so a
full propose→observe cycle is one XLA program per technique.

Conventions:

* QoR is always *minimized* inside the engine (the driver negates for
  'max' objectives, like the reference's MinimizeTime normal form,
  `search/objective.py:161-183`).  Missing/failed results are +inf.
* `best` carries the global best configuration and QoR — the cross-technique
  information-sharing channel (the reference reads `driver.best_result`,
  e.g. differentialevolution.py:111-113, evolutionarytechniques.py:90-95).
* All shapes are static given (space, technique hyperparams); no
  data-dependent control flow — decisions are `jnp.where` selections.

The registry mirrors the reference's global technique registry
(`search/technique.py:287-331`): instances registered by name, portfolios
included.
"""
from __future__ import annotations

from typing import Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..space.spec import CandBatch, Space


class Best(NamedTuple):
    """Global best configuration in flat encoding; qor == +inf before any
    result has been observed."""
    u: jax.Array                   # [D] f32
    perms: Tuple[jax.Array, ...]   # each [s_k] i32
    qor: jax.Array                 # scalar f32

    @staticmethod
    def empty(space: Space) -> "Best":
        return Best(
            jnp.zeros((space.n_scalar,), jnp.float32),
            tuple(jnp.arange(s, dtype=jnp.int32) for s in space.perm_sizes),
            jnp.asarray(jnp.inf, jnp.float32))

    def update(self, cands: CandBatch, qor: jax.Array) -> "Best":
        """Fold a measured batch into the running best (pure, jittable)."""
        i = jnp.argmin(qor)
        better = qor[i] < self.qor
        return Best(
            jnp.where(better, cands.u[i], self.u),
            tuple(jnp.where(better, p[i], q)
                  for p, q in zip(cands.perms, self.perms)),
            jnp.minimum(self.qor, qor[i]))

    def as_batch(self, n: int) -> CandBatch:
        return CandBatch(
            jnp.tile(self.u[None, :], (n, 1)),
            tuple(jnp.tile(p[None, :], (n, 1)) for p in self.perms))


class Technique:
    """Base class. Subclasses define hyperparameters in __init__ (static
    Python values — they specialize the jitted step) and implement the three
    state functions."""

    def __init__(self, name: Optional[str] = None):
        self.name = name or type(self).__name__

    # number of candidates emitted per propose() call
    def natural_batch(self, space: Space) -> int:
        raise NotImplementedError

    def supports(self, space: Space) -> bool:
        """False when the technique degenerates on this space (e.g. simplex
        methods on a pure-permutation space — the reference logs 'only 1
        point in simplex, will not use' and exits, simplextechniques.py:284)."""
        return True

    def init_state(self, space: Space, key: jax.Array):
        raise NotImplementedError

    def propose(self, space: Space, state, key: jax.Array, best: Best):
        raise NotImplementedError

    def observe(self, space: Space, state, cands: CandBatch,
                qor: jax.Array, best: Best):
        raise NotImplementedError

    def __repr__(self):
        return f"<{type(self).__name__} {self.name!r}>"


# --------------------------------------------------------------------------
# registry (the equivalent of search/technique.py:287-331)
# --------------------------------------------------------------------------
_registry: Dict[str, Technique] = {}


_experimental: set = set()


def register(t: Technique, experimental: bool = False) -> Technique:
    """`experimental=True` flags a registered name as measured BEHIND
    the defaults on the reference fixtures (surfaced as a suffix in
    `ut --list-techniques`); it stays selectable via --technique but
    its name alone must not suggest it is a recommended choice."""
    if t.name in _registry:
        raise ValueError(f"duplicate technique name {t.name!r}")
    _registry[t.name] = t
    if experimental:
        _experimental.add(t.name)
    return t


def is_experimental(name: str) -> bool:
    _ensure_loaded()
    return name in _experimental


def all_technique_names() -> List[str]:
    _ensure_loaded()
    return sorted(_registry)


def get_technique(name: str) -> Technique:
    _ensure_loaded()
    try:
        return _registry[name]
    except KeyError:
        raise KeyError(
            f"unknown technique {name!r}; known: {sorted(_registry)}") from None


def get_root(names: Optional[Sequence[str]] = None) -> Technique:
    """Resolve --technique args to a root technique: default portfolio when
    none given, the single technique when one, a round-robin portfolio when
    several (search/technique.py:345-362).

    Returns a deep copy: registry entries are shared singletons, but
    meta-techniques carry mutable host state (bandit credit window,
    round-robin cursor) that must not leak between tuning runs."""
    import copy
    _ensure_loaded()
    from .bandit import RoundRobinMeta  # circular-safe: bandit imports base
    if not names:
        return copy.deepcopy(_registry["AUCBanditMetaTechniqueA"])
    if len(names) == 1:
        return copy.deepcopy(get_technique(names[0]))
    return RoundRobinMeta([copy.deepcopy(get_technique(n)) for n in names],
                          name="+".join(names))


_loaded = False


def _ensure_loaded():
    """Import all technique modules so their register() calls run."""
    global _loaded
    if _loaded:
        return
    from . import purerandom, de, evolutionary, pso, annealing  # noqa: F401
    from . import pattern, simplex, bandit, banditmutation      # noqa: F401
    from . import cmaes                                         # noqa: F401
    _loaded = True
