"""Batched permutation operators as fixed-shape gather/scatter kernels.

TPU-native reimplementation of the reference's PermutationParameter /
ScheduleParameter operator set (`/root/reference/python/uptune/opentuner/
search/manipulator.py:1048-1445`): random shuffle, adjacent-bubble mutation,
segment inversion, and the PX / PMX / CX / OX1 / OX3 crossovers, plus the
dependency-respecting topological normalisation.

Every op works on a single permutation `[n] int32` (a row of item indices)
with a PRNG key, and is exposed batched via `jax.vmap` wrappers with the
`*_batch` suffix.  Cut *positions* are traced (data-dependent), but segment
*lengths* are static Python ints — the ops compile once per (n, d) pair and
contain no data-dependent shapes, as required for XLA.

Where the reference's list-based code is sequential (PMX repair chains, CX
cycle walks), we use bounded `fori_loop`s: PMX's mapping chains have length
<= d, CX's cycle has length <= n.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _inv(p: jax.Array) -> jax.Array:
    """Inverse permutation: inv[item] = position of item in p."""
    n = p.shape[-1]
    return jnp.zeros(n, p.dtype).at[p].set(jnp.arange(n, dtype=p.dtype))


def shuffle(key: jax.Array, p: jax.Array) -> jax.Array:
    """Uniform reshuffle (op1_randomize, manipulator.py:1058-1065)."""
    return jax.random.permutation(key, p)


def small_random_change(key: jax.Array, p: jax.Array, prob: float = 0.25) -> jax.Array:
    """Left-to-right adjacent-swap bubble pass (op1_small_random_change,
    manipulator.py:1067-1080): element i-1 swaps with i with probability
    `prob`, sequentially, so a value can bubble several positions right."""
    n = p.shape[0]
    do_swap = jax.random.uniform(key, (n,)) < prob  # index 0 unused

    def body(i, arr):
        a, b = arr[i - 1], arr[i]
        sw = do_swap[i]
        arr = arr.at[i - 1].set(jnp.where(sw, b, a))
        arr = arr.at[i].set(jnp.where(sw, a, b))
        return arr

    return lax.fori_loop(1, n, body, p)


def random_swap(key: jax.Array, p: jax.Array) -> jax.Array:
    """Swap two random positions (op2_random_swap, manipulator.py:1143-1159)."""
    n = p.shape[0]
    kr, ks = jax.random.split(key)
    r = jax.random.randint(kr, (), 0, n)
    s = jax.random.randint(ks, (), 0, n)
    pr, ps = p[r], p[s]
    return p.at[r].set(ps).at[s].set(pr)


def random_invert(key: jax.Array, p: jax.Array, d: int) -> jax.Array:
    """Reverse a random length-d window (op2_random_invert,
    manipulator.py:1161-1177).  d is static."""
    n = p.shape[0]
    d = max(1, min(int(d), n))
    r = jax.random.randint(key, (), 0, n - d + 1)
    i = jnp.arange(n)
    in_win = (i >= r) & (i < r + d)
    src = jnp.where(in_win, 2 * r + d - 1 - i, i)
    return p[src]


def cross_px(key: jax.Array, p1: jax.Array, p2: jax.Array, d: int = 0) -> jax.Array:
    """Partition crossover (op3_cross_PX, manipulator.py:1336-1352): pick a
    random cut c in [2, n] and reorder p1's first c elements by their order
    in p2; the tail keeps p1's order."""
    n = p1.shape[0]
    c = jax.random.randint(key, (), 2, n + 1)
    pos2 = _inv(p2)
    i = jnp.arange(n)
    # stable sort key: head elements rank by position-in-p2, tail keeps order
    sortkey = jnp.where(i < c, pos2[p1], n + i)
    order = jnp.argsort(sortkey, stable=True)
    return p1[order]


def cross_pmx(key: jax.Array, p1: jax.Array, p2: jax.Array, d: int) -> jax.Array:
    """Partially-mapped crossover, Goldberg & Lingle 1985 (op3_cross_PMX,
    manipulator.py:1199-1263): copy p2's window [r, r+d) into p1; values
    displaced outside the window follow the window's p2->p1 mapping chain
    until they land on a value not present in the copied window."""
    n = p1.shape[0]
    d = max(1, min(int(d), n))
    r = jax.random.randint(key, (), 0, n - d + 1)
    pos2 = _inv(p2)
    i = jnp.arange(n)
    in_win = (i >= r) & (i < r + d)

    def in_seg(v):  # value v is inside the copied p2-window?
        return (pos2[v] >= r) & (pos2[v] < r + d)

    # outside the window start from p1's value; chase the mapping <= d times
    def chase(_, v):
        return jnp.where(in_seg(v), p1[pos2[v]], v)

    fixed = lax.fori_loop(0, d, chase, p1)
    return jnp.where(in_win, p2, fixed)


def cross_cx(key: jax.Array, p1: jax.Array, p2: jax.Array, d: int = 0) -> jax.Array:
    """Cyclic crossover (op3_cross_CX, manipulator.py:1265-1302): walk the
    cycle i -> pos2[p1[i]] from a random start, then take p2's values on the
    cycle and p1's elsewhere."""
    n = p1.shape[0]
    s = jax.random.randint(key, (), 0, n)
    pos2 = _inv(p2)

    def body(_, carry):
        i, mask, done = carry
        mask = mask.at[i].set(True)
        nxt = pos2[p1[i]]
        done = done | (nxt == s)
        i = jnp.where(done, i, nxt)
        return i, mask, done

    _, mask, _ = lax.fori_loop(
        0, n, body, (s, jnp.zeros(n, bool), jnp.asarray(False)))
    return jnp.where(mask, p2, p1)


def _ox(key: jax.Array, p1: jax.Array, p2: jax.Array, d: int,
        same_cut: bool) -> jax.Array:
    """Shared core of OX1/OX3 (manipulator.py:1304-1356): insert p2's window
    [r2, r2+d) at position r1 of the sequence formed by p1's remaining
    elements in p1-order."""
    n = p1.shape[0]
    d = max(1, min(int(d), n))
    k1, k2 = jax.random.split(key)
    r2 = jax.random.randint(k2, (), 0, n - d + 1)
    r1 = r2 if same_cut else jax.random.randint(k1, (), 0, n - d + 1)
    pos2 = _inv(p2)
    seg_of = (pos2 >= r2) & (pos2 < r2 + d)        # by item id
    keep = ~seg_of[p1]                              # p1 positions kept
    rem_rank = jnp.cumsum(keep.astype(jnp.int32)) - 1
    out_keep = jnp.where(rem_rank < r1, rem_rank, rem_rank + d)
    out_idx = jnp.where(keep, out_keep, r1 + (pos2[p1] - r2))
    return jnp.zeros_like(p1).at[out_idx].set(p1)


def cross_ox1(key: jax.Array, p1: jax.Array, p2: jax.Array, d: int) -> jax.Array:
    """Ordered crossover, Davis 1985 (op3_cross_OX1): one shared cut."""
    return _ox(key, p1, p2, d, same_cut=True)


def cross_ox3(key: jax.Array, p1: jax.Array, p2: jax.Array, d: int) -> jax.Array:
    """Ordered crossover v3, Deep 2010 (op3_cross_OX3): independent cuts."""
    return _ox(key, p1, p2, d, same_cut=False)


CROSSOVERS = {
    "PX": cross_px,
    "PMX": cross_pmx,
    "CX": cross_cx,
    "OX1": cross_ox1,
    "OX3": cross_ox3,
}


def toposort_one(p: jax.Array, dep: jax.Array) -> jax.Array:
    """Stable topological normalisation of one permutation.

    dep[i, j] True means item i requires item j earlier.  Emits, n times, the
    not-yet-emitted item with all prerequisites emitted that currently sits
    earliest in p.  This is the *intent* of ScheduleParameter.normalize
    (manipulator.py:1425-1445); the reference's queue implementation reverses
    its output (and its `is_topologically_sorted` guard uses `union` where
    `difference` was meant, manipulator.py:1400-1406) — we implement the
    correct stable ordering rather than reproducing those bugs.
    """
    n = p.shape[0]
    rank = _inv(p)  # rank[item] = current position

    def body(i, carry):
        emitted, out = carry
        ready = (~emitted) & jnp.all((~dep) | emitted[None, :], axis=1)
        score = jnp.where(ready, rank, n + 1)
        item = jnp.argmin(score).astype(p.dtype)
        emitted = emitted.at[item].set(True)
        out = out.at[i].set(item)
        return emitted, out

    _, out = lax.fori_loop(
        0, n, body, (jnp.zeros(n, bool), jnp.zeros(n, p.dtype)))
    return out


@functools.partial(jax.jit, static_argnames=())
def toposort_batch(pm: jax.Array, dep: jax.Array) -> jax.Array:
    """[B, n] batched topological normalisation."""
    return jax.vmap(toposort_one, in_axes=(0, None))(pm, dep)


# -- batched wrappers -------------------------------------------------------

def _vmap1(fn):
    """Batch a (key, p, ...) op over [B, n] with per-row keys."""
    @functools.wraps(fn)
    def wrapped(key, pm, *args, **kwargs):
        keys = jax.random.split(key, pm.shape[0])
        return jax.vmap(lambda k, p: fn(k, p, *args, **kwargs))(keys, pm)
    return wrapped


def _vmap2(fn):
    @functools.wraps(fn)
    def wrapped(key, pm1, pm2, *args, **kwargs):
        keys = jax.random.split(key, pm1.shape[0])
        return jax.vmap(lambda k, a, b: fn(k, a, b, *args, **kwargs))(
            keys, pm1, pm2)
    return wrapped


shuffle_batch = _vmap1(shuffle)
small_random_change_batch = _vmap1(small_random_change)
random_swap_batch = _vmap1(random_swap)
random_invert_batch = _vmap1(random_invert)
cross_px_batch = _vmap2(cross_px)
cross_pmx_batch = _vmap2(cross_pmx)
cross_cx_batch = _vmap2(cross_cx)
cross_ox1_batch = _vmap2(cross_ox1)
cross_ox3_batch = _vmap2(cross_ox3)
