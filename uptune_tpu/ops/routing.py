"""Shared Pallas-kernel routing: one documented decision function for
every kernel/XLA fork in the repo.

Before this module each Pallas surface carried its own ad-hoc gate:
`ops/dedup.py` forked on ``backend == "tpu" and shapes qualify``,
`surrogate/pallas_score.py` + `gp.score_flat` on the bare
``PALLAS_MIN_POOL`` constant with an ``interpret = backend != "tpu"``
default, and the new fused acquisition kernel (`ops/acquire.py`) would
have added a third copy.  They all route here now, under one
user-facing knob:

    UT_PALLAS=off | interpret | auto     (env, highest precedence)
    ut.config(pallas='off'|'interpret'|'auto')
    default: auto

* ``auto``      — the production policy: the compiled Pallas kernel on
  TPU when the call site's shapes qualify, the interpret-mode kernel on
  CPU past each site's min-rows threshold (where the site opts in —
  the `gp.score_flat` scoring kernels do, so the CPU mesh exercises
  kernel math; the dedup merge and the fused acquisition pipeline do
  not, their XLA fallbacks measure faster there), and the plain-XLA
  fallback otherwise.
* ``interpret`` — force the kernel route in interpret mode everywhere
  the shapes are SUPPORTED, regardless of backend or batch size: the
  debugging/CI setting that makes every kernel's math observable and
  bitwise-comparable on any host.
* ``off``       — force the XLA fallback everywhere: the bisection
  setting (is a regression in the kernel or around it?).

The decision runs at TRACE time (python, static shapes) — no
jit-reachable host reads.
"""
from __future__ import annotations

import os

MODES = ("off", "interpret", "auto")

# route verdicts
PALLAS = "pallas"        # compiled kernel (TPU)
INTERPRET = "interpret"  # kernel in pallas interpret mode (any host)
XLA = "xla"              # plain-XLA fallback


def pallas_mode(env: dict = None) -> str:
    """The session's routing mode: ``UT_PALLAS`` env var >
    ``ut.config('pallas')`` > ``'auto'``.  Unknown values raise — a
    typo'd UT_PALLAS silently falling back to auto would unforce the
    route mid-debug."""
    e = os.environ if env is None else env
    val = (e.get("UT_PALLAS") or "").strip().lower()
    if not val:
        from ..api import session as _session
        val = (_session.settings.get("pallas") or "auto")
        val = str(val).strip().lower()
    if val not in MODES:
        raise ValueError(
            f"UT_PALLAS/config('pallas') must be one of {MODES}: {val!r}")
    return val


def decide(n_rows: int, min_rows: int = 0, supported: bool = True,
           cpu_ok: bool = True, mode: str = None) -> str:
    """Route one kernel call site: 'pallas' | 'interpret' | 'xla'.

    `n_rows`/`min_rows` express the site's size gate (dedup's merge has
    none — it passes min_rows=0); `supported` is the site's static
    shape-qualification predicate; `cpu_ok` says whether the site wants
    the interpret-mode kernel on non-TPU hosts in auto mode (the
    `gp.score_flat` scoring kernels do; the dedup merge and the fused
    acquisition pipeline do not — their XLA fallbacks are faster on
    CPU).
    `mode` overrides `pallas_mode()` for explicit-impl call sites."""
    mode = pallas_mode() if mode is None else mode
    if not supported or mode == "off":
        return XLA
    if mode == "interpret":
        return INTERPRET
    import jax
    if jax.default_backend() == "tpu":
        return PALLAS if n_rows >= min_rows else XLA
    return INTERPRET if (cpu_ok and n_rows >= min_rows) else XLA


def interpret_default() -> bool:
    """The `interpret=None` resolution for kernel entries a caller
    reaches DIRECTLY (the route fork already happened upstream, or the
    caller forced the kernel explicitly): forced-interpret mode wins;
    otherwise interpret off-TPU — the historical per-kernel default,
    now honored in one place."""
    if pallas_mode() == "interpret":
        return True
    import jax
    return jax.default_backend() != "tpu"


def interpret_flag(route: str) -> bool:
    """The `interpret=` argument a pallas_call should receive for a
    kernel-route verdict (PALLAS or INTERPRET)."""
    assert route in (PALLAS, INTERPRET), route
    return route == INTERPRET
