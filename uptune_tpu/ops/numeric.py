"""Batched unit-space scalar operators.

TPU-native reimplementation of the reference's primitive-parameter operator
algebra (`/root/reference/python/uptune/opentuner/search/manipulator.py:
446-737`).  All scalar lanes hold unit values in [0, 1] (the scale the
reference searches primitives on), so every operator is a pure elementwise
function over `[B, D]` float32 arrays — exactly what the MXU/VPU want.

"Complex" lanes (bool / switch / enum — non-cartesian parameters in the
reference, manipulator.py:841-1046) are handled by masks:

* linear-combination (DE's engine, `op4_set_linear` manipulator.py:523-542 /
  :866-917) degenerates to copy-a-then-randomize-if-b-differs-from-c;
* normal mutation degenerates to a uniform redraw (the reference picks a
  random manipulator — randomize/flip — for complex params,
  evolutionarytechniques.py:104-115).

Equality of complex lanes is decided on *decoded codes*, not raw unit
values, so two unit values that round to the same enum option count as
equal (matching `same_value`, manipulator.py:851-853).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def reflect_unit(v: jax.Array) -> jax.Array:
    """Reflect out-of-range values back into [0, 1] the way
    op1_normal_mutation does (manipulator.py:505-521): negative values flip
    sign; values > 1 map to 1 - (v mod 1)."""
    v = jnp.abs(v)
    return jnp.where(v > 1.0, 1.0 - jnp.mod(v, 1.0), v)


def randomize(key: jax.Array, u: jax.Array,
              mask: Optional[jax.Array] = None) -> jax.Array:
    """Uniform redraw of (masked) lanes — op1_randomize
    (manipulator.py:595-605) batched.  `mask` broadcasts against u."""
    r = jax.random.uniform(key, u.shape, dtype=u.dtype)
    if mask is None:
        return r
    return jnp.where(mask, r, u)


def normal_mutation(key: jax.Array, u: jax.Array, sigma: float,
                    complex_mask: jax.Array,
                    mask: Optional[jax.Array] = None) -> jax.Array:
    """op1_normal_mutation (manipulator.py:505-521) on primitive lanes,
    uniform redraw on complex lanes; `mask` selects which lanes mutate."""
    kn, kr = jax.random.split(key)
    noisy = reflect_unit(u + sigma * jax.random.normal(kn, u.shape, u.dtype))
    redraw = jax.random.uniform(kr, u.shape, dtype=u.dtype)
    out = jnp.where(complex_mask, redraw, noisy)
    if mask is None:
        return out
    return jnp.where(mask, out, u)


def set_linear(key: jax.Array,
               ua: jax.Array, ub: jax.Array, uc: jax.Array,
               a: jax.Array, b: jax.Array, c: jax.Array,
               complex_mask: jax.Array,
               codes_equal_bc: jax.Array,
               mask: Optional[jax.Array] = None,
               base: Optional[jax.Array] = None) -> jax.Array:
    """a*ua + b*ub + c*uc clipped to [0, 1] on primitive lanes
    (op4_set_linear, manipulator.py:523-542); on complex lanes copy ua and
    redraw only where ub's and uc's decoded codes differ (add_difference,
    manipulator.py:905-917).

    `mask` selects which lanes the operator applies to (DE's per-parameter
    crossover mask); unmasked lanes keep `base` (default ua).
    """
    if base is None:
        base = ua
    lin = jnp.clip(a * ua + b * ub + c * uc, 0.0, 1.0)
    redraw = jax.random.uniform(key, ua.shape, dtype=ua.dtype)
    cplx = jnp.where(codes_equal_bc, ua, redraw)
    out = jnp.where(complex_mask, cplx, lin)
    if mask is None:
        return out
    return jnp.where(mask, out, base)


def scale(u: jax.Array, k: float) -> jax.Array:
    """op1_scale (manipulator.py:607-617) in unit space."""
    return jnp.clip(u * k, 0.0, 1.0)


def swarm(key: jax.Array, u: jax.Array, u_local: jax.Array,
          u_global: jax.Array, velocity: jax.Array,
          complex_mask: jax.Array, bool_mask: jax.Array,
          c: float = 1.0, c1: float = 0.5, c2: float = 0.5):
    """One PSO position/velocity update per lane, the batched op3_swarm
    (manipulator.py:660-700 int / :725-745 float / :965-997 bool /
    :409-423 generic complex).

    Primitive lanes follow the float form (position += velocity, clip) —
    on the unit scale the integer variant's sigmoid squashing reduces to
    the same move.  BOOL lanes use the reference's sigmoid-as-coin form.
    Other complex lanes (SWITCH/ENUM) use the generic ComplexParameter
    fallback: stochastically keep the current value or copy the local/
    global best, weighted by (c, c1, c2) — every option stays reachable.

    Returns (new_u, new_velocity).
    """
    k1, k2, k3, k4 = jax.random.split(key, 4)
    r1 = jax.random.uniform(k1, u.shape, u.dtype)
    r2 = jax.random.uniform(k2, u.shape, u.dtype)
    v = velocity * c + (u_local - u) * c1 * r1 + (u_global - u) * c2 * r2
    prim = jnp.clip(u + v, 0.0, 1.0)
    # bool lanes: sigmoid(v) vs uniform coin decides 1/0
    coin = jax.random.uniform(k3, u.shape, u.dtype)
    boolean = (jax.nn.sigmoid(v) - coin > 0).astype(u.dtype)
    # other complex lanes: stochastic mix of (current, local, global)
    total = c + c1 + c2
    pick = jax.random.uniform(k4, u.shape, u.dtype) * total
    mixed = jnp.where(pick < c, u, jnp.where(pick < c + c1, u_local, u_global))
    cplx = jnp.where(bool_mask, boolean, mixed)
    return jnp.where(complex_mask, cplx, prim), v
