"""Batched operator kernels over the flat space encoding."""
from . import numeric, perm  # noqa: F401
