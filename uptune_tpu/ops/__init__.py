"""Batched operator kernels over the flat space encoding."""
# NOTE: ops.acquire is deliberately NOT imported here — it imports
# surrogate.pallas_score (shared tile math), and surrogate/__init__
# imports ops.perm via the manager, so pulling acquire at package init
# would close an import cycle.  Consumers import uptune_tpu.ops.acquire
# directly.
from . import numeric, perm, routing  # noqa: F401
