"""Fused acquisition pipeline: surrogate score + acquisition transform
+ streaming top-k as ONE device program over a flat candidate batch.

The propose hot path used to be three dispatches with HBM round trips
between them: `surrogate/pallas_score.py` produced mu/sd `[B]`, the
acquisition transform (EI / LCB / mean) read them back, and selection
ran `argsort`/`top_k` over the `[B]` score vector.  At north-star batch
sizes (`[N*B]` flat rows from the batched engine, ISSUE 19) those
intermediates are pure HBM traffic: every value the pipeline ships
between stages is recomputable inside the tile that produced it.

This module collapses the pipeline.  Each grid step loads one
`[TILE, F]` candidate tile plus the `[N, F]` train block, `alpha`, and
the premasked `K^-1` into VMEM, computes the cross-kernel tile, the
posterior moments (the `pallas_score` quadratic-form tiling), the
acquisition UTILITY (higher = better), and — in the top-k variant — a
streaming per-tile selection, writing only `[TILE]` utilities or
`[KPAD]` (value, index) lanes per tile.  Nothing of size `[B, N]` or
even `[B]` crosses HBM between stages.

Route selection follows the `ops/dedup.py` precedent via
`ops/routing.py` (`UT_PALLAS` / `ut.config('pallas')`): the compiled
kernel on TPU past `MIN_ROWS` and the single-program XLA fallback
everywhere else — including CPU in auto mode (`cpu_ok=False`, like
dedup's merge: at the bench shape the fallback beats the pre-fusion
staging ~1.1x while the interpret-mode emulation loses ~8%, so auto
must not pay the emulator for production CPU runs).  Force
`UT_PALLAS=interpret` to exercise kernel math on any host.  The
fallback runs the SAME tile function under `lax.map` — identical
shapes, identical op sequence per tile — so kernel-vs-fallback parity
is bitwise by construction, not by tolerance (tier-1 tested).

Top-k semantics match `lax.top_k` exactly: values descending, ties
broken by the LOWEST flat candidate index.  The kernel selects
`min(k, TILE)` local winners per tile by repeated max + lowest-index
tie-break, then one tiny `[n_tiles * KPAD]` merge outside the grid
reproduces the global order (each tile's winners are emitted in
(value desc, index asc) order and tiles concatenate in index order, so
the merge's positional tie-break equals the global index tie-break).

VMEM budget per grid step (f32, the mean+variance kinds): the
candidate tile `4*TILE*F`, train block `4*N*F`, `K^-1` `4*N*N`, and
two `[TILE, N]` intermediates — at TILE=1024, N=1024, F<=64 that is
~12.6 MB, the same envelope `pallas_score._mean_var_padded` already
ships under the 16 MB/core budget (docs/PERF.md "Fused acquisition
pipeline").
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from . import routing
# math helpers are SHARED with the scoring kernel (bitwise contract);
# pallas_score imports nothing from ops, so this edge is acyclic —
# but ops/__init__ must NOT import this module (surrogate/manager
# imports ops.perm at package init)
from ..surrogate.pallas_score import (PALLAS_MIN_POOL, ROWS, VLANES,
                                      VTILE, _matern_tile, _tile_d2)

LANES = VLANES        # 128-lane output width (scores variant)
TILE = VTILE          # 1024 candidate rows per grid step
KLANES = 128          # top-k output lane quantum (KPAD = ceil to this)
MIN_ROWS = PALLAS_MIN_POOL  # auto-route threshold, shared with scoring

KINDS = ("mean", "ei", "lcb")


# ---------------------------------------------------------- tile math
def _ei_transform(mu, sd, best_y, beta):
    from ..surrogate import gp as _gp  # lazy: gp imports nothing of ops
    return _gp.ei_from_moments(mu, sd, best_y)


def _lcb_transform(mu, sd, best_y, beta):
    return -(mu - beta * sd)


# static-kind dispatch (bound at trace time; 'mean' short-circuits on
# its missing kinv before the transform is reached)
_TRANSFORM = {"ei": _ei_transform, "lcb": _lcb_transform}


def _utility_tile(qc, qk, xc, xk, alpha, kinv, params, kind: str):
    """Acquisition utility (higher = better) for ONE candidate tile, as
    a (ROWS, LANES) block — the single source of math for BOTH the
    Pallas kernel body and the XLA fallback (bitwise parity rests on
    this sharing).  `params` is the (1, 8) scalar pack (anything
    supporting [0, j] reads: an SMEM ref in-kernel, a jnp array in the
    fallback); `kinv` is the premasked K^-1 (None for kind='mean',
    which needs no variance)."""
    if qc is None:
        k = jnp.exp(-_tile_d2(qk, xk))
    else:
        k = _matern_tile(_tile_d2(qc, xc))
        if qk is not None:
            k = k * jnp.exp(-_tile_d2(qk, xk))
    noise, y_mean, y_std = params[0, 0], params[0, 1], params[0, 2]
    best_y, beta = params[0, 3], params[0, 4]
    mu = (k @ alpha).reshape(ROWS, LANES) * y_std + y_mean
    if kinv is None:            # 'mean': no variance needed
        return -mu
    w = jnp.dot(k, kinv, preferred_element_type=jnp.float32)
    q = (w * k).sum(axis=1).reshape(ROWS, LANES)
    sd = jnp.sqrt(jnp.maximum(1.0 + noise - q, 1e-9)) * y_std
    return _TRANSFORM[kind](mu, sd, best_y, beta)


def _local_topk(u, gidx, k_sel: int, kpad: int):
    """Streaming in-tile selection: `k_sel` rounds of (max value,
    lowest-flat-index tie-break, mask) over the (ROWS, LANES) utility
    block — the exact `lax.top_k` order.  Returns ((1, kpad) values
    desc, (1, kpad) global indices); unfilled lanes hold (-inf, 2^30)
    and can only surface when fewer than k finite candidates exist."""
    col = jax.lax.broadcasted_iota(jnp.int32, (1, kpad), 1)
    big = jnp.int32(1 << 30)
    neg = jnp.float32(-jnp.inf)

    def body(j, carry):
        vals, idxs, uu = carry
        m = jnp.max(uu)
        sel = jnp.min(jnp.where(uu == m, gidx, big))
        vals = jnp.where(col == j, m, vals)
        idxs = jnp.where(col == j, sel, idxs)
        return vals, idxs, jnp.where(gidx == sel, neg, uu)

    vals0 = jnp.full((1, kpad), neg, jnp.float32)
    idxs0 = jnp.full((1, kpad), big, jnp.int32)
    vals, idxs, _ = jax.lax.fori_loop(
        0, k_sel, body, (vals0, idxs0, u))
    return vals, idxs


def _unpack(refs, kind: str, has_cont: bool, has_cat: bool):
    """Positional ref unpack shared by both kernel bodies (the spec
    list is built with the same flags in `_call_specs`)."""
    it = iter(refs)
    qc = next(it)[:] if has_cont else None
    qk = next(it)[:] if has_cat else None
    xc = next(it)[:] if has_cont else None
    xk = next(it)[:] if has_cat else None
    alpha = next(it)[:]
    kinv = next(it)[:] if kind != "mean" else None
    params = next(it)     # ref, read scalar-wise in _utility_tile
    return qc, qk, xc, xk, alpha, kinv, params, list(it)


def _scores_kernel(*refs, kind: str, has_cont: bool, has_cat: bool):
    qc, qk, xc, xk, alpha, kinv, params, (out_ref,) = _unpack(
        refs, kind, has_cont, has_cat)
    out_ref[:] = _utility_tile(qc, qk, xc, xk, alpha, kinv, params, kind)


def _topk_kernel(*refs, kind: str, has_cont: bool, has_cat: bool,
                 k_sel: int, kpad: int, b_real: int):
    from jax.experimental import pallas as pl
    qc, qk, xc, xk, alpha, kinv, params, (vals_ref, idx_ref) = _unpack(
        refs, kind, has_cont, has_cat)
    u = _utility_tile(qc, qk, xc, xk, alpha, kinv, params, kind)
    # global flat candidate index of each block element (row-major,
    # matching the scores variant's reshape(B)); padded tail rows are
    # masked out of the selection entirely
    r = jax.lax.broadcasted_iota(jnp.int32, (ROWS, LANES), 0)
    c = jax.lax.broadcasted_iota(jnp.int32, (ROWS, LANES), 1)
    gidx = pl.program_id(0) * (ROWS * LANES) + r * LANES + c
    u = jnp.where(gidx < b_real, u, jnp.float32(-jnp.inf))
    vals, idxs = _local_topk(u, gidx, k_sel, kpad)
    vals_ref[:] = jnp.broadcast_to(vals, (ROWS, kpad))
    idx_ref[:] = jnp.broadcast_to(idxs, (ROWS, kpad))


# ------------------------------------------------------- pallas calls
def _pl_setup():
    from jax.experimental import pallas as pl
    try:
        from jax.experimental.pallas import tpu as pltpu
        vmem, smem = pltpu.VMEM, pltpu.SMEM
    except ImportError:  # pragma: no cover
        vmem = smem = None

    def spec(shape, index_map=None, space=None):
        kw = ({"memory_space": space or vmem}
              if vmem is not None else {})
        return pl.BlockSpec(shape, index_map, **kw)

    return pl, spec, smem


def _specs(spec, smem, qc, qk, xc, xk, alpha, kinv, params):
    """(in_specs, args) for one fused call, in `_unpack` order: query
    tiles stream by grid step; train blocks, alpha and K^-1 stay VMEM-
    resident across the grid; the scalar pack rides SMEM."""
    n = alpha.shape[0]
    in_specs, args = [], []
    if qc is not None:
        in_specs.append(spec((TILE, qc.shape[1]), lambda i: (i, 0)))
        args.append(qc)
    if qk is not None:
        in_specs.append(spec((TILE, qk.shape[1]), lambda i: (i, 0)))
        args.append(qk)
    if xc is not None:
        in_specs.append(spec((n, xc.shape[1]), lambda i: (0, 0)))
        args.append(xc)
    if xk is not None:
        in_specs.append(spec((n, xk.shape[1]), lambda i: (0, 0)))
        args.append(xk)
    in_specs.append(spec((n,), lambda i: (0,)))
    args.append(alpha)
    if kinv is not None:
        in_specs.append(spec((n, n), lambda i: (0, 0)))
        args.append(kinv)
    in_specs.append(spec((1, 8), lambda i: (0, 0), space=smem))
    args.append(params)
    return in_specs, args


@functools.partial(jax.jit, static_argnames=("kind", "interpret"))
def _scores_padded(qc, qk, xc, xk, alpha, kinv, params,
                   kind: str, interpret: bool):
    """Kernel route: [Bpad] utilities (Bpad a TILE multiple)."""
    pl, spec, smem = _pl_setup()
    b = (qc if qc is not None else qk).shape[0]
    in_specs, args = _specs(spec, smem, qc, qk, xc, xk, alpha, kinv,
                            params)
    kernel = functools.partial(
        _scores_kernel, kind=kind,
        has_cont=qc is not None, has_cat=qk is not None)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b // LANES, LANES), jnp.float32),
        grid=(b // TILE,),
        in_specs=in_specs,
        out_specs=spec((ROWS, LANES), lambda i: (i, 0)),
        interpret=interpret,
    )(*args)
    return out.reshape(b)


@functools.partial(jax.jit,
                   static_argnames=("kind", "k", "b_real", "interpret"))
def _topk_padded(qc, qk, xc, xk, alpha, kinv, params,
                 kind: str, k: int, b_real: int, interpret: bool):
    """Kernel route: per-tile streaming top-k + one [n_tiles * kpad]
    merge -> (values [k] desc, flat indices [k] i32)."""
    pl, spec, smem = _pl_setup()
    b = (qc if qc is not None else qk).shape[0]
    nt = b // TILE
    k_sel = min(k, TILE)
    kpad = -(-k_sel // KLANES) * KLANES
    in_specs, args = _specs(spec, smem, qc, qk, xc, xk, alpha, kinv,
                            params)
    kernel = functools.partial(
        _topk_kernel, kind=kind,
        has_cont=qc is not None, has_cat=qk is not None,
        k_sel=k_sel, kpad=kpad, b_real=b_real)
    ospec = spec((ROWS, kpad), lambda i: (i, 0))
    vals, idxs = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((nt * ROWS, kpad), jnp.float32),
                   jax.ShapeDtypeStruct((nt * ROWS, kpad), jnp.int32)),
        grid=(nt,),
        in_specs=in_specs,
        out_specs=(ospec, ospec),
        interpret=interpret,
    )(*args)
    # row 0 of each block carries the tile's winners; tiles concatenate
    # in candidate-index order, so the merge's positional tie-break
    # reproduces lax.top_k's global lowest-index tie-break
    tv = vals.reshape(nt, ROWS, kpad)[:, 0, :].reshape(nt * kpad)
    ti = idxs.reshape(nt, ROWS, kpad)[:, 0, :].reshape(nt * kpad)
    mv, mp = jax.lax.top_k(tv, k)
    return mv, jnp.minimum(ti[mp], jnp.int32(b_real - 1))


# ------------------------------------------------------- XLA fallback
def _utilities_xla(qc, qk, xc, xk, alpha, kinv, params, kind: str):
    """[Bpad] utilities as ONE XLA program: the SAME tile function the
    kernel runs, under lax.map over the SAME [TILE, ...] tiles — per-
    tile intermediates only (no [B, N] in flight), and bitwise-equal
    per-row results by construction."""
    b = (qc if qc is not None else qk).shape[0]
    nt = b // TILE

    def tiles(a):
        return None if a is None else a.reshape(nt, TILE, a.shape[1])

    def body(t):
        tqc, tqk = t
        return _utility_tile(tqc, tqk, xc, xk, alpha, kinv, params,
                             kind).reshape(TILE)

    return jax.lax.map(body, (tiles(qc), tiles(qk))).reshape(b)


@functools.partial(jax.jit, static_argnames=("kind",))
def _scores_xla(qc, qk, xc, xk, alpha, kinv, params, kind: str):
    return _utilities_xla(qc, qk, xc, xk, alpha, kinv, params, kind)


@functools.partial(jax.jit, static_argnames=("kind", "k", "b_real"))
def _topk_xla(qc, qk, xc, xk, alpha, kinv, params,
              kind: str, k: int, b_real: int):
    u = _utilities_xla(qc, qk, xc, xk, alpha, kinv, params, kind)
    gidx = jnp.arange(u.shape[0], dtype=jnp.int32)
    u = jnp.where(gidx < b_real, u, jnp.float32(-jnp.inf))
    mv, mp = jax.lax.top_k(u, k)
    return mv, jnp.minimum(mp.astype(jnp.int32), jnp.int32(b_real - 1))


# -------------------------------------------------- unfused reference
@functools.partial(jax.jit, static_argnames=("kind",))
def _scores_unfused(qc, qk, xc, xk, alpha, kinv, params, kind: str):
    """The PRE-fusion pipeline staging, kept as the parity/bench
    comparator: materialize the full [B, N] cross-kernel and the [B]
    moment vectors — exactly the HBM intermediates the fused program
    deletes — then apply the acquisition transform.  Same math as
    `_utility_tile`; un-tiled staging (XLA may fuse differently, so
    'mean' is bitwise-equal to the fused routes while 'ei'/'lcb' agree
    to float32 fusion noise — the parity tests pin both)."""
    if qc is None:
        k = jnp.exp(-_tile_d2(qk, xk))
    else:
        k = _matern_tile(_tile_d2(qc, xc))
        if qk is not None:
            k = k * jnp.exp(-_tile_d2(qk, xk))
    noise, y_mean, y_std = params[0, 0], params[0, 1], params[0, 2]
    best_y, beta = params[0, 3], params[0, 4]
    mu = (k @ alpha) * y_std + y_mean
    if kinv is None:            # 'mean'
        return -mu
    w = jnp.dot(k, kinv, preferred_element_type=jnp.float32)
    q = (w * k).sum(axis=1)
    sd = jnp.sqrt(jnp.maximum(1.0 + noise - q, 1e-9)) * y_std
    return _TRANSFORM[kind](mu, sd, best_y, beta)


def acquire_scores_ref(state, xq: jax.Array, kind: str = "mean",
                       best_y=None, beta: float = 2.0,
                       n_cont: Optional[int] = None,
                       n_cat: int = 0) -> jax.Array:
    """Unfused-reference utilities (materialized intermediates) — the
    A/B baseline `bench.py --multi` measures the fused pipeline
    against, and the parity anchor for the tier-1 tests."""
    _check(kind, best_y)
    b = xq.shape[0]
    args = _prep(state, xq, kind, best_y, beta, n_cont, n_cat)
    return _scores_unfused(*args, kind=kind)[:b]


def acquire_topk_ref(state, xq: jax.Array, k: int, kind: str = "mean",
                     best_y=None, beta: float = 2.0,
                     n_cont: Optional[int] = None, n_cat: int = 0
                     ) -> Tuple[jax.Array, jax.Array]:
    """Unfused-reference top-k: full scores vector, then `lax.top_k`."""
    u = acquire_scores_ref(state, xq, kind, best_y, beta, n_cont, n_cat)
    mv, mp = jax.lax.top_k(u, k)
    return mv, mp.astype(jnp.int32)


# ------------------------------------------------------ routed entries
def _prep(state, xq, kind: str, best_y, beta: float,
          n_cont: Optional[int], n_cat: int):
    """Pre-scaled feature blocks + scalar pack, exactly the
    `pallas_score.gp_mean_var_scores` conventions (cont block / ls, cat
    one-hot block * sqrt(1/(n_cat*ls_cat)), alpha premasked, premasked
    K^-1 preferred from the state)."""
    b, f = xq.shape
    pad = (-b) % TILE
    xq32 = jnp.asarray(xq, jnp.float32)
    if pad:
        xq32 = jnp.concatenate(
            [xq32, jnp.zeros((pad, f), jnp.float32)])
    x32 = jnp.asarray(state.x, jnp.float32)
    alpha = jnp.asarray(state.alpha, jnp.float32) * state.mask
    kinv = None
    if kind != "mean":
        if state.kinv is not None:
            kinv = jnp.asarray(state.kinv, jnp.float32)
        else:
            from ..surrogate import gp as _gp
            kinv = jnp.asarray(_gp.precompute_kinv(state).kinv,
                               jnp.float32)
    mixed = n_cont is not None and n_cat and n_cont < f
    if mixed:
        cat_s = jnp.sqrt(1.0 / (float(n_cat) * state.ls_cat))
        if n_cont == 0:
            qc = xc = None
            qk, xk = xq32 * cat_s, x32 * cat_s
        else:
            qc = xq32[:, :n_cont] / state.lengthscale
            qk = xq32[:, n_cont:] * cat_s
            xc = x32[:, :n_cont] / state.lengthscale
            xk = x32[:, n_cont:] * cat_s
    else:
        qc, xc = xq32 / state.lengthscale, x32 / state.lengthscale
        qk = xk = None
    z = jnp.float32(0.0)
    params = jnp.stack([
        jnp.asarray(state.noise, jnp.float32),
        jnp.asarray(state.y_mean, jnp.float32),
        jnp.asarray(state.y_std, jnp.float32),
        z if best_y is None else jnp.asarray(best_y, jnp.float32),
        jnp.float32(beta), z, z, z]).reshape(1, 8)
    return qc, qk, xc, xk, alpha, kinv, params


def _check(kind: str, best_y):
    if kind not in KINDS:
        raise ValueError(f"unknown kind {kind!r}")
    if kind == "ei" and best_y is None:
        raise ValueError("kind='ei' needs best_y")


def acquire_scores(state, xq: jax.Array, kind: str = "mean",
                   best_y=None, beta: float = 2.0,
                   n_cont: Optional[int] = None, n_cat: int = 0,
                   route: Optional[str] = None) -> jax.Array:
    """Fused acquisition UTILITIES (higher = better) for a [B, F] query
    batch against a fitted GPState: -mean ('mean'), EI ('ei', vs
    `best_y`), or -(mu - beta*sd) ('lcb') — scoring, moments, and the
    acquisition transform in one device program (kernel or XLA
    fallback per `ops/routing.py`; pass `route` to pin one)."""
    _check(kind, best_y)
    b = xq.shape[0]
    if route is None:
        route = routing.decide(b, min_rows=MIN_ROWS, cpu_ok=False)
    args = _prep(state, xq, kind, best_y, beta, n_cont, n_cat)
    if route == routing.XLA:
        return _scores_xla(*args, kind=kind)[:b]
    return _scores_padded(*args, kind=kind,
                          interpret=routing.interpret_flag(route))[:b]


def acquire_topk(state, xq: jax.Array, k: int, kind: str = "mean",
                 best_y=None, beta: float = 2.0,
                 n_cont: Optional[int] = None, n_cat: int = 0,
                 route: Optional[str] = None
                 ) -> Tuple[jax.Array, jax.Array]:
    """Fused score + acquisition + top-k: (utilities [k] descending,
    flat candidate indices [k] i32), `lax.top_k` tie semantics (lowest
    index wins).  The kernel route streams the selection per tile and
    never writes the [B] utility vector to HBM."""
    _check(kind, best_y)
    b = int(xq.shape[0])
    if not 1 <= k <= b:
        raise ValueError(f"k must be in [1, {b}]: {k}")
    if route is None:
        route = routing.decide(b, min_rows=MIN_ROWS, cpu_ok=False)
    args = _prep(state, xq, kind, best_y, beta, n_cont, n_cat)
    if route == routing.XLA:
        return _topk_xla(*args, kind=kind, k=k, b_real=b)
    return _topk_padded(*args, kind=kind, k=k, b_real=b,
                        interpret=routing.interpret_flag(route))


def kernel_schema(n_train: int, n_feat: int, kind: str = "ei",
                  k: int = 0) -> dict:
    """Static tile/VMEM facts for one fused call shape — the roofline
    protocol fields bench.py records (docs/PERF.md): tile dims and the
    per-grid-step VMEM residency in bytes."""
    kpad = -(-min(max(k, 1), TILE) // KLANES) * KLANES
    vmem = 4 * (TILE * n_feat + n_train * n_feat + n_train + 8
                + 2 * TILE * kpad)
    if kind != "mean":
        vmem += 4 * (n_train * n_train + 2 * TILE * n_train)
    return {"tile_rows": TILE, "lanes": LANES, "sublanes": ROWS,
            "k_lanes": (kpad if k else 0), "n_train": n_train,
            "n_feat": n_feat, "vmem_bytes": vmem,
            "min_rows_auto": MIN_ROWS}
