"""The hash-dedup history merge as a Pallas TPU kernel (+ XLA fallback).

`driver.history.History.insert` maintains the device-resident dedup
history as an h0-sorted table; the hot inner operation is the STABLE
TWO-RUN MERGE of the (already sorted) [cap] history with a freshly
sorted [b] batch.  The XLA formulation (`merge_rows_xla`, the PR 2
gather+cumsum rewrite) materializes a [cap+b] boolean merge-path lane,
a full-width cumsum, and then four pairs of full-width clipped gathers
— on TPU each arbitrary-index gather lowers to slow scalarized or
one-hot code XLA chooses for us, and the intermediates make several
extra HBM round trips per step.

The Pallas kernel (`merge_rows_pallas`) computes the same merge
tile-by-tile in VMEM with the index arithmetic done once per tile:

* new rows occupy strictly-increasing output positions `pos_new`
  (computed by one [b] searchsorted outside the kernel), so for any
  output position p the number of new rows at-or-before it,
  `n_le(p) = #{i: pos_new[i] <= p}`, classifies p (`is_new = n_le(p) >
  n_le(p-1)`) AND locates its source row (`new[n_le-1]` or
  `hist[p - n_le]`) — no cumsum over cap+b, just a [T, chunk]
  compare-and-sum per tile that never leaves VMEM;
* the history rows a tile can pull from span `[tile_lo - b, tile_lo +
  T)`; with the history front-padded by one tile the window is exactly
  blocks `i` and `i+1` of the padded array — two static BlockSpecs, no
  data-dependent indexing;
* per-element gathers (unsupported as such on the VPU) become one-hot
  MXU matmuls over the VMEM window, shared by ALL merged columns: the
  four logical arrays (h0, h1, qor, age) are packed into 16-bit-exact
  f32 columns of one [*, 8] matrix, so each tile does ~(2T+b)/chunk
  small [T, chunk] x [chunk, 8] matmuls total, not per-array.

Off-TPU callers keep the XLA path (`merge_history` routes by backend);
`merge_rows_pallas(..., interpret=True)` runs the kernel through the
Pallas interpreter for parity tests on CPU, exactly like
surrogate/pallas_score.py.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

# output rows per grid step; must divide the history capacity (a
# multiple of the (8, 128) f32 tile — blocks here are [TILE, 8] 2D, so
# the 1D-f32-output layout mismatch pallas_score.py documents never
# arises)
TILE = 2048
# batch rows / window rows processed per one-hot matmul
CHUNK = 512

_Rows = Tuple[jax.Array, jax.Array, jax.Array, jax.Array]


def pallas_merge_supported(cap: int, b: int) -> bool:
    """Shapes the kernel's static tiling handles: capacity a multiple
    of one tile (power-of-two caps >= 2048 all qualify) and a batch
    that fits inside one tile's window."""
    return cap % TILE == 0 and b <= TILE


# -- packing: four logical columns as 16-bit-exact f32 ---------------------
def _pack_cols(h0: jax.Array, h1: jax.Array, q: jax.Array,
               age: jax.Array) -> jax.Array:
    """[n] (u32, u32, f32, i32) -> [n, 8] f32 whose columns are exact
    in f32: each u32 (and the qor's raw bits) split into 16-bit halves
    (<= 65535), age passed through (|age| < 2^24 — the insert-step
    counter).  Column 7 pads the matrix to an MXU-friendly width."""
    qbits = jax.lax.bitcast_convert_type(q.astype(jnp.float32), jnp.uint32)

    def halves(u):
        u = u.astype(jnp.uint32)
        return ((u & jnp.uint32(0xFFFF)).astype(jnp.float32),
                (u >> 16).astype(jnp.float32))

    a, bb = halves(h0)
    c, d = halves(h1)
    e, f = halves(qbits)
    g = age.astype(jnp.float32)
    return jnp.stack([a, bb, c, d, e, f, g, jnp.zeros_like(g)], axis=1)


def _unpack_cols(cols: jax.Array) -> _Rows:
    def join(lo, hi):
        return (lo.astype(jnp.uint32)
                | (hi.astype(jnp.uint32) << 16))

    h0 = join(cols[:, 0], cols[:, 1])
    h1 = join(cols[:, 2], cols[:, 3])
    q = jax.lax.bitcast_convert_type(join(cols[:, 4], cols[:, 5]),
                                     jnp.float32)
    age = cols[:, 6].astype(jnp.int32)
    return h0, h1, q, age


# -- the kernel ------------------------------------------------------------
def _merge_kernel(pos_ref, new_ref, win_a_ref, win_b_ref, out_ref, *,
                  n_new_chunks: int):
    """One [TILE, 8] output tile of the merged table.

    pos_ref [1, b8] i32: output positions of the new rows (ascending;
    padding rows hold an out-of-range sentinel so they count for no
    position).  new_ref [b8, 8]: packed new rows.  win_a/win_b
    [TILE, 8]: blocks i and i+1 of the FRONT-PADDED packed history —
    together the window hist[(i-1)*TILE : (i+1)*TILE)."""
    i = jax.lax.broadcasted_iota(jnp.float32, (TILE, 1), 0)
    base = (pl_program_id() * TILE).astype(jnp.float32)
    p = i + base                      # [TILE, 1] output positions

    pos = pos_ref[0, :].astype(jnp.float32)   # [b8]
    n_le = jnp.zeros((TILE, 1), jnp.float32)
    n_lt = jnp.zeros((TILE, 1), jnp.float32)
    for c in range(n_new_chunks):
        pc = pos[None, c * CHUNK:(c + 1) * CHUNK]       # [1, CHUNK]
        n_le += (pc <= p).astype(jnp.float32).sum(axis=1, keepdims=True)
        n_lt += (pc < p).astype(jnp.float32).sum(axis=1, keepdims=True)
    is_new = n_le > n_lt

    # source indices (exact small integers in f32)
    new_idx = n_le - 1.0                         # row into new_ref
    rel = i - n_le + float(TILE)                 # row into the window

    win = jnp.concatenate([win_a_ref[:], win_b_ref[:]], axis=0)
    j = jax.lax.broadcasted_iota(jnp.float32, (1, CHUNK), 1)
    acc_h = jnp.zeros((TILE, 8), jnp.float32)
    for c in range(2 * TILE // CHUNK):
        onehot = (rel == (j + float(c * CHUNK))).astype(jnp.float32)
        acc_h += jnp.dot(onehot, win[c * CHUNK:(c + 1) * CHUNK, :],
                         preferred_element_type=jnp.float32)
    acc_n = jnp.zeros((TILE, 8), jnp.float32)
    for c in range(n_new_chunks):
        onehot = (new_idx == (j + float(c * CHUNK))).astype(jnp.float32)
        acc_n += jnp.dot(onehot, new_ref[c * CHUNK:(c + 1) * CHUNK, :],
                         preferred_element_type=jnp.float32)

    out_ref[:] = jnp.where(is_new, acc_n, acc_h)


def pl_program_id():
    from jax.experimental import pallas as pl
    return pl.program_id(0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _merge_padded(pos2, new_cols, hist_padded, interpret: bool):
    from jax.experimental import pallas as pl
    try:
        from jax.experimental.pallas import tpu as pltpu
        vmem = pltpu.VMEM
    except ImportError:  # pragma: no cover
        vmem = None

    def spec(shape, index_map):
        kw = {"memory_space": vmem} if vmem is not None else {}
        return pl.BlockSpec(shape, index_map, **kw)

    b8 = new_cols.shape[0]
    cap = hist_padded.shape[0] - TILE
    kernel = functools.partial(_merge_kernel,
                               n_new_chunks=b8 // CHUNK)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((cap, 8), jnp.float32),
        grid=(cap // TILE,),
        in_specs=[
            spec((1, b8), lambda i: (0, 0)),
            spec((b8, 8), lambda i: (0, 0)),
            spec((TILE, 8), lambda i: (i, 0)),
            spec((TILE, 8), lambda i: (i + 1, 0)),
        ],
        out_specs=spec((TILE, 8), lambda i: (i, 0)),
        interpret=interpret,
        # the padded history feeds BOTH window specs (blocks i and i+1)
    )(pos2, new_cols, hist_padded, hist_padded)


def merge_rows_pallas(hist: _Rows, new: _Rows, pos_new: jax.Array,
                      interpret: bool = None) -> _Rows:
    """Tiled Pallas stable merge of the h0-sorted history `hist`
    (4 x [cap]) with the h0-sorted batch `new` (4 x [b], b <= TILE) at
    output positions `pos_new` ([b] i32, strictly increasing).  Output
    truncates at cap, exactly like merge_rows_xla."""
    if interpret is None:
        from . import routing as _routing
        interpret = _routing.interpret_default()
    cap = hist[0].shape[0]
    b = new[0].shape[0]
    if not pallas_merge_supported(cap, b):
        raise ValueError(f"unsupported merge shapes cap={cap} b={b}")
    b8 = -(-b // CHUNK) * CHUNK
    pad = b8 - b
    # padding rows: out-of-range position => they contribute to no
    # n_le count and are never gathered
    pos2 = jnp.concatenate(
        [pos_new.astype(jnp.int32),
         jnp.full((pad,), cap + TILE + 1, jnp.int32)])[None, :]
    new_cols = jnp.concatenate(
        [_pack_cols(*new), jnp.zeros((pad, 8), jnp.float32)], axis=0)
    hist_padded = jnp.concatenate(
        [jnp.zeros((TILE, 8), jnp.float32), _pack_cols(*hist)], axis=0)
    out = _merge_padded(pos2, new_cols, hist_padded, bool(interpret))
    return _unpack_cols(out)


# -- XLA fallback (the PR 2 gather+cumsum formulation) ---------------------
def merge_rows_xla(hist: _Rows, new: _Rows,
                   pos_new: jax.Array) -> _Rows:
    """Stable two-run merge as gathers off one tiny b-row scatter: the
    merge-path positions of the B new rows are marked in a boolean
    lane, and every output slot pulls its row via cumsum-derived
    indices.  (Big scatters lower to element loops — measured 25
    ms/commit at cap=2^16 on 1 CPU core, ~1 ms as gathers.  This
    formulation also measures FASTEST under the batched engine's vmap:
    a searchsorted-based scatter-free variant was ~2.3x slower at
    [32, 2^12] because vmapped binary search pays a batched gather per
    refinement step.)"""
    cap = hist[0].shape[0]
    b = new[0].shape[0]
    is_new = jnp.zeros((cap + b,), bool).at[pos_new].set(True)
    idx_new = jnp.cumsum(is_new.astype(jnp.int32)) - 1
    idx_hist = jnp.arange(cap + b, dtype=jnp.int32) - idx_new - 1
    idx_new = jnp.clip(idx_new, 0, b - 1)
    idx_hist = jnp.clip(idx_hist, 0, cap - 1)

    def mrg(hist_v, new_v):
        return jnp.where(is_new, new_v[idx_new], hist_v[idx_hist])[:cap]

    return tuple(mrg(h, n) for h, n in zip(hist, new))


def merge_history(hist: _Rows, new: _Rows, impl: str = "auto") -> _Rows:
    """Route one history merge: `new` must be h0-sorted (old rows come
    before new rows on equal h0 — the History invariant).  impl:
    'pallas' | 'xla' | 'auto'.  'auto' routes through the shared
    UT_PALLAS knob (`ops/routing.py`): the compiled kernel on TPU when
    the shapes qualify, the XLA fallback otherwise (this site opts OUT
    of the auto CPU-interpret route — the fallback is faster there —
    but UT_PALLAS=interpret still forces the kernel for parity runs)."""
    pos_new = (jnp.arange(new[0].shape[0], dtype=jnp.int32)
               + jnp.searchsorted(hist[0], new[0], side="right"
                                  ).astype(jnp.int32))
    from . import routing as _routing
    if impl == "pallas":
        return merge_rows_pallas(hist, new, pos_new)
    route = _routing.XLA
    if impl == "auto":
        route = _routing.decide(
            new[0].shape[0], min_rows=0,
            supported=pallas_merge_supported(hist[0].shape[0],
                                             new[0].shape[0]),
            cpu_ok=False)
    if route != _routing.XLA:
        return merge_rows_pallas(
            hist, new, pos_new,
            interpret=_routing.interpret_flag(route))
    return merge_rows_xla(hist, new, pos_new)
