"""The tuning driver: batched acquisition loop over on-device techniques.

Host-side replacement for the reference's controller + search-driver pair
(`/root/reference/python/uptune/api.py:399-594` `async_execute` and
`opentuner/search/driver.py:160-225`), re-shaped for TPU batching:

* each step, the meta-technique (AUC bandit) orders its arms host-side and
  the first supported arm emits a whole CandBatch from one jitted XLA
  program (vs. one config per `desired_result()` call);
* dedup + known-result reuse run on device against the sorted-hash history
  (driver/history.py) instead of per-proposal SQL lookups;
* only hash-novel candidates cross the host boundary for black-box
  evaluation; in-batch duplicates share one evaluation, history duplicates
  are served their recorded QoR (api.py:276-286 semantics);
* every evaluated trial is appended to a jsonl archive carrying the raw
  unit vectors, so `resume()` replays *exactly* (the reference's
  ut.archive.csv + `resume`, api.py:328-363,536-543).
"""
from __future__ import annotations

import json
import logging
import math
import os
import time
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..space.spec import CandBatch, Space, pad_cands
from ..techniques import base as tbase
from ..techniques.base import Best, Technique
from ..techniques.bandit import MetaTechnique
from .history import History, dup_source
from .plugins import fire as _fire

Objective = Callable[[List[Dict[str, Any]]], Sequence[float]]

log = logging.getLogger("uptune_tpu")


def _leaf_keys(tree):
    """(keys, certain): aliasing keys for every array leaf — the
    underlying device buffer address, which catches jit input-output
    forwarding even when it wraps the shared buffer in a new Array
    object.  certain=False when any leaf's address is unavailable
    (sharded arrays, jax API drift): the caller must then assume
    aliasing is possible, because a false negative here would let
    observe() donate a buffer a sibling in-flight ticket still holds."""
    keys, certain = set(), True
    for x in jax.tree_util.tree_leaves(tree):
        try:
            keys.add(x.unsafe_buffer_pointer())
        except Exception:
            certain = False
    return keys, certain


def _strong(tree):
    """Strip weak_type from every array leaf (stable input avals).
    Technique init_state()s built from python scalars (jnp.full(...,
    jnp.inf)) return WEAK float32 leaves while their observe() outputs
    are strong — so the arm's propose/observe programs would trace
    twice, once per weak-type combination (the PR 1 retrace-churn
    finding).  Normalizing at the init_state boundary keeps every
    wrapper at exactly one trace, including after restarts."""
    return jax.tree_util.tree_map(
        lambda x: (jax.lax.convert_element_type(x, x.dtype)
                   if getattr(x, "weak_type", False) else x), tree)


class StepStats(NamedTuple):
    step: int
    technique: str
    batch: int
    evaluated: int
    best_qor: float
    was_new_best: bool
    pruned: int = 0
    # cumulative live history rows evicted past capacity (oldest-first,
    # history.py insert): nonzero means dedup no longer sees the oldest
    # part of the run
    hist_dropped: int = 0
    # driver-plane timing for this ticket (seconds): device propose +
    # dedup dispatch, host-side pending-mask / config materialization,
    # and wall-clock from ticket open to finalize (the window external
    # evaluation has to hide device work in)
    t_propose: float = 0.0
    t_dedup: float = 0.0
    t_eval_wait: float = 0.0
    # surrogate-plane observability for this ticket: seconds the tell
    # path BLOCKED on surrogate learning (sync full fits + incremental
    # extensions; ~0 under async refit), the snapshot version scoring
    # currently reads, and its staleness in training rows
    t_refit: float = 0.0
    snapshot_version: int = 0
    refit_lag_rows: int = 0
    # device-plane compile activity (obs.device totals delta) that
    # landed in this ticket's open->finalize window: the first ticket
    # carries the arm-program compiles, later tickets ~0, and a
    # persistent-cache-served restart shows small t_compile with the
    # cache hits attributed in the device.* counters.  Both stay 0
    # while tracing is off (device telemetry rides the obs flag)
    n_compiles: int = 0
    t_compile: float = 0.0


class Trial:
    """One proposed configuration awaiting an external result (the
    ask/tell unit, mirroring the reference's DesiredResult lifecycle
    UNKNOWN->REQUESTED->RUNNING->COMPLETE, resultsdb/models.py:284-287)."""

    __slots__ = ("gid", "config", "ticket", "slot", "row", "qor", "dur",
                 "cancelled")

    def __init__(self, gid: int, config: Dict[str, Any], ticket: "_Ticket",
                 slot: int, row: int):
        self.gid = gid
        self.config = config
        self.ticket = ticket
        self.slot = slot          # index within the ticket's trial list
        self.row = row            # row within the proposed device batch
        self.qor: Optional[float] = None   # ENGINE orientation once told
        self.dur = 0.0
        self.cancelled = False

    def __repr__(self):
        return (f"Trial(gid={self.gid}, tech={self.ticket.arm_name!r}, "
                f"qor={self.qor})")


class _Ticket:
    """One arm's proposed batch plus its dedup verdicts; completes when
    every novel trial has been told its result."""

    __slots__ = ("arm", "arm_name", "tstate", "cands", "hashes", "known",
                 "src", "novel_np", "injected", "pruned", "trials",
                 "remaining", "u_np", "perms_np", "gen", "credit_virtual",
                 "packed", "t_propose", "t_dedup", "t_open", "pred",
                 "jpull", "dev0")

    def __init__(self, arm, arm_name, tstate, cands, hashes, known, src,
                 novel_np, injected, pruned, gen=0, credit_virtual=False):
        self.arm = arm
        self.arm_name = arm_name
        self.tstate = tstate
        self.cands = cands
        self.hashes = hashes
        self.known = known
        self.src = src
        self.novel_np = novel_np
        self.injected = injected
        self.pruned = pruned
        # injected ticket that still earns bandit credit: the surrogate
        # virtual arm (arbitration='bandit') — no technique state to
        # observe, but its pull outcome feeds the AUC queue
        self.credit_virtual = credit_virtual
        self.trials: List[Trial] = []
        self.remaining = 0
        self.u_np = None
        self.perms_np = None
        self.packed = None        # [B] uint64 packed hashes (host)
        # journal calibration join (ISSUE 12): (mu [B], sd [B],
        # snapshot version) recorded at propose time when the tuning
        # journal is on and the surrogate is fitted; None otherwise
        self.pred = None
        # journal pull verdicts captured at ticket OPEN (src, batch,
        # trials, pruned, filtered, dup) — emitted with the step row
        # at finalize: one journal row per ticket, not two
        self.jpull = None
        self.t_propose = 0.0      # s in the propose+dedup device call
        self.t_dedup = 0.0        # s in host-side mask + materialization
        self.t_open = 0.0         # perf_counter() when the ticket opened
        # obs.device compile totals at open: finalize reports the
        # window's (count, seconds) delta in StepStats (ISSUE 13).
        # _acquire / _surrogate_ticket / _open_injected_ticket override
        # with their pre-dispatch capture so a program's own first-pull
        # compile lands in its ticket's window
        self.dev0 = obs.device.compile_totals()
        # member-state generation at open time: a restart bumps the
        # member's generation, and stale tickets (opened before the
        # restart) must not write observe(tk.tstate) back over the
        # freshly re-initialized state
        self.gen = gen


class TuneResult(NamedTuple):
    best_config: Dict[str, Any]
    best_qor: float          # in USER orientation (negated back for 'max')
    evals: int
    steps: int
    trace: List[float]       # best-so-far (user orientation) after each eval
    # cumulative driver-plane timing (seconds; see StepStats): how much
    # device/host proposal work the run did, and how much wall-clock
    # tickets spent waiting on external evaluation (the budget async
    # prefetch hides the first two behind)
    t_propose: float = 0.0
    t_dedup: float = 0.0
    t_eval_wait: float = 0.0
    # cumulative seconds the driver hot path spent BLOCKED on surrogate
    # learning (sync refits; ~0 with the async surrogate plane)
    t_refit: float = 0.0
    # cumulative XLA compile seconds observed by the device-telemetry
    # layer across the run's ticket windows (obs.device; 0 untraced)
    t_compile: float = 0.0


class Tuner:
    """Single-instance batched tuner over an in-process objective.

    Parameters
    ----------
    space : Space
    objective : callable(list[config dict]) -> sequence of float
        QoR per config; non-finite values count as failures (+inf).
    technique : str | list[str] | Technique | None
        As the reference's --technique flag (technique.py:345-362);
        default is the AUCBanditMetaTechniqueA portfolio.
    sense : 'min' | 'max'
        User objective orientation; engine always minimizes
        (objective.py:161-183 normal form).
    archive : optional path of the jsonl trial archive (resume source).
    """

    def __init__(self, space: Space, objective: Optional[Objective] = None,
                 *, technique=None, seed: int = 0, sense: str = "min",
                 capacity: int = 1 << 16,
                 archive: Optional[str] = None,
                 resume: bool = False,
                 surrogate=None, surrogate_opts: Optional[dict] = None,
                 config_filter: Optional[
                     Callable[[Dict[str, Any]], bool]] = None,
                 hooks: Optional[Sequence] = None,
                 label: str = "",
                 input_manager=None):
        assert sense in ("min", "max"), sense
        # identifies this tuner in shared-hook output (multi-stage runs
        # pass one hook list to several tuners; events interleave)
        self.label = label
        self.space = space
        self.objective = objective
        # input-selection policy (driver/inputs.py, the reference's
        # measurement InputManager seam): when set, step() calls the
        # objective as objective(cfgs, inputs) with before/after hooks
        self.input_manager = input_manager
        # search-space restriction predicate (ut.rule); rejected configs
        # are never evaluated/archived and serve +inf to their technique
        self.config_filter = config_filter
        self.filtered_total = 0
        self.sense = sense
        self.sign = 1.0 if sense == "min" else -1.0
        self.key = jax.random.PRNGKey(seed)
        self.history = History(capacity)
        self.hist_state = self.history.init()
        self.best = Best.empty(space)
        self.archive_path = archive
        self.evals = 0
        # trials individually resolved via tell(); unlike `evals` (which
        # advances only when a whole ticket finalizes) this never lags,
        # so budget gates stay accurate while a wide batch is in flight
        self.told = 0
        self.steps = 0
        self.gid = 0
        self.trace: List[float] = []
        self._zero_novel_streak = 0
        self._cap_warned = False
        self._last_dropped = 0
        self.pruned_total = 0
        self._surr_tick = 0   # acquisition counter for propose_every
        # arms whose last proposal was entirely duplicates, keyed by the
        # acquisition counter (VERDICT round-1 weak #7): they are SKIPPED
        # for a few acquisitions so a saturating arm doesn't cost every
        # step a full propose+dedup XLA call before a productive arm gets
        # a turn.  Keyed on _acq_count, not steps: with many in-flight
        # ask() tickets, steps stays frozen until tickets finalize and a
        # step-keyed window would over-extend the skip
        self._arm_dry: Dict[str, int] = {}
        self._dry_backoff = 5
        self._acq_count = 0
        # hashes proposed but not yet resolved (the reference's _pending
        # list, api.py:254-280): asked trials must not be re-proposed
        self._pending: set = set()
        # per-technique attribution counters (pulls, evals, new-bests)
        self.arm_stats: Dict[str, List[int]] = {}
        # observer hooks (search/plugin.py:26-62 equivalents)
        self.hooks = list(hooks or [])

        # surrogate-ensemble pruning (api.py:291-326 semantics)
        if isinstance(surrogate, str):
            from ..surrogate.manager import SurrogateManager
            surrogate = SurrogateManager(
                space, surrogate, seed=seed, **(surrogate_opts or {}))
        self.surrogate = surrogate

        root = technique
        if root is None or isinstance(root, str) or (
                isinstance(root, (list, tuple))):
            names = ([root] if isinstance(root, str) else root)
            root = tbase.get_root(names)  # returns a private copy
        else:
            # a directly-passed Technique may be shared by the caller;
            # meta-techniques carry mutable host-side credit state
            import copy
            root = copy.deepcopy(root)
        self.root: Technique = root
        # MetaTechnique.credit grew step_best=/global_best= keywords in
        # r3; a user subclass written against the old 2-arg signature
        # must keep working.  Detect ONCE by inspection — catching
        # TypeError at call time would misread a genuine TypeError
        # raised INSIDE a modern credit() as a legacy signature
        # (ADVICE r3 / r4 review).
        self._credit_kw = True
        if isinstance(root, MetaTechnique):
            import inspect
            try:
                ps = inspect.signature(root.credit).parameters.values()
            except (TypeError, ValueError):  # builtins/C: assume modern
                ps = ()
            if ps and not any(
                    p.name == "step_best"
                    or p.kind == inspect.Parameter.VAR_KEYWORD
                    for p in ps):
                self._credit_kw = False
                import warnings
                warnings.warn(
                    f"{type(root).__name__}.credit uses the legacy "
                    "(name, was_new_best) signature; add step_best= "
                    "and global_best= keywords — quality-aware metas "
                    "(RecyclingMeta) need them. Falling back to the "
                    "2-arg call.", FutureWarning)
        members = (root.techniques if isinstance(root, MetaTechnique)
                   else [root])
        self.members: List[Technique] = [
            t for t in members if t.supports(space)]
        if not self.members:
            raise ValueError(
                f"no technique in {root.name!r} supports this space")
        self._tstates: Dict[str, Any] = {}
        self._propose_jit: Dict[str, Any] = {}
        self._observe_jit: Dict[str, Any] = {}
        self._member_by_name: Dict[str, Technique] = {
            t.name: t for t in self.members}
        # bumped on each RecyclingMeta restart; see _Ticket.gen
        self._tgen: Dict[str, int] = {t.name: 0 for t in self.members}
        # common dedup/commit batch size: every arm's proposal is padded
        # to this bucket inside its propose program, so `_commit` (and
        # the standalone `_dedup`) see ONE input aval across arms and
        # trace once instead of once per distinct arm batch (the PR 1
        # trace-guard finding: 3 traces/tune from DE=30 / GM=32 / NM=D+1
        # shapes).  inject() pads host-side to a multiple of the same
        # bucket.
        self._bucket = max(t.natural_batch(space) for t in self.members)
        sp, hist = self.space, self.history

        def _propose_dedup(t, st, k, best, hist_state):
            """One fused device program per arm: propose the arm's
            natural batch, pad to the bucket, hash + dedup vs history +
            in-batch.  Replaces two host dispatches (propose, _dedup)
            with one."""
            st2, c = t.propose(sp, st, k, best)
            cp = pad_cands(c, self._bucket)
            hashes = sp.hash_batch(cp)
            found, known = hist.contains(hist_state, hashes)
            src = dup_source(hashes)
            novel = (src == jnp.arange(hashes.shape[0])) & ~found
            return st2, cp, hashes, known, src, novel

        for t in self.members:
            self.key, k = jax.random.split(self.key)
            self._tstates[t.name] = _strong(t.init_state(space, k))
            # the driver's per-arm device programs ride the same
            # instrument seam as the engine plane (ISSUE 13): a traced
            # run harvests each program's XLA cost/memory analysis at
            # its first-pull compile and attributes persistent-cache
            # hits/misses; untraced runs pay one flag check
            self._propose_jit[t.name] = obs.instrument_device_fn(
                jax.jit(lambda st, k, best, hs, _t=t:
                        _propose_dedup(_t, st, k, best, hs)),
                f"driver.propose.{t.name}")
            # observe consumes the ticket's padded batch, slicing back
            # to the arm's own proposal rows; the technique state is
            # DONATED — tk.tstate must never be reused after this call.
            # Exception: an arm whose propose() FORWARDS state buffers
            # unchanged is detected on its first pull and routed
            # through a non-donating wrapper (_finalize) — with several
            # of its tickets in flight they alias one buffer, and
            # donating it under ticket A would delete ticket B's state
            self._observe_jit[t.name] = self._make_observe(t, True)
        self._observe_nodonate: Dict[str, Any] = {}
        self._arm_forwards: set = set()
        self._fwd_checked: set = set()

        # surrogate arbitration='bandit': the proposal plane becomes a
        # credit-earning VIRTUAL ARM of the AUC bandit instead of firing
        # on a fixed schedule — the bandit's AUC credit decides when the
        # pool displaces a technique batch, and starves it when its
        # pulls stop paying (the measured gcc-real failure mode of the
        # scheduled plane, BENCHREPORT.md).
        self._surr_arm = False
        sm = self.surrogate
        if sm is not None and getattr(sm, "arbitration", "") == "bandit":
            if not self._wire_surrogate_arm():
                import warnings
                warnings.warn(
                    "surrogate arbitration='bandit' needs an AUC-bandit "
                    "root technique and propose_batch > 0; falling back "
                    "to the scheduled proposal plane", UserWarning)

        @jax.jit
        def _dedup(hist_state, cands: CandBatch):
            hashes = sp.hash_batch(cands)
            found, known = hist.contains(hist_state, hashes)
            src = dup_source(hashes)
            first = src == jnp.arange(hashes.shape[0])
            novel = first & ~found
            return hashes, found, known, src, novel

        # history and best are DONATED: the [cap] history buffers are
        # updated in place instead of copied every step (the old
        # _commit copied the full capacity-sized state per ticket), and
        # the pre-commit HistState/Best objects are dead after the call
        # — the driver immediately rebinds self.hist_state/self.best
        # and nothing else may hold them (docs/PERF.md invariants)
        def _commit(hist_state, best, hashes, cands: CandBatch, qor,
                    newly):
            hist_state = hist.insert(hist_state, hashes, qor, newly)
            best = best.update(cands, qor)
            return hist_state, best

        self._dedup = obs.instrument_device_fn(_dedup, "driver.dedup")
        self._commit = obs.instrument_device_fn(
            jax.jit(_commit, donate_argnums=(0, 1)), "driver.commit")
        # driver-plane timing accumulators (seconds; surfaced via
        # StepStats per ticket and TuneResult totals)
        self.t_propose_total = 0.0
        self.t_dedup_total = 0.0
        self.t_eval_wait_total = 0.0
        self.t_refit_total = 0.0
        self.t_compile_total = 0.0

        if resume and archive and os.path.exists(archive):
            self._resume(archive)
        elif archive and os.path.exists(archive) and os.path.getsize(archive):
            # not resuming, but never append to a different space's file:
            # check (or backfill) the signature header before reuse
            self._check_archive_header(archive)
        _fire(self.hooks, "on_start", self)
        self._archive_f = open(archive, "a") if archive else None
        if self._archive_f is not None and self._archive_f.tell() == 0:
            # header: full space signature, checked on every reopen
            self._archive_f.write(
                json.dumps({"space_sig": self._space_sig()}) + "\n")
            self._archive_f.flush()

    # ------------------------------------------------------------------
    def _make_observe(self, t: Technique, donate: bool):
        """The per-arm observe program: slice the padded ticket batch
        back to the arm's own rows, feed the measured QoR.  One factory
        for both the donating default and the non-donating variant
        forwarding-state arms fall back to."""
        sp, nb = self.space, t.natural_batch(self.space)
        return obs.instrument_device_fn(
            jax.jit(
                lambda st, c, q, best, _t=t, _b=nb:
                _t.observe(sp, st, c[:_b], q[:_b], best),
                donate_argnums=(0,) if donate else ()),
            f"driver.observe.{t.name}")

    def _space_sig(self) -> List[str]:
        """Ordered structural signature of the space (Space.signature):
        any change invalidates position-indexed unit-vector replay."""
        return self.space.signature()

    def _rotate_mismatch(self, path: str) -> None:
        import warnings
        bak = path + ".mismatch"
        os.replace(path, bak)
        warnings.warn(
            f"archive {path} was recorded for a different space; "
            f"moved aside to {bak}")

    def _check_archive_header(self, path: str) -> None:
        """Rotate the archive aside unless its signature (or, for legacy
        headerless files, its first row's param-name set) matches."""
        try:
            with open(path) as f:
                first = json.loads(f.readline())
        except (json.JSONDecodeError, OSError):
            return
        if "space_sig" in first:
            if first["space_sig"] != self._space_sig():
                self._rotate_mismatch(path)
        elif "cfg" in first and set(first["cfg"]) != {
                s.name for s in self.space.specs}:
            self._rotate_mismatch(path)

    def _resume(self, path: str) -> None:
        """Replay the jsonl archive: exact unit vectors -> history + best
        (reference resume(), api.py:328-363 — replayed as technique 'seed',
        i.e. without touching technique states)."""
        rows = []
        sig = None
        compacted = 0
        good_end = 0
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            for line in f:
                text = line.strip()
                if not text:
                    good_end = f.tell()
                    continue
                try:
                    rec = json.loads(text)
                except json.JSONDecodeError:
                    break  # torn tail write; ignore the rest
                if not line.endswith(b"\n") and f.tell() == size:
                    break  # complete JSON but unterminated final line
                if "space_sig" in rec:
                    sig = rec["space_sig"]
                    # ut-stats --compact records how many duplicate rows
                    # it dropped; without this the resumed evals count
                    # would shrink and test_limit budgets would re-spend
                    # the difference in real evaluations
                    compacted = int(rec.get("compacted_rows", 0))
                else:
                    rows.append(rec)
                good_end = f.tell()
        if good_end < size:
            # drop the torn fragment so the next append starts clean
            with open(path, "r+b") as f:
                f.truncate(good_end)
        # the archive must match the current space STRUCTURALLY (order,
        # kinds, bounds — raw unit vectors are position-indexed); the
        # reference deletes a mismatched archive (api.py:334-339), we
        # rotate it aside so mixed-space records never share one file
        mismatch = (sig is not None and sig != self._space_sig()) or (
            sig is None and rows
            and set(rows[0]["cfg"]) != {s.name for s in self.space.specs})
        if mismatch:
            self._rotate_mismatch(path)
            return
        if not rows:
            return
        u = np.asarray([r["u"] for r in rows], np.float32)
        perms = [
            np.asarray([r["perms"][k] for r in rows], np.int32)
            for k in range(len(self.space.perm_sizes))]
        # archive rows are user-oriented; engine-internal = sign * user
        qor = self.sign * np.asarray([r["qor"] for r in rows], np.float32)
        self._ingest_batch(u, perms, qor)
        if self.surrogate is not None:
            # replayed trials are training data too: without this the
            # surrogate restarts cold after every resume while the
            # techniques resume warm (reference resume() replays into
            # the DBs its surrogate trains from, api.py:341-363).
            # Routed through the async plane when enabled (the fit runs
            # on the background worker and startup proceeds); a sync
            # fit over a large archive blocks HERE, so it is logged
            # rather than stalling silently (ISSUE 5 satellite)
            r0 = time.perf_counter()
            fitted = self.surrogate.maybe_refit()
            dt = time.perf_counter() - r0
            if getattr(self.surrogate, "_refit_future", None) \
                    is not None:
                log.info("[ut] resume: surrogate refit over %d replayed "
                         "rows scheduled on the background worker "
                         "(t_refit=%.3fs on the startup path)",
                         len(rows), dt)
            elif dt > 0.1 or fitted:
                log.info("[ut] resume: surrogate refit over %d replayed "
                         "rows took t_refit=%.3fs (enable the async "
                         "surrogate plane to move this off the startup "
                         "path)", len(rows), dt)
        self.gid = max(int(r["gid"]) for r in rows) + 1
        self.evals = len(rows) + compacted
        self.told = len(rows) + compacted
        running = float("inf")
        for q in qor:
            running = min(running, float(q))
            self.trace.append(self.sign * running)

    def _ingest_batch(self, u_np: np.ndarray, perms_np: List[np.ndarray],
                      qor_np: np.ndarray) -> None:
        """Commit externally-measured rows (exact unit vectors,
        ENGINE-oriented QoR) into history + best (+ surrogate training
        set) in bucket-sized chunks padded by repeating row 0, so
        archive replay and store warm-starts run through the SAME
        `_dedup`/`_commit` avals as the live tune and add no traces
        (the strict one-trace-per-program guarantee, docs/PERF.md).
        Counters/trace/archive are untouched — callers own those."""
        total = len(qor_np)
        bucket = self._bucket
        for s in range(0, total, bucket):
            n = min(bucket, total - s)
            cu = u_np[s:s + n]
            cp = [p[s:s + n] for p in perms_np]
            cq = qor_np[s:s + n]
            if n < bucket:
                pad = bucket - n
                cu = np.concatenate([cu, np.repeat(cu[:1], pad, axis=0)])
                cp = [np.concatenate([p, np.repeat(p[:1], pad, axis=0)])
                      for p in cp]
                cq = np.concatenate([cq, np.repeat(cq[:1], pad)])
            cands = CandBatch(jnp.asarray(cu),
                              tuple(jnp.asarray(p) for p in cp))
            hashes, found, known, src, novel = self._dedup(
                self.hist_state, cands)
            self.hist_state, self.best = self._commit(
                self.hist_state, self.best, hashes, cands,
                jnp.asarray(cq), novel)
            if self.surrogate is not None:
                # padding rows duplicate row 0 (sliced off via [:n]),
                # and rows ALREADY in the dedup history were observed
                # when they first entered it — e.g. a --resume replay
                # followed by a store warm-start covering the same
                # trials must not double-weight them in the training
                # set — so only history-novel rows train
                fresh = ~np.asarray(found)[:n]
                if fresh.any():
                    feats = np.asarray(self.space.features(cands))[:n]
                    self.surrogate.observe(feats[fresh], cq[:n][fresh])
        self._last_dropped = int(self.hist_state.dropped)

    def preload(self, u, perms, qor, refit: bool = True) -> int:
        """Warm-start ingestion of externally-recorded trials (the
        results store's cross-tune path, uptune_tpu/store/): rows enter
        the dedup history — never re-proposed, and dup-served their
        recorded QoR if a technique finds them again — fold into the
        best-so-far, and train the surrogate.  They touch NO run
        counters (evals/told/steps), archive rows, or trace entries:
        prior knowledge, not this run's work.

        `u` is [B, n_scalar] unit vectors, `perms` a list of [B, size]
        index arrays (one per perm spec), `qor` USER-oriented values;
        non-finite rows are dropped.  Returns the rows ingested."""
        u = np.atleast_2d(np.asarray(u, np.float32))
        qor_e = self.sign * np.asarray(qor, np.float32).reshape(-1)
        perms_np = [np.asarray(p, np.int32) for p in (perms or [])]
        if len(perms_np) != len(self.space.perm_sizes):
            raise ValueError(
                f"preload needs {len(self.space.perm_sizes)} perm "
                f"arrays, got {len(perms_np)}")
        keep = np.isfinite(qor_e)
        if not keep.all():
            u = u[keep]
            perms_np = [p[keep] for p in perms_np]
            qor_e = qor_e[keep]
        if not len(qor_e):
            return 0
        self._ingest_batch(u, perms_np, qor_e)
        sm = self.surrogate
        if refit and sm is not None:
            if hasattr(sm, "force_refit"):
                sm.force_refit()   # warm guidance live from trial 1
            else:
                sm.maybe_refit()
        return int(len(qor_e))

    def preload_rows(self, rows, refit: bool = True) -> int:
        """`preload` over result-store row dicts (``cfg``/``qor`` plus
        optional exact ``u``/``perms``): the ONE row-encoding path the
        controller's warm start, the cooperative-store federated feed
        (ISSUE 18), and library callers share.  Rows carrying exact
        unit vectors matching this space replay bit-exactly; the rest
        are re-encoded from their configs (close enough for warm-start
        dedup — a boundary float that re-encodes differently just gets
        re-measured once)."""
        rows = [r for r in rows if isinstance(r, dict) and "cfg" in r]
        if not rows:
            return 0
        space = self.space
        sizes = space.perm_sizes

        def exact(r):
            u, pp = r.get("u"), r.get("perms")
            return (u is not None and len(u) == space.n_scalar
                    and len(pp or []) == len(sizes)
                    and all(len(p) == s for p, s in zip(pp or [], sizes)))

        ex = [r for r in rows if exact(r)]
        ap = [r for r in rows if not exact(r)]
        n = 0
        if ex:
            u = np.asarray([r["u"] for r in ex], np.float32)
            perms = [np.asarray([r["perms"][k] for r in ex], np.int32)
                     for k in range(len(sizes))]
            # defer any refit to the LAST preload call of this batch
            n += self.preload(u, perms, [r["qor"] for r in ex],
                              refit=refit and not ap)
        if ap:
            cb = space.from_configs([r["cfg"] for r in ap])
            n += self.preload(np.asarray(cb.u),
                              [np.asarray(p) for p in cb.perms],
                              [r["qor"] for r in ap], refit=refit)
        return n

    def _log_trial(self, gid, tech, cfg, u_row, perm_rows, qor, is_best,
                   dur) -> None:
        """Append one archive row; `tech` records the proposing technique
        (the reference stores the requestor per Result,
        resultsdb/models.py:234-300, powering post-hoc attribution)."""
        if self._archive_f is None:
            return
        rec = {"gid": gid, "tech": tech, "time": round(dur, 6), "cfg": cfg,
               "u": [float(x) for x in u_row],
               "perms": [[int(i) for i in p] for p in perm_rows],
               "qor": float(qor), "best": bool(is_best)}
        self._archive_f.write(json.dumps(rec) + "\n")

    def _flush_archive(self):
        if self._archive_f is not None:
            self._archive_f.flush()

    # ------------------------------------------------------------------
    @staticmethod
    def _pack_hashes(hashes) -> np.ndarray:
        """[B, 2] uint32 device hash pairs -> [B] python-int-safe uint64."""
        hs = np.asarray(hashes).astype(np.uint64)
        return (hs[:, 0] << np.uint64(32)) | hs[:, 1]

    def _mask_pending(self, hashes, novel):
        """Drop candidates whose hash is already out for evaluation.
        Returns (novel mask, novel count, packed host hashes) — packed
        flows into the ticket so the batch is pulled host-side exactly
        once per acquisition."""
        novel_np = np.array(novel)  # writable copy: filters mutate it
        packed = self._pack_hashes(hashes)
        if self._pending:
            pend = np.fromiter(self._pending, np.uint64,
                               len(self._pending))
            novel_np = novel_np & ~np.isin(packed, pend)
        return novel_np, int(novel_np.sum()), packed

    def _surrogate_ticket(self, credit: bool) -> Optional[_Ticket]:
        """Try to pull the surrogate proposal plane once: EI-maximizing
        batch from an oversampled pool (surrogate/manager.py
        propose_pool), deduped and opened as an injected ticket
        attributed 'surrogate'.

        Either way a saturated pool opens NO ticket — no pull counted,
        no phantom zero-eval step (ADVICE r2) — it just marks the arm
        dry (backoff skips the next few acquisitions) and the walk
        falls through to a technique arm.  Under credit=True that
        fall-through is load-bearing: a dup-serving virtual ticket
        would return from _acquire without running the technique path,
        freezing _zero_novel_streak and its random-injection
        saturation escape (r4 review).  Negative bandit feedback still
        flows from pulls that evaluate and fail to improve."""
        sm = self.surrogate
        if not self._surrogate_ready():
            return None
        # compile-window baseline BEFORE the pool/dedup dispatches, so
        # a first-pull compile on this path lands in this ticket's
        # StepStats window (same rule as _acquire's dev0)
        dev0 = obs.device.compile_totals()
        self.key, k = jax.random.split(self.key)
        cands = sm.propose_pool(k, self.best.u, self.best.perms,
                                float(self.best.qor))
        if cands is None:
            return None
        pre = self._dedup_masked(cands)
        if not pre[3].any():
            self._arm_dry["surrogate"] = self._acq_count
            return None
        self._arm_dry.pop("surrogate", None)
        tk = self._open_injected_ticket(cands, "surrogate", _pre=pre,
                                        credit_virtual=credit,
                                        dev0=dev0)
        if not tk.trials:
            # every novel row was rejected by the user's config filter:
            # the pull produced nothing to evaluate.  Treated like pool
            # saturation (ADVICE r4): mark the arm dry and open no
            # ticket — under credit=True a zero-trial ticket would
            # otherwise be finalized as a NEGATIVE AUC event despite
            # never evaluating, letting a filter hostile to the pool
            # region starve the plane without it ever getting a trial.
            # Nothing is pending, so no finalize is needed; the pull is
            # still counted in arm_stats.
            self._arm_dry["surrogate"] = self._acq_count
            return None
        return tk

    def _surrogate_ready(self) -> bool:
        """Can the proposal plane emit a pool right now? (enabled,
        fitted, and there is a finite incumbent to perturb around)"""
        sm = self.surrogate
        return (sm is not None and bool(getattr(sm, "propose_batch", 0))
                and sm.fitted
                and math.isfinite(float(self.best.qor)))

    def _acquire_surrogate(self) -> Optional[_Ticket]:
        """Scheduled surrogate proposal plane: every `propose_every`-th
        acquisition (once fitted) the manager emits its own batch
        instead of only filtering an arm's batch.  The ticket carries no
        technique state and earns no bandit credit (like injected
        seeds), but IS attributed in the archive as 'surrogate'.  Under
        arbitration='bandit' this path is off — the AUC bandit pulls
        the plane as a virtual arm in _acquire instead."""
        if not self._surrogate_ready():
            return None
        self._surr_tick += 1
        if self._surr_tick % max(1, self.surrogate.propose_every):
            return None
        return self._surrogate_ticket(credit=False)

    def _dedup_masked(self, cands: CandBatch):
        """(hashes, known, src, novel_np, packed): dedup vs history +
        in-batch, then mask hashes already out for evaluation."""
        hashes, found, known, src, novel = self._dedup(
            self.hist_state, cands)
        novel_np, _, packed = self._mask_pending(hashes, novel)
        return (hashes, np.asarray(known, np.float32).copy(),
                np.asarray(src), novel_np, packed)

    def _open_injected_ticket(self, cands: CandBatch, source: str,
                              _pre=None, credit_virtual=False,
                              dev0=None) -> _Ticket:
        """Dedup -> pending-mask -> injected ticket -> open: the shared
        plumbing behind inject() and the surrogate proposal plane.
        Injected tickets never touch technique states; they skip bandit
        credit too unless credit_virtual (the bandit-arbitrated
        surrogate arm).  `dev0` is the caller's pre-dispatch compile
        baseline when it already ran device work for this ticket
        (`_pre`); otherwise it is captured here, before the dedup
        dispatch, so a first-ever driver.dedup compile lands in THIS
        ticket's StepStats window."""
        if dev0 is None:
            dev0 = obs.device.compile_totals()
        hashes, known, src, novel_np, packed = (
            _pre if _pre is not None else self._dedup_masked(cands))
        tk = _Ticket(None, source, None, cands, hashes, known, src,
                     novel_np, injected=True, pruned=0,
                     credit_virtual=credit_virtual)
        tk.packed = packed
        tk.dev0 = dev0
        self._open_ticket(tk)
        return tk

    def _acquire(self) -> _Ticket:
        """Choose arm -> propose batch -> dedup (history + in-batch +
        pending) -> surrogate prune; returns the open ticket."""
        self._acq_count += 1
        dev0 = obs.device.compile_totals()
        if not self._surr_arm:
            tk = self._acquire_surrogate()
            if tk is not None:
                return tk
            order = (self.root.select_order()
                     if isinstance(self.root, MetaTechnique)
                     else [self.root])
            order = [t for t in order if t.name in self._tstates]
        else:
            # bandit arbitration: the AUC queue orders techniques AND
            # the 'surrogate' virtual arm together; the sentinel string
            # marks the virtual pull in the walk below
            order = []
            for n in self.root.ordered_names():
                if n in self.root.virtual_arms:
                    order.append(n)
                elif n in self._tstates:
                    order.append(self._member_by_name[n])
        if self._arm_dry:
            dry = {n for n, s in self._arm_dry.items()
                   if self._acq_count - s < self._dry_backoff}
            if dry:
                # arms inside the backoff window are skipped outright;
                # when every arm is dry, one proposes (to serve dups /
                # advance the saturation streak) instead of all of them
                active = [t for t in order
                          if (t if isinstance(t, str) else t.name)
                          not in dry]
                order = active if active else order[:1]
        if all(isinstance(t, str) for t in order):
            # every surviving entry is virtual: a failed virtual pull
            # must still leave a technique to fall back on
            order.append(self.members[0])

        chosen = None
        t_prop = 0.0
        t_host0 = time.perf_counter()
        for t in order:
            if isinstance(t, str):  # virtual arm: the surrogate plane
                stk = self._surrogate_ticket(credit=True)
                if stk is not None:
                    return stk
                continue  # can't pull (not fitted / saturated): next arm
            self.key, k = jax.random.split(self.key)
            # ONE fused device program: propose + pad + hash + dedup
            p0 = time.perf_counter()
            with obs.device_span("ticket.propose", arm=t.name):
                tstate, cands, hashes, known, src, novel = \
                    self._propose_jit[t.name](
                        self._tstates[t.name], k, self.best,
                        self.hist_state)
            t_prop += time.perf_counter() - p0
            if t.name not in self._fwd_checked:
                self._fwd_checked.add(t.name)
                held, ok_in = _leaf_keys(self._tstates[t.name])
                out, ok_out = _leaf_keys(tstate)
                if (held & out) or not (ok_in and ok_out):
                    # proven aliasing — or unprovable: donation is a
                    # perf nicety, never worth a deleted-buffer crash
                    self._arm_forwards.add(t.name)
            novel_np, n_novel, packed = self._mask_pending(hashes, novel)
            if n_novel > 0:
                self._arm_dry.pop(t.name, None)
            else:
                self._arm_dry[t.name] = self._acq_count
            if n_novel > 0 or chosen is None:
                chosen = (t, tstate, cands, hashes, known, src, novel_np,
                          n_novel, packed)
            if n_novel > 0:
                break
        (t, tstate, cands, hashes, known, src, novel_np, n_novel,
         packed) = chosen

        injected = False
        if n_novel == 0:
            self._zero_novel_streak += 1
            if self._zero_novel_streak >= 3:
                # saturation fallback: random injection (the reference's
                # space is never exhausted because SQL dedup just drops the
                # DR and the driver retries; we top up explicitly).  The
                # injected batch is NOT the arm's proposal: it must not
                # flow into the arm's observe() or bandit credit.
                injected = True
                self.key, k = jax.random.split(self.key)
                p0 = time.perf_counter()
                with obs.device_span("ticket.propose", arm="random"):
                    cands = self.space.random(k, cands.batch)
                    hashes, found, known, src, novel = self._dedup(
                        self.hist_state, cands)
                t_prop += time.perf_counter() - p0
                novel_np, n_novel, packed = self._mask_pending(hashes,
                                                               novel)
        else:
            self._zero_novel_streak = 0

        pruned = 0
        if n_novel and self.surrogate is not None and not injected:
            keep = self.surrogate.keep_mask(cands, novel_np)
            if keep is not None:
                pruned = int((novel_np & ~keep).sum())
                if pruned:
                    # rejected without evaluation (multivoting prune,
                    # api.py:307-326): NOT archived, NOT inserted into
                    # history (may be re-proposed after a refit)
                    novel_np = novel_np & np.asarray(keep)
                    n_novel = int(novel_np.sum())
                    self.pruned_total += pruned

        name = "random" if injected else t.name
        tk = _Ticket(t, name, tstate, cands, hashes,
                     np.asarray(known, np.float32).copy(), np.asarray(src),
                     novel_np, injected, pruned,
                     gen=self._tgen.get(t.name, 0))
        tk.packed = packed
        tk.t_propose = t_prop
        tk.dev0 = dev0
        self._open_ticket(tk)
        tk.t_dedup = time.perf_counter() - t_host0 - t_prop
        return tk

    def _open_ticket(self, tk: _Ticket) -> None:
        """Materialize trials for a ticket's novel rows (after the
        optional ut.rule config filter) and register them pending."""
        tk.t_open = time.perf_counter()
        f0 = self.filtered_total
        sp_obs = obs.span("ticket.dedup", arm=tk.arm_name)
        sp_obs.__enter__()
        try:
            if tk.packed is None:  # all acquisition paths pre-pack
                tk.packed = self._pack_hashes(tk.hashes)
            if tk.novel_np.any():
                idx = np.nonzero(tk.novel_np)[0]
                # one device->host transfer of the whole batch, then
                # plain numpy row selection: the old per-ticket device
                # gather was two extra dispatches on the ask() critical
                # path
                u_all = np.asarray(tk.cands.u)
                perms_all = [np.asarray(p) for p in tk.cands.perms]
                sub = CandBatch(u_all[idx],
                                tuple(p[idx] for p in perms_all))
                cfgs = self.space.to_configs(sub)
                if self.config_filter is not None:
                    keep = np.asarray([bool(self.config_filter(c))
                                       for c in cfgs])
                    if not keep.all():
                        self.filtered_total += int((~keep).sum())
                        tk.novel_np[idx[~keep]] = False
                        idx = idx[keep]
                        cfgs = [c for c, k in zip(cfgs, keep) if k]
                        sub = CandBatch(u_all[idx],
                                        tuple(p[idx] for p in perms_all))
                if len(idx):
                    tk.u_np = np.asarray(sub.u)
                    tk.perms_np = [np.asarray(p) for p in sub.perms]
                    for j, (row, cfg) in enumerate(zip(idx, cfgs)):
                        tk.trials.append(
                            Trial(self.gid, cfg, tk, j, int(row)))
                        self.gid += 1
                        self._pending.add(int(tk.packed[row]))
            tk.remaining = len(tk.trials)
            sp_obs.set(trials=len(tk.trials),
                       gid0=(tk.trials[0].gid if tk.trials else None))
        finally:
            # a raising user config_filter must not lose the span —
            # the half-open ticket is exactly what a trace debugger
            # needs to see
            sp_obs.__exit__(None, None, None)
        if tk.trials:
            obs.count("driver.trials_opened", len(tk.trials))
        st = self.arm_stats.setdefault(tk.arm_name, [0, 0, 0])
        st[0] += 1
        st[1] += len(tk.trials)
        if obs.journal.enabled():
            self._journal_open(tk, self.filtered_total - f0)

    def _journal_open(self, tk: _Ticket, filtered: int) -> None:
        """Capture the pull verdicts (dedup / prune / filter counts)
        and the surrogate's predictive moments for the proposed batch
        AT PROPOSE TIME — the step row emitted at finalize carries
        both, joining belief with outcome (ISSUE 12).  Only reached
        when the journal is on: the extra predict dispatch and host
        transfer never tax an unjournaled run."""
        batch = int(tk.cands.batch)
        trials = len(tk.trials)
        src = ("surrogate" if tk.arm_name == "surrogate"
               else "random" if tk.injected and tk.arm_name == "random"
               else "injected" if tk.injected else "technique")
        tk.jpull = (src, batch, trials, int(tk.pruned), int(filtered),
                    max(0, batch - trials - int(tk.pruned)
                        - int(filtered)))
        sm = self.surrogate
        if trials and sm is not None and hasattr(sm, "predict_cands"):
            tk.pred = sm.predict_cands(tk.cands)

    def _journal_step(self, tk: _Ticket, live: List[Trial],
                      evaluated: int, withdrawn: bool,
                      was_new_best: bool, nb_flags: List[bool],
                      new: float, dropped: int, t_wait: float,
                      snap_v: int, lag: int) -> None:
        """One journal 'step' row per finalized ticket, carrying every
        live trial's outcome as parallel arrays — the measured
        (user-oriented) QoR joined with the surrogate's propose-time
        predictive moments (the calibration stream `ut report` and the
        online QualityMonitor consume).  One row per TICKET, not per
        trial: serializing per trial measured ~15 us on this hot path,
        enough to break the BENCH_OBS >= 0.95x bar on its own.  Every
        value is a plain python scalar: the journal never holds a
        device buffer."""
        row: Dict[str, Any] = {
            "ev": "step", "step": self.steps, "arm": tk.arm_name,
            "evaluated": evaluated, "withdrawn": withdrawn,
            "new_best": was_new_best,
            "best": (round(self.sign * new, 6)
                     if math.isfinite(new) else None),
            "evals": self.evals, "pruned": int(tk.pruned),
            "hist_dropped": int(dropped),
            "t_wait": round(t_wait, 6), "snap_v": snap_v, "lag": lag}
        if tk.jpull is not None:
            (row["src"], row["batch"], row["trials"], _,
             row["filtered"], row["dup"]) = tk.jpull
        if self.sense == "max":
            row["sense"] = "max"
        if live:
            # compact encoding (obs/journal.py EVENT_KINDS): arrays
            # whose value is the documented default are omitted —
            # `ok` absent = all true, `nb` absent = all false, `durs`
            # absent = all zero, contiguous gids collapse to `gid0`.
            # Most rows hit every default, halving both the
            # serialization bytes and the allocation pressure (gen0
            # GC passes in a jax-sized process are part of the
            # BENCH_OBS budget)
            sign = self.sign
            g0 = live[0].gid
            if all(tr.gid == g0 + i for i, tr in enumerate(live)):
                row["gid0"] = g0
            else:
                row["gids"] = [tr.gid for tr in live]
            # one pass, one list in the common all-finite case
            qors: List[Any] = []
            all_ok = True
            for tr in live:
                if math.isfinite(tr.qor):
                    qors.append(round(sign * tr.qor, 6))
                else:
                    qors.append(None)
                    all_ok = False
            row["qors"] = qors
            if not all_ok:
                row["ok"] = [q is not None for q in qors]
            if any(nb_flags):
                row["nb"] = nb_flags
            if any(tr.dur for tr in live):
                row["durs"] = [round(tr.dur, 6) for tr in live]
            if tk.pred is not None:
                mu, sd, ver = tk.pred
                row["mus"] = [round(float(sign * mu[tr.row]), 6)
                              for tr in live]
                row["sigmas"] = [round(float(sd[tr.row]), 6)
                                 for tr in live]
                # propose-time snapshot version of the prediction —
                # distinct from the TELL-time `snap_v`/`lag` pair
                # above, which samples the plane at finalize
                row["pred_v"] = int(ver)
        obs.journal.emit_row(row)

    def inject(self, cfgs: Sequence[Dict[str, Any]],
               source: str = "seed") -> List[Trial]:
        """Open a ticket for externally-proposed configs (user models via
        @ut.model, seed/default configs — the reference's technique
        'seed' rows, api.py:341-363).  Injected tickets never touch
        technique states or bandit credit; resolve the returned trials
        via tell()."""
        cfgs = list(cfgs)
        # pad to a multiple of the dedup bucket by repeating the first
        # config: padding rows are exact in-batch duplicates (never
        # novel, never trials), and the standalone _dedup/_commit
        # programs keep seeing the same input aval as the arm tickets
        # instead of tracing once per injected batch size
        n = len(cfgs)
        target = -(-n // self._bucket) * self._bucket
        if n and n < target:
            cfgs = cfgs + [cfgs[0]] * (target - n)
        cands = self.space.from_configs(cfgs)
        tk = self._open_injected_ticket(cands, source)
        if not tk.trials:
            self._finalize(tk)  # all dups: serve + commit immediately
            return []
        return tk.trials

    # ------------------------------------------------------------------
    # ask/tell: the externally-paced surface (the reference's OpenTuner
    # slave API, opentuner/api.py:18-53 get_next_desired_result /
    # report_result), batched.
    def ask(self, min_trials: int = 1, max_attempts: int = 8) -> List[Trial]:
        """Propose >= min_trials hash-novel trials for external
        evaluation (fewer only if the space saturates)."""
        trials: List[Trial] = []
        obs.count("driver.asks")
        for _ in range(max_attempts):
            tk = self._acquire()
            if tk.trials:
                trials.extend(tk.trials)
            else:
                self._finalize(tk)  # serve dups / credit immediately
            if len(trials) >= min_trials:
                break
        return trials

    def tell(self, trial: Trial, qor: Optional[float],
             dur: float = 0.0) -> Optional[StepStats]:
        """Report a trial's USER-oriented QoR (None/NaN/inf = failure).
        Returns StepStats when the trial's whole ticket resolves."""
        if trial.qor is not None or trial.cancelled:
            raise ValueError(f"trial gid={trial.gid} already resolved")
        v = float("nan") if qor is None else float(qor)
        # engine minimizes; failures are +inf in ENGINE orientation
        # (sign applies to valid values only, else sense='max' would
        # turn a failure into an unbeatable -inf best)
        trial.qor = self.sign * v if math.isfinite(v) else float("inf")
        trial.dur = dur
        self.told += 1
        obs.count("driver.told")
        if self.hooks:
            _fire(self.hooks, "on_result", self, trial,
                  float(qor) if math.isfinite(v) else None)
        tk = trial.ticket
        tk.remaining -= 1
        if tk.remaining == 0:
            return self._finalize(tk)
        return None

    def cancel(self, trial: Trial) -> Optional[StepStats]:
        """Withdraw an un-told trial (e.g. the run limit was reached
        before it launched): no archive row, no history insert, no eval
        count — the config may be re-proposed later."""
        if trial.qor is not None or trial.cancelled:
            raise ValueError(f"trial gid={trial.gid} already resolved")
        trial.cancelled = True
        obs.event("ticket.withdraw", gid=trial.gid,
                  arm=trial.ticket.arm_name)
        obs.count("driver.withdrawn")
        tk = trial.ticket
        tk.remaining -= 1
        if tk.remaining == 0:
            return self._finalize(tk)
        return None

    def _credit(self, name: str, was_new_best: bool, live, global_best:
                float) -> None:
        """One AUC credit event for a resolved pull.  step_best comes
        from the ticket's LIVE trials only: the batch qor also carries
        history-dup rows served their recorded result, which would let
        an arm that only re-proposes known configs inherit the
        incumbent's QoR and dodge recycling."""
        step_best = min((tr.qor for tr in live), default=float("inf"))
        if self._credit_kw:
            self.root.credit(name, was_new_best, step_best=step_best,
                             global_best=global_best)
        else:
            self.root.credit(name, was_new_best)

    def _finalize(self, tk: _Ticket) -> StepStats:
        """Commit a completed ticket: history insert, best update,
        archive rows, technique observe + bandit credit."""
        qor_np = tk.known  # history dups served their recorded result
        packed = tk.packed
        live = [tr for tr in tk.trials if not tr.cancelled]
        for tr in tk.trials:
            self._pending.discard(int(packed[tr.row]))
            if tr.cancelled:
                tk.novel_np[tr.row] = False  # never entered history
            else:
                qor_np[tr.row] = tr.qor
        evaluated = len(live)
        # a ticket whose trials were ALL withdrawn (speculative prefetch
        # invalidated by a new best, or the run limit arriving first)
        # was never evaluated: no observe, no bandit credit — the pull
        # outcome is unknown, not negative.  A ZERO-trial ticket (every
        # row a served duplicate) is different: its dup-serving credit
        # event is the load-bearing negative feedback that lets the
        # bandit starve a saturated arm.
        withdrawn = bool(tk.trials) and not live

        prev = float(self.best.qor)
        qor = None
        if evaluated or tk.novel_np.any():
            # in-batch duplicates copy their source row's result
            qor = jnp.asarray(qor_np[tk.src])
            with obs.device_span("ticket.commit", arm=tk.arm_name):
                self.hist_state, self.best = self._commit(
                    self.hist_state, self.best, tk.hashes, tk.cands,
                    qor, jnp.asarray(tk.novel_np))
            self._last_dropped = int(self.hist_state.dropped)
            new = float(self.best.qor)
        else:
            # nothing evaluated and nothing novel: the commit would be
            # a pure no-op — skip the device dispatch entirely
            new = prev
        was_new_best = new < prev

        running = prev
        jn = obs.journal.enabled()
        nb_flags: List[bool] = [] if jn else None
        for tr in live:
            is_best = tr.qor < running
            running = min(running, tr.qor)
            self._log_trial(tr.gid, tk.arm_name, tr.config,
                            tk.u_np[tr.slot],
                            [p[tr.slot] for p in tk.perms_np],
                            self.sign * tr.qor, is_best, tr.dur)
            self.trace.append(self.sign * running)
            if jn:
                nb_flags.append(is_best)
        self.evals += evaluated

        if not tk.injected and not withdrawn:
            if tk.gen == self._tgen.get(tk.arm.name, 0):
                if qor is None:
                    qor = jnp.asarray(qor_np[tk.src])
                # tk.tstate is DONATED into observe: a ticket's propose
                # snapshot is dead after its own observe call (unless
                # the arm forwards state through propose — then several
                # in-flight tickets alias one buffer and donation would
                # delete a sibling's state)
                nm = tk.arm.name
                if nm in self._arm_forwards:
                    fn = self._observe_nodonate.get(nm)
                    if fn is None:
                        fn = self._make_observe(
                            self._member_by_name[nm], False)
                        self._observe_nodonate[nm] = fn
                else:
                    fn = self._observe_jit[nm]
                with obs.device_span("ticket.observe", arm=nm):
                    self._tstates[nm] = fn(tk.tstate, tk.cands, qor,
                                           self.best)
            # else: the member was restarted while this ticket was in
            # flight — observing would write the pre-restart snapshot
            # back over the fresh state, silently undoing the restart
            if isinstance(self.root, MetaTechnique):
                self._credit(tk.arm.name, was_new_best, live, new)
                # quality-aware metas (RecyclingMeta) may ask for member
                # restarts: re-initialize the member's device state (the
                # jitted programs are keyed by name and stay cached)
                for nm in self.root.poll_restart():
                    t = self._member_by_name.get(nm)
                    if t is not None:
                        self.key, k = jax.random.split(self.key)
                        self._tstates[nm] = _strong(
                            t.init_state(self.space, k))
                        self._tgen[nm] = self._tgen.get(nm, 0) + 1
        elif tk.credit_virtual and isinstance(self.root, MetaTechnique) \
                and not withdrawn:
            # bandit-arbitrated surrogate pull: no technique state to
            # observe, but the outcome is the virtual arm's AUC event
            self._credit(tk.arm_name, was_new_best, live, new)
        if was_new_best:
            self.arm_stats.setdefault(tk.arm_name, [0, 0, 0])[2] += 1
        t_refit = 0.0
        if evaluated and self.surrogate is not None:
            # surrogate learning is the LAST act of the ticket, after
            # every driver device dispatch (_commit, arm observe): an
            # async submission starts the background fit immediately,
            # and a device op issued after it would queue behind the
            # fit's execution on the shared CPU threadpool — ordered
            # this way the tell returns with nothing left to wait on,
            # and the fit overlaps the next build window.  Sync mode
            # pays the full O(N^3) fit inline here; async submits and
            # folds fresh rows in via O(N^2) incremental extension, so
            # t_refit stays ~0 on the tell path.
            idx = jnp.asarray([tr.row for tr in live])
            with obs.span("surrogate.tick", arm=tk.arm_name) as so:
                self.surrogate.observe(
                    np.asarray(self.space.features(tk.cands[idx])),
                    qor_np[np.asarray(idx)])
                r0 = time.perf_counter()
                self.surrogate.maybe_refit()
                t_refit = time.perf_counter() - r0
                so.set(t_refit_ms=round(t_refit * 1e3, 3))
        dropped = self._last_dropped
        if dropped and not self._cap_warned:
            self._cap_warned = True
            import warnings
            warnings.warn(
                f"history capacity ({self.history.capacity}) exceeded; "
                f"oldest entries are being evicted (dedup no longer sees "
                f"the start of the run) — raise Tuner(capacity=...); "
                f"running drop count is in StepStats.hist_dropped")
        self.steps += 1
        self._flush_archive()
        t_wait = time.perf_counter() - tk.t_open if tk.t_open else 0.0
        self.t_propose_total += tk.t_propose
        self.t_dedup_total += tk.t_dedup
        self.t_eval_wait_total += t_wait
        self.t_refit_total += t_refit
        sm = self.surrogate
        snap_v = int(getattr(sm, "snapshot_version", 0) or 0)
        lag = int(getattr(sm, "refit_lag_rows", 0) or 0)
        # device-plane compile activity over this ticket's window
        # (zeros while tracing is off; concurrent tickets attribute a
        # shared compile to each open window — a window report, not an
        # exclusive cost split)
        dc1, ds1 = obs.device.compile_totals()
        n_compiles = dc1 - tk.dev0[0]
        t_compile = ds1 - tk.dev0[1]
        self.t_compile_total += t_compile
        stats = StepStats(self.steps, tk.arm_name, tk.cands.batch,
                          evaluated, self.sign * new, was_new_best,
                          tk.pruned, dropped, tk.t_propose, tk.t_dedup,
                          t_wait, t_refit, snap_v, lag,
                          n_compiles, t_compile)
        if jn:
            self._journal_step(tk, live, evaluated, withdrawn,
                               was_new_best, nb_flags, new, dropped,
                               t_wait, snap_v, lag)
        if obs.enabled():
            obs.event("ticket.finalize", arm=tk.arm_name,
                      evaluated=evaluated, withdrawn=withdrawn,
                      new_best=was_new_best, step=self.steps)
            obs.observe("driver.eval_wait_s", t_wait)
            obs.gauge("surrogate.snapshot_version", snap_v)
            obs.gauge("surrogate.refit_lag_rows", lag)
            obs.gauge("driver.hist_dropped", dropped)
            if was_new_best:
                obs.count("driver.new_bests")
            if withdrawn:
                obs.count("driver.tickets_withdrawn")
        if self.hooks:
            if was_new_best:
                res = self.result()
                _fire(self.hooks, "on_new_best", self,
                      res.best_config, res.best_qor)
            _fire(self.hooks, "on_step", self, stats)
        return stats

    def step(self) -> StepStats:
        """One synchronous acquisition step: acquire -> evaluate novel
        via the in-process objective -> finalize."""
        if self.objective is None:
            raise RuntimeError(
                "Tuner has no in-process objective: drive it externally "
                "via ask()/tell() instead of step()/run()")
        tk = self._acquire()
        if not tk.trials:
            return self._finalize(tk)
        cfgs = [tr.config for tr in tk.trials]
        t0 = time.time()
        im = self.input_manager
        if im is not None:
            inps = [im.select_input(tr) for tr in tk.trials]
            for tr, i in zip(tk.trials, inps):
                im.before_run(tr, i)
            vals = np.asarray(self.objective(cfgs, inps),
                              np.float64).reshape(-1)
            for tr, i in zip(tk.trials, inps):
                im.after_run(tr, i)
        else:
            vals = np.asarray(self.objective(cfgs),
                              np.float64).reshape(-1)
        dur = (time.time() - t0) / max(1, len(cfgs))
        stats = None
        for tr, v in zip(tk.trials, vals):
            stats = self.tell(tr, float(v), dur)
        return stats

    # ------------------------------------------------------------------
    def run(self, test_limit: int = 5000,
            time_limit: Optional[float] = None,
            target: Optional[float] = None) -> TuneResult:
        """Run until `test_limit` evaluations (driver.py:25-26 default
        5000), a wall-clock limit, or a target QoR is reached."""
        self._apply_budget_rule(test_limit)
        t0 = time.time()
        no_eval_streak = 0
        while self.evals < test_limit:
            stats = self.step()
            no_eval_streak = 0 if stats.evaluated else no_eval_streak + 1
            if no_eval_streak >= 25:
                # search space exhausted: even random injection finds
                # nothing hash-novel any more
                break
            if time_limit is not None and time.time() - t0 > time_limit:
                break
            if target is not None and self._target_met(target):
                break
        return self.result()

    def _wire_surrogate_arm(self) -> bool:
        """Register the surrogate proposal plane as a credit-earning
        virtual arm of the AUC bandit (arbitration='bandit').  Shared by
        __init__ and the run-budget rule; returns False when the root
        is not an AUC bandit or the plane is disabled."""
        sm = self.surrogate
        from ..techniques.bandit import AUCBanditMeta
        if not (isinstance(self.root, AUCBanditMeta)
                and getattr(sm, "propose_batch", 0)):
            return False
        if "surrogate" not in self.root.virtual_arms:
            self.root.register_virtual_arm("surrogate")
        self._surr_arm = True
        if getattr(sm, "propose_batch_parity", False):
            # pull-size parity: raise the pool batch to the median
            # technique-arm batch so one virtual pull spends about as
            # many evaluations as one arm pull.  Without it the plane's
            # small pulls inflate its AUC use_count ~4x faster per eval
            # and the exploration term starves it in the endgame
            # (measured, exp_bandit_batch.jsonl / BENCHREPORT)
            bs = sorted(t.natural_batch(self.space)
                        for t in self.members)
            med = int(bs[len(bs) // 2])
            if med > sm.propose_batch:
                sm.propose_batch = med
        return True

    def _apply_budget_rule(self, test_limit: int) -> None:
        """Run-budget surrogate rule (measured, BENCHREPORT "Why the
        surrogate does not beat the bandit on gcc-real"): with fewer
        evals than scalar parameters the GP posterior stays
        prior-dominated for the whole run and scheduled in-loop guidance
        measured neutral-to-harmful (1.49x on gcc-real) — while the SAME
        guidance wins 0.14-0.46x when the budget dwarfs the dimension.

        The measured-BEST configuration in the small-budget regime is
        neither the schedule nor passivity: it is bandit ARBITRATION
        with affordable (non-parity) pulls — 0.88x baseline median and
        the top solve-rate at 30 matched gcc-real seeds
        (BUDGET_CONSTRAINED_OPTS, BENCHREPORT.md "Bandit-arbitrated
        plane", exp_bandit_gccreal_r4f.jsonl).  So when `test_limit <
        n_scalar` the driver now applies that recipe itself (r4 verdict
        #4): the plane becomes an AUC-credit virtual arm with its
        calibrated 8-eval pulls.  If the root technique cannot
        arbitrate (not an AUC bandit, or the plane is disabled) it
        falls back to passivation, the measured-safe default.  Users
        opt out of the whole rule via auto_passive=False; explicit
        arbitration/parity settings are left untouched.  Called from
        run(); external ask/tell pacers know their own budgets and can
        set surrogate.passive / arbitration directly (the CLI
        controller applies the same rule)."""
        sm = self.surrogate
        if sm is None or not getattr(sm, "auto_passive", False):
            return
        import warnings
        if test_limit < self.space.n_scalar:
            if getattr(sm, "passive", False):
                return      # already passive (this rule or the user)
            if self._surr_arm or getattr(sm, "_auto_budget", False):
                return      # user chose arbitration, or already applied
            prev = (sm.arbitration, sm.propose_batch_parity,
                    sm.propose_batch)
            from ..calibrated import BUDGET_CONSTRAINED_OPTS
            sm.arbitration = "bandit"
            sm.propose_batch_parity = False
            # pin the pull size to the measured recipe: the 0.88x
            # evidence was captured at the calibrated 8-eval pulls, and
            # the warning below claims exactly that — a library caller
            # with a custom propose_batch (e.g. 32) must not silently
            # get 32-eval pulls under the 8-eval rule (ADVICE r5).
            # propose_batch == 0 means the plane is DISABLED: leave it
            # so _wire_surrogate_arm declines and the rule falls back
            # to passivation instead of resurrecting the plane
            if sm.propose_batch:
                sm.propose_batch = \
                    BUDGET_CONSTRAINED_OPTS["propose_batch"]
            if self._wire_surrogate_arm():
                sm._auto_budget = prev
                warnings.warn(
                    f"surrogate switched to BUDGET-CONSTRAINED bandit "
                    f"arbitration for this run: budget {test_limit} "
                    f"evals < {self.space.n_scalar} scalar parameters — "
                    f"the regime where AUC-arbitrated "
                    f"{sm.propose_batch}-eval pool pulls "
                    f"are the best measured configuration (0.88x "
                    f"baseline median, BENCHREPORT.md); pass "
                    f"surrogate_opts={{'auto_passive': False}} to "
                    f"override", UserWarning)
                return
            # can't arbitrate: fall back to passivation (measured-safe)
            (sm.arbitration, sm.propose_batch_parity,
             sm.propose_batch) = prev
            sm.passive = True
            sm._auto_passivated = True
            warnings.warn(
                f"surrogate set PASSIVE for this run: budget "
                f"{test_limit} evals < {self.space.n_scalar} scalar "
                f"parameters, a regime where scheduled in-loop guidance "
                f"is measured neutral-to-harmful (BENCHREPORT.md) and "
                f"the root technique cannot bandit-arbitrate the plane; "
                f"pass surrogate_opts={{'auto_passive': False}} to "
                f"override", UserWarning)
        else:
            # the rule is per RUN: a later large-budget run on the same
            # tuner reverts what the rule itself changed (user-set
            # flags are left alone)
            if getattr(sm, "_auto_passivated", False):
                sm.passive = False
                sm._auto_passivated = False
            prev = getattr(sm, "_auto_budget", None)
            if prev:
                (sm.arbitration, sm.propose_batch_parity,
                 sm.propose_batch) = prev
                sm._auto_budget = None
                if sm.arbitration != "bandit":
                    # virtual-arm registration is harmless to leave in
                    # the bandit (select_order filters to real members);
                    # only the pull path is disabled
                    self._surr_arm = False

    def _target_met(self, target: float) -> bool:
        q = float(self.best.qor)
        if not math.isfinite(q):
            return False
        user = self.sign * q
        return user <= target if self.sense == "min" else user >= target

    def result(self) -> TuneResult:
        q = float(self.best.qor)
        cfg = {}
        if math.isfinite(q):
            cfg = self.space.to_configs(self.best.as_batch(1))[0]
        return TuneResult(cfg, self.sign * q, self.evals, self.steps,
                          list(self.trace), self.t_propose_total,
                          self.t_dedup_total, self.t_eval_wait_total,
                          self.t_refit_total, self.t_compile_total)

    def best_config(self) -> Dict[str, Any]:
        return self.result().best_config

    def close(self):
        if self.hooks:
            _fire(self.hooks, "on_finish", self, self.result())
            self.hooks = []
        sm = self.surrogate
        if sm is not None:
            # let an in-flight background refit publish and shut the
            # worker down so no refit thread outlives the run
            if hasattr(sm, "close"):
                sm.close()
            elif hasattr(sm, "drain"):
                sm.drain()
        if self._archive_f is not None:
            self._archive_f.close()
            self._archive_f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
