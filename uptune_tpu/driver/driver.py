"""The tuning driver: batched acquisition loop over on-device techniques.

Host-side replacement for the reference's controller + search-driver pair
(`/root/reference/python/uptune/api.py:399-594` `async_execute` and
`opentuner/search/driver.py:160-225`), re-shaped for TPU batching:

* each step, the meta-technique (AUC bandit) orders its arms host-side and
  the first supported arm emits a whole CandBatch from one jitted XLA
  program (vs. one config per `desired_result()` call);
* dedup + known-result reuse run on device against the sorted-hash history
  (driver/history.py) instead of per-proposal SQL lookups;
* only hash-novel candidates cross the host boundary for black-box
  evaluation; in-batch duplicates share one evaluation, history duplicates
  are served their recorded QoR (api.py:276-286 semantics);
* every evaluated trial is appended to a jsonl archive carrying the raw
  unit vectors, so `resume()` replays *exactly* (the reference's
  ut.archive.csv + `resume`, api.py:328-363,536-543).
"""
from __future__ import annotations

import json
import math
import os
import time
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..space.spec import CandBatch, Space
from ..techniques import base as tbase
from ..techniques.base import Best, Technique
from ..techniques.bandit import MetaTechnique
from .history import History, dup_source

Objective = Callable[[List[Dict[str, Any]]], Sequence[float]]


class StepStats(NamedTuple):
    step: int
    technique: str
    batch: int
    evaluated: int
    best_qor: float
    was_new_best: bool
    pruned: int = 0


class TuneResult(NamedTuple):
    best_config: Dict[str, Any]
    best_qor: float          # in USER orientation (negated back for 'max')
    evals: int
    steps: int
    trace: List[float]       # best-so-far (user orientation) after each eval


class Tuner:
    """Single-instance batched tuner over an in-process objective.

    Parameters
    ----------
    space : Space
    objective : callable(list[config dict]) -> sequence of float
        QoR per config; non-finite values count as failures (+inf).
    technique : str | list[str] | Technique | None
        As the reference's --technique flag (technique.py:345-362);
        default is the AUCBanditMetaTechniqueA portfolio.
    sense : 'min' | 'max'
        User objective orientation; engine always minimizes
        (objective.py:161-183 normal form).
    archive : optional path of the jsonl trial archive (resume source).
    """

    def __init__(self, space: Space, objective: Objective, *,
                 technique=None, seed: int = 0, sense: str = "min",
                 capacity: int = 1 << 16,
                 archive: Optional[str] = None,
                 resume: bool = False,
                 surrogate=None, surrogate_opts: Optional[dict] = None):
        assert sense in ("min", "max"), sense
        self.space = space
        self.objective = objective
        self.sense = sense
        self.sign = 1.0 if sense == "min" else -1.0
        self.key = jax.random.PRNGKey(seed)
        self.history = History(capacity)
        self.hist_state = self.history.init()
        self.best = Best.empty(space)
        self.archive_path = archive
        self.evals = 0
        self.steps = 0
        self.gid = 0
        self.trace: List[float] = []
        self._zero_novel_streak = 0
        self._cap_warned = False
        self.pruned_total = 0

        # surrogate-ensemble pruning (api.py:291-326 semantics)
        if isinstance(surrogate, str):
            from ..surrogate.manager import SurrogateManager
            surrogate = SurrogateManager(
                space, surrogate, seed=seed, **(surrogate_opts or {}))
        self.surrogate = surrogate

        root = technique
        if root is None or isinstance(root, str) or (
                isinstance(root, (list, tuple))):
            names = ([root] if isinstance(root, str) else root)
            root = tbase.get_root(names)  # returns a private copy
        else:
            # a directly-passed Technique may be shared by the caller;
            # meta-techniques carry mutable host-side credit state
            import copy
            root = copy.deepcopy(root)
        self.root: Technique = root
        members = (root.techniques if isinstance(root, MetaTechnique)
                   else [root])
        self.members: List[Technique] = [
            t for t in members if t.supports(space)]
        if not self.members:
            raise ValueError(
                f"no technique in {root.name!r} supports this space")
        self._tstates: Dict[str, Any] = {}
        self._propose_jit: Dict[str, Any] = {}
        self._observe_jit: Dict[str, Any] = {}
        for t in self.members:
            self.key, k = jax.random.split(self.key)
            self._tstates[t.name] = t.init_state(space, k)
            self._propose_jit[t.name] = jax.jit(
                lambda st, k, best, _t=t: _t.propose(space, st, k, best))
            self._observe_jit[t.name] = jax.jit(
                lambda st, c, q, best, _t=t: _t.observe(space, st, c, q, best))

        sp, hist = self.space, self.history

        @jax.jit
        def _dedup(hist_state, cands: CandBatch):
            hashes = sp.hash_batch(cands)
            found, known = hist.contains(hist_state, hashes)
            src = dup_source(hashes)
            first = src == jnp.arange(hashes.shape[0])
            novel = first & ~found
            return hashes, found, known, src, novel

        @jax.jit
        def _commit(hist_state, best, hashes, cands: CandBatch, qor,
                    newly):
            hist_state = hist.insert(hist_state, hashes, qor, newly)
            best = best.update(cands, qor)
            return hist_state, best

        self._dedup = _dedup
        self._commit = _commit

        if resume and archive and os.path.exists(archive):
            self._resume(archive)
        elif archive and os.path.exists(archive) and os.path.getsize(archive):
            # not resuming, but never append to a different space's file:
            # check (or backfill) the signature header before reuse
            self._check_archive_header(archive)
        self._archive_f = open(archive, "a") if archive else None
        if self._archive_f is not None and self._archive_f.tell() == 0:
            # header: full space signature, checked on every reopen
            self._archive_f.write(
                json.dumps({"space_sig": self._space_sig()}) + "\n")
            self._archive_f.flush()

    # ------------------------------------------------------------------
    def _space_sig(self) -> List[str]:
        """Ordered structural signature of the space: spec dataclass reprs
        carry name, kind, bounds, options/items — any change invalidates
        position-indexed unit-vector replay."""
        return [repr(s) for s in self.space.specs]

    def _rotate_mismatch(self, path: str) -> None:
        import warnings
        bak = path + ".mismatch"
        os.replace(path, bak)
        warnings.warn(
            f"archive {path} was recorded for a different space; "
            f"moved aside to {bak}")

    def _check_archive_header(self, path: str) -> None:
        """Rotate the archive aside unless its signature (or, for legacy
        headerless files, its first row's param-name set) matches."""
        try:
            with open(path) as f:
                first = json.loads(f.readline())
        except (json.JSONDecodeError, OSError):
            return
        if "space_sig" in first:
            if first["space_sig"] != self._space_sig():
                self._rotate_mismatch(path)
        elif "cfg" in first and set(first["cfg"]) != {
                s.name for s in self.space.specs}:
            self._rotate_mismatch(path)

    def _resume(self, path: str) -> None:
        """Replay the jsonl archive: exact unit vectors -> history + best
        (reference resume(), api.py:328-363 — replayed as technique 'seed',
        i.e. without touching technique states)."""
        rows = []
        sig = None
        good_end = 0
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            for line in f:
                text = line.strip()
                if not text:
                    good_end = f.tell()
                    continue
                try:
                    rec = json.loads(text)
                except json.JSONDecodeError:
                    break  # torn tail write; ignore the rest
                if not line.endswith(b"\n") and f.tell() == size:
                    break  # complete JSON but unterminated final line
                if "space_sig" in rec:
                    sig = rec["space_sig"]
                else:
                    rows.append(rec)
                good_end = f.tell()
        if good_end < size:
            # drop the torn fragment so the next append starts clean
            with open(path, "r+b") as f:
                f.truncate(good_end)
        # the archive must match the current space STRUCTURALLY (order,
        # kinds, bounds — raw unit vectors are position-indexed); the
        # reference deletes a mismatched archive (api.py:334-339), we
        # rotate it aside so mixed-space records never share one file
        mismatch = (sig is not None and sig != self._space_sig()) or (
            sig is None and rows
            and set(rows[0]["cfg"]) != {s.name for s in self.space.specs})
        if mismatch:
            self._rotate_mismatch(path)
            return
        if not rows:
            return
        B = len(rows)
        u = np.asarray([r["u"] for r in rows], np.float32)
        perms = tuple(
            np.asarray([r["perms"][k] for r in rows], np.int32)
            for k in range(len(self.space.perm_sizes)))
        # archive rows are user-oriented; engine-internal = sign * user
        qor = self.sign * np.asarray([r["qor"] for r in rows], np.float32)
        cands = CandBatch(jnp.asarray(u), tuple(jnp.asarray(p) for p in perms))
        hashes, found, known, src, novel = self._dedup(self.hist_state, cands)
        self.hist_state, self.best = self._commit(
            self.hist_state, self.best, hashes, cands, jnp.asarray(qor),
            novel)
        self.gid = max(int(r["gid"]) for r in rows) + 1
        self.evals = len(rows)
        running = float("inf")
        for q in qor:
            running = min(running, float(q))
            self.trace.append(self.sign * running)

    def _log_trial(self, cfg, u_row, perm_rows, qor, is_best, dur) -> None:
        self.gid += 1
        if self._archive_f is None:
            return
        rec = {"gid": self.gid - 1, "time": round(dur, 6), "cfg": cfg,
               "u": [float(x) for x in u_row],
               "perms": [[int(i) for i in p] for p in perm_rows],
               "qor": float(qor), "best": bool(is_best)}
        self._archive_f.write(json.dumps(rec) + "\n")

    def _flush_archive(self):
        if self._archive_f is not None:
            self._archive_f.flush()

    # ------------------------------------------------------------------
    def step(self) -> StepStats:
        """One acquisition step: choose arm -> propose batch -> dedup ->
        evaluate novel -> observe + credit."""
        order = (self.root.select_order()
                 if isinstance(self.root, MetaTechnique) else [self.root])
        order = [t for t in order if t.name in self._tstates]

        chosen = None
        for t in order:
            self.key, k = jax.random.split(self.key)
            tstate, cands = self._propose_jit[t.name](
                self._tstates[t.name], k, self.best)
            hashes, found, known, src, novel = self._dedup(
                self.hist_state, cands)
            n_novel = int(novel.sum())
            if n_novel > 0 or chosen is None:
                chosen = (t, tstate, cands, hashes, found, known, src, novel,
                          n_novel)
            if n_novel > 0:
                break
        t, tstate, cands, hashes, found, known, src, novel, n_novel = chosen

        injected = False
        if n_novel == 0:
            self._zero_novel_streak += 1
            if self._zero_novel_streak >= 3:
                # saturation fallback: random injection (the reference's
                # space is never exhausted because SQL dedup just drops the
                # DR and the driver retries; we top up explicitly).  The
                # injected batch is NOT the arm's proposal: it must not
                # flow into the arm's observe() or bandit credit.
                injected = True
                self.key, k = jax.random.split(self.key)
                cands = self.space.random(k, cands.batch)
                hashes, found, known, src, novel = self._dedup(
                    self.hist_state, cands)
                n_novel = int(novel.sum())
        else:
            self._zero_novel_streak = 0

        novel_np = np.asarray(novel)
        src_np = np.asarray(src)
        qor_np = np.asarray(known, np.float32).copy()  # history dups served
        evaluated = 0
        pruned = 0
        if n_novel and self.surrogate is not None and not injected:
            keep = self.surrogate.keep_mask(cands)
            if keep is not None:
                pruned = int((novel_np & ~keep).sum())
                if pruned:
                    # rejected without evaluation (multivoting prune,
                    # api.py:307-326): +inf to the technique, NOT archived,
                    # NOT inserted into history (may be re-proposed and
                    # re-judged after a refit)
                    novel_np = novel_np & keep
                    novel = jnp.asarray(novel_np)
                    n_novel = int(novel_np.sum())
                    self.pruned_total += pruned
        if n_novel:
            idx = np.nonzero(novel_np)[0]
            sub = cands[jnp.asarray(idx)]
            cfgs = self.space.to_configs(sub)
            t0 = time.time()
            vals = np.asarray(self.objective(cfgs), np.float64).reshape(-1)
            dur = (time.time() - t0) / max(1, len(cfgs))
            # engine minimizes; failures are +inf in ENGINE orientation
            # (sign applies to valid values only, else sense='max' would
            # turn a failure into an unbeatable -inf best)
            qor_np[idx] = np.where(np.isfinite(vals), self.sign * vals,
                                   np.inf)
            evaluated = len(idx)
            u_np = np.asarray(sub.u)
            perms_np = [np.asarray(p) for p in sub.perms]
            running = float(self.best.qor)
            for j, cfg in enumerate(cfgs):
                q_int = float(qor_np[idx[j]])
                is_best = q_int < running
                running = min(running, q_int)
                self._log_trial(cfg, u_np[j], [p[j] for p in perms_np],
                                self.sign * q_int, is_best, dur)
                self.trace.append(self.sign * running)
            self.evals += evaluated
            if self.surrogate is not None:
                self.surrogate.observe(
                    np.asarray(self.space.features(sub)), qor_np[idx])
                self.surrogate.maybe_refit()
        # in-batch duplicates copy their source row's result
        qor_np = qor_np[src_np]
        qor = jnp.asarray(qor_np)

        prev = float(self.best.qor)
        self.hist_state, self.best = self._commit(
            self.hist_state, self.best, hashes, cands, qor, novel)
        new = float(self.best.qor)
        was_new_best = new < prev
        if not injected:
            self._tstates[t.name] = self._observe_jit[t.name](
                tstate, cands, qor, self.best)
            if isinstance(self.root, MetaTechnique):
                self.root.credit(t.name, was_new_best)
        if self.evals > self.history.capacity and not self._cap_warned:
            self._cap_warned = True
            import warnings
            warnings.warn(
                f"evaluation count ({self.evals}) exceeded history capacity "
                f"({self.history.capacity}); dedup will degrade — raise "
                f"Tuner(capacity=...)")
        self.steps += 1
        self._flush_archive()
        return StepStats(self.steps, "random" if injected else t.name,
                         cands.batch, evaluated, self.sign * new,
                         was_new_best, pruned)

    # ------------------------------------------------------------------
    def run(self, test_limit: int = 5000,
            time_limit: Optional[float] = None,
            target: Optional[float] = None) -> TuneResult:
        """Run until `test_limit` evaluations (driver.py:25-26 default
        5000), a wall-clock limit, or a target QoR is reached."""
        t0 = time.time()
        no_eval_streak = 0
        while self.evals < test_limit:
            stats = self.step()
            no_eval_streak = 0 if stats.evaluated else no_eval_streak + 1
            if no_eval_streak >= 25:
                # search space exhausted: even random injection finds
                # nothing hash-novel any more
                break
            if time_limit is not None and time.time() - t0 > time_limit:
                break
            if target is not None and self._target_met(target):
                break
        return self.result()

    def _target_met(self, target: float) -> bool:
        q = float(self.best.qor)
        if not math.isfinite(q):
            return False
        user = self.sign * q
        return user <= target if self.sense == "min" else user >= target

    def result(self) -> TuneResult:
        q = float(self.best.qor)
        cfg = {}
        if math.isfinite(q):
            cfg = self.space.to_configs(self.best.as_batch(1))[0]
        return TuneResult(cfg, self.sign * q, self.evals, self.steps,
                          list(self.trace))

    def best_config(self) -> Dict[str, Any]:
        return self.result().best_config

    def close(self):
        if self._archive_f is not None:
            self._archive_f.close()
            self._archive_f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
