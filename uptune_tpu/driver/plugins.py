"""Search hooks: observer callbacks over the tuning loop.

The reference's SearchPlugin interface + periodic display plugins
(`/root/reference/python/uptune/opentuner/search/plugin.py:26-103`:
before/after main, on_result, on_new_best_result; LogDisplayPlugin
prints best/elapsed every ~5s of result waits, FileDisplayPlugin tees
to a file).  Here hooks attach to the batched Tuner: per-trial
on_result, per-ticket on_step, on_new_best, plus start/finish.
"""
from __future__ import annotations

import json
import logging
import time
from typing import Any, Dict, Optional

log = logging.getLogger("uptune_tpu")


class SearchHook:
    """Base observer; override any subset (plugin.py:26-62)."""

    def on_start(self, tuner) -> None:
        pass

    def on_result(self, tuner, trial, qor: Optional[float]) -> None:
        """Called for every individually-told trial (user orientation)."""

    def on_step(self, tuner, stats) -> None:
        """Called when a ticket finalizes (one StepStats)."""

    def on_new_best(self, tuner, config: Dict[str, Any],
                    qor: float) -> None:
        pass

    def on_finish(self, tuner, result) -> None:
        pass


class LogDisplay(SearchHook):
    """Periodic status line (LogDisplayPlugin, plugin.py:86-101):
    elapsed, evals, best-so-far — at most once per `interval` seconds."""

    def __init__(self, interval: float = 5.0, out=None):
        self.interval = interval
        self.out = out
        self._t0 = time.time()
        self._last = 0.0

    def _emit(self, text: str) -> None:
        if self.out is not None:
            print(text, file=self.out)
        else:
            log.info(text)

    def on_start(self, tuner) -> None:
        self._t0 = time.time()

    @staticmethod
    def _tag(tuner) -> str:
        lbl = getattr(tuner, "label", "")
        return f"[{lbl}] " if lbl else ""

    def on_step(self, tuner, stats) -> None:
        now = time.time()
        if now - self._last < self.interval:
            return
        self._last = now
        self._emit(f"[{now - self._t0:7.1f}s] {self._tag(tuner)}"
                   f"evals={tuner.evals} best={stats.best_qor:.6g} "
                   f"arm={stats.technique} pruned={tuner.pruned_total}")

    def on_new_best(self, tuner, config, qor) -> None:
        self._emit(f"[{time.time() - self._t0:7.1f}s] {self._tag(tuner)}"
                   f"NEW BEST qor={qor:.6g} after {tuner.evals} evals")


class FileDisplay(SearchHook):
    """Append one JSON line per new best to a file
    (FileDisplayPlugin, plugin.py:103-153)."""

    def __init__(self, path: str):
        self.path = path
        self._t0 = time.time()

    def on_start(self, tuner) -> None:
        self._t0 = time.time()

    def on_new_best(self, tuner, config, qor) -> None:
        rec = {"elapsed": round(time.time() - self._t0, 3),
               "evals": tuner.evals, "qor": qor, "config": config}
        # disambiguate interleaved events when several tuners (one per
        # pipeline stage) share this hook instance
        if getattr(tuner, "label", ""):
            rec["tuner"] = tuner.label
        with open(self.path, "a") as f:
            f.write(json.dumps(rec) + "\n")


def fire(hooks, method: str, *args) -> None:
    """Dispatch to every hook, isolating observer failures from the
    tuning loop (an exception in a display must not kill the run)."""
    for h in hooks or ():
        try:
            getattr(h, method)(*args)
        except Exception:  # noqa: BLE001 — observers are best-effort
            log.warning("search hook %s.%s failed", type(h).__name__,
                        method, exc_info=True)
