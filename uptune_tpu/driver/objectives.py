"""Search objectives: map multi-metric results to the engine's scalar
minimization key.

The reference compares Result ORM rows through objective strategy
classes (`/root/reference/python/uptune/opentuner/search/objective.py`:
`MinimizeTime:161`, `MaximizeAccuracy:186`,
`MaximizeAccuracyMinimizeSize:218`, `ThresholdAccuracyMinimizeTime:246`)
with pairwise compare/relative methods.  The TPU-native engine ranks
candidates by one scalar on device, so each objective here is a
*scalarization* `scalarize(metrics) -> float` whose total order matches
the reference's pairwise comparisons:

* lexicographic composites use a documented `scale` separating the
  primary and secondary keys;
* threshold composites place every below-threshold result after every
  above-threshold one, ordered by how far below they are.

Use with the ask/tell driver::

    tuner = Tuner(space, sense="min")
    obj = ThresholdAccuracyMinimizeTime(target=0.95)
    ...
    tuner.tell(trial, obj.scalarize({"time": 3.2, "accuracy": 0.97}))
"""
from __future__ import annotations

import math
from typing import Any, Dict, Optional

Metrics = Dict[str, float]

#: ordering gap between the primary and secondary lexicographic keys;
#: secondary values are clipped into (-SCALE/2, SCALE/2)
SCALE = 1e7


class _NonFinite(Exception):
    """Raised internally when a required metric is nan/inf; __call__
    converts it to the +inf failure rank."""


def _get(metrics: Metrics, key: str) -> float:
    try:
        v = float(metrics[key])
    except KeyError:
        raise KeyError(
            f"objective needs metric {key!r}; got {sorted(metrics)}"
        ) from None
    if not math.isfinite(v):
        raise _NonFinite(key)
    return v


def _clip_secondary(v: float) -> float:
    lim = SCALE / 2.0 - 1.0
    return max(-lim, min(lim, v))


class SearchObjective:
    """Base: scalarize() must be monotone in the objective's preference
    order (smaller = better, the engine's normal form)."""

    #: metric keys this objective reads
    keys = ("time",)

    def scalarize(self, metrics: Metrics) -> float:
        raise NotImplementedError

    def __call__(self, metrics: Metrics) -> float:
        try:
            v = self.scalarize(metrics)
        except _NonFinite:
            return float("inf")   # failed measurement: worst rank
        return v if math.isfinite(v) else float("inf")


class MinimizeTime(SearchObjective):
    """objective.py:161 — the default."""
    keys = ("time",)

    def scalarize(self, metrics: Metrics) -> float:
        return _get(metrics, "time")


class MaximizeAccuracy(SearchObjective):
    """objective.py:186."""
    keys = ("accuracy",)

    def scalarize(self, metrics: Metrics) -> float:
        return -_get(metrics, "accuracy")


class MinimizeSize(SearchObjective):
    keys = ("size",)

    def scalarize(self, metrics: Metrics) -> float:
        return _get(metrics, "size")


class MaximizeAccuracyMinimizeSize(SearchObjective):
    """objective.py:218 — accuracy dominates; size breaks ties (the
    reference compares accuracy first, then size).  Accuracy is
    quantized to `accuracy_resolution` so near-equal accuracies compete
    on size, matching the reference's float-compare tolerance in spirit."""
    keys = ("accuracy", "size")

    def __init__(self, accuracy_resolution: float = 1e-3):
        self.resolution = accuracy_resolution

    def scalarize(self, metrics: Metrics) -> float:
        acc = _get(metrics, "accuracy")
        size = _get(metrics, "size")
        acc_q = round(acc / self.resolution)
        return -acc_q * SCALE + _clip_secondary(size)


class ThresholdAccuracyMinimizeTime(SearchObjective):
    """objective.py:246 — minimize time subject to accuracy >= target;
    any result below the target ranks after every result above it,
    ordered by accuracy shortfall."""
    keys = ("accuracy", "time")

    def __init__(self, target: float):
        self.target = float(target)

    def scalarize(self, metrics: Metrics) -> float:
        acc = _get(metrics, "accuracy")
        t = _get(metrics, "time")
        if acc >= self.target:
            return _clip_secondary(t)
        return SCALE * (1.0 + (self.target - acc))


_BY_NAME = {
    "MinimizeTime": MinimizeTime,
    "MaximizeAccuracy": MaximizeAccuracy,
    "MinimizeSize": MinimizeSize,
    "MaximizeAccuracyMinimizeSize": MaximizeAccuracyMinimizeSize,
    "ThresholdAccuracyMinimizeTime": ThresholdAccuracyMinimizeTime,
}


def get_objective(name: str, **kwargs: Any) -> SearchObjective:
    """Resolve an objective by its reference class name."""
    try:
        return _BY_NAME[name](**kwargs)
    except KeyError:
        raise KeyError(f"unknown objective {name!r}; "
                       f"known: {sorted(_BY_NAME)}") from None
