"""Input management for library-mode measurement.

Reference parity (r4 verdict missing #2): the reference's measurement
driver asks an InputManager which input each desired_result is tested
on, with before/after hooks around the run
(`/root/reference/python/uptune/opentuner/measurement/inputmanager.py:8-70`,
`measurement/driver.py:119`).  Its only shipped policy is
FixedInputManager (one input for every test).

Here the same seam hangs off the library Tuner: when an `input_manager`
is installed, the in-process objective is called as
`objective(cfgs, inputs)` — one input per config, chosen by
`select_input(trial)` — and the before/after hooks bracket the batch.
Without one, nothing changes (`objective(cfgs)`), so existing
objectives keep their signature.

Beyond the reference's fixed policy, RotatingInputManager cycles a
pool of inputs (dataset variants, problem sizes) so a tuned config
cannot overfit one input — the batched analogue of input classes the
reference modeled in its DB but never exercised.
"""
from __future__ import annotations

import itertools
from typing import Any, Optional, Sequence


class Input:
    """One measurement input: an opaque payload plus bookkeeping
    (models.py Input rows carried input_class/path/extra)."""

    __slots__ = ("name", "path", "size", "extra")

    def __init__(self, name: str = "fixed", path: Optional[str] = None,
                 size: int = -1, extra: Any = None):
        self.name = name
        self.path = path
        self.size = size
        self.extra = extra

    def __repr__(self):
        return (f"Input(name={self.name!r}, path={self.path!r}, "
                f"size={self.size})")


class InputManager:
    """Abstract policy: which input does a trial measure on?"""

    def select_input(self, trial) -> Input:
        raise NotImplementedError

    def before_run(self, trial, inp: Input) -> None:
        """Hook before a trial runs on `inp` (inputmanager.py:26-29)."""

    def after_run(self, trial, inp: Input) -> None:
        """Hook after a trial ran on `inp` (inputmanager.py:31-33)."""


class FixedInputManager(InputManager):
    """One cached input for every test (inputmanager.py:38-70)."""

    def __init__(self, name: str = "fixed", path: Optional[str] = None,
                 size: int = -1, extra: Any = None):
        self.name = name
        self.path = path
        self.size = size
        self.extra = extra
        self._input: Optional[Input] = None

    def select_input(self, trial) -> Input:
        if self._input is None:
            self._input = Input(self.name, self.path, self.size,
                                self.extra)
        return self._input


class RotatingInputManager(InputManager):
    """Cycle through a pool of inputs round-robin — tuned configs are
    measured across dataset variants instead of overfitting one."""

    def __init__(self, inputs: Sequence[Input]):
        if not inputs:
            raise ValueError("RotatingInputManager needs >= 1 input")
        self.inputs = list(inputs)
        self._cycle = itertools.cycle(self.inputs)

    def select_input(self, trial) -> Input:
        return next(self._cycle)
