"""Device-resident evaluation history: dedup membership + QoR lookup.

The reference dedups every proposal with an O(1-per-proposal) SQL hash
lookup against a global SQLite table (`/root/reference/python/uptune/
api.py:254-288`) and re-serves known results from it.  At 10^4-10^5
candidates per acquisition step that structure is impossible; here the
history is a pair of sorted uint32 hash arrays living on device, and both
membership and known-QoR lookup are a single vectorized `searchsorted` +
windowed compare over the whole candidate batch.

Insertion is a TRUE MERGE (r5, the acquisition-loop hot spot on both
the 1-core fallback and the TPU scale ladder): the history is already
h0-sorted, so only the incoming batch is sorted (B rows, cheap) and the
two runs are interleaved with two `searchsorted`s + one scatter —
O(cap) data movement instead of the previous two full-width
multi-operand `lax.sort`s over cap+B rows.  Empty slots hold the
(0xFFFFFFFF, 0xFFFFFFFF) sentinel so they land at the end; real h0
values are clamped to 0xFFFFFFFE.  All functions are pure and jittable
with static shapes.

Invariant: h0 ascending with equal-h0 runs CONTIGUOUS; h1 is NOT
ordered within a run (contains() scans the short run window and never
needed it — h0 collisions of distinct configs are ~n^2/2^33).

Past capacity, eviction is OLDEST-FIRST (each row carries the insert-step
it arrived in; overflow drops the smallest ages), not largest-hash: recent
entries are the ones proposals collide with, so dedup degrades
predictably on long runs (VERDICT r2 weak #5 — the old truncate-by-hash
dropped arbitrary configs).  Eviction runs under `lax.cond`, so
non-overflowing steps skip it entirely; ties at the threshold age drop
in hash order (deterministic — the old single-key unstable sort left
the tie order unspecified).  Evicted-live-row counts accumulate in
`HistState.dropped` so the driver can surface degradation instead of
warning once and going silent.  A batch with more valid rows than the
whole capacity is out of contract (the excess drops from the merge
tail in hash order).
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..space.spec import CandBatch, Space

# plain int, cast at use sites: a module-level jnp scalar would create a
# device array at import time and initialize the XLA backend, which
# breaks jax.distributed.initialize() in multi-process runs (it must run
# before any backend init)
_SENTINEL = 0xFFFFFFFF
# max number of equal-h0 neighbours scanned on lookup; h0 collisions of
# distinct configs are ~n^2/2^33 over a run, so 8 is far beyond need
_WINDOW = 8


class HistState(NamedTuple):
    h0: jax.Array    # [cap] uint32, sorted ascending (sentinel-padded)
    h1: jax.Array    # [cap] uint32, lexicographic tie order with h0
    qor: jax.Array   # [cap] f32, aligned with (h0, h1)
    n: jax.Array     # scalar int32 count of live entries
    age: jax.Array   # [cap] i32 insert-step per row (-1 = empty slot)
    step: jax.Array      # scalar i32: insert-batch counter
    dropped: jax.Array   # scalar i32: live rows evicted past capacity


class History:
    """Static config (capacity) + pure state transforms.

    `merge_impl` selects the insert-merge backend ('auto' | 'pallas' |
    'xla', see ops/dedup.py): 'auto' takes the Pallas kernel on TPU
    when the shapes qualify and the parity-tested XLA gather+cumsum
    path everywhere else."""

    def __init__(self, capacity: int = 1 << 16, merge_impl: str = "auto"):
        self.capacity = int(capacity)
        assert merge_impl in ("auto", "pallas", "xla"), merge_impl
        self.merge_impl = merge_impl

    def init(self) -> HistState:
        cap = self.capacity
        return HistState(
            jnp.full((cap,), _SENTINEL, jnp.uint32),
            jnp.full((cap,), _SENTINEL, jnp.uint32),
            jnp.full((cap,), jnp.inf, jnp.float32),
            jnp.asarray(0, jnp.int32),
            jnp.full((cap,), -1, jnp.int32),
            jnp.asarray(0, jnp.int32),
            jnp.asarray(0, jnp.int32))

    @staticmethod
    def _clamp(hashes: jax.Array) -> Tuple[jax.Array, jax.Array]:
        h0 = jnp.minimum(hashes[:, 0].astype(jnp.uint32),
                         jnp.uint32(_SENTINEL - 1))
        h1 = hashes[:, 1].astype(jnp.uint32)
        return h0, h1

    def contains(self, st: HistState,
                 hashes: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """hashes [B, 2] -> (found [B] bool, known_qor [B] f32 (+inf when
        absent)).  The reference analogue is the `unique`/global-DB `get`
        duplicate check (api.py:254-288, database/globalmodels.py:38-45)."""
        h0, h1 = self._clamp(hashes)
        idx = jnp.searchsorted(st.h0, h0, side="left")
        found = jnp.zeros(h0.shape, bool)
        qor = jnp.full(h0.shape, jnp.inf, jnp.float32)
        cap = self.capacity
        for j in range(_WINDOW):
            pos = jnp.minimum(idx + j, cap - 1)
            hit = (st.h0[pos] == h0) & (st.h1[pos] == h1) & ~found
            qor = jnp.where(hit, st.qor[pos], qor)
            found = found | hit
        return found, qor

    def insert(self, st: HistState, hashes: jax.Array, qor: jax.Array,
               valid: jax.Array, evict_pred=None) -> HistState:
        """Merge a batch of (hash, qor) rows where `valid` is True.
        Overflow beyond capacity evicts the OLDEST live rows first; the
        count of evicted live rows accumulates in `dropped`.

        Pipeline (module docstring): [cond] evict-and-compact the
        history in place, sort ONLY the B-row batch, then stable-merge
        the two h0-sorted runs by scatter.  No full-width sort.

        `evict_pred` (optional traced bool) OVERRIDES the eviction
        cond's predicate with a conservative one the caller computed —
        the batched engine passes a batch-level `any instance might
        overflow` scalar from OUTSIDE its vmap, because a cond on a
        per-instance (batched) predicate lowers to a select that runs
        the evict branch every step for every instance.  Must be True
        whenever overflow > 0; spurious True is safe (evict at
        overflow 0 is the identity)."""
        cap = self.capacity
        b = hashes.shape[0]
        h0n, h1n = self._clamp(hashes)
        h0n = jnp.where(valid, h0n, jnp.uint32(_SENTINEL))
        h1n = jnp.where(valid, h1n, jnp.uint32(_SENTINEL))
        age_n = jnp.where(valid, st.step, -1).astype(jnp.int32)
        qn = jnp.where(valid, qor.astype(jnp.float32), jnp.inf)

        n_new = valid.sum().astype(jnp.int32)
        total = st.n + n_new
        overflow = jnp.maximum(total - cap, 0)

        def evict(args):
            """Must stay CHEAP even when it does nothing: under the
            batched multi-instance engine this whole cond runs as a
            vmapped select, i.e. the evict branch executes EVERY step
            for EVERY instance.  The original full-width sort + 4
            scatter compactions cost more than the rest of the step
            combined in that regime; the threshold is now a 31-round
            value-space binary search (compare+count passes, VPU/SIMD
            friendly) and the compaction is cumsum+searchsorted
            GATHERS — no sort, no scatter."""
            h0, h1, q, age, k = args
            live = age >= 0
            big = jnp.asarray(0x7FFFFFFF, jnp.int32)
            ages_live = jnp.where(live, age, big)
            # k-th smallest live age = eviction threshold; rows strictly
            # older all drop, ties at the threshold drop in hash order.
            # Minimal v with count(ages_live <= v) >= k == sorted[k-1]
            # (k <= live count always: k = n + n_new - cap <= n)
            lo = jnp.asarray(0, jnp.int32)
            hi = big
            for _ in range(31):
                mid = lo + (hi - lo) // 2
                cnt = (ages_live <= mid).sum().astype(jnp.int32)
                take = cnt >= k
                lo, hi = (jnp.where(take, lo, mid + 1),
                          jnp.where(take, mid, hi))
            thr = lo
            drop_lt = live & (age < thr)
            eq = live & (age == thr)
            m = k - drop_lt.sum().astype(jnp.int32)
            drop_eq = eq & (jnp.cumsum(eq.astype(jnp.int32)) <= m)
            keep = live & ~(drop_lt | drop_eq)
            # compact kept rows to the front (stays h0-sorted): output
            # slot j pulls the row where the keep-cumsum first reaches
            # j+1; slots past the kept count read the sentinel row
            cum = jnp.cumsum(keep.astype(jnp.int32))
            src = jnp.searchsorted(
                cum, jnp.arange(1, cap + 1, dtype=jnp.int32),
                side="left").astype(jnp.int32)
            ok = jnp.arange(cap, dtype=jnp.int32) < cum[-1]
            src = jnp.clip(src, 0, cap - 1)
            h0c = jnp.where(ok, h0[src], jnp.uint32(_SENTINEL))
            h1c = jnp.where(ok, h1[src], jnp.uint32(_SENTINEL))
            qc = jnp.where(ok, q[src], jnp.inf)
            ac = jnp.where(ok, age[src], -1)
            return h0c, h1c, qc, ac

        h0h, h1h, qh, ah = jax.lax.cond(
            (overflow > 0) if evict_pred is None else evict_pred,
            evict, lambda a: a[:4],
            (st.h0, st.h1, st.qor, st.age, overflow))

        # sort the batch by h0 (B rows — the only sort in the pipeline)
        h0s, order = jax.lax.sort(
            (h0n, jnp.arange(b, dtype=jnp.int32)), num_keys=1)
        h1s, qs, ags = h1n[order], qn[order], age_n[order]

        # stable two-run merge: old rows before new rows on equal h0
        # (keeps equal-h0 runs contiguous; h1 order within a run is
        # unspecified by the invariant).  ops/dedup.py owns the merge:
        # a tiled Pallas kernel on TPU (one-hot MXU gathers over VMEM
        # windows, all four columns in one packed pass), the PR 2
        # gather+cumsum formulation elsewhere — parity-tested in
        # tests/test_batched.py.
        from ..ops import dedup as dedup_ops  # local: avoid cycle
        h0m, h1m, qm, am = dedup_ops.merge_history(
            (h0h, h1h, qh, ah), (h0s, h1s, qs, ags),
            impl=self.merge_impl)

        n = jnp.minimum(total, cap)
        return HistState(h0m, h1m, qm, n, am, st.step + 1,
                         st.dropped + overflow)


def unique_mask(hashes: jax.Array) -> jax.Array:
    """[B, 2] -> [B] bool marking the FIRST occurrence of each distinct
    hash within the batch (in-batch dedup; stable, order-preserving)."""
    return dup_source(hashes) == jnp.arange(hashes.shape[0])


def dup_source(hashes: jax.Array) -> jax.Array:
    """[B, 2] -> [B] int32: index of the first in-batch occurrence of each
    row's hash (i for first occurrences themselves).  Lets the driver copy
    one evaluation result onto all in-batch duplicates."""
    h0 = hashes[:, 0].astype(jnp.uint32)
    h1 = hashes[:, 1].astype(jnp.uint32)
    order = jnp.arange(h0.shape[0], dtype=jnp.int32)
    h0s, h1s, osort = jax.lax.sort((h0, h1, order), num_keys=3)
    is_first = jnp.concatenate([
        jnp.ones((1,), bool),
        (h0s[1:] != h0s[:-1]) | (h1s[1:] != h1s[:-1])])
    # carry forward the original index of the head of each equal run
    group_head = jax.lax.associative_scan(
        jnp.maximum, jnp.where(is_first, jnp.arange(h0.shape[0]), 0))
    src_sorted = osort[group_head]
    return jnp.zeros(h0.shape, jnp.int32).at[osort].set(src_sorted)
