"""Device-resident evaluation history: dedup membership + QoR lookup.

The reference dedups every proposal with an O(1-per-proposal) SQL hash
lookup against a global SQLite table (`/root/reference/python/uptune/
api.py:254-288`) and re-serves known results from it.  At 10^4-10^5
candidates per acquisition step that structure is impossible; here the
history is a pair of sorted uint32 hash arrays living on device, and both
membership and known-QoR lookup are a single vectorized `searchsorted` +
windowed compare over the whole candidate batch.

Insertion is a merge: concatenate, lexicographic `lax.sort` on the two hash
words, truncate to capacity.  Empty slots hold the (0xFFFFFFFF, 0xFFFFFFFF)
sentinel so they sort to the end; real h0 values are clamped to
0xFFFFFFFE.  All functions are pure and jittable with static shapes.
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..space.spec import CandBatch, Space

_SENTINEL = jnp.uint32(0xFFFFFFFF)
# max number of equal-h0 neighbours scanned on lookup; h0 collisions of
# distinct configs are ~n^2/2^33 over a run, so 8 is far beyond need
_WINDOW = 8


class HistState(NamedTuple):
    h0: jax.Array    # [cap] uint32, sorted ascending (sentinel-padded)
    h1: jax.Array    # [cap] uint32, lexicographic tie order with h0
    qor: jax.Array   # [cap] f32, aligned with (h0, h1)
    n: jax.Array     # scalar int32 count of live entries


class History:
    """Static config (capacity) + pure state transforms."""

    def __init__(self, capacity: int = 1 << 16):
        self.capacity = int(capacity)

    def init(self) -> HistState:
        cap = self.capacity
        return HistState(
            jnp.full((cap,), _SENTINEL, jnp.uint32),
            jnp.full((cap,), _SENTINEL, jnp.uint32),
            jnp.full((cap,), jnp.inf, jnp.float32),
            jnp.asarray(0, jnp.int32))

    @staticmethod
    def _clamp(hashes: jax.Array) -> Tuple[jax.Array, jax.Array]:
        h0 = jnp.minimum(hashes[:, 0].astype(jnp.uint32), _SENTINEL - 1)
        h1 = hashes[:, 1].astype(jnp.uint32)
        return h0, h1

    def contains(self, st: HistState,
                 hashes: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """hashes [B, 2] -> (found [B] bool, known_qor [B] f32 (+inf when
        absent)).  The reference analogue is the `unique`/global-DB `get`
        duplicate check (api.py:254-288, database/globalmodels.py:38-45)."""
        h0, h1 = self._clamp(hashes)
        idx = jnp.searchsorted(st.h0, h0, side="left")
        found = jnp.zeros(h0.shape, bool)
        qor = jnp.full(h0.shape, jnp.inf, jnp.float32)
        cap = self.capacity
        for j in range(_WINDOW):
            pos = jnp.minimum(idx + j, cap - 1)
            hit = (st.h0[pos] == h0) & (st.h1[pos] == h1) & ~found
            qor = jnp.where(hit, st.qor[pos], qor)
            found = found | hit
        return found, qor

    def insert(self, st: HistState, hashes: jax.Array, qor: jax.Array,
               valid: jax.Array) -> HistState:
        """Merge a batch of (hash, qor) rows where `valid` is True.
        Overflow beyond capacity silently drops the largest hashes (the
        driver warns host-side)."""
        h0n, h1n = self._clamp(hashes)
        h0n = jnp.where(valid, h0n, _SENTINEL)
        h1n = jnp.where(valid, h1n, _SENTINEL)
        h0c = jnp.concatenate([st.h0, h0n])
        h1c = jnp.concatenate([st.h1, h1n])
        qc = jnp.concatenate([st.qor, qor.astype(jnp.float32)])
        h0s, h1s, qs = jax.lax.sort((h0c, h1c, qc), num_keys=2)
        cap = self.capacity
        n = jnp.minimum(st.n + valid.sum().astype(jnp.int32), cap)
        return HistState(h0s[:cap], h1s[:cap], qs[:cap], n)


def unique_mask(hashes: jax.Array) -> jax.Array:
    """[B, 2] -> [B] bool marking the FIRST occurrence of each distinct
    hash within the batch (in-batch dedup; stable, order-preserving)."""
    return dup_source(hashes) == jnp.arange(hashes.shape[0])


def dup_source(hashes: jax.Array) -> jax.Array:
    """[B, 2] -> [B] int32: index of the first in-batch occurrence of each
    row's hash (i for first occurrences themselves).  Lets the driver copy
    one evaluation result onto all in-batch duplicates."""
    h0 = hashes[:, 0].astype(jnp.uint32)
    h1 = hashes[:, 1].astype(jnp.uint32)
    order = jnp.arange(h0.shape[0], dtype=jnp.int32)
    h0s, h1s, osort = jax.lax.sort((h0, h1, order), num_keys=3)
    is_first = jnp.concatenate([
        jnp.ones((1,), bool),
        (h0s[1:] != h0s[:-1]) | (h1s[1:] != h1s[:-1])])
    # carry forward the original index of the head of each equal run
    group_head = jax.lax.associative_scan(
        jnp.maximum, jnp.where(is_first, jnp.arange(h0.shape[0]), 0))
    src_sorted = osort[group_head]
    return jnp.zeros(h0.shape, jnp.int32).at[osort].set(src_sorted)
