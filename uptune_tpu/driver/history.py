"""Device-resident evaluation history: dedup membership + QoR lookup.

The reference dedups every proposal with an O(1-per-proposal) SQL hash
lookup against a global SQLite table (`/root/reference/python/uptune/
api.py:254-288`) and re-serves known results from it.  At 10^4-10^5
candidates per acquisition step that structure is impossible; here the
history is a pair of sorted uint32 hash arrays living on device, and both
membership and known-QoR lookup are a single vectorized `searchsorted` +
windowed compare over the whole candidate batch.

Insertion is a merge: concatenate, lexicographic `lax.sort` on the two hash
words, truncate to capacity.  Empty slots hold the (0xFFFFFFFF, 0xFFFFFFFF)
sentinel so they sort to the end; real h0 values are clamped to
0xFFFFFFFE.  All functions are pure and jittable with static shapes.

Past capacity, eviction is OLDEST-FIRST (each row carries the insert-step
it arrived in; overflow drops the smallest ages), not largest-hash: recent
entries are the ones proposals collide with, so dedup degrades
predictably on long runs (VERDICT r2 weak #5 — the old truncate-by-hash
dropped arbitrary configs).  Evicted-live-row counts accumulate in
`HistState.dropped` so the driver can surface degradation instead of
warning once and going silent.
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..space.spec import CandBatch, Space

# plain int, cast at use sites: a module-level jnp scalar would create a
# device array at import time and initialize the XLA backend, which
# breaks jax.distributed.initialize() in multi-process runs (it must run
# before any backend init)
_SENTINEL = 0xFFFFFFFF
# max number of equal-h0 neighbours scanned on lookup; h0 collisions of
# distinct configs are ~n^2/2^33 over a run, so 8 is far beyond need
_WINDOW = 8


class HistState(NamedTuple):
    h0: jax.Array    # [cap] uint32, sorted ascending (sentinel-padded)
    h1: jax.Array    # [cap] uint32, lexicographic tie order with h0
    qor: jax.Array   # [cap] f32, aligned with (h0, h1)
    n: jax.Array     # scalar int32 count of live entries
    age: jax.Array   # [cap] i32 insert-step per row (-1 = empty slot)
    step: jax.Array      # scalar i32: insert-batch counter
    dropped: jax.Array   # scalar i32: live rows evicted past capacity


class History:
    """Static config (capacity) + pure state transforms."""

    def __init__(self, capacity: int = 1 << 16):
        self.capacity = int(capacity)

    def init(self) -> HistState:
        cap = self.capacity
        return HistState(
            jnp.full((cap,), _SENTINEL, jnp.uint32),
            jnp.full((cap,), _SENTINEL, jnp.uint32),
            jnp.full((cap,), jnp.inf, jnp.float32),
            jnp.asarray(0, jnp.int32),
            jnp.full((cap,), -1, jnp.int32),
            jnp.asarray(0, jnp.int32),
            jnp.asarray(0, jnp.int32))

    @staticmethod
    def _clamp(hashes: jax.Array) -> Tuple[jax.Array, jax.Array]:
        h0 = jnp.minimum(hashes[:, 0].astype(jnp.uint32),
                         jnp.uint32(_SENTINEL - 1))
        h1 = hashes[:, 1].astype(jnp.uint32)
        return h0, h1

    def contains(self, st: HistState,
                 hashes: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """hashes [B, 2] -> (found [B] bool, known_qor [B] f32 (+inf when
        absent)).  The reference analogue is the `unique`/global-DB `get`
        duplicate check (api.py:254-288, database/globalmodels.py:38-45)."""
        h0, h1 = self._clamp(hashes)
        idx = jnp.searchsorted(st.h0, h0, side="left")
        found = jnp.zeros(h0.shape, bool)
        qor = jnp.full(h0.shape, jnp.inf, jnp.float32)
        cap = self.capacity
        for j in range(_WINDOW):
            pos = jnp.minimum(idx + j, cap - 1)
            hit = (st.h0[pos] == h0) & (st.h1[pos] == h1) & ~found
            qor = jnp.where(hit, st.qor[pos], qor)
            found = found | hit
        return found, qor

    def insert(self, st: HistState, hashes: jax.Array, qor: jax.Array,
               valid: jax.Array) -> HistState:
        """Merge a batch of (hash, qor) rows where `valid` is True.
        Overflow beyond capacity evicts the OLDEST live rows first
        (empty slots before any live row); the count of evicted live
        rows accumulates in `dropped`."""
        h0n, h1n = self._clamp(hashes)
        h0n = jnp.where(valid, h0n, jnp.uint32(_SENTINEL))
        h1n = jnp.where(valid, h1n, jnp.uint32(_SENTINEL))
        age_n = jnp.where(valid, st.step, -1).astype(jnp.int32)
        h0c = jnp.concatenate([st.h0, h0n])
        h1c = jnp.concatenate([st.h1, h1n])
        qc = jnp.concatenate([st.qor, qor.astype(jnp.float32)])
        ac = jnp.concatenate([st.age, age_n])
        cap = self.capacity
        # phase 1: order by recency — live rows (age >= 0) newest-first,
        # then empty/invalid slots (age == -1 -> key +1, after all live
        # keys which are <= 0) — and keep the first `cap`
        key = jnp.where(ac >= 0, -ac, 1)
        _, h0k, h1k, qk, ak = jax.lax.sort(
            (key, h0c, h1c, qc, ac), num_keys=1)
        h0k, h1k, qk, ak = h0k[:cap], h1k[:cap], qk[:cap], ak[:cap]
        # evicted rows must not survive as hash-matchable ghosts
        h0k = jnp.where(ak >= 0, h0k, jnp.uint32(_SENTINEL))
        h1k = jnp.where(ak >= 0, h1k, jnp.uint32(_SENTINEL))
        # phase 2: restore the sorted-hash invariant contains() needs
        h0s, h1s, qs, ags = jax.lax.sort((h0k, h1k, qk, ak), num_keys=2)
        total = st.n + valid.sum().astype(jnp.int32)
        n = jnp.minimum(total, cap)
        overflow = jnp.maximum(total - cap, 0)
        return HistState(h0s, h1s, qs, n, ags, st.step + 1,
                         st.dropped + overflow)


def unique_mask(hashes: jax.Array) -> jax.Array:
    """[B, 2] -> [B] bool marking the FIRST occurrence of each distinct
    hash within the batch (in-batch dedup; stable, order-preserving)."""
    return dup_source(hashes) == jnp.arange(hashes.shape[0])


def dup_source(hashes: jax.Array) -> jax.Array:
    """[B, 2] -> [B] int32: index of the first in-batch occurrence of each
    row's hash (i for first occurrences themselves).  Lets the driver copy
    one evaluation result onto all in-batch duplicates."""
    h0 = hashes[:, 0].astype(jnp.uint32)
    h1 = hashes[:, 1].astype(jnp.uint32)
    order = jnp.arange(h0.shape[0], dtype=jnp.int32)
    h0s, h1s, osort = jax.lax.sort((h0, h1, order), num_keys=3)
    is_first = jnp.concatenate([
        jnp.ones((1,), bool),
        (h0s[1:] != h0s[:-1]) | (h1s[1:] != h1s[:-1])])
    # carry forward the original index of the head of each equal run
    group_head = jax.lax.associative_scan(
        jnp.maximum, jnp.where(is_first, jnp.arange(h0.shape[0]), 0))
    src_sorted = osort[group_head]
    return jnp.zeros(h0.shape, jnp.int32).at[osort].set(src_sorted)
