from .driver import StepStats, TuneResult, Tuner  # noqa: F401
from .history import History, HistState, dup_source, unique_mask  # noqa: F401
