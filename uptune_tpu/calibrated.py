"""Calibrated surrogate settings — importable WITHOUT jax.

Selected by the calibration grid (scripts/calibrate_tpu.py) and
validated at 30 seeds (BENCHREPORT.md): EI top-k concentration of
technique batches plus the surrogate proposal plane.  These are the
defaults the CLI / ProgramTuner apply when a learning model is enabled
by name; library users override any key via `surrogate_opts`.

This module must stay free of jax imports: benchmark and CLI entry
points read it before the platform guard (scripts/cpuenv.py) has run,
and importing jax eagerly can dial the wedgeable axon TPU tunnel.
"""

CALIBRATED_OPTS = {
    "min_points": 16, "refit_interval": 16, "max_points": 256,
    "select": "topk", "keep_frac": 0.35, "explore_frac": 0.1,
    "score": "ei", "propose_batch": 8, "propose_every": 2,
    "pool_mult": 64,
}

# Not in the calibrated dict (the schedule is the measured default):
# `arbitration='bandit'` turns the proposal plane into a credit-earning
# virtual arm of the AUC bandit (driver applies pull-size parity to the
# pool batch; the run-budget passivation rule still applies).  Opt in
# via `ut --surrogate-arbitration bandit` or surrogate_opts; measured
# tradeoffs in BENCHREPORT.md ("Bandit-arbitrated plane").

# The measured recommendation for BUDGET-CONSTRAINED real-build tuning
# (eval budget comparable to or below the parameter count, e.g. 80
# compiles over a ~330-flag gcc space): let the AUC credit arbitrate
# with affordable 8-eval pulls instead of passivating the plane.  At 30
# matched seeds on gcc-real this is the best measured configuration —
# median 25 iters vs baseline 28.5 (0.88x), solve-rate 28/30, vs the
# passive rule's 28/4-censored (BENCHREPORT.md "Why the surrogate...",
# exp_bandit_gccreal_r4f.jsonl).  CLI: --learning-models gp
# --surrogate-arbitration bandit-small-budget.
BUDGET_CONSTRAINED_OPTS = {
    **CALIBRATED_OPTS,
    "arbitration": "bandit",
    "auto_passive": False,
    "propose_batch_parity": False,
}
