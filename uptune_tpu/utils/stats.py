"""Offline archive analysis: per-technique attribution + convergence.

The reference answers "which technique found the best, and how fast did
each converge" by post-hoc SQL over its results DBs
(`/root/reference/python/uptune/opentuner/utils/stats.py`, 478 LoC of
per-technique convergence CSV extraction + `stats_matplotlib.py`
rendering, fed by the requestor column of every Result,
`resultsdb/models.py:234-300`).  Our jsonl trial archive carries the
same attribution (`tech` per row, driver/driver.py _log_trial), so the
whole analysis is one pass over the file.

CLI:  ut-stats ut.archive.jsonl [--csv out.csv] [--plot out.png]
      ut-stats ut.archive.jsonl --follow     # live during-run view

`--follow` replaces the reference's decouple-mode runtime matplotlib
dashboard (src/async_task_scheduler.py:148-209 blitting QoR curves): it
tails the archive as the controller appends trials and re-renders
best-so-far + per-technique attribution in place, working over ssh where
a GUI dashboard cannot.
"""
from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time
from typing import Any, Dict, List, Optional

Row = Dict[str, Any]


def load_archive(path: str) -> List[Row]:
    """Read archive rows (skipping the space-signature header and any
    torn tail line)."""
    rows: List[Row] = []
    bad_line = None   # one-line lookbehind: junk is only OK at EOF
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            if bad_line is not None:
                # the junk was mid-file, not a torn tail: skip THAT line
                # only — dropping the rest would silently falsify
                # attribution counts
                print(f"ut-stats: skipping corrupt line {bad_line} of "
                      f"{path}", file=sys.stderr)
                bad_line = None
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                bad_line = lineno
                continue
            if "space_sig" in rec:
                continue
            rows.append(rec)
    return rows


def technique_report(rows: List[Row], sense: str = "min"
                     ) -> Dict[str, Dict[str, Any]]:
    """Per-technique attribution: evals, failures, best QoR, new-best
    count, eval index of the global best, mean eval time."""
    sign = 1.0 if sense == "min" else -1.0
    best_val = math.inf
    best_tech: Optional[str] = None
    best_idx: Optional[int] = None
    out: Dict[str, Dict[str, Any]] = {}
    for i, r in enumerate(rows):
        tech = r.get("tech", "?")
        st = out.setdefault(tech, {
            "evals": 0, "failures": 0, "new_bests": 0,
            "best_qor": math.inf, "time_sum": 0.0,
            "first_eval": i, "global_best_at": None})
        st["evals"] += 1
        st["time_sum"] += float(r.get("time", 0.0))
        q = float(r["qor"])
        eng = sign * q
        if not math.isfinite(eng):
            st["failures"] += 1
            continue
        st["best_qor"] = min(st["best_qor"], eng)
        if r.get("best"):
            st["new_bests"] += 1
        if eng < best_val:
            best_val, best_tech, best_idx = eng, tech, i
    for tech, st in out.items():
        st["mean_time"] = (st["time_sum"] / st["evals"]
                           if st["evals"] else 0.0)
        del st["time_sum"]
        st["found_global_best"] = tech == best_tech
        if tech == best_tech:
            st["global_best_at"] = best_idx
        if math.isfinite(st["best_qor"]):
            st["best_qor"] = sign * st["best_qor"]   # user orientation
        else:
            st["best_qor"] = None
    return out


def convergence(rows: List[Row], sense: str = "min"
                ) -> Dict[str, List[List[float]]]:
    """Per-technique best-so-far curve: [eval_index, tech_best] pairs at
    each improvement (the per-technique convergence CSVs the reference
    extracts, opentuner/utils/stats.py)."""
    sign = 1.0 if sense == "min" else -1.0
    cur: Dict[str, float] = {}
    out: Dict[str, List[List[float]]] = {}
    for i, r in enumerate(rows):
        tech = r.get("tech", "?")
        q = sign * float(r["qor"])
        if not math.isfinite(q):
            continue
        if q < cur.get(tech, math.inf):
            cur[tech] = q
            out.setdefault(tech, []).append([i, sign * q])
    return out


def render_table(report: Dict[str, Dict[str, Any]]) -> str:
    cols = ("technique", "evals", "failures", "new_bests", "best_qor",
            "mean_time_s", "found_best")
    lines = ["  ".join(f"{c:>14}" for c in cols)]
    order = sorted(report, key=lambda t: -report[t]["evals"])
    for tech in order:
        st = report[tech]
        bq = ("-" if st["best_qor"] is None
              else f"{st['best_qor']:.6g}")
        row = (tech, st["evals"], st["failures"], st["new_bests"], bq,
               f"{st['mean_time']:.3f}",
               "*" if st["found_global_best"] else "")
        lines.append("  ".join(f"{str(v):>14}" for v in row))
    return "\n".join(lines)


def write_csv(rows: List[Row], path: str, sense: str = "min") -> None:
    conv = convergence(rows, sense)
    with open(path, "w") as f:
        f.write("technique,eval_index,best_so_far\n")
        for tech in sorted(conv):
            for i, v in conv[tech]:
                f.write(f"{tech},{int(i)},{v}\n")


def plot(rows: List[Row], path: str, sense: str = "min") -> bool:
    """Best-so-far-per-technique step plot; returns False when
    matplotlib is unavailable (optional dependency, like the
    reference's stats_matplotlib)."""
    try:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:
        return False
    conv = convergence(rows, sense)
    fig, ax = plt.subplots(figsize=(8, 5))
    for tech in sorted(conv):
        pts = conv[tech]
        xs = [p[0] for p in pts]
        ys = [p[1] for p in pts]
        ax.step(xs, ys, where="post", label=tech)
    ax.set_xlabel("evaluation")
    ax.set_ylabel("best QoR so far")
    ax.legend(fontsize=7)
    fig.tight_layout()
    fig.savefig(path, dpi=120)
    plt.close(fig)
    return True


class ArchiveTail:
    """Incremental archive reader for --follow: returns newly appended
    complete rows per poll, surviving slow writers (partial trailing
    lines are buffered, not dropped) and archive rotation (the driver
    rotates a space-mismatched archive on resume — detected by the file
    shrinking, which resets the cursor)."""

    def __init__(self, path: str):
        self.path = path
        self.offset = 0
        self.partial = b""

    def read_new(self) -> List[Row]:
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return []
        if size < self.offset:            # rotated/truncated: start over
            self.offset = 0
            self.partial = b""
        if size == self.offset:
            return []
        with open(self.path, "rb") as f:
            f.seek(self.offset)
            chunk = f.read()
            self.offset = f.tell()
        data = self.partial + chunk
        lines = data.split(b"\n")
        self.partial = lines.pop()        # b"" when chunk ended in \n
        rows: List[Row] = []
        for ln in lines:
            ln = ln.strip()
            if not ln:
                continue
            try:
                rec = json.loads(ln)
            except json.JSONDecodeError:
                continue
            if "space_sig" not in rec:
                rows.append(rec)
        return rows


def _render_follow(rows: List[Row], sense: str, started: float) -> str:
    sign = 1.0 if sense == "min" else -1.0
    finite = [sign * float(r["qor"]) for r in rows
              if math.isfinite(float(r["qor"]))]
    best = sign * min(finite) if finite else None
    last_best_i = max((i for i, r in enumerate(rows) if r.get("best")),
                      default=None)
    head = [
        f"ut-stats --follow   evals={len(rows)} "
        f"failures={len(rows) - len(finite)} "
        f"best={'-' if best is None else f'{best:.6g}'} "
        f"last_improvement=@{'-' if last_best_i is None else last_best_i} "
        f"uptime={time.time() - started:.0f}s",
        "",
    ]
    return "\n".join(head) + render_table(technique_report(rows, sense))


def follow(path: str, sense: str = "min", interval: float = 2.0,
           max_polls: Optional[int] = None) -> int:
    """Tail the archive and re-render the live view every `interval`
    seconds until interrupted (`max_polls` bounds the loop for tests)."""
    tail = ArchiveTail(path)
    rows: List[Row] = []
    started = time.time()
    polls = 0
    dirty = True
    try:
        while max_polls is None or polls < max_polls:
            polls += 1
            new = tail.read_new()
            if new:
                rows.extend(new)
                dirty = True
            if dirty:
                view = _render_follow(rows, sense, started)
                if sys.stdout.isatty():
                    sys.stdout.write("\x1b[2J\x1b[H" + view + "\n")
                else:
                    sys.stdout.write(view + "\n")
                sys.stdout.flush()
                dirty = False
            time.sleep(interval)
    except KeyboardInterrupt:
        pass
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="ut-stats",
        description="per-technique attribution report from a jsonl "
                    "trial archive")
    ap.add_argument("archive")
    ap.add_argument("--sense", choices=("min", "max"), default="min")
    ap.add_argument("--csv", help="write per-technique convergence CSV")
    ap.add_argument("--plot", help="write convergence plot PNG")
    ap.add_argument("--json", action="store_true",
                    help="print the report as JSON")
    ap.add_argument("--follow", action="store_true",
                    help="live during-run view: tail the archive and "
                         "re-render best-so-far + attribution")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="--follow poll interval in seconds")
    args = ap.parse_args(argv)
    if args.follow:
        return follow(args.archive, args.sense, args.interval)
    rows = load_archive(args.archive)
    if not rows:
        print("ut-stats: empty archive", file=sys.stderr)
        return 1
    report = technique_report(rows, args.sense)
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(render_table(report))
    if args.csv:
        write_csv(rows, args.csv, args.sense)
    if args.plot and not plot(rows, args.plot, args.sense):
        print("ut-stats: matplotlib unavailable; no plot",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
